"""Jitted numerical health checks on carried sampler state.

SVGD failure modes that survive a dispatch but poison the trajectory:

- **NaN/Inf contamination** — one non-finite score entry spreads through the
  φ interaction sum to every particle within a step or two (the kernel
  couples all pairs);
- **particle-norm explosion** — a too-large step size on a stiff posterior
  sends particles running down an unbounded likelihood direction;
- **step-size divergence** — per-step displacement growing instead of
  contracting toward the fixed point (Liu & Wang 2016's iteration is a
  contraction near the posterior for small enough ε).

Each check is one tiny jitted reduction over the ``(n, d)`` array — the
device→host cost is three scalars, so a supervised run can afford it at
every segment boundary.  On violation the supervisor rolls back to the last
good checkpoint and backs the step size off
(:class:`~dist_svgd_tpu.resilience.supervisor.RunSupervisor`), logging the
report through ``utils/metrics.py:JsonlLogger``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp


class GuardViolation(RuntimeError):
    """A numerical health check failed.  ``report`` holds the measured
    scalars (finite counts, norms, displacement) and ``reason`` the check
    that tripped."""

    def __init__(self, reason: str, report: dict):
        super().__init__(f"{reason}: {report}")
        self.reason = reason
        self.report = report


@dataclass
class GuardConfig:
    """What to check, and the recovery knob.

    Args:
        check_finite: trip on any NaN/Inf entry in the particle state.
        max_particle_norm: trip when any particle's L2 norm exceeds this
            (``None`` disables) — the norm-explosion guard.
        max_step_norm: trip when the maximum per-step particle displacement
            across the checked segment exceeds this (``None`` disables) —
            the step-size-divergence guard.  Needs the pre-segment state,
            which the supervisor snapshots only when this is set.
        backoff_factor: step-size multiplier applied on rollback (the
            supervisor's step-size-backoff policy).
        max_ksd: trip when the diagnosed kernelized Stein discrepancy
            exceeds this — the posterior-drift guard.  Evaluated (like the
            three thresholds below) against the supervisor's periodic
            :class:`~dist_svgd_tpu.telemetry.diagnostics.
            PosteriorDiagnostics` report, so it only fires on boundaries
            where diagnostics ran (and, for KSD, only when a score
            function is configured).
        min_ess_frac: trip when kernel-ESS over n falls below this — the
            particle-collapse guard (score-free).
        min_dim_var: trip when any dimension's particle variance falls
            below this — the dead-dimension / mode-collapse guard.
        max_shard_mean_div: trip when the scale-normalised inter-shard
            mean divergence exceeds this (``DistSampler`` runs only).
    """

    check_finite: bool = True
    max_particle_norm: Optional[float] = None
    max_step_norm: Optional[float] = None
    backoff_factor: float = 0.5
    max_ksd: Optional[float] = None
    min_ess_frac: Optional[float] = None
    min_dim_var: Optional[float] = None
    max_shard_mean_div: Optional[float] = None

    @property
    def needs_prev(self) -> bool:
        return self.max_step_norm is not None

    @property
    def checks_diagnostics(self) -> bool:
        """True when any drift/collapse threshold is set — the supervisor
        then routes diagnostics reports through :func:`check_diagnostics`."""
        return any(v is not None for v in (
            self.max_ksd, self.min_ess_frac, self.min_dim_var,
            self.max_shard_mean_div,
        ))


@jax.jit
def _health(particles, prev):
    """One fused reduction pass: (#non-finite entries, max particle norm,
    max row displacement vs ``prev``)."""
    nonfinite = jnp.size(particles) - jnp.sum(jnp.isfinite(particles))
    # a NaN-poisoned norm must still trip max_particle_norm comparisons:
    # jnp.max propagates NaN, and the caller checks non-finite first anyway
    max_norm = jnp.max(jnp.linalg.norm(particles, axis=-1))
    max_delta = jnp.max(jnp.linalg.norm(particles - prev, axis=-1))
    return nonfinite, max_norm, max_delta


def check_state(particles, prev=None, steps: int = 1,
                config: Optional[GuardConfig] = None) -> dict:
    """Run the configured checks on ``particles``; returns the measured
    report dict, raising :class:`GuardViolation` on the first tripped check.

    ``prev`` is the state ``steps`` steps earlier (for the displacement
    guard; defaults to ``particles``, making that guard inert), and the
    reported ``max_step_norm`` is the max row displacement divided by
    ``steps`` — a per-step divergence proxy that stays comparable across
    segment lengths."""
    config = config or GuardConfig()
    particles = jnp.asarray(particles)
    prev_arr = particles if prev is None else jnp.asarray(prev)
    nonfinite, max_norm, max_delta = _health(particles, prev_arr)
    report = {
        "nonfinite_entries": int(nonfinite),
        "max_particle_norm": float(max_norm),
        "max_step_norm": float(max_delta) / max(int(steps), 1),
    }
    if config.check_finite and report["nonfinite_entries"]:
        raise GuardViolation("non-finite particle state", report)
    if (config.max_particle_norm is not None
            and not report["max_particle_norm"] <= config.max_particle_norm):
        # `not <=` rather than `>`: a NaN norm with check_finite=False must
        # still trip here instead of comparing False
        raise GuardViolation(
            f"particle norm exceeds {config.max_particle_norm}", report
        )
    if (prev is not None and config.max_step_norm is not None
            and not report["max_step_norm"] <= config.max_step_norm):
        raise GuardViolation(
            f"per-step displacement exceeds {config.max_step_norm}", report
        )
    return report


def check_diagnostics(report: dict, config: GuardConfig) -> dict:
    """Judge a posterior-diagnostics report against the drift/collapse
    thresholds; returns ``report``, raising :class:`GuardViolation` on the
    first tripped check.

    ``report`` is a :class:`~dist_svgd_tpu.telemetry.diagnostics.
    PosteriorDiagnostics` report dict (plain floats).  A statistic absent
    from the report (e.g. ``ksd`` with no score function, shard divergence
    on a single-device run) leaves its check inert; every comparison is
    the NaN-safe ``not <=`` / ``not >=`` form, so a NaN statistic trips
    instead of comparing False.
    """
    ksd = report.get("ksd")
    if (config.max_ksd is not None and ksd is not None
            and not ksd <= config.max_ksd):
        raise GuardViolation(
            f"posterior drift: ksd exceeds {config.max_ksd}", report)
    ess_frac = report.get("ess_frac")
    if (config.min_ess_frac is not None and ess_frac is not None
            and not ess_frac >= config.min_ess_frac):
        raise GuardViolation(
            f"particle collapse: ess_frac below {config.min_ess_frac}",
            report)
    min_var = report.get("min_dim_var")
    if (config.min_dim_var is not None and min_var is not None
            and not min_var >= config.min_dim_var):
        raise GuardViolation(
            f"dimension collapse: min_dim_var below {config.min_dim_var}",
            report)
    shard_div = report.get("shard_mean_div")
    if (config.max_shard_mean_div is not None and shard_div is not None
            and not shard_div <= config.max_shard_mean_div):
        raise GuardViolation(
            f"shard divergence: shard_mean_div exceeds "
            f"{config.max_shard_mean_div}", report)
    return report
