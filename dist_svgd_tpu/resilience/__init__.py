"""Fault-tolerant training: supervised runs, fault injection, numerical
guards.

A multi-hour DistSampler run is glass without this package: one preemption,
transient dispatch failure, or NaN blowup loses the whole trajectory.  The
subsystem wraps both samplers with the recovery behaviours the serving path
already has for overload:

- :mod:`supervisor` — :class:`RunSupervisor`: bounded segments on an
  absolute step grid, periodic + signal-triggered checkpointing
  (``utils/checkpoint.py`` layouts), bitwise-exact resume-from-latest,
  retry with exponential backoff and a bounded restart budget;
- :mod:`guards` — jitted NaN/Inf / norm-explosion / step-divergence checks
  with a rollback + step-size-backoff policy;
- :mod:`faults` — deterministic fault injection (raise-on-step-k, NaN into
  the carry, simulated preemption, simulated hard kill, artificial slow
  dispatch, device loss / mesh shrink / mesh grow, plus the round-15
  process-level fleet faults: replica kill / hang / slowdown / network
  partition, consumed by ``serving/fleet.py``'s fake transport) so every
  recovery path runs in tier-1 on CPU;
- :mod:`federation` — :class:`FederationSupervisor`: the coordinator loop
  for W-process multi-host jobs, recovering the failure unit nothing
  in-process can (an entire worker dying) by tearing down the rendezvous
  and relaunching at W−1 against the host-sharded checkpoints
  (``tools/multihost_train.py`` drives it fake and real);
- :mod:`backoff` — the ONE capped-exponential-backoff implementation
  (jitter optional, RNG injectable) shared by the supervisor's
  :class:`RetryPolicy` and the serving fleet's router;
- **elastic capacity** — ``RunSupervisor(reshard=ReshardPolicy(factory))``
  survives topology faults by resharding the latest checkpoint onto the
  surviving mesh (``utils/checkpoint.py:reshard_state``) inside the same
  restart budget; ``tools/elastic_drill.py`` measures it end to end.

The serve side composes through
``serving/engine.py:CheckpointHotReloader`` (a live server picks up the
supervisor's checkpoints between micro-batches — train-while-serving);
``tools/fault_drill.py`` measures recovery wall / steps lost / checkpoint
overhead as one BENCH-style JSON row, and
``experiments/resilient_covertype.py`` demonstrates kill → resume → serve.
"""

from dist_svgd_tpu.resilience.backoff import Backoff, capped_delay
from dist_svgd_tpu.resilience.federation import (
    FakeWorker,
    FederationDead,
    FederationSupervisor,
    SubprocessWorker,
)
from dist_svgd_tpu.resilience.faults import (
    BadGenerationAt,
    DeviceLossAt,
    DriftAt,
    FaultPlan,
    FleetFault,
    HardKillAt,
    InjectNaNAt,
    MeshGrowAt,
    MeshShrinkAt,
    PartitionAt,
    PreemptAt,
    RaiseAt,
    ReplicaHangAt,
    ReplicaKillAt,
    SimulatedHardKill,
    SlowReplicaAt,
    SlowSegmentAt,
    TopologyFault,
    TransientDispatchError,
    WorkerLossAt,
)
from dist_svgd_tpu.resilience.guards import GuardConfig, GuardViolation, check_state
from dist_svgd_tpu.resilience.supervisor import (
    ReshardPolicy,
    RestartBudgetExhausted,
    RetryPolicy,
    RunSupervisor,
)

__all__ = [
    "RunSupervisor",
    "RetryPolicy",
    "ReshardPolicy",
    "RestartBudgetExhausted",
    "GuardConfig",
    "GuardViolation",
    "check_state",
    "FaultPlan",
    "RaiseAt",
    "InjectNaNAt",
    "PreemptAt",
    "HardKillAt",
    "SlowSegmentAt",
    "DeviceLossAt",
    "MeshShrinkAt",
    "MeshGrowAt",
    "WorkerLossAt",
    "TopologyFault",
    "TransientDispatchError",
    "SimulatedHardKill",
    "Backoff",
    "capped_delay",
    "FederationSupervisor",
    "FederationDead",
    "FakeWorker",
    "SubprocessWorker",
    "FleetFault",
    "BadGenerationAt",
    "DriftAt",
    "ReplicaKillAt",
    "ReplicaHangAt",
    "PartitionAt",
    "SlowReplicaAt",
]
