"""Federation supervision: keep a W-process multi-host training job alive
across whole-worker losses.

:class:`~dist_svgd_tpu.resilience.supervisor.RunSupervisor` recovers
*in-process* faults; a multi-host federation adds the failure unit nothing
in-process can catch — an entire worker process dying (host SIGKILL, OOM,
node loss).  The surviving coordinator must then tear the rest of the
rendezvous down (a federation with a hole deadlocks at its next collective)
and restart the job at W−1 processes, resuming from the host-sharded
checkpoints every worker wrote (``DistSampler.state_dict`` per-process
blocks → ``utils/checkpoint.py:assemble_full_state`` → ``reshard_state``),
on the same absolute step grid.

:class:`FederationSupervisor` is that coordinator loop, written against an
injectable **launcher** (``launcher(process_count, attempt) -> [worker
handles]``) so the whole recovery path runs in tier-1 with
:class:`FakeWorker` scripts — no processes, sockets, or signals — while
real mode (``tools/multihost_train.py``) passes a launcher that spawns the
actual worker subprocesses and delivers an actual ``SIGKILL``.  The same
fake/real split ``tools/fleet_drill.py`` uses for the serving fleet.

A worker handle is anything with ``name``, ``poll() -> Optional[int]``
(None while running, exit code once dead; negative = killed by signal),
``kill()``, and ``wait(timeout_s) -> Optional[int]``.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence

from dist_svgd_tpu.telemetry import metrics as _metrics
from dist_svgd_tpu.telemetry import trace as _trace


class FederationDead(RuntimeError):
    """The federation cannot make progress: the restart budget is gone or
    fewer than ``min_processes`` workers survive.  ``report`` carries the
    supervisor's transition history for the post-mortem."""

    def __init__(self, msg: str, report: Optional[dict] = None):
        super().__init__(msg)
        self.report = report or {}


class FakeWorker:
    """Deterministic scripted worker for tier-1 federation tests.

    ``script`` is the sequence of ``poll()`` results the worker plays back
    (``None`` = still running, an int = exit code from then on); an
    exhausted script keeps returning its final entry, and an all-``None``
    script models a worker that runs until :meth:`kill`.  ``kill`` flips
    the handle to exit code ``-9`` (SIGKILL-shaped), as a real killed
    subprocess reports."""

    def __init__(self, name: str, script: Sequence[Optional[int]] = (None,)):
        self.name = str(name)
        self._script = list(script) or [None]
        self._i = 0
        self._forced: Optional[int] = None
        self.killed = False

    def poll(self) -> Optional[int]:
        if self._forced is not None:
            return self._forced
        i = min(self._i, len(self._script) - 1)
        self._i += 1
        rc = self._script[i]
        if rc is not None:
            self._forced = int(rc)
        return rc

    def kill(self) -> None:
        self.killed = True
        self._forced = -9

    def wait(self, timeout_s: float = 0.0) -> Optional[int]:
        return self.poll()


class SubprocessWorker:
    """Real-mode handle over a ``subprocess.Popen`` worker."""

    def __init__(self, name: str, popen):
        self.name = str(name)
        self._p = popen

    @property
    def pid(self) -> int:
        return self._p.pid

    def poll(self) -> Optional[int]:
        return self._p.poll()

    def kill(self) -> None:
        if self._p.poll() is None:
            self._p.kill()

    def wait(self, timeout_s: float = 30.0) -> Optional[int]:
        import subprocess

        try:
            return self._p.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            return None


class FederationSupervisor:
    """Launch → watch → (on worker loss) shrink-and-relaunch loop.

    ``launcher(process_count, attempt)`` starts one federation generation
    and returns its worker handles; generation 0 is the fresh start, later
    attempts are resumed restarts (the launcher passes that fact to its
    workers — typically a ``--resume`` flag pointing at the per-process
    checkpoint directory).  :meth:`run` returns a report dict once a
    generation exits cleanly (every worker rc 0), after recording each
    transition's process dimension in the ``svgd_elastic_*`` metrics and
    the flight recorder (the same channel the in-process elastic reshard
    uses, so fleet dashboards see one topology-transition stream).

    ``min_processes`` is the floor a shrink may reach; losing workers past
    it — or spending the restart budget — raises :class:`FederationDead`.
    Time is injectable (``clock``/``sleep``) so tier-1 drills never wait.
    """

    def __init__(
        self,
        launcher: Callable[[int, int], Sequence],
        *,
        processes: int,
        min_processes: int = 1,
        restart_budget: int = 2,
        poll_interval_s: float = 0.05,
        shutdown_grace_s: float = 30.0,
        registry=None,
        recorder=None,
        logger: Optional[Callable[..., None]] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if processes < 1:
            raise ValueError(f"processes must be >= 1, got {processes}")
        if not 1 <= min_processes <= processes:
            raise ValueError(
                f"min_processes must be in [1, {processes}], "
                f"got {min_processes}"
            )
        if restart_budget < 0:
            raise ValueError("restart_budget must be >= 0")
        self._launcher = launcher
        self.processes = int(processes)
        self.min_processes = int(min_processes)
        self.restart_budget = int(restart_budget)
        self._poll_interval_s = float(poll_interval_s)
        self._grace_s = float(shutdown_grace_s)
        self._logger = logger
        self._clock = clock
        self._sleep = sleep
        self._recorder = recorder
        reg = registry if registry is not None else _metrics.default_registry()
        self.registry = reg
        self._m_losses = reg.counter(
            "svgd_elastic_worker_losses_total",
            "federation worker processes lost (per transition, by reason)")
        self._m_restarts = reg.counter(
            "svgd_elastic_federation_restarts_total",
            "federation generations relaunched after a worker loss")
        self._g_processes = reg.gauge(
            "svgd_elastic_processes",
            "current process count of the supervised run's mesh "
            "(1 = single-host)")
        self._h_restart_wall = reg.histogram(
            "svgd_elastic_federation_restart_seconds",
            "wall from loss detection to the relaunched generation running")
        self.transitions: List[dict] = []
        #: Report of the most recent :meth:`run` call.
        self.report: Optional[dict] = None

    def _log(self, **record) -> None:
        if self._logger is not None:
            self._logger(**record)

    def _flight(self, kind: str, **fields) -> None:
        rec = (self._recorder if self._recorder is not None
               else _trace.flight_recorder())
        if rec is not None:
            rec.record(kind, **fields)

    def _drain(self, workers, grace_s: float) -> None:
        """Kill-and-reap every still-running worker of a torn generation —
        a federation with a hole deadlocks at its next collective, so
        survivors cannot be left to finish."""
        for w in workers:
            if w.poll() is None:
                w.kill()
        deadline = self._clock() + grace_s
        for w in workers:
            remaining = max(0.0, deadline - self._clock())
            w.wait(remaining)

    def run(self) -> dict:
        t0 = self._clock()
        width = self.processes
        attempt = 0
        restarts_spent = 0
        # (event, detect_clock) of a transition whose relaunch is in flight
        pending: Optional[tuple] = None
        while True:
            workers = list(self._launcher(width, attempt))
            if len(workers) != width:
                raise ValueError(
                    f"launcher({width}, {attempt}) returned "
                    f"{len(workers)} workers"
                )
            if pending is not None:
                event, clock0 = pending
                wall = self._clock() - clock0
                event["restart_wall_s"] = round(wall, 4)
                self._h_restart_wall.observe(wall)
                pending = None
            self._g_processes.set(width)
            self._log(event="federation_up", processes=width,
                      attempt=attempt)
            dead = self._watch(workers)
            if not dead:  # every worker exited 0: clean finish
                self.report = {
                    "status": "ok",
                    "processes": width,
                    "initial_processes": self.processes,
                    "restarts": restarts_spent,
                    "transitions": self.transitions,
                    "wall_s": self._clock() - t0,
                }
                return self.report
            t_detect = self._clock()
            lost = len(dead)
            losses = {w.name: w.poll() for w in dead}
            self._m_losses.inc(lost)
            self._drain(workers, self._grace_s)
            survivors = width - lost
            if survivors < self.min_processes:
                raise FederationDead(
                    f"{lost} worker(s) died ({losses}) leaving {survivors} "
                    f"< min_processes {self.min_processes}",
                    report={"transitions": self.transitions,
                            "losses": losses},
                )
            if restarts_spent >= self.restart_budget:
                raise FederationDead(
                    f"restart budget ({self.restart_budget}) exhausted "
                    f"after worker loss ({losses})",
                    report={"transitions": self.transitions,
                            "losses": losses},
                )
            restarts_spent += 1
            attempt += 1
            self._m_restarts.inc()
            event = {
                "from_processes": width,
                "to_processes": survivors,
                "lost": losses,
                "attempt": attempt,
                "restart_wall_s": None,  # closed below, once relaunched
            }
            self._flight("federation_transition",
                         from_processes=width, to_processes=survivors,
                         lost=sorted(losses), attempt=attempt)
            self._log(event="worker_loss", from_processes=width,
                      to_processes=survivors, lost=losses, attempt=attempt)
            width = survivors
            self.transitions.append(event)
            # loop: relaunch at the shrunk width as a resumed generation;
            # the restart wall closes once the launcher returns up top
            pending = (event, t_detect)

    def _watch(self, workers) -> list:
        """Poll until the generation resolves: returns the list of workers
        that died with a nonzero/killed status (empty = clean finish).  A
        worker exiting 0 early is fine — it simply finished its share."""
        while True:
            codes = [w.poll() for w in workers]
            dead = [w for w, rc in zip(workers, codes)
                    if rc is not None and rc != 0]
            if dead:
                return dead
            if all(rc == 0 for rc in codes):
                return []
            self._sleep(self._poll_interval_s)
