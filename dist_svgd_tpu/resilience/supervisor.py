"""Supervised, fault-tolerant sampler runs.

``RunSupervisor`` drives a :class:`~dist_svgd_tpu.sampler.Sampler` or
:class:`~dist_svgd_tpu.distsampler.DistSampler` in **bounded segments** on an
absolute step grid, adding the four recovery behaviours a multi-hour run
needs (ROADMAP: production service; the serving path already survives
overload — this makes the training path survive faults):

- **periodic + signal-triggered checkpointing** through the existing
  ``utils/checkpoint.py`` layouts (atomic step dirs, retention, corrupt-
  newest fallback on restore);
- **resume-from-latest** that is *bitwise-identical* to an uninterrupted
  run: segments land on an absolute grid (multiples of ``segment_steps``
  and the checkpoint cadence), so an interrupted run resumed from any
  boundary issues the exact same sequence of ``run``/``run_steps`` calls —
  same compiled programs, same inputs — as one that never stopped.  SVGD's
  deterministic fixed-point iteration (Liu & Wang 2016) plus the samplers'
  carried step counter / minibatch-stream offsets make this exact, and
  ``tests/test_resilience.py`` pins it for both sampler kinds;
- **retry with exponential backoff** around transient dispatch failures
  (bounded restart budget; rollback to the last good checkpoint before
  each retry, so a mid-segment failure can never leave half-advanced
  state);
- **numerical guards** (:mod:`~dist_svgd_tpu.resilience.guards`) with a
  rollback + step-size-backoff policy on NaN/Inf, norm explosion, or
  per-step divergence.

Time and signals are injectable (``clock``, ``sleep``, and the fault hooks
in :mod:`~dist_svgd_tpu.resilience.faults`) the same way the serving
batcher's are, so every recovery path runs deterministically in tier-1 on
CPU — no real sleeps, no real signals.  Production drivers call
:meth:`RunSupervisor.install_signal_handlers` to map real SIGTERM/SIGINT
onto the same checkpoint-at-boundary path the injected preemption uses.
"""

from __future__ import annotations

import signal as _signal
import time
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dist_svgd_tpu.resilience.backoff import capped_delay
from dist_svgd_tpu.resilience.faults import (
    FaultPlan,
    TopologyFault,
    TransientDispatchError,
)
from dist_svgd_tpu.resilience.guards import (
    GuardConfig,
    GuardViolation,
    check_diagnostics,
    check_state,
)
from dist_svgd_tpu.telemetry import diagnostics as _diagnostics
from dist_svgd_tpu.telemetry import metrics as _metrics
from dist_svgd_tpu.telemetry import trace as _trace
from dist_svgd_tpu.utils.checkpoint import (
    CheckpointManager,
    check_topology,
    read_manifest,
    reshard_state,
    topology_manifest,
)


class RestartBudgetExhausted(RuntimeError):
    """The bounded restart budget ran out.  ``last_error`` carries the
    final failure (a retryable exception or a :class:`GuardViolation`)."""

    def __init__(self, msg: str, last_error: Optional[BaseException] = None):
        super().__init__(msg)
        self.last_error = last_error


def _default_retryable() -> tuple:
    exc = [TransientDispatchError]
    try:  # transient device/dispatch failures surface as JaxRuntimeError
        from jax.errors import JaxRuntimeError

        exc.append(JaxRuntimeError)
    except ImportError:  # pragma: no cover - very old jax
        pass
    return tuple(exc)


class RetryPolicy:
    """Retry knobs for transient failures (and the shared restart budget
    the guard rollbacks draw from).

    ``backoff_base_s · backoff_factor^(k-1)`` seconds before the k-th
    *consecutive* retry, capped at ``max_backoff_s``; a successful segment
    resets the consecutive counter but not the total budget.  The schedule
    is :func:`resilience.backoff.capped_delay` — the one shared backoff
    implementation (the fleet router jitters the same schedule; the
    supervisor stays jitter-free so recovery tests pin exact delays)."""

    def __init__(
        self,
        max_restarts: int = 3,
        backoff_base_s: float = 1.0,
        backoff_factor: float = 2.0,
        max_backoff_s: float = 60.0,
        retryable: Optional[Sequence[type]] = None,
    ):
        if max_restarts < 0:
            raise ValueError("max_restarts must be >= 0")
        self.max_restarts = int(max_restarts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_factor = float(backoff_factor)
        self.max_backoff_s = float(max_backoff_s)
        self.retryable = (tuple(retryable) if retryable is not None
                          else _default_retryable())

    def delay_s(self, consecutive_failures: int) -> float:
        """Backoff before retry number ``consecutive_failures`` (1-based)."""
        return capped_delay(consecutive_failures, self.backoff_base_s,
                            self.backoff_factor, self.max_backoff_s)


class ReshardPolicy:
    """Elastic-capacity policy: how :class:`RunSupervisor` rebuilds the
    training topology when a :class:`~dist_svgd_tpu.resilience.faults.
    TopologyFault` fires (device loss, mesh shrink/grow).

    With a policy installed, a topology fault no longer kills the run: the
    supervisor spends one restart from the SAME budget the transient
    retries draw on, reshards the latest checkpoint onto the new shard
    count (``utils/checkpoint.py:reshard_state``), rebuilds the sampler
    through ``sampler_factory``, and continues on the identical absolute
    segment grid — steps since the last checkpoint are replayed, nothing
    else changes.

    Args:
        sampler_factory: ``factory(num_shards) -> DistSampler`` — a FRESH
            sampler at the requested topology, constructed exactly as the
            original was (same model/kernel/options/seed; its initial
            particles are immediately overwritten by the resharded
            checkpoint).  ``tools/elastic_drill.py`` shows the pattern.
        device_loss_strategy: how :class:`~dist_svgd_tpu.resilience.faults.
            DeviceLossAt` (which names no explicit target) picks the new
            shard count from the survivors: ``'largest_divisor'`` (default)
            takes the largest shard count ≤ survivors that divides the
            particle count — keeping every particle sharded; ``'surviving'``
            takes the raw survivor count, accepting the replicate-and-warn
            fallback when it doesn't divide n (``Plan.shard_ensemble``'s
            degradation, applied by ``reshard_state``).
    """

    def __init__(self, sampler_factory: Callable[[int], object],
                 device_loss_strategy: str = "largest_divisor"):
        if device_loss_strategy not in ("largest_divisor", "surviving"):
            raise ValueError(
                f"unknown device_loss_strategy {device_loss_strategy!r}"
            )
        self.sampler_factory = sampler_factory
        self.device_loss_strategy = device_loss_strategy

    def target_for_device_loss(self, surviving: int, n_particles: int) -> int:
        """Shard count to run on after a device loss left ``surviving``
        devices (≥ 1 always — the last device serves alone)."""
        surviving = max(1, int(surviving))
        if self.device_loss_strategy == "surviving":
            return surviving
        for s in range(min(surviving, max(int(n_particles), 1)), 0, -1):
            if n_particles % s == 0:
                return s
        return 1

    def build(self, num_shards: int):
        """Construct (and validate) the factory's sampler at the target."""
        sampler = self.sampler_factory(num_shards)
        if not hasattr(sampler, "run_steps"):
            raise TypeError(
                "ReshardPolicy.sampler_factory must build a DistSampler "
                f"(got {type(sampler).__name__}) — elastic resharding is a "
                "mesh concept; a single-device Sampler has no topology"
            )
        built = getattr(sampler, "_num_shards", None)
        if built != num_shards:
            raise ValueError(
                f"sampler_factory({num_shards}) built a sampler at "
                f"{built} shards — the factory must honour its argument"
            )
        return sampler


def _sampler_process_count(sampler) -> int:
    """Process count of a sampler's mesh (1 for meshless/single-host) —
    the process dimension the elastic metrics and flight records carry so
    a multi-host transition (kill-one-host → W−1 federation) is
    distinguishable from a same-host shard shrink in the telemetry."""
    mesh = getattr(sampler, "_mesh", None)
    if mesh is None:
        return 1
    try:
        return len({d.process_index for d in mesh.devices.flat})
    except Exception:  # pragma: no cover - exotic mesh-like stand-ins
        return 1


# --------------------------------------------------------------------- #
# Sampler harnesses: one segmented-drive surface over both sampler kinds


class _DistHarness:
    """Drives a ``DistSampler`` — resume state is the sampler's own
    ``state_dict`` (particles, W2 snapshots, carried duals, step counter)."""

    kind = "distsampler"

    def __init__(self, sampler, h: float):
        self._s = sampler
        self._h = h

    @property
    def t(self) -> int:
        return self._s._t

    @property
    def particles(self):
        return self._s.particles

    @property
    def num_shards(self) -> int:
        return self._s._num_shards

    @property
    def score_fn(self):
        """No per-θ global score closure: the DistSampler's score is
        sharded with its data — KSD diagnostics need an explicit
        ``DiagnosticsConfig.score_fn`` here."""
        return None

    def run_segment(self, k: int, step_size: float) -> None:
        s = self._s
        if s._include_wasserstein and s._wasserstein_solver != "sinkhorn":
            # the host-LP W2 path is make_step-only (run_steps docstring)
            for _ in range(k):
                s.make_step(step_size, h=self._h)
        else:
            s.run_steps(k, step_size, record=False, h=self._h)

    def state_dict(self) -> dict:
        return self._s.state_dict()

    def load_state_dict(self, state: dict) -> None:
        self._s.load_state_dict(state)

    def corrupt_particles(self) -> None:
        p = jnp.asarray(self._s._particles)
        self._s._particles = p.at[(0,) * p.ndim].set(jnp.nan)


class _SamplerHarness:
    """Drives a single-device ``Sampler`` as resumable segments: carried
    state is ``(particles, t)``; ``step_offset=t`` keeps the minibatch
    stream identical to one monolithic run, and a ``kernel='median'``
    bandwidth is frozen from the run-initial particles (and recorded in the
    resume state) so segments never re-resolve it."""

    kind = "sampler"

    def __init__(self, sampler, n: int, seed=0, initial_particles=None,
                 dtype=None):
        from dist_svgd_tpu.utils.rng import as_key, init_particles

        self._s = sampler
        self._n = int(n)
        self._seed = seed
        if initial_particles is not None:
            parts = jnp.asarray(initial_particles, dtype=dtype)
        else:
            parts = init_particles(as_key(seed), self._n, sampler._d,
                                   dtype=dtype or jnp.float32)
        self.particles = parts
        self.t = 0
        self._bandwidth = None
        if getattr(sampler, "_median_kernel", False):
            self._bandwidth = sampler.freeze_median_kernel(parts)

    num_shards = 1

    @property
    def score_fn(self):
        """The sampler's own full-data score closure ``θ ↦ ∇log p(θ)`` —
        exactly what the KSD diagnostic needs."""
        return self._s._score_fn

    def run_segment(self, k: int, step_size: float) -> None:
        final, _ = self._s.run(
            self._n, k, step_size, seed=self._seed, record=False,
            initial_particles=self.particles, step_offset=self.t,
        )
        self.particles = final
        self.t += k

    def state_dict(self) -> dict:
        state = {
            "particles": np.asarray(self.particles),
            "t": np.asarray(self.t, dtype=np.int64),
        }
        state.update(topology_manifest(1, self._n, self._s._d))
        if self._bandwidth is not None:
            state["kernel_bandwidth"] = np.asarray(self._bandwidth)
        return state

    def load_state_dict(self, state: dict) -> None:
        check_topology(state, {"n_particles": self._n, "d": self._s._d},
                       context="checkpoint")
        self.particles = jnp.asarray(state["particles"])
        self.t = int(state["t"])
        bw = state.get("kernel_bandwidth")
        if bw is not None:
            self._bandwidth = float(np.asarray(bw))
            self._s.pin_kernel_bandwidth(self._bandwidth)

    def corrupt_particles(self) -> None:
        self.particles = jnp.asarray(self.particles).at[0, 0].set(jnp.nan)


# --------------------------------------------------------------------- #


class RunSupervisor:
    """Fault-tolerant segmented driver for one training run.

    Args:
        sampler: a ``DistSampler`` (resume state via its ``state_dict``) or
            a ``Sampler`` (pass ``n``, and optionally ``seed`` /
            ``initial_particles`` / ``dtype`` — the run-construction
            arguments ``Sampler.run`` would take).
        num_steps: total steps of the supervised run (absolute; a resumed
            run continues to the same total).
        step_size: SVGD ε.  May be reduced in flight by the guard policy;
            the *current* value is recorded in every checkpoint
            (``sup_step_size``) and restored on resume.
        checkpoint_dir / manager / checkpoint_every: periodic checkpointing
            through ``utils/checkpoint.py`` — pass a ``CheckpointManager``,
            or a directory (a manager is built with cadence
            ``checkpoint_every``, default 100).  ``None`` disables
            checkpointing: rollback then targets the in-memory run-start
            snapshot and resume is unavailable.
        segment_steps: max steps per dispatch segment (default: the
            checkpoint cadence, or the whole run when unmanaged).  Segment
            boundaries land on **absolute multiples** — the resume-exactness
            invariant (module docstring) — and are where faults fire, stops
            are honoured, and guards run.
        h: Wasserstein weight forwarded to the distributed step (inert
            without the W2 term).
        guard: :class:`GuardConfig` enabling the numerical guards.
        retry: :class:`RetryPolicy` for transient failures (default: 3
            restarts, 1 s base, ×2 backoff).
        logger: ``utils/metrics.py:JsonlLogger`` — one structured record per
            segment / checkpoint / retry / guard trip / preemption.
        faults: a :class:`~dist_svgd_tpu.resilience.faults.FaultPlan`
            (tests and drills; ``None`` in production).
        clock / sleep: injectable time (``time.perf_counter`` /
            ``time.sleep``) so recovery paths test without real waits.
        slow_segment_warn_s: log a ``slow_segment`` warning record when a
            segment's wall exceeds this (the watchdog surface the
            ``SlowSegmentAt`` fault exercises).
        registry: ``telemetry.MetricsRegistry`` for the supervisor's
            restart/guard/checkpoint counters and the segment/checkpoint
            duration histograms (default: the process-wide registry).
            While the span tracer is enabled each segment and checkpoint
            additionally records a ``train.segment`` / ``train.checkpoint``
            span, with retries, guard trips, rollbacks, and preemptions as
            instant events — the training half of the serving path's
            request-span story.
        diagnostics: :class:`~dist_svgd_tpu.telemetry.diagnostics.
            PosteriorDiagnostics` — computed on the carried particle array
            at the first segment boundary at or past each
            ``every_steps`` multiple (plus the final boundary), with the
            single-device sampler's own score closure wired in for KSD
            when the config has none.  When the :class:`GuardConfig` sets
            drift/collapse thresholds (``max_ksd``, ``min_ess_frac``,
            ``min_dim_var``, ``max_shard_mean_div``) each report is judged
            by ``guards.check_diagnostics`` and a violation takes the
            SAME rollback + step-size-backoff path as the numerical
            guards.  ``None`` holds the shared no-op (zero cost).
        recorder: :class:`~dist_svgd_tpu.telemetry.trace.FlightRecorder`
            for postmortem bundles; default: whatever recorder is
            installed process-wide (``telemetry.install_flight_recorder``)
            at dump time.  A bundle is dumped when a guard trips, a
            non-retryable fault fires, or the restart budget exhausts.
        reshard: :class:`ReshardPolicy` enabling **elastic capacity**: a
            :class:`~dist_svgd_tpu.resilience.faults.TopologyFault`
            (device loss, mesh shrink/grow) is handled by resharding the
            latest checkpoint onto the new shard count and continuing —
            one restart spent from the shared budget, a ``train.reshard``
            span, ``svgd_elastic_*`` counters and a flight-recorder
            ``topology_transition`` record per transition.  ``None``
            (default) keeps topology faults non-recoverable.
    """

    def __init__(
        self,
        sampler,
        num_steps: int,
        step_size: float,
        *,
        checkpoint_dir: Optional[str] = None,
        manager: Optional[CheckpointManager] = None,
        checkpoint_every: int = 100,
        segment_steps: Optional[int] = None,
        h: float = 1.0,
        guard: Optional[GuardConfig] = None,
        retry: Optional[RetryPolicy] = None,
        logger=None,
        faults: Optional[FaultPlan] = None,
        clock: Callable[[], float] = time.perf_counter,
        sleep: Callable[[float], None] = time.sleep,
        slow_segment_warn_s: Optional[float] = None,
        registry: Optional[_metrics.MetricsRegistry] = None,
        diagnostics=None,
        recorder=None,
        reshard: Optional[ReshardPolicy] = None,
        n: Optional[int] = None,
        seed=0,
        initial_particles=None,
        dtype=None,
    ):
        if num_steps < 1:
            raise ValueError(f"num_steps must be >= 1, got {num_steps}")
        if manager is not None and checkpoint_dir is not None:
            raise ValueError("pass checkpoint_dir or manager, not both")
        if manager is None and checkpoint_dir is not None:
            # npz backend for the supervisor's own manager: a periodic
            # cadence pays the save cost every `every` steps, and an orbax
            # save costs a fixed ~0.25 s of manifest machinery vs ~1 ms for
            # an npz of sampler-sized state (save_state docstring) — the
            # < 5% overhead target at the default cadence needs the fast
            # layout.  Pass an explicit `manager` to choose otherwise.
            manager = CheckpointManager(checkpoint_dir, every=checkpoint_every,
                                        backend="npz")
        self._manager = manager
        if hasattr(sampler, "run_steps"):  # DistSampler
            self._harness = _DistHarness(sampler, h)
        else:
            if n is None:
                raise ValueError(
                    "supervising a single-device Sampler requires n (the "
                    "particle count Sampler.run would take)"
                )
            self._harness = _SamplerHarness(
                sampler, n, seed=seed, initial_particles=initial_particles,
                dtype=dtype,
            )
        self.sampler = sampler
        self.num_steps = int(num_steps)
        self.step_size = float(step_size)
        if segment_steps is not None and segment_steps < 1:
            raise ValueError(f"segment_steps must be >= 1, got {segment_steps}")
        self._segment_steps = segment_steps or (
            manager.every if manager is not None else self.num_steps
        )
        self._guard = guard
        self._retry = retry or RetryPolicy()
        self._logger = logger
        self._faults = faults
        self._clock = clock
        self._sleep = sleep
        self._slow_warn = slow_segment_warn_s
        self._stop_requested = False
        self._stop_reason: Optional[str] = None
        self._restarts = 0
        self._consecutive_failures = 0
        self._last_good: Optional[Tuple[int, dict]] = None
        self._ckpt_wall_s = 0.0
        self._seg_wall_s = 0.0
        self._max_seg_wall_s = 0.0
        self._n_checkpoints = 0
        self._n_segments = 0
        reg = registry if registry is not None else _metrics.default_registry()
        self.registry = reg
        self._m_restarts = reg.counter(
            "svgd_train_restarts_total",
            "restart budget spent, by kind (transient retry / guard trip)")
        self._m_guard_trips = reg.counter(
            "svgd_train_guard_trips_total",
            "numerical guard violations (NaN/Inf, explosion, divergence)")
        self._m_checkpoints = reg.counter(
            "svgd_train_checkpoints_total", "checkpoints written, by tag")
        self._m_ckpt_seconds = reg.histogram(
            "svgd_train_checkpoint_seconds", "wall per checkpoint save")
        self._m_seg_seconds = reg.histogram(
            "svgd_train_segment_seconds", "wall per training segment")
        self._m_steps = reg.counter(
            "svgd_train_steps_total", "SVGD steps completed under supervision")
        self._reshard = reshard
        self._m_reshards = reg.counter(
            "svgd_elastic_reshards_total",
            "elastic topology transitions, by direction (shrink/grow/same)")
        self._m_steps_lost = reg.counter(
            "svgd_elastic_steps_lost_total",
            "steps replayed because a topology transition resumed from the "
            "last checkpoint")
        self._g_shards = reg.gauge(
            "svgd_elastic_shards",
            "current shard count of the supervised run's mesh")
        self._g_shards.set(self._harness.num_shards)
        self._g_processes = reg.gauge(
            "svgd_elastic_processes",
            "current process count of the supervised run's mesh "
            "(1 = single-host)")
        self._g_processes.set(_sampler_process_count(sampler))
        self._reshard_events: list = []
        self._pending_recovery: Optional[dict] = None
        if diagnostics is not None and diagnostics.enabled:
            # a Sampler's own score closure feeds KSD unless the config
            # already names one (DistSampler harnesses contribute none)
            diagnostics.ensure_score_fn(self._harness.score_fn)
        self._diag = diagnostics if diagnostics is not None else _diagnostics.DISABLED
        self._diag_last_t = 0
        self._diag_run_report = None
        self._recorder = recorder
        #: Report of the most recent :meth:`run` call.
        self.report: Optional[dict] = None

    # ------------------------------------------------------------------ #
    # injection / signal surface (the faults' ``ctx``)

    @property
    def t(self) -> int:
        """Current absolute step counter."""
        return self._harness.t

    @property
    def num_shards(self) -> int:
        """Current mesh shard count (1 for a single-device Sampler) — the
        topology the faults' ``ctx`` sees and elastic resharding changes."""
        return self._harness.num_shards

    def request_stop(self, reason: str = "stop requested") -> None:
        """Preemption-shaped stop: honoured at the next segment boundary
        with a final checkpoint.  Signal-handler and fault-plan safe (only
        sets a flag)."""
        self._stop_requested = True
        self._stop_reason = reason

    def install_signal_handlers(self, signals=(getattr(_signal, "SIGTERM", None),
                                               getattr(_signal, "SIGINT", None))):
        """Map real SIGTERM/SIGINT onto :meth:`request_stop` — the
        production preemption path (main thread only, like any
        ``signal.signal`` call).  Returns the previous handlers."""
        previous = {}
        for sig in signals:
            if sig is None:
                continue
            previous[sig] = _signal.signal(
                sig, lambda signum, frame: self.request_stop(
                    f"signal {signum}")
            )
        return previous

    def corrupt_particles(self) -> None:
        """NaN-poison one entry of the carried state (fault-injection
        surface — the guards must catch it)."""
        self._harness.corrupt_particles()

    def advance_clock(self, seconds: float) -> None:
        """Make the in-flight segment appear ``seconds`` slower: advances a
        manual clock when one is injected (tests), else consumes the
        injectable ``sleep``."""
        adv = getattr(self._clock, "advance", None)
        if adv is not None:
            adv(seconds)
        else:  # pragma: no cover - production clocks aren't advanceable
            self._sleep(seconds)

    # ------------------------------------------------------------------ #

    def _log(self, **record) -> None:
        if self._logger is not None:
            self._logger.log(**record)

    def _next_boundary(self, t: int) -> int:
        """First absolute grid point past ``t``: multiples of
        ``segment_steps`` and of the checkpoint cadence, capped at
        ``num_steps``.  Resume re-enters the identical grid from any
        boundary — the bitwise-resume invariant."""
        nxt = min(self.num_steps,
                  (t // self._segment_steps + 1) * self._segment_steps)
        if self._manager is not None:
            e = self._manager.every
            nxt = min(nxt, (t // e + 1) * e)
        return max(nxt, t + 1)

    def _state_with_meta(self) -> dict:
        state = self._harness.state_dict()
        # the supervisor's own resume state: the (possibly backed-off)
        # step size must survive a preemption or the resumed trajectory
        # silently re-runs at the diverging ε
        state["sup_step_size"] = np.asarray(self.step_size, dtype=np.float64)
        return state

    def _apply_resume_state(self, state: dict) -> None:
        """Restore a checkpoint's supervisor-side state: the harness payload
        plus the (possibly backed-off) step size.  Subclasses that stamp
        extra metadata into :meth:`_state_with_meta` extend this — the two
        methods are one serialisation seam."""
        self._harness.load_state_dict(state)
        eps = state.get("sup_step_size")
        if eps is not None:
            self.step_size = float(np.asarray(eps))

    def _checkpoint(self, tag: str = "periodic") -> Optional[str]:
        if self._manager is None:
            return None
        t0 = self._clock()
        with _trace.span("train.checkpoint", {"tag": tag, "t": self._harness.t}):
            state = self._state_with_meta()
            path = self._manager.save(self._harness.t, state)
        wall = self._clock() - t0
        self._ckpt_wall_s += wall
        self._n_checkpoints += 1
        self._m_checkpoints.inc(tag=tag)
        self._m_ckpt_seconds.observe(wall)
        self._last_good = (self._harness.t, state)
        self._log(event="checkpoint", tag=tag, t=self._harness.t,
                  wall_s=round(wall, 4), path=path)
        return path

    def _rollback(self) -> None:
        """Restore the last good state (most recent checkpoint, else the
        run-start snapshot)."""
        t_bad = self._harness.t
        t_good, state = self._last_good
        self._harness.load_state_dict(state)
        # replayed boundaries must re-run diagnostics: a drift guard that
        # tripped here has to be re-judged on the replayed trajectory
        self._diag_last_t = min(self._diag_last_t, t_good)
        _trace.instant("train.rollback", {"from_t": t_bad, "to_t": t_good})
        self._log(event="rollback", from_t=t_bad, to_t=t_good)

    def _diag_due(self, t: int) -> bool:
        """Diagnostics cadence on the boundary grid: fire at the first
        boundary at or past each ``every_steps`` multiple (boundaries need
        not be multiples themselves), plus the final boundary."""
        if not self._diag.enabled:
            return False
        k = self._diag.config.every_steps
        return (t // k > self._diag_last_t // k) or t >= self.num_steps

    def _flight(self, kind: str, **fields) -> None:
        """Ring-buffer record into the effective flight recorder (explicit
        arg, else the process-wide one); no-op when neither exists."""
        rec = (self._recorder if self._recorder is not None
               else _trace.flight_recorder())
        if rec is not None:
            rec.record(kind, **fields)

    def _postmortem(self, reason: str, **context) -> Optional[str]:
        """Dump a flight-recorder bundle (explicit ``recorder`` arg, else
        the process-wide one); ``None`` when no recorder is installed.  A
        failing dump is swallowed — it must never mask the real failure."""
        rec = (self._recorder if self._recorder is not None
               else _trace.flight_recorder())
        if rec is None:
            return None
        try:
            path = rec.dump(reason, {
                "t": self._harness.t, "step_size": self.step_size,
                "restarts": self._restarts, "kind": self._harness.kind,
                **context,
            })
        except Exception:
            return None
        self._log(event="postmortem", reason=reason, path=path)
        return path

    def _spend_restart(self, err: BaseException) -> None:
        self._restarts += 1
        self._consecutive_failures += 1
        if self._restarts > self._retry.max_restarts:
            self._log(event="restart_budget_exhausted", t=self._harness.t,
                      restarts=self._restarts - 1,
                      error=f"{type(err).__name__}: {err}")
            self._flight("restart_budget_exhausted", t=self._harness.t,
                         error=f"{type(err).__name__}: {err}")
            self._postmortem("restart_budget_exhausted",
                             error=f"{type(err).__name__}: {err}")
            raise RestartBudgetExhausted(
                f"restart budget ({self._retry.max_restarts}) exhausted at "
                f"step {self._harness.t}: {type(err).__name__}: {err}",
                last_error=err,
            ) from err

    def _handle_transient(self, err: Exception) -> None:
        self._spend_restart(err)
        self._m_restarts.inc(kind="transient")
        delay = self._retry.delay_s(self._consecutive_failures)
        _trace.instant("train.retry", {"t": self._harness.t,
                                       "error": type(err).__name__,
                                       "attempt": self._consecutive_failures})
        self._log(event="retry", t=self._harness.t,
                  error=f"{type(err).__name__}: {err}",
                  attempt=self._consecutive_failures,
                  backoff_s=round(delay, 3))
        self._sleep(delay)
        self._rollback()

    def _handle_topology(self, err: TopologyFault) -> None:
        """Elastic reshard: rebuild the sampler at the fault's topology from
        the latest checkpoint and continue on the same absolute grid —
        inside the shared restart budget (:meth:`_spend_restart` raises
        :class:`RestartBudgetExhausted` when it is gone)."""
        self._spend_restart(err)
        self._m_restarts.inc(kind="topology")
        from_shards = self._harness.num_shards
        from_processes = _sampler_process_count(self.sampler)
        n_particles = int(self._harness.particles.shape[0])
        requested = err.target_shards
        if requested is None:
            surviving = (err.surviving if err.surviving is not None
                         else from_shards - err.lost_devices)
            requested = self._reshard.target_for_device_loss(
                surviving, n_particles)
        t_detected = self._harness.t
        clock0 = self._clock()
        with _trace.span("train.reshard",
                         {"t": t_detected, "from_shards": from_shards,
                          "requested_shards": requested}):
            if self._manager is not None:
                t_good, state = self._manager.restore_latest(with_step=True)
                if state is None:
                    t_good, state = self._last_good
            else:
                t_good, state = self._last_good
            new_state = reshard_state(state, requested)
            man = read_manifest(new_state)
            to_shards = man["n_shards"] if man is not None else requested
            sampler = self._reshard.build(to_shards)
            harness = _DistHarness(sampler, self._harness._h)
            harness.load_state_dict(new_state)
            eps = new_state.get("sup_step_size")
            if eps is not None:
                self.step_size = float(np.asarray(eps))
            self.sampler = sampler
            self._harness = harness
            self._last_good = (harness.t, new_state)
            # replayed boundaries re-run diagnostics, like a rollback
            self._diag_last_t = min(self._diag_last_t, harness.t)
        reshard_wall = self._clock() - clock0
        steps_lost = t_detected - harness.t
        to_processes = _sampler_process_count(sampler)
        direction = ("grow" if to_shards > from_shards
                     else "shrink" if to_shards < from_shards else "same")
        self._m_reshards.inc(direction=direction)
        self._m_steps_lost.inc(steps_lost)
        self._g_shards.set(to_shards)
        self._g_processes.set(to_processes)
        event = {
            "t_detected": t_detected,
            "resumed_from": harness.t,
            "from_shards": from_shards,
            "requested_shards": requested,
            "to_shards": to_shards,
            "from_processes": from_processes,
            "to_processes": to_processes,
            "steps_lost": steps_lost,
            "reshard_wall_s": round(reshard_wall, 4),
            # filled when the run regains the detection step (replay done)
            "recovery_wall_s": None,
            "_clock0": clock0,
        }
        if self._pending_recovery is not None:
            # a second transition landed before the first replay regained
            # its detection step: close the superseded window honestly
            # (recovery_wall_s stays None) instead of leaking its clock
            self._pending_recovery.pop("_clock0", None)
        self._reshard_events.append(event)
        self._pending_recovery = event
        self._flight("topology_transition", t=t_detected,
                     from_shards=from_shards, to_shards=to_shards,
                     from_processes=from_processes,
                     to_processes=to_processes,
                     steps_lost=steps_lost, reason=str(err))
        self._log(event="reshard", t=t_detected, resumed_from=harness.t,
                  from_shards=from_shards, to_shards=to_shards,
                  from_processes=from_processes, to_processes=to_processes,
                  steps_lost=steps_lost, reshard_wall_s=round(reshard_wall, 4),
                  error=f"{type(err).__name__}: {err}")
        self._sleep(self._retry.delay_s(self._consecutive_failures))

    def _handle_guard(self, err: GuardViolation) -> None:
        self._spend_restart(err)
        self._m_restarts.inc(kind="guard")
        self._m_guard_trips.inc()
        old_eps = self.step_size
        backoff = self._guard.backoff_factor if self._guard else 0.5
        self.step_size = old_eps * backoff
        _trace.instant("train.guard_violation",
                       {"t": self._harness.t, "reason": err.reason})
        self._log(event="guard_violation", t=self._harness.t,
                  reason=err.reason, **err.report,
                  step_size=old_eps, new_step_size=self.step_size)
        self._flight("guard_violation", t=self._harness.t, reason=err.reason)
        self._postmortem("guard_violation", guard_reason=err.reason)
        self._rollback()

    # ------------------------------------------------------------------ #

    def run(self, resume: bool = False) -> dict:
        """Drive the run to ``num_steps`` (or a requested stop).

        ``resume=True`` restores the newest *loadable* checkpoint under the
        manager first (corrupt/partial newest step dirs are skipped —
        ``CheckpointManager.restore_latest``) and continues the exact
        trajectory; with no restorable checkpoint it starts from scratch.
        ``resume=False`` clears the manager root (a previous run's step
        dirs would poison retention and later resumes — the covertype
        driver's fresh-run hygiene).

        Returns a report dict (also kept as :attr:`report`):
        ``status`` (``'completed'`` | ``'preempted'``), ``t``,
        ``steps_run``, ``restarts``, ``checkpoints``, wall-clock totals and
        the checkpoint-overhead fraction.  Raises
        :class:`RestartBudgetExhausted` when recovery gives out; an
        exception outside the retryable set (e.g. a simulated hard kill)
        propagates unhandled — by design, that is the no-cleanup crash the
        next ``run(resume=True)`` recovers from."""
        wall0 = self._clock()
        # per-run state: a preempted supervisor is commonly re-run
        # (run(resume=True)) — totals must not accumulate across runs, and
        # restarts spent in an earlier run must not deplete this run's
        # retry budget
        self._restarts = 0
        self._consecutive_failures = 0
        self._ckpt_wall_s = 0.0
        self._seg_wall_s = 0.0
        self._max_seg_wall_s = 0.0
        self._n_checkpoints = 0
        self._n_segments = 0
        self._reshard_events = []
        self._pending_recovery = None
        # clear the stop flag BEFORE the (potentially long) resume-restore:
        # a real SIGTERM landing while a large checkpoint loads must be
        # honoured at the first boundary, not silently discarded
        self._stop_requested = False
        self._stop_reason = None
        resumed_from = None
        if resume and self._manager is not None:
            state = self._manager.restore_latest()
            if state is not None:
                self._apply_resume_state(state)
                resumed_from = self._harness.t
                self._log(event="resume", t=resumed_from,
                          step_size=self.step_size)
        elif self._manager is not None:
            self._manager.clear()
        start_t = self._harness.t
        self._diag_last_t = start_t
        # only a report computed during THIS run may land in its report
        # dict: the diagnostics instance is shareable (the fault drill
        # reuses one across phases) and a run preempted before its first
        # cadence boundary must not inherit another run's numbers
        self._diag_run_report = None
        self._last_good = (start_t, self._state_with_meta())
        if self._manager is not None and resumed_from is None:
            # a step-`start` baseline: retry/guard rollback and a very
            # early preemption always have an on-disk target
            self._checkpoint(tag="initial")

        status = "completed"
        while self._harness.t < self.num_steps:
            if self._stop_requested:
                status = "preempted"
                break
            t0 = self._harness.t
            k = self._next_boundary(t0) - t0
            prev = (self._harness.particles
                    if self._guard is not None and self._guard.needs_prev
                    else None)
            seg0 = self._clock()
            try:
                if self._faults is not None:
                    # inside the timed try block deliberately: a RaiseAt is
                    # a failed dispatch of THIS segment (retry path), a
                    # SlowSegmentAt lands in this segment's wall, a
                    # PreemptAt is honoured before the segment runs
                    self._faults.fire_due(self)
                if self._stop_requested:
                    continue  # loop top checkpoints and reports preempted
                with _trace.span("train.segment",
                                 {"t0": t0, "steps": k,
                                  "kind": self._harness.kind}):
                    self._harness.run_segment(k, self.step_size)
                    # fence inside the try (and the span): async dispatch
                    # failures must surface here (as retryable
                    # JaxRuntimeError), not at a random later host sync —
                    # and the segment wall must be honest
                    jax.block_until_ready(self._harness.particles)
            except self._retry.retryable as e:
                self._handle_transient(e)
                continue
            except TopologyFault as e:
                if self._reshard is None or self._harness.kind != "distsampler":
                    # no elastic policy (or a single-device run, which has
                    # no topology to reshard): non-recoverable, like any
                    # fault outside the retry set — black box, propagate
                    self._flight("fault", t=self._harness.t,
                                 error=f"{type(e).__name__}: {e}")
                    self._postmortem("fault",
                                     error=f"{type(e).__name__}: {e}")
                    raise
                self._handle_topology(e)
                continue
            except Exception as e:
                # non-retryable fault (a simulated hard kill, a crash
                # outside the retry set): dump the black box, then
                # propagate unhandled — by design this is the no-cleanup
                # crash the next run(resume=True) recovers from
                self._flight("fault", t=self._harness.t,
                             error=f"{type(e).__name__}: {e}")
                self._postmortem("fault",
                                 error=f"{type(e).__name__}: {e}")
                raise
            seg_wall = self._clock() - seg0
            self._seg_wall_s += seg_wall
            self._max_seg_wall_s = max(self._max_seg_wall_s, seg_wall)
            self._n_segments += 1
            # the histogram mirrors _n_segments (a guard-tripped segment
            # still burned this wall); the steps counter must NOT mirror it
            # — rolled-back steps are not progress, so it increments only
            # after the guard admits the segment (below)
            self._m_seg_seconds.observe(seg_wall)
            if self._slow_warn is not None and seg_wall > self._slow_warn:
                self._log(event="slow_segment", t=self._harness.t,
                          wall_s=round(seg_wall, 4),
                          threshold_s=self._slow_warn)
            if self._guard is not None:
                try:
                    check_state(self._harness.particles, prev=prev,
                                steps=k, config=self._guard)
                except GuardViolation as e:
                    self._handle_guard(e)
                    continue
            t_now = self._harness.t
            if self._diag_due(t_now):
                d_report = self._diag.compute(
                    self._harness.particles,
                    num_shards=self._harness.num_shards, step=t_now)
                self._diag_last_t = t_now
                self._diag_run_report = d_report
                if (d_report is not None and self._guard is not None
                        and self._guard.checks_diagnostics):
                    try:
                        check_diagnostics(d_report, self._guard)
                    except GuardViolation as e:
                        self._handle_guard(e)
                        continue
            self._consecutive_failures = 0
            self._m_steps.inc(k)
            if (self._pending_recovery is not None
                    and self._harness.t >= self._pending_recovery["t_detected"]):
                # the replay regained the step the topology fault landed on:
                # close the recovery window (reshard + backoff + replay)
                ev = self._pending_recovery
                ev["recovery_wall_s"] = round(
                    self._clock() - ev.pop("_clock0"), 4)
                self._pending_recovery = None
            self._log(event="segment", t=self._harness.t, steps=k,
                      wall_s=round(seg_wall, 4), step_size=self.step_size)
            if self._manager is not None and (
                    self._harness.t % self._manager.every == 0
                    or self._harness.t >= self.num_steps):
                self._checkpoint()

        if status == "preempted":
            # signal-triggered checkpoint: the whole point of catching the
            # preemption notice is saving right now, not at the cadence
            self._checkpoint(tag="preempt")
            _trace.instant("train.preempt", {"t": self._harness.t,
                                             "reason": self._stop_reason})
            self._log(event="preempted", t=self._harness.t,
                      reason=self._stop_reason)

        if self._pending_recovery is not None:
            # run ended (preempt/complete) before the replay regained the
            # detection step: recovery_wall_s honestly stays None
            self._pending_recovery.pop("_clock0", None)
            self._pending_recovery = None
        wall = self._clock() - wall0
        self.report = {
            "status": status,
            "t": self._harness.t,
            "steps_run": self._harness.t - start_t,
            "resumed_from": resumed_from,
            "num_shards": self._harness.num_shards,
            "reshards": len(self._reshard_events),
            "reshard_events": list(self._reshard_events),
            "restarts": self._restarts,
            "checkpoints": self._n_checkpoints,
            "segments": self._n_segments,
            "step_size": self.step_size,
            "stop_reason": self._stop_reason,
            "wall_s": round(wall, 4),
            "segment_wall_s": round(self._seg_wall_s, 4),
            "max_segment_wall_s": round(self._max_seg_wall_s, 4),
            "checkpoint_wall_s": round(self._ckpt_wall_s, 4),
            "checkpoint_overhead_frac": round(
                self._ckpt_wall_s / self._seg_wall_s, 4
            ) if self._seg_wall_s > 0 else 0.0,
            "last_diagnostics": self._diag_run_report,
        }
        self._log(event=status, **{k: v for k, v in self.report.items()
                                   if k != "status"})
        return self.report

    @property
    def particles(self):
        """The supervised run's current global particle array."""
        return self._harness.particles
