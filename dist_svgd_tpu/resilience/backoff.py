"""One capped-exponential-backoff implementation, shared by every retrier.

The codebase grew two places that wait-and-retry — the training
:class:`~dist_svgd_tpu.resilience.supervisor.RunSupervisor` (transient
dispatch failures) and the serving
:class:`~dist_svgd_tpu.serving.fleet.FleetRouter` (replica failover) — and
a third copy was one PR away.  This module is the single source of truth
for the delay schedule:

- **capped exponential**: ``base_s · factor^(k-1)`` before the k-th
  *consecutive* failure, capped at ``max_s`` (:func:`capped_delay` — the
  pure function, exactly the schedule the supervisor has always used);
- **jitter**: :class:`Backoff` multiplies each delay by a uniform factor
  in ``[1 − jitter_frac, 1 + jitter_frac]`` so N clients backing off from
  the same overload event don't reconverge into synchronized retry waves
  (the classic thundering-herd fix).  ``jitter_frac=0`` disables it — the
  supervisor's deterministic recovery tests rely on exact delays — and the
  RNG is injectable so jittered paths stay reproducible in tests.

Sleeping is the *caller's* job (the supervisor's clock is injectable, the
router clamps delays to the request deadline); this module only computes
durations.
"""

from __future__ import annotations

import random
from typing import Optional

__all__ = ["capped_delay", "Backoff"]


def capped_delay(attempt: int, base_s: float, factor: float,
                 max_s: float) -> float:
    """Delay before retry number ``attempt`` (1-based; values < 1 clamp to
    1): ``base_s · factor^(attempt-1)``, capped at ``max_s``."""
    d = base_s * factor ** max(attempt - 1, 0)
    return min(d, max_s)


class Backoff:
    """Capped exponential backoff with optional multiplicative jitter.

    Args:
        base_s: delay before the first retry.
        factor: growth per consecutive failure.
        max_s: hard cap on any single delay (applied after jitter too —
            the cap is a promise, not an average).
        jitter_frac: half-width of the uniform jitter band; ``0`` yields
            the exact :func:`capped_delay` schedule.
        rng: ``random.Random`` (or anything with ``.random()``) for the
            jitter draw — inject a seeded one for deterministic tests.
    """

    def __init__(self, base_s: float = 1.0, factor: float = 2.0,
                 max_s: float = 60.0, jitter_frac: float = 0.0,
                 rng: Optional[random.Random] = None):
        if base_s < 0:
            raise ValueError(f"base_s must be >= 0, got {base_s}")
        if factor < 1.0:
            raise ValueError(f"factor must be >= 1, got {factor}")
        if max_s < base_s:
            raise ValueError(
                f"max_s ({max_s}) must be >= base_s ({base_s})")
        if not 0.0 <= jitter_frac < 1.0:
            raise ValueError(
                f"jitter_frac must be in [0, 1), got {jitter_frac}")
        self.base_s = float(base_s)
        self.factor = float(factor)
        self.max_s = float(max_s)
        self.jitter_frac = float(jitter_frac)
        self._rng = rng if rng is not None else random.Random()

    def delay_s(self, attempt: int) -> float:
        """Jittered delay before retry number ``attempt`` (1-based)."""
        d = capped_delay(attempt, self.base_s, self.factor, self.max_s)
        if self.jitter_frac:
            d *= 1.0 + self.jitter_frac * (2.0 * self._rng.random() - 1.0)
        return min(d, self.max_s)

    def __repr__(self):
        return (f"Backoff(base_s={self.base_s}, factor={self.factor}, "
                f"max_s={self.max_s}, jitter_frac={self.jitter_frac})")
