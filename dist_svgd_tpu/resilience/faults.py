"""Deterministic fault injection for supervised runs.

A multi-hour training run meets faults the test suite cannot wait for —
preemptions, transient dispatch failures, NaN blowups, pool slowdowns.  This
module makes every one of them a **scheduled, deterministic event** so each
recovery path in :mod:`~dist_svgd_tpu.resilience.supervisor` runs in tier-1
on CPU with no real signals, sleeps, or flaky hardware:

- faults are keyed by **absolute step index** and fire at the first segment
  boundary whose step counter reaches it (the same quantisation a real
  SIGTERM gets: the supervisor finishes the in-flight dispatch first, then
  acts).  Run with ``segment_steps=1`` to pin a fault to an exact step.
- each fault fires **once** — a retried/rolled-back segment replays clean,
  which is exactly how a transient fault behaves.

The injection surface is the supervisor itself (the ``ctx`` argument):
``ctx.t``, ``ctx.corrupt_particles()``, ``ctx.request_stop()``,
``ctx.advance_clock()`` — the same hooks a signal handler or a watchdog
would use, so injected faults and real ones share one recovery code path.
"""

from __future__ import annotations

from typing import Optional, Sequence


class TransientDispatchError(RuntimeError):
    """Stand-in for a transient device/dispatch failure (the retryable kind:
    a pool hiccup, a severed tunnel, a watchdog kill).  The supervisor's
    default retry policy catches it alongside ``jax.errors.JaxRuntimeError``."""


class SimulatedHardKill(RuntimeError):
    """Stand-in for SIGKILL / power loss: deliberately **not** in the default
    retryable set, so it unwinds straight through the supervisor without a
    checkpoint — the process is simply gone.  Recovery is a fresh
    ``RunSupervisor(...).run(resume=True)``, which is what
    ``tools/fault_drill.py`` measures."""


class TopologyFault(RuntimeError):
    """The mesh topology changed under the run — a device dropped out of the
    pool (the most common real TPU failure) or the scheduler resized the
    slice.  Deliberately outside the default retryable set: replaying the
    same segment on the same (now wrong-sized) sampler cannot help.  A
    supervisor with a :class:`~dist_svgd_tpu.resilience.supervisor.
    ReshardPolicy` catches it and reshards the latest checkpoint onto the
    new topology inside the restart budget; without one it propagates like
    any non-recoverable fault.

    Carries either an explicit ``target_shards`` (mesh shrink/grow notice)
    or the ``surviving`` device count (device loss — the policy picks the
    shard count)."""

    def __init__(self, msg: str, *, target_shards: Optional[int] = None,
                 surviving: Optional[int] = None, lost_devices: int = 0):
        super().__init__(msg)
        self.target_shards = target_shards
        self.surviving = surviving
        self.lost_devices = int(lost_devices)


class Fault:
    """One scheduled fault.  Fires once, at the first segment boundary with
    step counter ``>= step``."""

    def __init__(self, step: int):
        self.step = int(step)
        self.fired = False

    def fire(self, ctx) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}(step={self.step}, fired={self.fired})"


class RaiseAt(Fault):
    """Raise a transient dispatch failure — exercises retry + exponential
    backoff + rollback-to-last-checkpoint."""

    def __init__(self, step: int, exc: Optional[Exception] = None):
        super().__init__(step)
        self.exc = exc

    def fire(self, ctx) -> None:
        raise self.exc if self.exc is not None else TransientDispatchError(
            f"injected transient dispatch failure at step {ctx.t}"
        )


class InjectNaNAt(Fault):
    """Overwrite one entry of the carried particle state with NaN — the
    minimal numerical blowup the guards must detect and roll back."""

    def fire(self, ctx) -> None:
        ctx.corrupt_particles()


class PreemptAt(Fault):
    """Simulated preemption notice (SIGTERM-shaped): requests a stop, which
    the supervisor honours at the boundary with a final checkpoint and a
    ``'preempted'`` report — resume-exact by construction."""

    def fire(self, ctx) -> None:
        ctx.request_stop(f"injected preemption at step {ctx.t}")


class HardKillAt(Fault):
    """Simulated SIGKILL: raises :class:`SimulatedHardKill`, which the
    supervisor does NOT catch — no checkpoint, no cleanup, state as of the
    last periodic save.  The fault-drill's kill-mid-run event."""

    def fire(self, ctx) -> None:
        raise SimulatedHardKill(f"injected hard kill at step {ctx.t}")


class DeviceLossAt(Fault):
    """Simulated loss of ``lost`` mesh device(s): raises
    :class:`TopologyFault` with the surviving device count, exactly as a
    real pool-shrink surfaces (the in-flight dispatch dies, the next
    attempt sees fewer devices).  The supervisor's :class:`ReshardPolicy`
    picks the new shard count from the survivors."""

    def __init__(self, step: int, lost: int = 1):
        super().__init__(step)
        if lost < 1:
            raise ValueError(f"lost must be >= 1, got {lost}")
        self.lost = int(lost)

    def fire(self, ctx) -> None:
        surviving = max(0, ctx.num_shards - self.lost)
        raise TopologyFault(
            f"injected loss of {self.lost} device(s) at step {ctx.t} "
            f"({ctx.num_shards} -> {surviving} surviving)",
            surviving=surviving, lost_devices=self.lost,
        )


class MeshShrinkAt(Fault):
    """Scheduler-shaped capacity notice: the mesh must shrink to
    ``to_shards`` (an explicit target, unlike :class:`DeviceLossAt`'s
    policy-chosen one)."""

    def __init__(self, step: int, to_shards: int):
        super().__init__(step)
        if to_shards < 1:
            raise ValueError(f"to_shards must be >= 1, got {to_shards}")
        self.to_shards = int(to_shards)

    def fire(self, ctx) -> None:
        raise TopologyFault(
            f"injected mesh shrink to {self.to_shards} shards at step "
            f"{ctx.t} (from {ctx.num_shards})",
            target_shards=self.to_shards,
        )


class MeshGrowAt(Fault):
    """Capacity-returned notice: the mesh may grow to ``to_shards`` — the
    recovery direction after a loss, same reshard path as the shrink."""

    def __init__(self, step: int, to_shards: int):
        super().__init__(step)
        if to_shards < 1:
            raise ValueError(f"to_shards must be >= 1, got {to_shards}")
        self.to_shards = int(to_shards)

    def fire(self, ctx) -> None:
        raise TopologyFault(
            f"injected mesh grow to {self.to_shards} shards at step "
            f"{ctx.t} (from {ctx.num_shards})",
            target_shards=self.to_shards,
        )


class WorkerLossAt(Fault):
    """Loss of whole federation worker process(es) — host SIGKILL, node
    death — on a ``processes``-way multi-host run: every shard of the lost
    process's DCN granule leaves the mesh at once, not one device.  Raises
    :class:`TopologyFault` with the surviving shard count under the equal
    granule layout (``make_particle_mesh``'s contract), so the supervisor's
    :class:`~dist_svgd_tpu.resilience.supervisor.ReshardPolicy` resumes the
    run at the W−1 federation's shard count on the same absolute step grid.
    The kill-one-host leg of ``tools/multihost_train.py`` fires this in
    fake mode; real mode delivers an actual SIGKILL instead."""

    def __init__(self, step: int, processes: int, lost: int = 1):
        super().__init__(step)
        if processes < 2:
            raise ValueError(f"processes must be >= 2, got {processes}")
        if not 1 <= lost < processes:
            raise ValueError(
                f"lost must be in [1, {processes - 1}], got {lost}"
            )
        self.processes = int(processes)
        self.lost = int(lost)

    def fire(self, ctx) -> None:
        S = ctx.num_shards
        if S % self.processes:
            raise ValueError(
                f"WorkerLossAt(processes={self.processes}) on a {S}-shard "
                "mesh: the granule layout must be equal per process"
            )
        per_granule = S // self.processes
        surviving_p = self.processes - self.lost
        raise TopologyFault(
            f"injected loss of {self.lost} worker process(es) at step "
            f"{ctx.t} ({self.processes} -> {surviving_p} processes, "
            f"{S} -> {per_granule * surviving_p} shards)",
            surviving=per_granule * surviving_p,
            lost_devices=per_granule * self.lost,
        )


class SlowSegmentAt(Fault):
    """Artificial slow dispatch: advances the supervisor's (injectable)
    clock by ``seconds`` so the next segment wall measures slow — exercises
    the ``slow_segment_warn_s`` watchdog without real waiting."""

    def __init__(self, step: int, seconds: float):
        super().__init__(step)
        self.seconds = float(seconds)

    def fire(self, ctx) -> None:
        ctx.advance_clock(self.seconds)


# --------------------------------------------------------------------- #
# fleet faults (round 15): process-level failures of a serving replica,
# consumed by serving/fleet.py's injectable FakeTransport rather than the
# supervisor — the unit of failure is a whole replica process, and the
# schedule is keyed by the transport's request ordinal (every probe or
# forward through the fake increments it) so failover tests are
# deterministic without real sockets, signals, or sleeps.


class FleetFault:
    """One scheduled replica-level fault window: active for transport
    request ordinals in ``[at, until)`` (``until=None`` → forever, i.e.
    until a runtime override like ``FakeTransport.restore`` lifts it).
    Unlike the training faults above these do not "fire once" — a dead
    process stays dead for every request in the window."""

    kind = "abstract"

    def __init__(self, at: int, replica: str, until: Optional[int] = None):
        if at < 0:
            raise ValueError(f"at must be >= 0, got {at}")
        if until is not None and until <= at:
            raise ValueError(f"until ({until}) must be > at ({at})")
        self.at = int(at)
        self.replica = str(replica)
        self.until = None if until is None else int(until)

    def active(self, ordinal: int) -> bool:
        return self.at <= ordinal and (self.until is None
                                       or ordinal < self.until)

    def __repr__(self):
        return (f"{type(self).__name__}(at={self.at}, "
                f"replica={self.replica!r}, until={self.until})")


class ReplicaKillAt(FleetFault):
    """The replica process is gone (SIGKILL / OOM / node loss): every
    connection from the router is refused — probes and forwards alike.
    ``until=`` models the restart (the process comes back and the router
    must re-admit it through the half-open circuit)."""

    kind = "kill"


class ReplicaHangAt(FleetFault):
    """The replica process accepts connections but never responds (a
    wedged GIL, a stuck device call): the router's request times out after
    its per-try budget.  The fake transport charges the full timeout to
    the injected clock so hang cost is measured, not waited for."""

    kind = "hang"


class PartitionAt(FleetFault):
    """Network partition: the replica is **alive and healthy** — it keeps
    serving anyone who can reach it, its own flight recorder records
    nothing — but the router cannot reach it.  Must trip the same ejection
    path as a crash (from the router's seat they are indistinguishable)
    without any replica-side effect; ``until=`` heals the partition."""

    kind = "partition"


class SlowReplicaAt(FleetFault):
    """Degraded replica: every response is delayed by ``seconds`` (GC
    storms, a noisy neighbor).  The tail-hedging path exists for exactly
    this shape — the request completes, just slowly."""

    kind = "slow"

    def __init__(self, at: int, replica: str, seconds: float,
                 until: Optional[int] = None):
        super().__init__(at, replica, until=until)
        if seconds < 0:
            raise ValueError(f"seconds must be >= 0, got {seconds}")
        self.seconds = float(seconds)


# --------------------------------------------------------------------- #
# stream faults (round 20): deterministic distribution shift injected
# into a streaming data source, consumed by streaming/source.py rather
# than the supervisor — the unit of failure is the DATA, and the
# schedule is keyed by the source's batch ordinal (like FleetFault's
# request ordinal) so every drift-detection/retrain path runs tier-1 on
# CPU with no real drift to wait for.


class DriftAt:
    """One scheduled distribution-shift window: batches with source
    ordinal in ``[step, until)`` (``until=None`` → forever) are transformed
    by a pure, deterministic ``apply`` — so a replayed stream reproduces
    the drift bitwise (the kill→resume invariant extends through the
    fault).  Kinds:

    - ``'mean_shift'``: add ``magnitude`` to every feature column — the
      covariate-shift shape KSD sees as a posterior/data mismatch.
    - ``'label_flip'``: negate the ±1 labels of a deterministic
      ``magnitude`` fraction of each batch's rows (strided, not sampled —
      no RNG, so replay needs no extra state).
    """

    KINDS = ("mean_shift", "label_flip")

    def __init__(self, step: int, kind: str = "mean_shift",
                 magnitude: float = 1.0, until: Optional[int] = None):
        if step < 0:
            raise ValueError(f"step must be >= 0, got {step}")
        if kind not in self.KINDS:
            raise ValueError(f"unknown drift kind {kind!r} "
                             f"(one of {self.KINDS})")
        if until is not None and until <= step:
            raise ValueError(f"until ({until}) must be > step ({step})")
        if kind == "label_flip" and not 0.0 <= magnitude <= 1.0:
            raise ValueError(
                f"label_flip magnitude is a flip fraction in [0, 1], "
                f"got {magnitude}"
            )
        self.step = int(step)
        self.kind = kind
        self.magnitude = float(magnitude)
        self.until = None if until is None else int(until)

    def active(self, ordinal: int) -> bool:
        return self.step <= ordinal and (self.until is None
                                         or ordinal < self.until)

    def apply(self, x, y):
        """Transform one ``(x, y)`` batch (numpy arrays; pure — never
        mutates its inputs)."""
        import numpy as np

        if self.kind == "mean_shift":
            return x + np.asarray(self.magnitude, dtype=x.dtype), y
        # label_flip: deterministic strided rows — round(frac * n) rows,
        # evenly spread, replay-stable with zero extra state
        n = y.shape[0]
        k = int(round(self.magnitude * n))
        if k <= 0:
            return x, y
        idx = np.linspace(0, n - 1, num=k).round().astype(int)
        out = np.array(y)
        out[idx] = -out[idx]
        return x, out

    def __repr__(self):
        return (f"DriftAt(step={self.step}, kind={self.kind!r}, "
                f"magnitude={self.magnitude}, until={self.until})")


class BadGenerationAt:
    """One scheduled bad candidate generation: rollout offers with
    publish ordinal in ``[step, until)`` (``until=None`` → forever) carry
    particles transformed by a pure, deterministic ``apply`` into
    prediction garbage — so the progressive-delivery rollback path runs
    tier-1 on CPU with no real bad training run to wait for (and a
    replayed publish schedule reproduces the bad candidate bitwise).
    Consumed at the offer seam — the rollout driver (a drill, a test, or
    a supervisor shim) transforms the candidate ensemble before
    ``RolloutController.offer``; the controller itself never knows the
    candidate is synthetic, which is the point: detection must come from
    the live divergence/burn windows.  Kinds:

    - ``'saturate'``: scale every parameter by ``magnitude`` (default
      1e6) — predictions saturate/overflow, the divergence histogram's
      overflow bucket fills, the shadow stage breaches immediately.
    - ``'scramble'``: deterministically reverse the parameter axis and
      negate — finite, plausible-looking particles whose *predictions*
      disagree with the incumbent (the subtle shape: passes any
      all-finite check, only the divergence window catches it).
    """

    KINDS = ("saturate", "scramble")

    def __init__(self, step: int, kind: str = "saturate",
                 magnitude: float = 1e6, until: Optional[int] = None):
        if step < 0:
            raise ValueError(f"step must be >= 0, got {step}")
        if kind not in self.KINDS:
            raise ValueError(f"unknown bad-generation kind {kind!r} "
                             f"(one of {self.KINDS})")
        if until is not None and until <= step:
            raise ValueError(f"until ({until}) must be > step ({step})")
        if kind == "saturate" and magnitude <= 1.0:
            raise ValueError(
                f"saturate magnitude must be > 1, got {magnitude}")
        self.step = int(step)
        self.kind = kind
        self.magnitude = float(magnitude)
        self.until = None if until is None else int(until)

    def active(self, ordinal: int) -> bool:
        return self.step <= ordinal and (self.until is None
                                         or ordinal < self.until)

    def apply(self, particles):
        """Transform one ``(n, d)`` candidate ensemble (numpy array;
        pure — never mutates its input)."""
        import numpy as np

        particles = np.asarray(particles)
        if self.kind == "saturate":
            return particles * np.asarray(self.magnitude,
                                          dtype=particles.dtype)
        # scramble: reverse the parameter axis and negate — deterministic,
        # finite, and prediction-breaking for any non-symmetric model
        return -particles[:, ::-1].copy()

    def __repr__(self):
        return (f"BadGenerationAt(step={self.step}, kind={self.kind!r}, "
                f"magnitude={self.magnitude}, until={self.until})")


class FaultPlan:
    """An ordered schedule of faults, consumed by the supervisor at every
    segment boundary.  ``fire_due`` fires every not-yet-fired fault whose
    step has been reached, in step order; a raising fault leaves later ones
    pending for the retried boundary (each still fires exactly once)."""

    def __init__(self, *faults: Fault):
        if len(faults) == 1 and isinstance(faults[0], (list, tuple)):
            faults = tuple(faults[0])
        self.faults: Sequence[Fault] = sorted(faults, key=lambda f: f.step)

    def fire_due(self, ctx) -> None:
        for f in self.faults:
            if not f.fired and f.step <= ctx.t:
                f.fired = True  # before fire(): a raising fault is spent
                f.fire(ctx)

    @property
    def exhausted(self) -> bool:
        return all(f.fired for f in self.faults)

    def __repr__(self):
        return f"FaultPlan({list(self.faults)!r})"
