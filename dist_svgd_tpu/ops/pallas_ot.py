"""Pallas TPU kernels for the Sinkhorn W2 solve — the flash-attention
argument applied to entropic OT.

Round-3 decomposition (docs/notes.md): with warm-started duals the W2
solve's scaling iterations cost ~0.1 ms each — ~95% of the solve is the
*fixed* passes, each of which materialises or re-reads an ``(n/S, n)``
float32 matrix in HBM (50 MB per shard at the north star):

- the cost-matrix build (``squared_distances``),
- the two soft-c-transform ``logsumexp`` passes over it,
- the absorbed-kernel rebuild per block,
- the final plan build plus two plan-sized reads for the gradient.

At d ≤ :data:`~dist_svgd_tpu.ops.pallas_svgd.SMALL_D` the cost entries are
recomputable from O(n·d) data for a handful of VPU ops, so — exactly like
the φ kernel (ops/pallas_svgd.py) — these passes can stream (bk, bm) cost
tiles through VMEM and never materialise the matrix:

- :func:`ctransform_reduce` — one fused pass producing a row-wise
  ``min_j (C_ij − p_j)`` (hard c-transform) or a running-max-rescaled
  ``logsumexp_j ((p_j − C_ij)/reg)`` (soft c-transform; the flash-softmax
  accumulator) from the particle coordinates directly;
- :func:`kexp` — the absorbed kernel ``exp((f_i + g_j − C_ij)/reg)``
  materialised for the matvec block (the one matrix worth keeping: the
  scaling iterations reuse it ~``absorb_every`` times);
- :func:`plan_grad` — a fused one-pass gradient ``grad_i = x_i·Σ_j P_ij −
  Σ_j P_ij·prev_j`` with the plan recomputed tile-by-tile (the same
  rowsum + per-dim-contraction accumulator pattern as the φ kernel's
  repulsive + drive terms).  Kept as a standalone utility: the production
  finish instead reuses the last block's materialised ``(kmat, u, v)``
  (``plan = diag(u)·kmat·diag(v)`` exactly), where the gradient is two
  cheap matvecs and costs no exp pass at all.

``mean(C)`` (for the relative ``eps``) needs no pass at all:
``mean‖x_i − y_j‖² = mean‖x‖² + mean‖y‖² − 2·mean(x)·mean(y)``.

:func:`sinkhorn_grad_fused` assembles the full W2 gradient with the same
algorithm as ``ops/ot.py`` (absorption-stabilised scaling, uniform
``absorb_every`` blocks, the same ``tol`` exit statistic and u/v clamps) —
same math, different memory movement; pinned against the XLA path by
``tests/test_pallas_ot.py``.

Small-d (d ≤ SMALL_D), float32 only; callers fall back to the XLA path
elsewhere (``ops/ot.py:wasserstein_grad_sinkhorn(impl=...)``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from dist_svgd_tpu.ops.pallas_svgd import (
    SMALL_D,
    _D2_CAP,
    _FAR,
    _VMEM,
    _auto_block,
    _pad_to,
    _round_up,
    pltpu,
)

#: Default tile sizes — the φ kernel's small-d autotune result (1024² —
#: docs/notes.md) applies to the accumulator kernels (ctransform_reduce,
#: plan_grad, kmat_vec), whose outputs are lane-dense (1, bk)/(SMALL_D, bk)
#: row slivers and whose VMEM residents beyond the (bk, bm) distance
#: temporaries are the (bk, 128) accumulators plus the small transposed
#: row caches (``_row_tile``).  ``kexp`` writes full (bk, bm) tiles (4 MB
#: at 1024², double-buffered) and needs a smaller k tile to fit scoped
#: VMEM alongside its distance temporaries.
_BLOCK_K = 1024
_BLOCK_M = 1024
_KEXP_BLOCK_K = 512


def _blocks(k, m, default_k, default_m):
    """Per-axis tiles with the φ kernel's ≤~10% padding rule (a 1250-row
    shard axis pads 64% at 1024 tiles but 2.4% at 256 — _auto_block)."""
    bk = min(_auto_block(k, default_k), _round_up(k, 8))
    bm = min(_auto_block(m, default_m), _round_up(m, 8))
    return bk, bm

#: Finite stand-in for −inf in the running-max accumulator (f32 min is
#: ~−3.4e38; exp(x − m) with both finite never NaNs, unlike −inf − −inf).
_NEG_HUGE = -3.0e38


def _col(rowvec):
    """(1, bk) → (bk, 1) in-kernel relayout.

    The row-side operands of every kernel here (coordinates, potentials,
    outputs) are stored **transposed and lane-dense** — ``(d, kp)`` /
    ``(1, kp)`` instead of ``(kp, 128)`` — because TPU tiles every 2-D f32
    array to (8, 128): a ``(kp, small)`` array physically occupies
    ``kp × 128`` floats (42.7× waste at d=3), which at streaming sizes is
    gigabytes per operand (measured: the 1M-particle W2 step OOMed HBM on
    three 3.8 GB lane-padded row operands).  The lane↔sublane relayout is
    NOT free (a naive per-tile transpose measured ~15–25% per pass), so
    the kernels hoist it: :func:`_row_tile` caches the transposed row
    block in VMEM scratch once per outer grid index and the inner column
    sweep reads the cache.
    """
    return jnp.transpose(rowvec, (1, 0))


def _row_tile(j, yT_ref, yc_ref, d_true: int):
    """Cache the transposed row-coordinate block in scratch at the start of
    each row tile's column sweep (``j == 0``; the grid iterates columns
    innermost, so the row block is invariant until the next outer step).
    Returns the ``(bk, ·)`` column view the distance broadcasts use."""
    @pl.when(j == 0)
    def _():
        yc_ref[:, :d_true] = jnp.transpose(yT_ref[:d_true, :], (1, 0))

    return yc_ref


def _d2_tile(j, yT_ref, xT, yc_ref, d_true):
    """(bk, bm) squared distances via per-dim VPU broadcasts, clamped so
    sentinel-padded columns stay finite (ops/pallas_svgd.py conventions).
    Coordinate operands arrive transposed (``(SMALL_D, bk)`` /
    ``(SMALL_D, bm)`` — see :func:`_col`); the row block's relayout is
    served from the ``yc_ref`` scratch cache (:func:`_row_tile`)."""
    yc = _row_tile(j, yT_ref, yc_ref, d_true)
    d2 = None
    for c in range(d_true):  # static unroll
        diff = yc[:, c:c + 1] - xT[c:c + 1, :]
        d2 = diff * diff if d2 is None else d2 + diff * diff
    return jnp.minimum(d2, _D2_CAP)


def _ct_kernel(yT_ref, xT_ref, p_ref, o_ref, m_ref, s_ref, yc_ref, *,
               inv_reg: float, d_true: int, nm: int, soft: bool):
    """One (i, j) grid step of :func:`ctransform_reduce`.

    soft=True: running-max-rescaled sum of ``exp((p_j − C_ij)·inv_reg −
    m_run)`` (flash-softmax); the output tile is ``m_run + log(s_run)``.
    soft=False: running ``min_j (C_ij − p_j)``.
    Padded columns carry the :data:`_FAR` sentinel ⇒ C ≈ 1e30 ⇒ they are
    exp-zero / never-min without any mask.
    """
    j = pl.program_id(1)
    d2 = _d2_tile(j, yT_ref, xT_ref[:], yc_ref, d_true)
    p = p_ref[:]  # (1, bm) column potentials

    if soft:
        e = (p - d2) * inv_reg  # (bk, bm)

        @pl.when(j == 0)
        def _():
            m_ref[:] = jnp.full_like(m_ref, _NEG_HUGE)
            s_ref[:] = jnp.zeros_like(s_ref)

        m_run = m_ref[:, :1]
        tile_max = jnp.max(e, axis=1, keepdims=True)
        m_new = jnp.maximum(m_run, tile_max)
        scale = jnp.exp(m_run - m_new)
        s_ref[:] = s_ref[:] * scale + jnp.sum(
            jnp.exp(e - m_new), axis=1, keepdims=True
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

        @pl.when(j == nm - 1)
        def _():
            o_ref[:] = _col(m_ref[:, :1] + jnp.log(s_ref[:, :1]))
    else:
        e = d2 - p  # (bk, bm)

        @pl.when(j == 0)
        def _():
            m_ref[:] = jnp.full_like(m_ref, jnp.asarray(3.0e38, m_ref.dtype))

        m_ref[:] = jnp.minimum(
            m_ref[:], jnp.min(e, axis=1, keepdims=True)
        )

        @pl.when(j == nm - 1)
        def _():
            o_ref[:] = _col(m_ref[:, :1])


@functools.partial(
    jax.jit, static_argnames=("inv_reg", "soft", "interpret"),
)
def ctransform_reduce(rows, cols, col_pot, inv_reg: float, soft: bool,
                      interpret: bool = False):
    """Row-wise c-transform reduction without materialising C.

    Args:
        rows: ``(k, d)`` points indexing the output rows.
        cols: ``(m, d)`` points indexed by the reduction.
        col_pot: ``(m,)`` column potentials ``p``.
        inv_reg: ``1/reg`` (static; ignored for ``soft=False``).
        soft: logsumexp (True) or hard min (False) — docstring above.

    Returns ``(k,)``: ``LSE_j((p_j − C_ij)·inv_reg)`` or ``min_j (C_ij −
    p_j)``.  All row-side operands and the output travel transposed and
    lane-dense (:func:`_col`): HBM cost is O(k·d), not O(k·128).
    """
    k, d = rows.shape
    m = cols.shape[0]
    assert d <= SMALL_D, d
    f32 = jnp.float32
    bk, bm = _blocks(k, m, _BLOCK_K, _BLOCK_M)
    kp, mp = _round_up(k, bk), _round_up(m, bm)
    nk, nm = kp // bk, mp // bm

    yT = _pad_to(rows.T.astype(f32), SMALL_D, kp)
    xT = _pad_to(cols.T.astype(f32), SMALL_D, mp, value=_FAR)
    p = _pad_to(col_pot.astype(f32)[None, :], 1, mp)

    vmem = {} if _VMEM is None else {"memory_space": _VMEM}
    scratch = (
        [pltpu.VMEM((bk, 128), f32), pltpu.VMEM((bk, 128), f32),
         pltpu.VMEM((bk, SMALL_D), f32)]
        if pltpu is not None
        else [jax.ShapeDtypeStruct((bk, 128), f32),
              jax.ShapeDtypeStruct((bk, 128), f32),
              jax.ShapeDtypeStruct((bk, SMALL_D), f32)]
    )
    out = pl.pallas_call(
        functools.partial(_ct_kernel, inv_reg=float(inv_reg), d_true=d,
                          nm=nm, soft=soft),
        out_shape=jax.ShapeDtypeStruct((1, kp), f32),
        grid=(nk, nm),
        in_specs=[
            pl.BlockSpec((SMALL_D, bk), lambda i, j: (0, i), **vmem),
            pl.BlockSpec((SMALL_D, bm), lambda i, j: (0, j), **vmem),
            pl.BlockSpec((1, bm), lambda i, j: (0, j), **vmem),
        ],
        out_specs=pl.BlockSpec((1, bk), lambda i, j: (0, i), **vmem),
        scratch_shapes=scratch,
        interpret=interpret,
    )(yT, xT, p)
    return out[0, :k]


def _kexp_kernel(yT_ref, xT_ref, f_ref, g_ref, o_ref, yc_ref, fc_ref, *,
                 inv_reg: float, d_true: int):
    j = pl.program_id(1)
    d2 = _d2_tile(j, yT_ref, xT_ref[:], yc_ref, d_true)

    @pl.when(j == 0)
    def _():
        fc_ref[:, :1] = _col(f_ref[:])

    e = (fc_ref[:, :1] + g_ref[:] - d2) * inv_reg
    o_ref[:] = jnp.exp(e)


@functools.partial(jax.jit, static_argnames=("inv_reg", "interpret"))
def kexp(rows, cols, f, g, inv_reg: float, interpret: bool = False):
    """Absorbed kernel ``exp((f_i + g_j − C_ij)·inv_reg)`` as a ``(k, m)``
    matrix, with C recomputed tile-by-tile (one write, no C read).  Padded
    columns are exp-zero via the distance sentinel; padded rows are sliced
    off."""
    k, d = rows.shape
    m = cols.shape[0]
    assert d <= SMALL_D, d
    f32 = jnp.float32
    bk, bm = _blocks(k, m, _KEXP_BLOCK_K, _BLOCK_M)
    kp, mp = _round_up(k, bk), _round_up(m, bm)

    yT = _pad_to(rows.T.astype(f32), SMALL_D, kp)
    xT = _pad_to(cols.T.astype(f32), SMALL_D, mp, value=_FAR)
    fp = _pad_to(f.astype(f32)[None, :], 1, kp)
    gp = _pad_to(g.astype(f32)[None, :], 1, mp)

    vmem = {} if _VMEM is None else {"memory_space": _VMEM}
    scratch = (
        [pltpu.VMEM((bk, SMALL_D), f32), pltpu.VMEM((bk, 1), f32)]
        if pltpu is not None
        else [jax.ShapeDtypeStruct((bk, SMALL_D), f32),
              jax.ShapeDtypeStruct((bk, 1), f32)]
    )
    out = pl.pallas_call(
        functools.partial(_kexp_kernel, inv_reg=float(inv_reg), d_true=d),
        out_shape=jax.ShapeDtypeStruct((kp, mp), f32),
        grid=(kp // bk, mp // bm),
        in_specs=[
            pl.BlockSpec((SMALL_D, bk), lambda i, j: (0, i), **vmem),
            pl.BlockSpec((SMALL_D, bm), lambda i, j: (0, j), **vmem),
            pl.BlockSpec((1, bk), lambda i, j: (0, i), **vmem),
            pl.BlockSpec((1, bm), lambda i, j: (0, j), **vmem),
        ],
        out_specs=pl.BlockSpec((bk, bm), lambda i, j: (i, j), **vmem),
        scratch_shapes=scratch,
        interpret=interpret,
    )(yT, xT, fp, gp)
    return out[:k, :m]


def _plan_grad_kernel(yT_ref, xT_ref, f_ref, g_ref, o_ref, acc_ref, ksum_ref,
                      yc_ref, fc_ref, *, inv_reg: float, d_true: int,
                      nm: int):
    """φ-kernel-style accumulation: per tile, plan entries ``P = exp((f + g
    − C)·inv_reg)`` feed a row-sum accumulator and d per-dim contractions
    ``Σ_j P_ij·prevᵀ_cj``; the epilogue emits ``y·rowsum − acc``
    (transposed, matching the lane-dense output layout)."""
    j = pl.program_id(1)
    xT = xT_ref[:]
    d2 = _d2_tile(j, yT_ref, xT, yc_ref, d_true)

    @pl.when(j == 0)
    def _():
        fc_ref[:, :1] = _col(f_ref[:])
        acc_ref[:] = jnp.zeros_like(acc_ref)
        ksum_ref[:] = jnp.zeros_like(ksum_ref)

    p = jnp.exp((fc_ref[:, :1] + g_ref[:] - d2) * inv_reg)  # (bk, bm)

    cols = [
        jnp.sum(p * xT[c:c + 1, :], axis=1, keepdims=True)
        for c in range(d_true)
    ]
    pad = acc_ref.shape[1] - d_true
    acc_ref[:] = acc_ref[:] + jnp.concatenate(
        cols + [jnp.zeros((p.shape[0], pad), jnp.float32)], axis=1
    )
    ksum_ref[:] = ksum_ref[:] + jnp.sum(p, axis=1, keepdims=True)

    @pl.when(j == nm - 1)
    def _():
        # (SMALL_D, bk) output tile: yT·rowsumᵀ − accᵀ (once per row tile)
        ksum_row = _col(ksum_ref[:, :1])                    # (1, bk)
        accT = jnp.transpose(acc_ref[:, :o_ref.shape[0]], (1, 0))
        o_ref[:] = yT_ref[:] * ksum_row - accT


@functools.partial(jax.jit, static_argnames=("inv_reg", "interpret"))
def plan_grad(rows, cols, f, g, inv_reg: float, interpret: bool = False):
    """Fused W2 gradient ``grad_i = rows_i·Σ_j P_ij − Σ_j P_ij·cols_j`` with
    the plan ``P = exp((f_i + g_j − C_ij)·inv_reg)`` recomputed per tile —
    the plan never exists in HBM.  Row-side operands travel transposed and
    lane-dense (:func:`_col`)."""
    k, d = rows.shape
    m = cols.shape[0]
    assert d <= SMALL_D, d
    f32 = jnp.float32
    bk, bm = _blocks(k, m, _BLOCK_K, _BLOCK_M)
    kp, mp = _round_up(k, bk), _round_up(m, bm)
    nm = mp // bm

    yT = _pad_to(rows.T.astype(f32), SMALL_D, kp)
    # padded columns contribute nothing because P underflows to an EXACT
    # zero there (the clamped sentinel distance gives exp(−1e30·inv_reg)
    # == 0.0 for any inv_reg ≳ 1e-28), and 0.0 · _FAR == 0.0 — the
    # sentinel coordinate never reaches the accumulators
    xT = _pad_to(cols.T.astype(f32), SMALL_D, mp, value=_FAR)
    fp = _pad_to(f.astype(f32)[None, :], 1, kp)
    gp = _pad_to(g.astype(f32)[None, :], 1, mp)

    vmem = {} if _VMEM is None else {"memory_space": _VMEM}
    scratch = (
        [pltpu.VMEM((bk, 128), f32), pltpu.VMEM((bk, 128), f32),
         pltpu.VMEM((bk, SMALL_D), f32), pltpu.VMEM((bk, 1), f32)]
        if pltpu is not None
        else [jax.ShapeDtypeStruct((bk, 128), f32),
              jax.ShapeDtypeStruct((bk, 128), f32),
              jax.ShapeDtypeStruct((bk, SMALL_D), f32),
              jax.ShapeDtypeStruct((bk, 1), f32)]
    )
    out = pl.pallas_call(
        functools.partial(_plan_grad_kernel, inv_reg=float(inv_reg),
                          d_true=d, nm=nm),
        out_shape=jax.ShapeDtypeStruct((SMALL_D, kp), f32),
        grid=(kp // bk, nm),
        in_specs=[
            pl.BlockSpec((SMALL_D, bk), lambda i, j: (0, i), **vmem),
            pl.BlockSpec((SMALL_D, bm), lambda i, j: (0, j), **vmem),
            pl.BlockSpec((1, bk), lambda i, j: (0, i), **vmem),
            pl.BlockSpec((1, bm), lambda i, j: (0, j), **vmem),
        ],
        out_specs=pl.BlockSpec((SMALL_D, bk), lambda i, j: (0, i), **vmem),
        scratch_shapes=scratch,
        interpret=interpret,
    )(yT, xT, fp, gp)
    return out[:d, :k].T


def _solve_setup(particles, previous, eps, g_init, interpret):
    """Shared preamble of the fused and streaming solves: f32 cast, the
    closed-form distance mean (module docstring), the reg-rescaling to
    inv_reg == 1 kernels, and the cold/warm dual start (the soft
    c-transform pair of the carried g — ops/ot.py:_sinkhorn_start's
    contract, in rescaled units).  One copy so the warm-start safety
    semantics cannot drift between the two Pallas paths.

    The returned ``delta0`` (warm starts only; ``None`` cold) is the exit
    statistic of the start itself: the soft c-transform pair is one exact
    log-domain Sinkhorn iteration from the carried ``g``, so
    ``max|g⁰ − g_init|`` (rescaled units — the same log-scaling units the
    scaling loop's per-iteration exit measures) IS that iteration's
    sup-change.  A ``tol`` consumer can therefore skip the scaling loop
    outright when ``delta0 ≤ tol`` — the start pair already satisfies the
    exit the loop would be polling for."""
    x = jnp.asarray(particles, jnp.float32)
    y = jnp.asarray(previous, jnp.float32)
    m, d = x.shape
    n = y.shape[0]
    dt = jnp.float32
    tiny = jnp.finfo(dt).tiny

    # mean(C) without a C pass: E||x-y||^2 = E||x||^2 + E||y||^2 - 2*Ex.Ey
    mean_c = (jnp.mean(jnp.sum(x * x, axis=1))
              + jnp.mean(jnp.sum(y * y, axis=1))
              - 2.0 * jnp.dot(jnp.mean(x, axis=0), jnp.mean(y, axis=0)))
    mean_c = jnp.maximum(mean_c, tiny)
    reg = eps * mean_c
    a = jnp.asarray(1.0 / m, dt)
    b = jnp.asarray(1.0 / n, dt)

    # The Pallas kernels take inv_reg as a STATIC float, but reg is traced
    # (it depends on the particle positions).  Rescale instead: with
    # C' = C/reg, potentials in units of reg (f' = f/reg), every kernel
    # runs at inv_reg == 1:  exp((f+g-C)/reg) == exp(f'+g'-C'), and
    # C'(x', y') for x' = x/sqrt(reg) is exactly ||x'-y'||^2.  The same
    # rescaling identity the adaptive-bandwidth phi path uses
    # (ops/pallas_svgd.py:resolve_phi_fn).
    sr = jnp.sqrt(reg)
    xs_, ys_ = x / sr, y / sr

    def ct(rows, cols, pot, soft):
        return ctransform_reduce(rows, cols, pot, 1.0, soft,
                                 interpret=interpret)

    if g_init is None:
        f0 = ct(xs_, ys_, jnp.zeros((n,), dt), soft=False)   # min_j C'_ij
        g0 = ct(ys_, xs_, f0, soft=False)                    # c-transform
        delta0 = None
    else:
        # warm start: the soft c-transform pair of the carried g
        # (ops/ot.py:_sinkhorn_start — both passes kept; the column-side
        # tightening is the safety pin for arbitrary g_init)
        gi = jnp.asarray(g_init, dt) / reg
        f0 = jnp.log(a) - ct(xs_, ys_, gi, soft=True)
        g0 = jnp.log(b) - ct(ys_, xs_, f0, soft=True)
        delta0 = jnp.max(jnp.abs(g0 - gi))  # the start's own exit statistic
    return xs_, ys_, f0, g0, delta0, reg, sr, a, b, m, n, dt, tiny


def sinkhorn_grad_fused(particles, previous, eps: float = 0.05,
                        iters: int = 200, tol=None, absorb_every: int = 10,
                        g_init=None, return_g: bool = False,
                        duals_only: bool = False,
                        interpret: bool = False):
    """W2 gradient via the fused kernels — same algorithm and exit
    semantics as ``ops/ot.py:sinkhorn_plan`` + ``wasserstein_grad_sinkhorn``
    (absorption-stabilised scaling, uniform ``absorb_every`` blocks, the
    per-iteration ``log v`` sup-change exit, identical u/v clamps), with
    the fixed passes fused:

    - ``reg`` from the closed-form distance mean (module docstring);
    - cold start: two hard-c-transform reductions; warm start
      (``g_init``): two soft (logsumexp) reductions — both via
      :func:`ctransform_reduce`, no C matrix;
    - per block, the absorbed kernel comes from :func:`kexp` (one write;
      the scaling loop itself is the SAME code as the XLA path —
      ``ops/ot.py:_sinkhorn_scaling_loop`` with this kernel builder);
    - the final gradient is the matvec finish against the last block's
      ``(kmat, u, v)`` — no exp pass, and the plan is never materialised.

    Returns ``grad`` or ``(grad, g)`` like the XLA path; ``duals_only=True``
    skips the gradient finish and returns just ``g`` (cost units) — the
    resumable-solve chunk behind ``ops/ot.py:sinkhorn_dual_advance``.
    Numerically equal to the XLA path up to f32 reduction-order roundoff
    (pinned by tests/test_pallas_ot.py).
    """
    if absorb_every <= 0:
        raise ValueError(f"absorb_every must be positive, got {absorb_every}")
    (xs_, ys_, f0, g0, _, reg, sr, a, b,
     m, n, dt, tiny) = _solve_setup(particles, previous, eps, g_init,
                                    interpret)

    # ONE copy of the absorbed-scaling loop, shared with the XLA path
    # (ops/ot.py:_sinkhorn_scaling_loop): only the kernel builder differs
    # (fused VMEM-streaming kexp vs dense exp over a materialised cost),
    # plus the reg-rescaled units (fold_scale 1.0).
    from dist_svgd_tpu.ops.ot import _sinkhorn_scaling_loop

    def make_ops(f, g):
        kmat = kexp(xs_, ys_, f, g, 1.0, interpret=interpret)
        return (lambda v: kmat @ v), (lambda u: kmat.T @ u), kmat

    f, g, kmat, u, v = _sinkhorn_scaling_loop(
        f0, g0, make_ops, 1.0, m, n, iters, tol, absorb_every, dt,
    )
    if duals_only:
        return (g * reg).astype(particles.dtype)

    # Gradient from the last block's (kmat, u, v) — the plan is
    # diag(u)·kmat·diag(v) entrywise, so rowsum and P@y' are two cheap
    # matvecs against the materialised kernel; no further exp pass
    # (ops/ot.py:wasserstein_grad_sinkhorn, same finish; HIGHEST on both —
    # they feed the gradient directly).  In rescaled coordinates the
    # result is grad/√reg (P is scale-free), so the true gradient is √reg
    # times it; the carried dual converts back to cost units as g·reg.
    row = u * jnp.matmul(
        kmat, v[:, None], precision=jax.lax.Precision.HIGHEST
    )[:, 0]
    py = u[:, None] * jnp.matmul(
        kmat, v[:, None] * ys_, precision=jax.lax.Precision.HIGHEST
    )
    grad = (xs_ * row[:, None] - py) * sr
    if return_g:
        return grad.astype(particles.dtype), (g * reg).astype(particles.dtype)
    return grad.astype(particles.dtype)


def _kmat_vec_kernel(yT_ref, xT_ref, f_ref, g_ref, rT_ref, o_ref, acc_ref,
                     yc_ref, fc_ref, *, inv_reg: float, d_true: int,
                     r_true: int, nm: int):
    """Accumulate ``Σ_j P_ij · R_jc`` per output tile without materialising
    P: the absorbed-kernel tile is rebuilt from coordinates (the
    :func:`_d2_tile` broadcast) and contracted against the RHS columns as
    per-column VPU reductions — :func:`_plan_grad_kernel`'s pattern with an
    arbitrary (small, static) RHS instead of the coordinates."""
    j = pl.program_id(1)
    d2 = _d2_tile(j, yT_ref, xT_ref[:], yc_ref, d_true)

    @pl.when(j == 0)
    def _():
        fc_ref[:, :1] = _col(f_ref[:])
        acc_ref[:] = jnp.zeros_like(acc_ref)

    p = jnp.exp((fc_ref[:, :1] + g_ref[:] - d2) * inv_reg)  # (bk, bm)

    cols = [
        jnp.sum(p * rT_ref[c:c + 1, :], axis=1, keepdims=True)
        for c in range(r_true)
    ]
    pad = acc_ref.shape[1] - r_true
    acc_ref[:] = acc_ref[:] + jnp.concatenate(
        cols + [jnp.zeros((p.shape[0], pad), jnp.float32)], axis=1
    )

    @pl.when(j == nm - 1)
    def _():
        o_ref[:] = jnp.transpose(acc_ref[:, :o_ref.shape[0]], (1, 0))


@functools.partial(jax.jit, static_argnames=("inv_reg", "interpret"))
def kmat_vec(rows, cols, f, g, rhs, inv_reg: float, interpret: bool = False):
    """Streaming absorbed-kernel mat-vec/mat-mat: ``out = P @ rhs`` with
    ``P_ij = exp((f_i + g_j − C_ij)·inv_reg)`` rebuilt tile-by-tile — O(n·d)
    memory, no ``(k, m)`` matrix ever exists.  ``rhs`` is ``(m,)`` or
    ``(m, r)`` with small static ``r`` (≤ :data:`SMALL_D`).  The transpose
    product ``Pᵀ u`` is the same kernel with the roles (and potentials)
    swapped: ``kmat_vec(cols, rows, g, f, u, inv_reg)``.  Row-side
    operands and the output travel transposed and lane-dense
    (:func:`_col`): at k = 1M rows, O(k) HBM instead of the 512 MB-per-
    operand lane padding that OOMed the 1M-particle W2 step."""
    squeeze = rhs.ndim == 1
    if squeeze:
        rhs = rhs[:, None]
    k, d = rows.shape
    m, r = rhs.shape
    assert d <= SMALL_D and r <= SMALL_D, (d, r)
    f32 = jnp.float32
    bk, bm = _blocks(k, m, _BLOCK_K, _BLOCK_M)
    kp, mp = _round_up(k, bk), _round_up(m, bm)
    nm = mp // bm

    yT = _pad_to(rows.T.astype(f32), SMALL_D, kp)
    # padded columns: P underflows to an exact 0.0 there (clamped sentinel
    # distance), so the rhs pad value never reaches the accumulators
    xT = _pad_to(cols.T.astype(f32), SMALL_D, mp, value=_FAR)
    fp = _pad_to(f.astype(f32)[None, :], 1, kp)
    gp = _pad_to(g.astype(f32)[None, :], 1, mp)
    rT = _pad_to(rhs.T.astype(f32), SMALL_D, mp)

    vmem = {} if _VMEM is None else {"memory_space": _VMEM}
    scratch = (
        [pltpu.VMEM((bk, 128), f32), pltpu.VMEM((bk, SMALL_D), f32),
         pltpu.VMEM((bk, 1), f32)]
        if pltpu is not None
        else [jax.ShapeDtypeStruct((bk, 128), f32),
              jax.ShapeDtypeStruct((bk, SMALL_D), f32),
              jax.ShapeDtypeStruct((bk, 1), f32)]
    )
    out = pl.pallas_call(
        functools.partial(_kmat_vec_kernel, inv_reg=float(inv_reg),
                          d_true=d, r_true=r, nm=nm),
        out_shape=jax.ShapeDtypeStruct((SMALL_D, kp), f32),
        grid=(kp // bk, nm),
        in_specs=[
            pl.BlockSpec((SMALL_D, bk), lambda i, j: (0, i), **vmem),
            pl.BlockSpec((SMALL_D, bm), lambda i, j: (0, j), **vmem),
            pl.BlockSpec((1, bk), lambda i, j: (0, i), **vmem),
            pl.BlockSpec((1, bm), lambda i, j: (0, j), **vmem),
            pl.BlockSpec((SMALL_D, bm), lambda i, j: (0, j), **vmem),
        ],
        out_specs=pl.BlockSpec((SMALL_D, bk), lambda i, j: (0, i), **vmem),
        scratch_shapes=scratch,
        interpret=interpret,
    )(yT, xT, fp, gp, rT)
    if squeeze:
        return out[0, :k]
    return out[:r, :k].T


def sinkhorn_grad_streaming(particles, previous, eps: float = 0.05,
                            iters: int = 200, tol=None,
                            absorb_every: int = 10, g_init=None,
                            return_g: bool = False,
                            duals_only: bool = False,
                            interpret: bool = False):
    """W2 gradient with O(n·d) memory — for particle counts where even ONE
    ``(n/S, n)`` kernel matrix does not fit HBM (the exchanged-mode W2
    snapshot pairs each block against the full previous set, so at n=100k
    a per-shard kmat is 5 GB and the materialised solvers OOM; the plain
    SVGD step handles 1M particles via the same streaming idea —
    docs/notes.md large-n section).

    Same algorithm and exit semantics as the other two paths, but every
    scaling matvec rebuilds the absorbed kernel from coordinates
    (:func:`kmat_vec`) instead of reusing a materialised block kernel —
    trading ~``2·absorb_every`` extra tile-recompute passes per block for
    never holding the matrix.  The finish is :func:`plan_grad` (one more
    rebuild pass; there is no kmat to matvec against).  Use only when
    memory demands it: at materialisable sizes the fused/XLA paths are
    strictly faster (``FUSED_SINKHORN_STREAM_MIN_PAIRS`` in ops/ot.py
    gates the auto choice).

    **Block size**: the materialised paths amortise one kernel build over
    ``absorb_every`` cheap matvecs, so big blocks win there — but here
    every matvec rebuilds tiles regardless, making the block size pure
    *exit-granularity* loss: the ``tol`` exit fires only at block ends, so
    a warm-started solve whose dual is 1–2 iterations from the fixpoint
    still pays the full ``absorb_every`` iterations (measured 1.87 s/step
    warm at the 100k-particle 8-shard config with blocks of 10, vs the
    per-iteration cost implying ~2 iterations needed).  The scaling loop
    therefore runs with ``absorb_every=1`` — plain log-domain iteration,
    the finest exit granularity, identical semantics — whenever a ``tol``
    exit is active; the argument is honored for fixed-count runs (where
    there is no exit to granulate and fewer folds save a few O(n) passes).
    """
    (xs_, ys_, f0, g0, delta0, reg, sr, a, b,
     m, n, dt, tiny) = _solve_setup(particles, previous, eps, g_init,
                                    interpret)

    # The SAME absorbed-scaling loop as the other two paths
    # (ops/ot.py:_sinkhorn_scaling_loop), with closure matvecs that rebuild
    # kernel tiles from coordinates and ``carry_kmat=False`` — the loop
    # then carries only the potentials, so no kernel-sized buffer ever
    # exists (the whole point of this path).
    from dist_svgd_tpu.ops.ot import _sinkhorn_scaling_loop

    def make_ops(f, g):
        mv = lambda v: kmat_vec(xs_, ys_, f, g, v, 1.0, interpret=interpret)
        rmv = lambda u: kmat_vec(ys_, xs_, g, f, u, 1.0, interpret=interpret)
        return mv, rmv, None

    def run_loop(fg):
        return _sinkhorn_scaling_loop(
            fg[0], fg[1], make_ops, 1.0, m, n, iters, tol,
            1 if tol is not None else absorb_every,  # docstring: block size
            dt,                                      # is pure exit
            carry_kmat=False,                        # granularity here
        )

    if tol is not None and delta0 is not None:
        # warm + tol: the start pair is one exact log-domain iteration from
        # the carried g, and delta0 is that iteration's sup-change — when it
        # is already within tol the loop has nothing to do and a warm solve
        # collapses to the two soft-transform passes plus the finish
        # (_solve_setup docstring; the dominant term of the 100k-particle
        # streaming W2 step, docs/notes.md round-4 section)
        f, g = lax.cond(
            delta0 <= jnp.asarray(tol, dt), lambda fg: fg, run_loop, (f0, g0)
        )
    else:
        f, g = run_loop((f0, g0))

    if duals_only:
        # the resumable-solve chunk (ops/ot.py:sinkhorn_dual_advance): no
        # plan_grad pass — at streaming sizes the finish is a whole extra
        # rebuild pass over n²/S pairs, paid once per *solve*, not per chunk
        return (g * reg).astype(particles.dtype)
    grad = plan_grad(xs_, ys_, f, g, 1.0, interpret=interpret) * sr
    if return_g:
        return grad.astype(particles.dtype), (g * reg).astype(particles.dtype)
    return grad.astype(particles.dtype)
