"""Fused numerical primitives for SVGD on TPU."""

from dist_svgd_tpu.ops.kernels import (
    RBF,
    AdaptiveRBF,
    kernel_matrix,
    kernel_grad_matrix,
    median_bandwidth,
    median_bandwidth_approx,
    squared_distances,
)
from dist_svgd_tpu.ops.svgd import (
    phi,
    phi_blockwise,
    phi_chunked,
    svgd_step,
    svgd_step_sequential,
)
from dist_svgd_tpu.ops.approx import (
    KernelApprox,
    as_kernel_approx,
    default_error_budget,
    is_gram_free,
    phi_nystrom,
    phi_rff,
)

__all__ = [
    "RBF",
    "AdaptiveRBF",
    "KernelApprox",
    "as_kernel_approx",
    "default_error_budget",
    "is_gram_free",
    "phi_nystrom",
    "phi_rff",
    "kernel_matrix",
    "kernel_grad_matrix",
    "median_bandwidth",
    "median_bandwidth_approx",
    "squared_distances",
    "phi",
    "phi_blockwise",
    "phi_chunked",
    "svgd_step",
    "svgd_step_sequential",
]
