"""Pallas TPU kernel for the fused SVGD φ̂* direction.

The XLA path (ops/svgd.py:phi) is already one fused program; this kernel goes
one step further for the TPU hot loop: the Gram tile, its row-sums, and both
MXU contractions are computed per (block_k × block_m) tile entirely in VMEM,
so the ``(m, k)`` Gram matrix never round-trips through HBM.  For the
10k-particle north-star config that saves reading/writing a 400 MB K (and a
second pass for the repulsive term) per step — the flash-attention argument
applied to Stein variational updates.

Math (identical to ops/svgd.py:phi, reference Algorithm 1,
writeup/writeup.tex:106-124):

    Kᵗ[i, j] = exp(-‖y_i − x_j‖² / h)
    φ(y_i)   = (1/m) [ Σ_j Kᵗ[i,j]·(s_j − (2/h)·x_j)  +  (2/h)·y_i·Σ_j Kᵗ[i,j] ]

using ``drive + repulse = Kᵗ(s − (2/h)x) + (2/h)·y⊙ksum`` — one fewer MXU
pass than computing ``Kᵗs`` and ``Kᵗx`` separately.

Two distance variants, chosen statically on the feature dim: d ≤
:data:`SMALL_D` computes ``Σ_c (y_c − x_c)²`` with one rank-1 VPU broadcast
per dim (exact, no 128-lane-padded matmul — the win for the d=3/d=1
reference models); larger d uses the classic ``y²+x²−2·y·x`` MXU form.

The grid is ``(k/bk, m/bm)`` with the m-axis innermost; per output tile the
two accumulators (φ partial sum and Gram row-sum) live in VMEM scratch, which
persists across the sequentially-executed grid steps (standard TPU
accumulation pattern).  Ragged edges: the big-d variant zero-pads and masks
padded columns in-kernel from the *static* true ``m``; the small-d variant
instead pads interaction columns with the :data:`_FAR` sentinel, whose
(clamped) squared distance saturates the exp to an exact zero — no mask
arithmetic on any tile.

CPU/testing: ``interpret=True`` runs the same kernel under the Pallas
interpreter — used by tests/test_pallas.py to check bit-level agreement with
the XLA path.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU memory spaces are unavailable in some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None

#: Feature dims up to this use the broadcast-distance kernel (one (bk, bm)
#: subtract/square per dim on the VPU) instead of the y²+x²−2·y·x matmul.
SMALL_D = 8


def _phi_tail(j, y, kt, contrib, o_ref, acc_ref, ksum_ref, *,
              inv_h: float, m_true: int, nm: int):
    """Shared accumulator epilogue of both kernel variants."""
    rowsum = jnp.sum(kt.astype(jnp.float32), axis=1, keepdims=True)  # (bk, 1)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        ksum_ref[:] = jnp.zeros_like(ksum_ref)

    acc_ref[:] = acc_ref[:] + contrib
    ksum_ref[:] = ksum_ref[:] + rowsum  # broadcast across the lane dim

    @pl.when(j == nm - 1)
    def _():
        o_ref[:] = (acc_ref[:] + (2.0 * inv_h) * y * ksum_ref[:, :1]) / m_true


def _phi_kernel(y_ref, x_ref, xs_ref, o_ref, acc_ref, ksum_ref, *,
                inv_h: float, m_true: int, block_m: int, nm: int,
                bf16_gram: bool):
    """One (i, j) grid step: accumulate tile j's contribution to output tile i."""
    j = pl.program_id(1)

    y = y_ref[:]   # (bk, dp)
    x = x_ref[:]   # (bm, dp)
    xs = xs_ref[:]  # (bm, dp)  == s − (2/h)·x, precomputed once outside

    # pairwise squared distances, clamped like ops/kernels.py:squared_distances.
    # HIGHEST precision: the TPU MXU's default bf16 passes put ~1e-2 absolute
    # error into d2, which the exp() turns into percent-level kernel error
    # (observed 9e-2 rel vs the f32 XLA path on a v5e).  The fast tier
    # replaces the 6-pass HIGHEST decomposition with a 3-pass bf16x3 split
    # (:func:`_dot3`) — d2 error ~1e-6·|y·x|, below the f32 drive-sum floor.
    y2 = jnp.sum(y * y, axis=1, keepdims=True)          # (bk, 1)
    x2 = jnp.sum(x * x, axis=1, keepdims=True)          # (bm, 1)
    if bf16_gram:
        yx = _dot3(y, x.T)                              # (bk, bm) 3 MXU passes
    else:
        yx = jnp.dot(y, x.T, preferred_element_type=jnp.float32,
                     precision=jax.lax.Precision.HIGHEST)  # (bk, bm) 6 passes
    neg = -jnp.maximum(y2 + x2.T - 2.0 * yx, 0.0) * inv_h
    kt = jnp.exp(neg)  # f32 exp in both tiers — a bf16 Gram's per-entry 0.4%
    # rounding decorrelates the drive sum's cancellation (measured 0.67 max
    # rel φ error at (1250, 10k, 55) with a median bandwidth; docs/notes.md)

    # mask padded columns (static m_true ⇒ no SMEM scalar plumbing needed)
    col = jax.lax.broadcasted_iota(jnp.int32, kt.shape, dimension=1)
    kt = jnp.where(col + j * block_m < m_true, kt, jnp.zeros((), kt.dtype))

    if bf16_gram:
        contrib = _dot3(kt, xs)                          # (bk, dp) 3 MXU passes
    else:
        contrib = jnp.dot(kt, xs, preferred_element_type=jnp.float32,
                          precision=jax.lax.Precision.HIGHEST)
    _phi_tail(j, y, kt, contrib, o_ref, acc_ref, ksum_ref,
              inv_h=inv_h, m_true=m_true, nm=nm)


def _phi_kernel_small_d(y_ref, xT_ref, xsT_ref, o_ref, acc_ref, ksum_ref, *,
                        inv_h: float, m_true: int, d_true: int,
                        nm: int, bf16_gram: bool):
    """Small-d variant: distances as Σ_c (y_c − x_c)² via rank-1 VPU
    broadcasts (one ``(bk,1) − (1,bm)`` per feature dim, d ≤ :data:`SMALL_D`).
    Skips the 128-lane-padded distance matmul entirely — ~30% faster at the
    10k-particle d=3 north star on a v5e — and is *exact* f32: no
    y²+x²−2·y·x cancellation, so no clamp is needed.

    The drive term is computed on the **VPU as per-dim reductions**
    (``Σ_j Kᵗ[i,j]·xsᵀ[c,j]`` — one (bk, bm) multiply + row-reduce per
    feature dim) instead of the 128-lane-padded MXU contraction: at d=3 the
    ``precision=HIGHEST`` dot pays its multi-pass decomposition on 128-wide
    tiles that are 97% padding, and the per-dim form measured 1.6× faster
    at the north star at identical f32 exactness (docs/notes.md).

    ``bf16_gram``: evaluate the exp in bfloat16; distances and the drive
    accumulation stay f32 — the bf16·f32 multiply promotes.  Measured
    ~3e-4 max error of max|φ| vs the f64 oracle, and *parity* speed with
    exact f32 on this variant (the MXU left the critical path) — opt-in
    via ``phi_pallas(gram_dtype=jnp.bfloat16)``, mainly for the big-d
    kernel where the drive is a real matmul.

    No in-kernel column mask: padded interaction columns hold the
    :data:`_FAR` sentinel, whose squared distance saturates the exp to an
    exact zero — the VPU iota/compare/select of the masked form is dead
    weight on every non-edge tile.
    """
    j = pl.program_id(1)

    y = y_ref[:]      # (bk, dp)
    xT = xT_ref[:]    # (SMALL_D, bm)  — interaction block, transposed
    xsT = xsT_ref[:]  # (SMALL_D, bm)  == (s − (2/h)·x)ᵀ

    d2 = None
    for c in range(d_true):  # static unroll
        diff = y[:, c:c + 1] - xT[c:c + 1, :]  # (bk, bm)
        d2 = diff * diff if d2 is None else d2 + diff * diff
    # cap the sentinel columns' distance so no inf/nan can reach the exp or
    # the bf16 cast regardless of d and bandwidth (real distances are
    # untouched: the cap is ~1e30)
    neg = -jnp.minimum(d2, _D2_CAP) * inv_h
    if bf16_gram:
        kt = jnp.exp(neg.astype(jnp.bfloat16))
    else:
        kt = jnp.exp(neg)

    cols = [
        jnp.sum(kt * xsT[c:c + 1, :], axis=1, keepdims=True)  # (bk, 1) f32
        for c in range(d_true)
    ]
    pad = y.shape[1] - d_true
    contrib = jnp.concatenate(
        cols + [jnp.zeros((y.shape[0], pad), jnp.float32)], axis=1
    )
    _phi_tail(j, y, kt, contrib, o_ref, acc_ref, ksum_ref,
              inv_h=inv_h, m_true=m_true, nm=nm)


def _dot3(a, b):
    """``a @ b`` with f32 accumulation via a 3-pass bf16x3 split — the
    ``Precision.HIGH`` decomposition, hand-rolled because Mosaic's dot
    lowering accepts only DEFAULT and HIGHEST.  Each f32 operand splits into
    a bf16 high part and a bf16 residual; the residual captures only ~8 of
    the remaining 16 mantissa bits, so the two-term split itself carries
    ~2⁻¹⁶ relative representation error, and the dropped ``lo·lo`` cross
    term is of the same ~2⁻¹⁶..2⁻¹⁸ order:

        a·b ≈ a_hi·b_hi + a_hi·b_lo + a_lo·b_hi

    Three native bf16 MXU passes instead of HIGHEST's six — measured 1.3×
    on the (8×1250, 10k, 55) covertype φ at the default tiles, 1.4e-3 max
    rel error vs the f64 oracle (the exact path's own f32 floor there is
    4.4e-4; docs/notes.md)."""
    a_hi = a.astype(jnp.bfloat16)
    a_lo = (a - a_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    b_hi = b.astype(jnp.bfloat16)
    b_lo = (b - b_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    dot = functools.partial(jnp.dot, preferred_element_type=jnp.float32)
    return dot(a_hi, b_hi) + dot(a_hi, b_lo) + dot(a_lo, b_hi)


#: Sentinel coordinate for padded interaction columns in the small-d kernel:
#: (y − 6e18)² ≈ 3.6e37 per dim keeps even the SMALL_D-dim sum finite in f32
#: (8 · 3.6e37 < f32 max), and the kernel clamps d² at :data:`_D2_CAP`
#: before the bandwidth scaling so ``exp`` sees a large finite negative —
#: an exact zero for every realistic bandwidth, with no inf/nan anywhere
#: and no in-kernel mask.
_FAR = 6e18

#: Upper clamp on the padded-column squared distance (see :data:`_FAR`):
#: exp(−1e30 / h) == 0 for any h < ~1e27 while −1e30 · inv_h stays finite
#: (f32 and bf16) for any h > ~3e-9.
_D2_CAP = 1e30

#: Scoped-VMEM stack budget for the big-d tile-fit estimate (the v5e limit
#: is 16 MB; leave headroom for Mosaic's own temporaries).
_VMEM_BUDGET = 14 * 1024 * 1024


def fits_vmem_big_d(d: int) -> bool:
    """Whether the big-d kernel can fit the scoped-VMEM budget for feature
    dim ``d`` at its minimum (128×256) tile floor — false beyond d ≈ 2400.
    The ``'auto'`` dispatch checks this before choosing the kernel, so huge-d
    models fall back to the XLA φ instead of hitting a compile failure."""
    dp = _round_up(d, 128)
    floor = 4 * (2 * dp * (128 + 2 * 256) + 4 * 128 * 256 + 128 * (dp + 128))
    return floor <= _VMEM_BUDGET


def _pad_to(a: jax.Array, rows: int, cols: int, value: float = 0.0) -> jax.Array:
    return jnp.pad(
        a, ((0, rows - a.shape[0]), (0, cols - a.shape[1])),
        constant_values=value,
    )


@functools.partial(
    jax.jit,
    static_argnames=("bandwidth", "block_k", "block_m", "interpret", "gram_dtype"),
)
def phi_pallas(
    updated: jax.Array,
    interacting: jax.Array,
    scores: jax.Array,
    bandwidth: float = 1.0,
    block_k: Optional[int] = None,
    block_m: Optional[int] = None,
    interpret: bool = False,
    gram_dtype=None,
) -> jax.Array:
    """Fused-tile φ̂* — drop-in for ``ops.svgd.phi(..., RBF(bandwidth))``.

    Args:
        updated: ``(k, d)`` particles being moved.
        interacting: ``(m, d)`` interaction set.
        scores: ``(m, d)`` scores for the interaction set.
        bandwidth: RBF bandwidth ``h`` (static).
        block_k / block_m: output/interaction tile sizes (static).  Default:
            1024×1024 in the small-d variant (round-2 autotune at the
            10k-particle north star: 1024² runs 1.56 ms vs 2.0 ms at the
            old 512² default; 2048-wide k-tiles overflow VMEM) and
            256×1024 in the big-d variant (covertype-shape sweep —
            docs/notes.md).  Auto-shrunk per axis to keep padding ≤ ~10%,
            and — for big-d axes left unset — further shrunk to fit the
            scoped-VMEM stack budget (:data:`_VMEM_BUDGET`; e.g. 256×512
            at dp=768, where the default tiles fail to compile on a v5e).
            An explicitly passed block size is taken as-is and may
            overflow VMEM at large d.
        interpret: run under the Pallas interpreter (CPU testing).
        gram_dtype: ``None`` (f32, exact — the default) or ``jnp.bfloat16``,
            the fast reduced-precision tier.  Big-d variant: both MXU
            contractions (distance and drive) run as 3-pass bf16x3 splits
            (:func:`_dot3`) instead of HIGHEST's 6 passes; the Gram exp and
            all accumulators stay f32 — measured 1.3× end-to-end at the
            (8×1250, 10k, 55) covertype shape at 1.4e-3 max rel φ error vs
            the f64 oracle (vs a 4.4e-4 exact-f32 floor there).  Small-d
            variant: bf16 exp only (~3e-4 error) — parity speed with exact
            f32, since its drive is per-dim VPU reductions with no MXU.

    Note: computation is float32 internally regardless of input dtype (the
    TPU MXU has no f64 path); float64 inputs are cast down and the result
    cast back, so f64 callers get f32 accuracy — use the XLA ``phi`` when
    genuine f64 is needed.
    """
    k, d = updated.shape
    m = interacting.shape[0]
    in_dtype = updated.dtype
    if gram_dtype is not None and gram_dtype != jnp.bfloat16:
        raise ValueError("gram_dtype must be None (f32) or jnp.bfloat16")
    bf16_gram = gram_dtype == jnp.bfloat16

    if d <= SMALL_D:
        default_k = default_m = 1024
    else:
        # asymmetric: small output tiles, wide interaction tiles — with the
        # m-axis innermost, a wider bm cuts the per-tile overheads (mask,
        # rowsum, accumulator traffic) without re-loading the y tile; the
        # round-2 sweep at (8×1250, 10k, 55) measured 256×1024 at 2.52 ms
        # vs 2.78 at 256² (f32) and 1.93 vs 2.80 (bf16x3) — docs/notes.md
        default_k, default_m = 256, 1024
    if block_k is None and block_m is None:
        # shape-keyed measured defaults (round 5): when the caller asked
        # for no specific tiling, consult the harvested per-regime table
        # before the generic heuristic — still padding-clamped and (big-d)
        # VMEM-fitted below, so a measured tile can only shrink, not OOM
        measured = _measured_block(k, m, d <= SMALL_D)
        if measured is not None:
            default_k, default_m = measured
    bk = min(block_k or _auto_block(k, default_k), _round_up(k, 8))
    bm = min(block_m or _auto_block(m, default_m), _round_up(m, 8))
    fit_m, fit_k = block_m is None, block_k is None
    if d > SMALL_D and (fit_m or fit_k):
        # VMEM-fit auto-shrink: at large dp the default tiles overflow the
        # ~16 MB scoped-VMEM stack (measured: 256×1024 tiles at dp=768
        # fail to compile with a 19.4 MB scoped allocation on a v5e).
        # Estimate the stack — double-buffered input tiles (y, x, xs),
        # the (bk, bm) Gram/distance temporaries (~3 live copies), output
        # and scratch — and halve the wide axis first (bm, whose width is
        # a per-tile-overhead optimisation, not a reuse win) until it
        # fits.  Only axes the caller left unset are shrunk (an explicit
        # block size is an expert override); halved sizes re-round to the
        # sublane multiple of 8 that every tile-size path here preserves.
        dp_est = _round_up(d, 128)

        def stack_bytes(bk_, bm_):
            return 4 * (2 * dp_est * (bk_ + 2 * bm_) + 4 * bk_ * bm_
                        + bk_ * (dp_est + 128))

        while stack_bytes(bk, bm) > _VMEM_BUDGET and fit_m and bm > 256:
            bm = _round_up(bm // 2, 8)
        while stack_bytes(bk, bm) > _VMEM_BUDGET and fit_k and bk > 128:
            bk = _round_up(bk // 2, 8)
        if fit_m and fit_k and not fits_vmem_big_d(d):
            # even the floor tiles overflow (d beyond ~2400): fail with a
            # clear message instead of a Mosaic scoped-vmem compile error.
            # 'auto' never reaches here — it checks fits_vmem_big_d first
            raise ValueError(
                f"phi_pallas: d={d} needs more than the ~{_VMEM_BUDGET >> 20} MB "
                "scoped-VMEM budget even at the minimum 128x256 tiles; use "
                "the XLA phi (phi_impl='xla') for this shape"
            )
    kp, mp = _round_up(k, bk), _round_up(m, bm)
    dp = _round_up(d, 128)
    inv_h = 1.0 / float(bandwidth)

    f32 = jnp.float32
    y = _pad_to(updated.astype(f32), kp, dp)
    # s − (2/h)·x, computed once instead of per output tile — in f32, so
    # low-precision inputs keep the "float32 internally" contract below
    xs_full = scores.astype(f32) - (2.0 * inv_h) * interacting.astype(f32)

    nk, nm = kp // bk, mp // bm
    vmem = {} if _VMEM is None else {"memory_space": _VMEM}
    small_d = d <= SMALL_D
    if small_d:
        kern = functools.partial(
            _phi_kernel_small_d,
            inv_h=inv_h, m_true=m, d_true=d, nm=nm,
            bf16_gram=bf16_gram,
        )
        x_in = _pad_to(interacting.T.astype(f32), SMALL_D, mp, value=_FAR)
        x_spec = pl.BlockSpec((SMALL_D, bm), lambda i, j: (0, j), **vmem)
        # transposed for the per-dim VPU drive (kernel docstring); padded
        # columns multiply kt == 0 so the pad value is irrelevant
        xs = _pad_to(xs_full.T, SMALL_D, mp)
        xs_spec = x_spec  # same (SMALL_D, bm) column blocking as xT
    else:
        kern = functools.partial(
            _phi_kernel, inv_h=inv_h, m_true=m, block_m=bm, nm=nm,
            bf16_gram=bf16_gram,
        )
        x_in = _pad_to(interacting.astype(f32), mp, dp)
        x_spec = pl.BlockSpec((bm, dp), lambda i, j: (j, 0), **vmem)
        xs = _pad_to(xs_full, mp, dp)
        xs_spec = x_spec  # same (bm, dp) row blocking as x
    scratch = (
        [pltpu.VMEM((bk, dp), f32), pltpu.VMEM((bk, 128), f32)]
        if pltpu is not None
        else [
            # interpreter fallback when TPU memory-space ctors are absent
            jax.ShapeDtypeStruct((bk, dp), f32),
            jax.ShapeDtypeStruct((bk, 128), f32),
        ]
    )
    out = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((kp, dp), f32),
        grid=(nk, nm),
        in_specs=[
            pl.BlockSpec((bk, dp), lambda i, j: (i, 0), **vmem),
            x_spec,
            xs_spec,
        ],
        out_specs=pl.BlockSpec((bk, dp), lambda i, j: (i, 0), **vmem),
        scratch_shapes=scratch,
        interpret=interpret,
    )(y, x_in, xs)
    return out[:k, :d].astype(in_dtype)


#: Measured-best (block_k, block_m) per φ shape regime, harvested on a v5e
#: (``tools/pallas_autotune.py --harvest`` + the vmapped-lane A/B —
#: docs/notes.md round-5).  Keyed ``(small_d, k, m)`` at the measured
#: ladder points; :func:`_measured_block` picks the nearest regime in
#: log-shape space and the chosen tiles still pass the padding clamp and
#: the big-d VMEM fit downstream.  Evidence notes:
#:
#: - the 8-shard lane row was measured UNDER ``vmap(8)`` — the framework's
#:   actual regime.  The single-lane sweep crowns 512×1024 there (all
#:   combos within 8%, dispatch-bound), but batched, 256×1024 wins by 31%
#:   (0.842 ms/sweep, 118.8 G pairs/s vs 1.101 for 512×1024): per-lane
#:   dead work from tile padding multiplies by the lane count;
#: - the big-d lane keeps 256×1024 on STEP-LEVEL evidence, and is the
#:   cautionary tale for this table: a bare-φ vmap(8) sweep measured
#:   128×1024 16.5% faster (bf16x3; 13.9% f32), but an interleaved A/B of
#:   the full covertype *step* (minibatched scores + gather + update
#:   around the same φ shape) measured the 128 tile 22% SLOWER — kernel
#:   microbenchmarks don't transfer when the kernel shares the program
#:   with other VMEM/HBM tenants.  Tiles here are promoted only on
#:   step-level interleaved wins (round-2's bf16x3 256×1024-vs-256² win
#:   was step-level; the round-5 small-d entries were re-checked by the
#:   north-star gate at 0.999× incumbent);
#: - the large squares have the only strong k-axis signal: at (100k, 100k)
#:   1024×1024 reaches 129.4 G pairs/s vs 76.6 for 256² — tall AND wide
#:   tiles pay off once k amortises the m-axis accumulator traffic.
_MEASURED_BLOCKS = (
    ((True, 1_250, 10_000), (256, 1024)),     # vmap8 0.842 ms, 118.8 G pairs/s
    ((True, 10_000, 10_000), (1024, 1024)),   # 2.032 ms, 49.2 G pairs/s
    ((True, 12_500, 100_000), (512, 1024)),   # 25.43 ms (≈ tie w/ 1024×1024)
    ((True, 100_000, 100_000), (1024, 1024)), # 77.30 ms, 129.4 G pairs/s
    ((False, 1_250, 10_000), (256, 1024)),    # step-level winner (comment
                                              # above; bare-φ sweeps mislead
                                              # in this regime)
)


def _measured_block(k: int, m: int, small_d: bool):
    """Tiles of the nearest measured regime (sum of |log| distances on both
    axes), or ``None`` when the shape sits >4× away from every measured
    point on average — there the padding heuristic stands alone rather
    than extrapolating a measurement that never covered the regime."""
    best = None
    for (sd, mk, mm), tiles in _MEASURED_BLOCKS:
        if sd != small_d:
            continue
        dist = abs(math.log(k / mk)) + abs(math.log(m / mm))
        if best is None or dist < best[0]:
            best = (dist, tiles)
    if best is None or best[0] > 2 * math.log(4.0):
        return None
    return best[1]


def _round_up(v: int, mult: int) -> int:
    return ((v + mult - 1) // mult) * mult


def _auto_block(n: int, default: int) -> int:
    """Largest tile ≤ ``default`` that pads this axis ≤ ~10%.

    Big tiles win at the north star (1024² measured 1.56 ms vs 2.0 ms at
    512² — docs/notes.md), but zero-padding to the tile multiple is pure
    waste: a vmap-emulated 8-shard lane has k = 1250, which a 1024 tile
    pads to 2048 (64% dead work) while a 256 tile pads to 1280 (2.4%)."""
    if n <= default:
        # a single exact tile (the old behaviour): zero padding beyond the
        # 8-row alignment — e.g. n=300 gets one 304-row tile, not 128-tiles
        # padding to 384
        return _round_up(n, 8)
    b = default
    while b > 128 and _round_up(n, b) > 1.1 * n + 8:
        b //= 2
    return b


def pallas_available() -> bool:
    """True when the default backend is a TPU (the only platform this kernel
    is compiled for; elsewhere use ``interpret=True`` or the XLA path)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # backend init failure
        return False


#: In 'auto' mode with a SMALL-d shape (d ≤ SMALL_D), use the Pallas kernel
#: only at/above this many pairwise interactions (k·m).  Below it the Gram
#: tile pressure the kernel exists to relieve isn't the bottleneck and XLA's
#: fusion wins (measured on a v5e at d=3: XLA ahead at n = 512–2048, Pallas
#: from ~4096² — re-validated after the VPU-drive change, docs/notes.md).
PALLAS_MIN_PAIRS = 1 << 22

#: 'auto' threshold for BIG-d shapes (d > SMALL_D), where the distance and
#: drive contractions are genuine MXU matmuls and the kernel's 3-vs-6-pass
#: advantage plus VMEM-resident Gram win at every measured size: round-3
#: interleaved A/B at d=753 (sustained chains, not round-trip-polluted like
#: the round-2 parity reading) measured Pallas f32 over XLA 1.37× at 256²,
#: 1.12× at 500², 1.11× at 2000², 1.23× at 10k² — so the gate is only a
#: guard against trivial shapes (docs/notes.md round-3 big-d section).
PALLAS_MIN_PAIRS_BIG_D = 1 << 16

#: On the XLA path, switch from the one-shot ``phi`` (whole (m, k) Gram in
#: memory) to the both-axes-chunked ``phi_blockwise`` at/above this many
#: pairs: 2³¹ pairs is an 8.6 GB f32 Gram — near the memory cliff on every
#: supported platform, far above any size where the blockwise scan overhead
#: could matter.
XLA_BLOCKWISE_MIN_PAIRS = 1 << 31


def resolve_phi_fn(kernel, phi_impl: str, batch_hint: int = 1,
                   kernel_approx=None):
    """The framework-wide φ-backend policy, shared by ``Sampler``,
    ``DistSampler``, and ``parallel/exchange.py``.

    ``batch_hint``: how many copies of the per-call shape run as one
    batched kernel (``DistSampler`` passes its shard count under vmap
    emulation, 1 on a real mesh where each device runs a single lane).
    The ``'auto'`` thresholds compare ``k·m·batch_hint``: a vmapped
    pallas_call runs all lanes as one batched grid, so an 8-lane
    (1250, 1250) φ is one 12.5M-pair kernel — measured 1.31× over the
    per-lane-XLA choice at the ws=8 partitions config, where the
    per-call shape alone sits below the single-call crossover
    (docs/notes.md round-3 scaling).

    An :class:`~dist_svgd_tpu.ops.kernels.AdaptiveRBF` kernel composes with
    every ``phi_impl`` below: the returned function first re-estimates the
    median bandwidth from the interaction set, then calls the bandwidth-1
    backend through the rescaling identity (see the inline comment).

    ``kernel_approx`` (``None`` | ``'rff'`` | ``'nystrom'`` | a
    :class:`~dist_svgd_tpu.ops.approx.KernelApprox`) swaps the exact Gram
    evaluation for the sub-quadratic feature/landmark φ (``ops/approx.py``):

    - with ``phi_impl='auto'`` the (k·batch_hint, m) crossover
      (``approx.approx_preferred``) picks exact (Pallas on TPU, XLA
      otherwise — exact is faster AND exact below it) vs approximate per
      traced shape;
    - ``phi_impl='xla'`` forces the approximate φ unconditionally (its
      feature-space matmuls ARE XLA programs);
    - ``'pallas'``/``'pallas_bf16'`` are refused — the approximation has
      no Pallas tier; ``'auto'`` is how exact-Pallas composes with it;
    - ``AdaptiveRBF`` + ``'rff'`` at the default ``rff_redraw='run'`` is
      refused in one line (the bank is drawn once at a frozen bandwidth;
      per-step drift would silently decalibrate it), while
      ``KernelApprox('rff', rff_redraw='step')`` composes: the bank is
      re-folded from ``(bank_root, t)`` inside the program each step, so
      the returned φ carries ``needs_step = True`` and the step builders
      bind the index via ``ops.approx.bind_phi_step``; ``'nystrom'``
      composes through the rescaling identity (landmarks are re-selected
      and re-factored every call anyway).

    Returns ``phi_fn(updated, interacting, scores)``:

    - ``'auto'``   — on TPU with an RBF kernel, this Pallas kernel above a
      static trace-time pair-count threshold (``PALLAS_MIN_PAIRS`` for
      d ≤ SMALL_D where XLA wins small shapes; the near-always
      ``PALLAS_MIN_PAIRS_BIG_D`` for larger d, where the kernel measured
      faster at every size) and the fused XLA program (ops/svgd.py:phi)
      below it; plain XLA everywhere else;
    - ``'xla'``    — always the XLA program;
    - ``'pallas'`` — force this kernel (requires RBF); off-TPU it runs under
      the Pallas interpreter — slow but exact, for CPU testing;
    - ``'pallas_bf16'`` — this kernel's fast reduced-precision tier
      (``gram_dtype=jnp.bfloat16``): at big d both MXU contractions run as
      3-pass bf16x3 splits (1.4e-3 max rel φ error, 1.3× at the covertype
      shape — docs/notes.md); at small d, bf16 exp only (~3e-4 error,
      parity speed — the small-d drive has no MXU).  Opt-in, never chosen
      by ``'auto'``; appropriate when the score is already stochastic
      (minibatched configs).
    """
    from dist_svgd_tpu.ops.kernels import (
        RBF,
        AdaptiveRBF,
        median_bandwidth_approx,
    )

    if phi_impl not in ("auto", "xla", "pallas", "pallas_bf16"):
        raise ValueError(f"unknown phi_impl {phi_impl!r}")
    if kernel_approx is not None:
        from dist_svgd_tpu.ops.approx import as_kernel_approx

        kernel_approx = as_kernel_approx(kernel_approx)
        if phi_impl in ("pallas", "pallas_bf16"):
            raise ValueError(
                f"phi_impl={phi_impl!r} is incompatible with kernel_approx: "
                "the approximate φ has no Pallas tier — use 'auto' (exact "
                "Pallas below the crossover, features/landmarks above) or "
                "'xla' (always approximate)"
            )
        if (isinstance(kernel, AdaptiveRBF) and kernel_approx.method == "rff"
                and kernel_approx.rff_redraw != "step"):
            raise ValueError(
                "kernel_approx='rff' with the per-step median bandwidth "
                "(kernel='median_step' / AdaptiveRBF) is refused at "
                "rff_redraw='run': the bank is drawn once at a frozen "
                "bandwidth and per-step drift would silently decalibrate "
                "it — use KernelApprox('rff', rff_redraw='step') (fresh "
                "bank folded from (bank_root, t) every step), "
                "kernel='median' (frozen per run), or "
                "kernel_approx='nystrom' (re-factored every step)"
            )
    if isinstance(kernel, AdaptiveRBF):
        # Per-step median bandwidth via the exact rescaling identity
        #     φ_h(y; x, s) = φ₁(y/√h; x/√h, √h·s) / √h
        # (k_h(y, x) = exp(-‖y−x‖²/h) = k₁(y/√h, x/√h), and the repulsive
        # term's 2/h factor becomes 2·(1/√h)² — algebra in docs/notes.md).
        # Every backend below stays compiled at the static bandwidth 1; the
        # traced h touches only elementwise scalings XLA fuses away.
        # kernel_approx ('nystrom' here — 'rff' was refused above) passes
        # through: its landmarks come from the rescaled interaction set,
        # which IS the rescaled landmark set, so the identity holds exactly.
        base = resolve_phi_fn(RBF(1.0), phi_impl, batch_hint, kernel_approx)
        max_points = kernel.max_points

        if getattr(base, "needs_step", False):
            # redraw-per-step RFF under the identity: each step's fresh
            # bandwidth-1 bank sees that step's rescaled inputs, so the
            # estimate is calibrated to the step's own median bandwidth
            def adaptive_step_fn(y, x, s, t=None):
                h = median_bandwidth_approx(x, max_points)
                sh = jnp.sqrt(h.astype(y.dtype))
                return base(y / sh, x / sh, s * sh, t=t) / sh

            adaptive_step_fn.needs_step = True
            return adaptive_step_fn

        def adaptive_fn(y, x, s):
            h = median_bandwidth_approx(x, max_points)
            sh = jnp.sqrt(h.astype(y.dtype))
            return base(y / sh, x / sh, s * sh) / sh

        return adaptive_fn
    if kernel_approx is not None:
        from dist_svgd_tpu.ops.approx import (
            approx_preferred,
            make_approx_phi_fn,
        )

        approx_fn = make_approx_phi_fn(kernel, kernel_approx)
        if phi_impl == "xla":
            return approx_fn
        exact_fn = resolve_phi_fn(kernel, "auto", batch_hint)
        feature_count = kernel_approx.feature_count

        if getattr(approx_fn, "needs_step", False):

            def auto_approx_step_fn(y, x, s, t=None):
                if approx_preferred(y.shape[0] * batch_hint, x.shape[0],
                                    feature_count):
                    return approx_fn(y, x, s, t=t)
                return exact_fn(y, x, s)

            auto_approx_step_fn.needs_step = True
            return auto_approx_step_fn

        def auto_approx_fn(y, x, s):
            if approx_preferred(y.shape[0] * batch_hint, x.shape[0],
                                feature_count):
                return approx_fn(y, x, s)
            return exact_fn(y, x, s)

        return auto_approx_fn
    on_tpu = pallas_available()
    if phi_impl == "auto":
        if on_tpu and isinstance(kernel, RBF):
            from dist_svgd_tpu.ops.svgd import phi

            bw = kernel.bandwidth

            def auto_fn(y, x, s):
                d = y.shape[1]
                if d <= SMALL_D:
                    thresh, fits = PALLAS_MIN_PAIRS, True
                else:
                    thresh, fits = PALLAS_MIN_PAIRS_BIG_D, fits_vmem_big_d(d)
                if fits and y.shape[0] * x.shape[0] * batch_hint >= thresh:
                    return phi_pallas(y, x, s, bandwidth=bw)
                return phi(y, x, s, kernel)

            return auto_fn
        phi_impl = "xla"
    if phi_impl == "xla":
        from dist_svgd_tpu.ops.svgd import phi, phi_blockwise

        def xla_fn(y, x, s):
            # the memory-cliff gate must also see the batched total: a
            # vmapped call materialises all lanes' Grams at once
            if y.shape[0] * x.shape[0] * batch_hint >= XLA_BLOCKWISE_MIN_PAIRS:
                return phi_blockwise(y, x, s, kernel)
            return phi(y, x, s, kernel)

        return xla_fn
    if not isinstance(kernel, RBF):
        raise ValueError(f"phi_impl={phi_impl!r} requires an RBF kernel")
    bw = kernel.bandwidth
    interp = not on_tpu
    gd = jnp.bfloat16 if phi_impl == "pallas_bf16" else None
    return lambda y, x, s: phi_pallas(
        y, x, s, bandwidth=bw, interpret=interp, gram_dtype=gd
    )
