"""Kernels for SVGD, designed for batched TPU evaluation.

The reference evaluates its RBF kernel one particle pair at a time and obtains
the kernel gradient with a fresh autograd graph per pair
(reference: dsvgd/sampler.py:19-26, experiments/gmm.py:23-24,
experiments/logreg.py:60-61 — ``k(x, y) = exp(-||x-y||^2)`` with fixed
bandwidth 1, no median heuristic).

Here a kernel is a small static object that can evaluate the full Gram matrix
in one broadcasted expression (an MXU-friendly ``x @ y.T``) and, when an
analytic gradient exists (RBF), exposes the pieces the SVGD step needs so that
no ``(m, k, d)`` gradient tensor is ever materialised.  Arbitrary user-supplied
kernel callables remain supported through ``jax.grad``/``jax.vmap`` fallbacks,
preserving the reference's model-agnostic design (kernel and logp are
user-supplied closures, dsvgd/sampler.py:7-17).
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp


def squared_distances(x: jax.Array, y: jax.Array) -> jax.Array:
    """Pairwise squared Euclidean distances.

    Args:
        x: ``(m, d)`` array.
        y: ``(k, d)`` array.

    Returns:
        ``(m, k)`` array of ``||x_i - y_j||^2``, clamped at zero (the
        broadcasted form can go slightly negative in floating point).
    """
    x2 = jnp.sum(x * x, axis=-1)[:, None]
    y2 = jnp.sum(y * y, axis=-1)[None, :]
    # HIGHEST: the TPU MXU's default bf16 passes leave ~1e-2 absolute error
    # here, which exp(-sq/h) turns into percent-level kernel error; the
    # distance matmul is cheap (contraction over small d) so full f32 is free
    sq = x2 + y2 - 2.0 * jnp.matmul(x, y.T, precision=jax.lax.Precision.HIGHEST)
    return jnp.maximum(sq, 0.0)


class RBF:
    """Gaussian RBF kernel ``k(x, y) = exp(-||x - y||^2 / bandwidth)``.

    ``bandwidth=1`` reproduces the reference kernel exactly
    (experiments/gmm.py:23-24, experiments/logreg.py:60-61).  The analytic
    gradient is ``∇_x k(x, y) = -(2 / bandwidth) (x - y) k(x, y)`` — identical
    to what the reference's per-pair autograd computes, but closed-form.

    Instances are static configuration: close over them (or pass them as
    static args) rather than tracing them through ``jit``.
    """

    analytic_grad = True

    def __init__(self, bandwidth: float = 1.0):
        if bandwidth <= 0:
            raise ValueError(f"bandwidth must be positive, got {bandwidth}")
        self.bandwidth = float(bandwidth)

    def __call__(self, x: jax.Array, y: jax.Array) -> jax.Array:
        """Scalar kernel value for single particles ``x, y`` of shape ``(d,)``."""
        diff = x - y
        return jnp.exp(-jnp.sum(diff * diff) / self.bandwidth)

    def matrix(self, x: jax.Array, y: jax.Array) -> jax.Array:
        """Gram matrix ``K[i, j] = k(x_i, y_j)`` for ``(m, d)``/``(k, d)`` inputs."""
        return jnp.exp(-squared_distances(x, y) / self.bandwidth)

    def __repr__(self) -> str:  # pragma: no cover
        return f"RBF(bandwidth={self.bandwidth})"

    def __eq__(self, other) -> bool:
        return isinstance(other, RBF) and other.bandwidth == self.bandwidth

    def __hash__(self) -> int:
        return hash(("RBF", self.bandwidth))


#: Above this many particles, :func:`median_bandwidth` computes the median
#: over an evenly-strided subsample (the O(n²) sort of all pairwise
#: distances — 10⁸ entries at n=10k — costs more than the SVGD step it
#: configures; a 4096-point strided subsample estimates the same median).
MEDIAN_BANDWIDTH_MAX_POINTS = 4096


def median_bandwidth(particles: jax.Array, max_points: int = MEDIAN_BANDWIDTH_MAX_POINTS) -> jax.Array:
    """Median heuristic ``h = med^2 / log(n + 1)`` (Liu & Wang 2016, eq. 13).

    Extension beyond the reference, which hard-codes bandwidth 1
    (SURVEY.md §0); useful for the larger BASELINE.json configs — samplers
    accept ``kernel='median'`` to resolve this per run from the initial
    particles.  Returns a scalar ``jax.Array``.  ``log(n + 1)`` uses the
    *full* particle count even when the median itself is estimated on a
    ``max_points`` subsample.
    """
    full_n = particles.shape[0]
    if full_n > max_points:
        stride = -(-full_n // max_points)  # ceil: at most max_points rows
        particles = particles[::stride]
    n = particles.shape[0]
    sq = squared_distances(particles, particles)
    # median over *pairwise* (off-diagonal) distances; the n zero diagonal
    # entries would bias the bandwidth low for small n.  Jit-safe form: push
    # the diagonal to +inf and take the fixed order statistics of the sort.
    sq = jnp.where(jnp.eye(n, dtype=bool), jnp.inf, sq)
    flat = jnp.sort(sq.reshape(-1))
    m = n * n - n  # count of finite (off-diagonal) entries
    med_sq = 0.5 * (flat[(m - 1) // 2] + flat[m // 2])
    return med_sq / math.log(full_n + 1.0)


def median_bandwidth_approx(
    particles: jax.Array,
    max_points: int = 1024,
    probes: int = 16,
) -> jax.Array:
    """Per-step estimate of the Liu & Wang median bandwidth, sort-free.

    :func:`median_bandwidth` takes the exact order statistic of the pairwise
    distances with ``jnp.sort`` — fine once per run, but a 4096-point
    subsample sort costs 34 ms on a v5e, 36× the whole 10k-particle SVGD
    step.  This estimator instead brackets the median with four multi-probe
    counting passes (``probes`` thresholds per pass, each one broadcast
    compare + count over the subsample's distance matrix — pure VPU work,
    no sort): resolution ``max(d²)/probes⁴`` (~1.5e-5 of the range at the
    default 16⁴), measured ~1e-4 relative vs the exact median and **free
    against the scan-step floor** at ``max_points ≤ 1024`` on a v5e
    (docs/notes.md).  Used by :class:`AdaptiveRBF` to re-resolve the
    bandwidth *inside* the jitted step, every step.

    Returns a scalar ``jax.Array``: ``max(med², 1e-12) / log(n + 1)``
    (the floor keeps a degenerate all-identical particle set from producing
    a zero bandwidth).  Converges to the *lower middle* order statistic —
    no even-count interpolation, unlike :func:`median_bandwidth`; the gap
    between adjacent order statistics is O(1/p²) of the range and
    immaterial for a kernel bandwidth.
    """
    full_n = particles.shape[0]
    if full_n > max_points:
        stride = -(-full_n // max_points)  # ceil: at most max_points rows
        particles = particles[::stride]
    p = particles.shape[0]
    sq = squared_distances(particles, particles)
    # rank of the off-diagonal median within the full p² count — the p
    # diagonal zeros always fall below any positive threshold, so they are
    # simply added to the target rank instead of being masked out
    target = p + (p * p - p + 1) // 2
    med_sq = _median_bracket(sq, target, probes)
    return med_sq / math.log(full_n + 1.0)


def _median_bracket(sq, target: int, probes: int, pair=None):
    """The four-pass counting bracket shared by the plain and masked median
    estimators — ONE copy of the thresholds, rank comparison, midpoint, and
    floor, so the ring ≡ gather bandwidth guarantee cannot drift between
    the twins.  ``pair`` (optional boolean matrix) restricts both the
    counts and the initial width to valid entries; ``None`` keeps the
    unmasked hot path free of mask arithmetic."""
    ks = jnp.arange(1, probes + 1, dtype=sq.dtype)

    def refine(lo, width):
        t = lo + width * ks / probes                      # (probes,)
        hit = sq[None] <= t[:, None, None]
        if pair is not None:
            hit = hit & pair[None]
        cnt = jnp.sum(hit, axis=(1, 2))                   # (probes,)
        i = jnp.argmax(cnt >= target)  # first bucket reaching the rank
        return lo + width * i.astype(sq.dtype) / probes, width / probes

    w0 = jnp.max(sq) if pair is None else jnp.max(jnp.where(pair, sq, 0.0))
    lo, w = refine(jnp.zeros((), sq.dtype), w0)
    for _ in range(3):
        lo, w = refine(lo, w)
    return jnp.maximum(lo + 0.5 * w, 1e-12)  # probes⁻⁴ ≈ 1.5e-5 of range


def median_bandwidth_approx_masked(
    points: jax.Array,
    valid: jax.Array,
    n_valid: int,
    full_n: int,
    probes: int = 16,
) -> jax.Array:
    """:func:`median_bandwidth_approx` over the ``valid`` rows of an
    already-subsampled, possibly padded point set — the SPMD form used by
    the ring exchange's ``median_step`` path (``parallel/exchange.py``),
    where each shard contributes its (ragged, padded-to-uniform) slice of
    the global strided subsample via ``lax.all_gather``.

    ``n_valid`` (static) is the true subsample size and ``full_n`` (static)
    the full particle count feeding the ``log(n + 1)`` normaliser.  Counting
    only valid×valid pairs against the same thresholds makes this numerically
    identical to ``median_bandwidth_approx`` run on the compacted subsample:
    the bracket thresholds, target rank, and per-pair distances all match
    (padded rows never enter a count or the initial width).
    """
    sq = squared_distances(points, points)
    pair = valid[:, None] & valid[None, :]
    # rank bookkeeping as in median_bandwidth_approx: the n_valid diagonal
    # zeros always fall below any positive threshold, so they are added to
    # the target rank rather than masked out
    target = n_valid + (n_valid * n_valid - n_valid + 1) // 2
    med_sq = _median_bracket(sq, target, probes, pair=pair)
    return med_sq / math.log(full_n + 1.0)


class AdaptiveRBF:
    """Marker kernel: RBF whose bandwidth is re-resolved **every step** from
    the current interaction set via :func:`median_bandwidth_approx` — the
    standard adaptive median heuristic (Liu & Wang 2016, eq. 13) evaluated
    inside the jitted scan, an extension beyond both the reference (fixed
    ``h=1``, SURVEY.md §0) and the per-run ``kernel='median'`` resolution.

    The φ backends stay compiled at bandwidth 1: ``resolve_phi_fn`` applies
    the exact rescaling identity ``φ_h(y; x, s) = φ₁(y/√h; x/√h, √h·s)/√h``
    outside the kernel, so the same Pallas/XLA programs serve every traced
    bandwidth value (docs/notes.md).

    Jacobi paths only (the literal Gauss–Seidel sweep exists for reference
    parity, which has no adaptive bandwidth).  The ring exchange resolves
    the bandwidth once per step from a gathered strided subsample — the
    gather path's exact subsample, so ring ≡ gather holds
    (``parallel/exchange.py:_ring_median_bandwidth``).
    """

    def __init__(self, max_points: int = 1024):
        if max_points <= 0:
            raise ValueError(f"max_points must be positive, got {max_points}")
        self.max_points = int(max_points)

    def __repr__(self) -> str:  # pragma: no cover
        return f"AdaptiveRBF(max_points={self.max_points})"

    def __eq__(self, other) -> bool:
        return isinstance(other, AdaptiveRBF) and other.max_points == self.max_points

    def __hash__(self) -> int:
        return hash(("AdaptiveRBF", self.max_points))


def kernel_matrix(kernel: Callable, x: jax.Array, y: jax.Array) -> jax.Array:
    """Gram matrix for an arbitrary scalar kernel callable (vmap fallback)."""
    if hasattr(kernel, "matrix"):
        return kernel.matrix(x, y)
    return jax.vmap(lambda xi: jax.vmap(lambda yj: kernel(xi, yj))(y))(x)


def kernel_grad_matrix(kernel: Callable, x: jax.Array, y: jax.Array) -> jax.Array:
    """``G[i, j] = ∇_{x_i} k(x_i, y_j)`` as an ``(m, k, d)`` array.

    Generic-autograd counterpart of the reference's per-pair
    ``_dkernel`` (dsvgd/sampler.py:19-26).  Only used for non-analytic
    kernels; the RBF path in :mod:`dist_svgd_tpu.ops.svgd` never builds
    this tensor.
    """
    dk = jax.grad(kernel, argnums=0)
    return jax.vmap(lambda xi: jax.vmap(lambda yj: dk(xi, yj))(y))(x)
