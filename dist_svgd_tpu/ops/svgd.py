"""The fused SVGD step.

The update everything else exists to compute (reference writeup Algorithm 1,
writeup/writeup.tex:106-124):

    θ_i ← θ_i + ε · φ̂*(θ_i)
    φ̂*(y) = (1/m) Σ_j [ k(x_j, y) · ∇_{x_j} log p(x_j) + ∇_{x_j} k(x_j, y) ]

The reference computes φ̂ with a Python loop over pairs, building two fresh
autograd graphs per pair (dsvgd/sampler.py:35-40, dsvgd/distsampler.py:84-101)
— the dominant cost identified in SURVEY.md §3.3.  Here the entire step is one
fused XLA program:

- scores come in batched (``vmap(grad(logp))`` computed by the caller, so the
  same φ works for exact/scaled/exchanged score variants);
- the Gram matrix is one broadcasted matmul on the MXU;
- for the RBF kernel the repulsive term uses the closed form
  ``Σ_j ∇_{x_j} k(x_j, y) = (2/h) (y·Σ_j K_j  −  Kᵀ x)``,
  so no ``(m, k, d)`` tensor is materialised — O(m·k + (m+k)·d) memory.

Update semantics: the vectorised step is **Jacobi** (all particles updated
simultaneously), a deliberate, documented deviation from the reference's
in-place Gauss–Seidel sweep (dsvgd/sampler.py:62-68) — same fixed point,
different trajectory (SURVEY.md §3.2).  ``svgd_step_sequential`` provides a
``lax.scan`` Gauss–Seidel mode with the reference's exact semantics for
small-n verification.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from dist_svgd_tpu.ops.kernels import RBF, kernel_grad_matrix, kernel_matrix


def phi(
    updated: jax.Array,
    interacting: jax.Array,
    scores: jax.Array,
    kernel=None,
) -> jax.Array:
    """Stein variational direction φ̂* for each row of ``updated``.

    Args:
        updated: ``(k, d)`` particles being moved (the local block).
        interacting: ``(m, d)`` interaction set (full set in the ``all_*``
            exchange modes, the local block in ``partitions`` mode —
            reference dsvgd/distsampler.py:85-87).
        scores: ``(m, d)`` score vectors ``∇ log p`` for each interacting
            particle (already scaled/exchanged by the caller as the exchange
            mode dictates).
        kernel: an :class:`RBF` instance (fused path) or any scalar kernel
            callable (autograd fallback).  Defaults to the reference's
            ``RBF(bandwidth=1)``.

    Returns:
        ``(k, d)`` array of update directions.
    """
    if kernel is None:
        kernel = RBF(1.0)
    m = interacting.shape[0]
    if isinstance(kernel, RBF):
        # HIGHEST precision on the φ contractions: the TPU MXU's default bf16
        # passes put ~1e-2 absolute error into the update direction (measured
        # 6e-2 rel on a v5e); with small d these matmuls are a rounding error
        # next to the m·k exp() evaluations, so full f32 costs ~nothing.
        hi = jax.lax.Precision.HIGHEST
        K = kernel.matrix(interacting, updated)  # (m, k)
        drive = jnp.matmul(K.T, scores, precision=hi)  # Σ_j k(x_j, y_i) s_j
        # Σ_j ∇_{x_j} k(x_j, y_i) = (2/h) (y_i Σ_j K_ji − Σ_j K_ji x_j)
        ksum = jnp.sum(K, axis=0)  # (k,)
        repulse = (2.0 / kernel.bandwidth) * (
            updated * ksum[:, None] - jnp.matmul(K.T, interacting, precision=hi)
        )
        return (drive + repulse) / m
    K = kernel_matrix(kernel, interacting, updated)  # (m, k)
    gK = kernel_grad_matrix(kernel, interacting, updated)  # (m, k, d)
    return (K.T @ scores + jnp.sum(gK, axis=0)) / m


def phi_chunked(
    updated: jax.Array,
    interacting: jax.Array,
    scores: jax.Array,
    kernel=None,
    chunk_size: int = 1024,
) -> jax.Array:
    """φ̂* accumulated over chunks of the interaction set — identical result
    to :func:`phi` (modulo float summation order) without materialising the
    full ``(m, k)`` Gram matrix.

    The single-device counterpart of the distributed ring accumulation
    (``parallel/exchange.py``): peak memory is O(chunk_size · k) instead of
    O(m · k), for interaction sets too large for HBM (SURVEY.md §7.3 item 4).
    """
    if kernel is None:
        kernel = RBF(1.0)
    m, d = interacting.shape
    main = (m // chunk_size) * chunk_size

    def body(acc, xs):
        x, s = xs
        return acc + (chunk_size / m) * phi(updated, x, s, kernel), None

    acc = jnp.zeros_like(updated)
    if main:
        xb = interacting[:main].reshape(-1, chunk_size, d)
        sb = scores[:main].reshape(-1, chunk_size, d)
        acc, _ = lax.scan(body, acc, (xb, sb))
    if main < m:
        tail = m - main
        acc = acc + (tail / m) * phi(updated, interacting[main:], scores[main:], kernel)
    return acc


def phi_blockwise(
    updated: jax.Array,
    interacting: jax.Array,
    scores: jax.Array,
    kernel=None,
    chunk_k: int = 4096,
    chunk_m: int = 1024,
) -> jax.Array:
    """φ̂* accumulated over chunks of **both** axes — identical result to
    :func:`phi` (modulo float summation order) with O(chunk_k · chunk_m)
    peak Gram memory.

    :func:`phi_chunked` bounds memory only along the interaction axis: its
    per-chunk Gram block is ``(chunk, k)``, which at k = 1M is 32 GB on its
    own.  This wrapper additionally ``lax.map``s over k-chunks, making the
    XLA path viable at any n on platforms without the Pallas kernel (which
    streams VMEM tiles and needs neither — docs/notes.md 1M measurement).
    """
    k, d = updated.shape
    main = (k // chunk_k) * chunk_k
    parts = []
    if main:
        yb = updated[:main].reshape(-1, chunk_k, d)
        out = lax.map(
            lambda y: phi_chunked(y, interacting, scores, kernel, chunk_m), yb
        )
        parts.append(out.reshape(main, d))
    if main < k:
        parts.append(
            phi_chunked(updated[main:], interacting, scores, kernel, chunk_m)
        )
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


def svgd_step(
    particles: jax.Array,
    scores: jax.Array,
    step_size,
    kernel=None,
    extra_grad: Optional[jax.Array] = None,
    extra_weight=0.0,
) -> jax.Array:
    """One Jacobi SVGD step over the full particle set.

    ``extra_grad``/``extra_weight`` add an optional proximal term the way the
    reference adds its Wasserstein/JKO gradient: ``δ += h · w_grad`` before
    ``θ += ε · δ`` (dsvgd/distsampler.py:194-200).
    """
    delta = phi(particles, particles, scores, kernel)
    if extra_grad is not None:
        delta = delta + extra_weight * extra_grad
    return particles + step_size * delta


def svgd_step_sequential(
    particles: jax.Array,
    score_fn: Callable[[jax.Array], jax.Array],
    step_size,
    kernel=None,
) -> jax.Array:
    """Gauss–Seidel SVGD sweep with the reference's exact in-place semantics.

    Particle ``i``'s update sees particles ``< i`` already updated, and every
    pair re-evaluates the score at the *current* value of the interacting
    particle (reference dsvgd/sampler.py:62-68: ``particles[i] = particle +
    ε·φ̂`` mutates the array the next ``_phi_hat`` reads, and ``_dlogp(other)``
    is called fresh per pair).  O(n²) score evaluations per sweep — use only
    for small-n parity verification; the Jacobi path is the TPU-native mode.
    """
    if kernel is None:
        kernel = RBF(1.0)
    n = particles.shape[0]
    batched_score = jax.vmap(score_fn)

    def body(parts, i):
        scores = batched_score(parts)
        y = lax.dynamic_slice_in_dim(parts, i, 1, axis=0)  # (1, d)
        delta = phi(y, parts, scores, kernel)
        parts = lax.dynamic_update_slice_in_dim(parts, y + step_size * delta, i, axis=0)
        return parts, None

    parts, _ = lax.scan(body, particles, jnp.arange(n))
    return parts
