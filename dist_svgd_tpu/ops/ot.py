"""Wasserstein-2 / JKO proximal term.

The reference adds an optional W2 gradient to each SVGD step
(dsvgd/distsampler.py:103-129, applied at :190-198): solve the discrete-OT
linear program between the current particles ``x`` (weights 1/m) and the
previous step's particles ``y`` (weights 1/n) with cost ``‖x_i − y_j‖²``, then

    w_grad_i = Σ_j  plan_ij · (x_i − y_j).

Two solvers are provided:

- :func:`wasserstein_grad_lp` — exact-parity path: the same dense LP the
  reference builds, solved on the **host** with ``scipy.optimize.linprog``.
  O((m+n)·m·n) constraint matrix — the reference's single biggest scalability
  cliff (SURVEY.md §3.3); kept for fidelity and as the oracle for tests.
- :func:`wasserstein_grad_sinkhorn` — TPU-native fast path: entropic OT via
  log-domain Sinkhorn iterations, fully jittable and fusable into the
  sharded step (fixed-count ``lax.fori_loop``, or a ``lax.while_loop``
  bounded by ``iters`` when the ``tol`` early exit is enabled — the
  ``DistSampler`` default).  Converges to the LP plan as ``eps → 0``;
  tested against the LP on small problems.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy.special import logsumexp

from dist_svgd_tpu.ops.kernels import squared_distances


def wasserstein_grad_lp(particles, previous) -> np.ndarray:
    """Exact discrete-OT W2 gradient via the host LP (reference parity).

    Builds the same flattened cost/equality system as the reference
    (dsvgd/distsampler.py:111-127): ``c`` is the row-major flattened squared
    distance matrix, the first ``m`` rows of ``A_eq`` constrain row sums to
    ``1/m``, the next ``n`` rows constrain column sums to ``1/n``.  scipy's
    modern default (HiGHS) replaces the scipy-1.1-era simplex; both return a
    vertex solution (a matching when ``m == n``).
    """
    import scipy.optimize

    x = np.asarray(particles, dtype=np.float64)
    y = np.asarray(previous, dtype=np.float64)
    m, d = x.shape
    n = y.shape[0]

    diffs = x[:, None, :] - y[None, :, :]  # (m, n, d)
    c = np.sum(diffs**2, axis=2).reshape(-1)  # row-major flatten

    a_rows = np.kron(np.eye(m), np.ones((1, n)))  # row-sum constraints
    a_cols = np.kron(np.ones((1, m)), np.eye(n))  # column-sum constraints
    a_eq = np.vstack([a_rows, a_cols])
    b_eq = np.concatenate([np.full(m, 1.0 / m), np.full(n, 1.0 / n)])

    res = scipy.optimize.linprog(c, A_eq=a_eq, b_eq=b_eq)
    if res.x is None:  # pragma: no cover - defensive
        raise RuntimeError(f"OT linear program failed: {res.message}")
    plan = res.x.reshape(m, n)
    return np.sum(plan[:, :, None] * diffs, axis=1)


def sinkhorn_plan(x, y, eps: float = 0.05, iters: int = 200,
                  tol: float | None = None):
    """Entropic-OT transport plan between uniform measures on ``x`` and ``y``.

    ``eps`` is *relative*: the entropic regulariser is ``eps · mean(C)``,
    making the solver scale-free across targets.  Log-domain updates for
    stability.

    ``tol=None`` runs exactly ``iters`` iterations (compile-time-constant
    ``fori_loop``).  A float ``tol`` adds an early exit (``lax.while_loop``
    bounded by ``iters``): stop once the sup-norm change of ``log v`` per
    iteration drops below ``tol``.  Log-scaling units are the right ones —
    plan entries ``exp(log u ⊕ log k ⊕ log v)`` are stable to ~``tol``
    relatively, and the equivalent dual-potential precision is ``tol·reg``
    in cost units, so the exit *tracks the precision intent encoded in
    eps* (a tiny-``eps`` run converges further before exiting).  At the
    10k-particle north-star shard shape (1250 × 10000, eps=0.05) the
    default-precision potentials stabilise in a few dozen iterations while
    small problems need ~120+ of the 200 default — the adaptive exit
    serves both without a tuning knob (docs/notes.md).
    """
    m, n = x.shape[0], y.shape[0]
    cost = squared_distances(x, y)
    mean_c = jnp.maximum(jnp.mean(cost), jnp.finfo(cost.dtype).tiny)
    reg = eps * mean_c
    log_k = -cost / reg
    log_a = jnp.full((m,), -jnp.log(float(m)), dtype=cost.dtype)
    log_b = jnp.full((n,), -jnp.log(float(n)), dtype=cost.dtype)

    def half_steps(log_v):
        log_u = log_a - logsumexp(log_k + log_v[None, :], axis=1)
        return log_u, log_b - logsumexp(log_k + log_u[:, None], axis=0)

    log_v0 = jnp.zeros((n,), dtype=cost.dtype)
    if tol is None:
        def body(_, carry):
            _, log_v = carry
            return half_steps(log_v)

        log_u, log_v = lax.fori_loop(
            0, iters, body, (jnp.zeros((m,), dtype=cost.dtype), log_v0)
        )
    else:
        thresh = jnp.asarray(tol, cost.dtype)

        def cond(carry):
            i, _, _, delta = carry
            return (i < iters) & (delta > thresh)

        def body(carry):
            i, _, log_v, _ = carry
            log_u, new_v = half_steps(log_v)
            delta = jnp.max(jnp.abs(new_v - log_v))
            return i + 1, log_u, new_v, delta

        _, log_u, log_v, _ = lax.while_loop(
            cond,
            body,
            (0, jnp.zeros((m,), dtype=cost.dtype), log_v0,
             jnp.asarray(jnp.inf, cost.dtype)),
        )
    return jnp.exp(log_u[:, None] + log_k + log_v[None, :])


def wasserstein_grad_sinkhorn(particles, previous, eps: float = 0.05,
                              iters: int = 200, tol: float | None = None):
    """W2 gradient from the Sinkhorn plan — same formula as the LP path:
    ``grad_i = Σ_j P_ij (x_i − y_j) = x_i · rowsum_i − P @ y``, computed
    without materialising the ``(m, n, d)`` difference tensor."""
    plan = sinkhorn_plan(particles, previous, eps=eps, iters=iters, tol=tol)
    row = jnp.sum(plan, axis=1)
    return particles * row[:, None] - plan @ previous
