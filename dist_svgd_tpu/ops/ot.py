"""Wasserstein-2 / JKO proximal term.

The reference adds an optional W2 gradient to each SVGD step
(dsvgd/distsampler.py:103-129, applied at :190-198): solve the discrete-OT
linear program between the current particles ``x`` (weights 1/m) and the
previous step's particles ``y`` (weights 1/n) with cost ``‖x_i − y_j‖²``, then

    w_grad_i = Σ_j  plan_ij · (x_i − y_j).

Two solvers are provided:

- :func:`wasserstein_grad_lp` — exact-parity path: the same dense LP the
  reference builds, solved on the **host** with ``scipy.optimize.linprog``.
  O((m+n)·m·n) constraint matrix — the reference's single biggest scalability
  cliff (SURVEY.md §3.3); kept for fidelity and as the oracle for tests.
- :func:`wasserstein_grad_sinkhorn` — TPU-native fast path: entropic OT via
  log-domain Sinkhorn iterations, fully jittable (``lax.fori_loop``), fusable
  into the sharded step.  Converges to the LP plan as ``eps → 0``; tested
  against the LP on small problems.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy.special import logsumexp

from dist_svgd_tpu.ops.kernels import squared_distances


def wasserstein_grad_lp(particles, previous) -> np.ndarray:
    """Exact discrete-OT W2 gradient via the host LP (reference parity).

    Builds the same flattened cost/equality system as the reference
    (dsvgd/distsampler.py:111-127): ``c`` is the row-major flattened squared
    distance matrix, the first ``m`` rows of ``A_eq`` constrain row sums to
    ``1/m``, the next ``n`` rows constrain column sums to ``1/n``.  scipy's
    modern default (HiGHS) replaces the scipy-1.1-era simplex; both return a
    vertex solution (a matching when ``m == n``).
    """
    import scipy.optimize

    x = np.asarray(particles, dtype=np.float64)
    y = np.asarray(previous, dtype=np.float64)
    m, d = x.shape
    n = y.shape[0]

    diffs = x[:, None, :] - y[None, :, :]  # (m, n, d)
    c = np.sum(diffs**2, axis=2).reshape(-1)  # row-major flatten

    a_rows = np.kron(np.eye(m), np.ones((1, n)))  # row-sum constraints
    a_cols = np.kron(np.ones((1, m)), np.eye(n))  # column-sum constraints
    a_eq = np.vstack([a_rows, a_cols])
    b_eq = np.concatenate([np.full(m, 1.0 / m), np.full(n, 1.0 / n)])

    res = scipy.optimize.linprog(c, A_eq=a_eq, b_eq=b_eq)
    if res.x is None:  # pragma: no cover - defensive
        raise RuntimeError(f"OT linear program failed: {res.message}")
    plan = res.x.reshape(m, n)
    return np.sum(plan[:, :, None] * diffs, axis=1)


def sinkhorn_plan(x, y, eps: float = 0.05, iters: int = 200):
    """Entropic-OT transport plan between uniform measures on ``x`` and ``y``.

    ``eps`` is *relative*: the entropic regulariser is ``eps · mean(C)``,
    making the solver scale-free across targets.  Log-domain updates for
    stability; fixed iteration count so the loop is a compile-time constant
    (XLA-friendly control flow).
    """
    m, n = x.shape[0], y.shape[0]
    cost = squared_distances(x, y)
    reg = eps * jnp.maximum(jnp.mean(cost), jnp.finfo(cost.dtype).tiny)
    log_k = -cost / reg
    log_a = jnp.full((m,), -jnp.log(float(m)), dtype=cost.dtype)
    log_b = jnp.full((n,), -jnp.log(float(n)), dtype=cost.dtype)

    def body(_, carry):
        log_u, log_v = carry
        log_u = log_a - logsumexp(log_k + log_v[None, :], axis=1)
        log_v = log_b - logsumexp(log_k + log_u[:, None], axis=0)
        return log_u, log_v

    log_u = jnp.zeros((m,), dtype=cost.dtype)
    log_v = jnp.zeros((n,), dtype=cost.dtype)
    log_u, log_v = lax.fori_loop(0, iters, body, (log_u, log_v))
    return jnp.exp(log_u[:, None] + log_k + log_v[None, :])


def wasserstein_grad_sinkhorn(particles, previous, eps: float = 0.05, iters: int = 200):
    """W2 gradient from the Sinkhorn plan — same formula as the LP path:
    ``grad_i = Σ_j P_ij (x_i − y_j) = x_i · rowsum_i − P @ y``, computed
    without materialising the ``(m, n, d)`` difference tensor."""
    plan = sinkhorn_plan(particles, previous, eps=eps, iters=iters)
    row = jnp.sum(plan, axis=1)
    return particles * row[:, None] - plan @ previous
