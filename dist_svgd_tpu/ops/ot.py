"""Wasserstein-2 / JKO proximal term.

The reference adds an optional W2 gradient to each SVGD step
(dsvgd/distsampler.py:103-129, applied at :190-198): solve the discrete-OT
linear program between the current particles ``x`` (weights 1/m) and the
previous step's particles ``y`` (weights 1/n) with cost ``‖x_i − y_j‖²``, then

    w_grad_i = Σ_j  plan_ij · (x_i − y_j).

Two solvers are provided:

- :func:`wasserstein_grad_lp` — exact-parity path: the same dense LP the
  reference builds, solved on the **host** with ``scipy.optimize.linprog``.
  O((m+n)·m·n) constraint matrix — the reference's single biggest scalability
  cliff (SURVEY.md §3.3); kept for fidelity and as the oracle for tests.
- :func:`wasserstein_grad_sinkhorn` — TPU-native fast path: entropic OT via
  absorption-stabilised Sinkhorn scaling (matvec blocks between log-domain
  absorptions — see :func:`sinkhorn_plan`), fully jittable and fusable
  into the sharded step (fixed-count loop, or a ``lax.while_loop`` bounded
  by ``iters`` when the ``tol`` early exit is enabled — the ``DistSampler``
  default).  Converges to the LP plan as ``eps → 0``; tested against the
  LP on small problems.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from dist_svgd_tpu.ops.kernels import squared_distances


def wasserstein_grad_lp(particles, previous) -> np.ndarray:
    """Exact discrete-OT W2 gradient via the host LP (reference parity).

    Builds the same flattened cost/equality system as the reference
    (dsvgd/distsampler.py:111-127): ``c`` is the row-major flattened squared
    distance matrix, the first ``m`` rows of ``A_eq`` constrain row sums to
    ``1/m``, the next ``n`` rows constrain column sums to ``1/n``.  scipy's
    modern default (HiGHS) replaces the scipy-1.1-era simplex; both return a
    vertex solution (a matching when ``m == n``).
    """
    import scipy.optimize

    x = np.asarray(particles, dtype=np.float64)
    y = np.asarray(previous, dtype=np.float64)
    m, d = x.shape
    n = y.shape[0]

    diffs = x[:, None, :] - y[None, :, :]  # (m, n, d)
    c = np.sum(diffs**2, axis=2).reshape(-1)  # row-major flatten

    a_rows = np.kron(np.eye(m), np.ones((1, n)))  # row-sum constraints
    a_cols = np.kron(np.ones((1, m)), np.eye(n))  # column-sum constraints
    a_eq = np.vstack([a_rows, a_cols])
    b_eq = np.concatenate([np.full(m, 1.0 / m), np.full(n, 1.0 / n)])

    res = scipy.optimize.linprog(c, A_eq=a_eq, b_eq=b_eq)
    if res.x is None:  # pragma: no cover - defensive
        raise RuntimeError(f"OT linear program failed: {res.message}")
    plan = res.x.reshape(m, n)
    return np.sum(plan[:, :, None] * diffs, axis=1)


def sinkhorn_plan(x, y, eps: float = 0.05, iters: int = 200,
                  tol: float | None = None, absorb_every: int = 10,
                  g_init=None, return_potentials: bool = False):
    """Entropic-OT transport plan between uniform measures on ``x`` and ``y``.

    ``eps`` is *relative*: the entropic regulariser is ``eps · mean(C)``,
    making the solver scale-free across targets.

    Implementation is **absorption-stabilised scaling** (Schmitzer-style):
    blocks of ``absorb_every`` plain Sinkhorn matvec iterations
    (``u ← a/(K v)``, ``v ← b/(Kᵀ u)`` — two streamed multiply-reduce
    passes, no transcendentals) between log-domain absorptions that fold
    ``reg·log u`` / ``reg·log v`` into the dual potentials and rebuild the
    kernel (one ``exp`` pass per block).  Measured 2.3× faster than
    all-log-domain updates at the 10k-particle north-star shard shape at
    plan agreement ~1e-8 (docs/notes.md).  The potentials start at the
    exact c-transform warm start ``f⁰_i = min_j C_ij``,
    ``g⁰_j = min_i (C_ij − f⁰_i)``, which makes the max entry of every row
    *and* every column of the initial log-kernel exactly zero (for the
    row-wise argmin ``j*``, ``g⁰_{j*} = 0`` since ``C_{ij*} − f⁰_i = 0``,
    so the row's best entry is ``0``; columns by construction) — no
    outlier row can start underflowed, however far away it sits, for two
    cheap min passes over ``C``.  **The warm start is the correctness
    guard**: a zero-init run on the same clamp-and-absorb code corrupts a
    far outlier's row outright (measured NaN/zero row mass and a zero W2
    gradient at the regression config tests/test_ot.py pins — the clamp
    only prevents division by zero within a block; repeated absorption of
    a clamped-dead row is not a general no-NaN guarantee, and the
    ``~87·reg``-per-absorption recovery walk cannot cover a far outlier's
    cost within any realistic ``iters`` budget).

    ``tol=None`` runs exactly ``iters`` iterations (compile-time-constant
    loop).  A float ``tol`` adds an early exit (``lax.while_loop`` over
    uniform absorption blocks, checked at block ends — the cap may
    overshoot ``iters`` by up to ``absorb_every − 1`` iterations on the
    final block): stop once the sup-norm change of ``log v`` per iteration
    drops below ``tol``.  Log-scaling units are the right ones — plan
    entries are stable to ~``tol`` relatively, and the equivalent
    dual-potential precision is ``tol·reg`` in cost units, so the exit
    *tracks the precision intent encoded in eps* (a tiny-``eps`` run
    converges further before exiting).  Note the exit bounds the
    *per-iteration* change only; the distance to the fixpoint is the
    geometric tail ~``delta/(1 − rate)``, so a non-contractive oscillating
    tail could in principle exit early — in practice the scaling iteration
    is contractive and the tests hold with a small atol margin.  Measured
    from the cold start at eps=0.05: ``tol=1e-2`` is reached in ~25
    iterations at the north-star shard shape (1250 × 10000) and ~75 at a
    small 200² problem, while eps=0.01 runs use the full 200 default — the
    adaptive exit serves all of these without a tuning knob (docs/notes.md).

    ``g_init`` warm-starts the solve from a previous dual potential ``g``
    (cost units, shape ``(n,)``): the start is then the **soft (entropic)
    c-transform pair of** ``g_init`` — one exact log-domain Sinkhorn
    iteration, ``f⁰_i = reg·log a_i − reg·logsumexp_j((g_init_j − C_ij)/
    reg)`` and ``g⁰`` likewise from ``f⁰``; see :func:`_sinkhorn_start`.
    Two properties: (1) the soft transform of an *optimal* ``g`` IS the
    entropic fixpoint (a hard min would land O(reg·log n) off it —
    measured ~10 residual polish iterations at the north star, vs ~0
    soft), so from a near-optimal carry the ``tol`` exit fires on the
    first block; (2) safety for *any* ``g_init`` — after the ``f⁰`` update
    every row of ``exp((f⁰+g⁰′−C)/reg)`` sums to exactly its marginal,
    so no row can start underflowed (the guarantee the cold c-transform
    start provides, in soft form).  Across consecutive SVGD steps the
    particles move by O(ε·φ), making the previous step's ``g`` that
    near-optimal carry (measured 4.4× over the cold start at the north
    star, docs/notes.md).

    ``return_potentials=True`` returns ``(plan, (f, g))`` — feed ``g`` back
    as the next solve's ``g_init``.
    """
    if absorb_every <= 0:
        raise ValueError(f"absorb_every must be positive, got {absorb_every}")
    m, n = x.shape[0], y.shape[0]
    cost = squared_distances(x, y)
    dt = cost.dtype
    if iters == 0:  # degenerate edge: the bare start, no scaling pass
        f, g = _sinkhorn_start(cost, eps, g_init)
        reg = eps * jnp.maximum(jnp.mean(cost), jnp.finfo(dt).tiny)
        plan = jnp.exp((f[:, None] + g[None, :] - cost) / reg)
        return (plan, (f, g)) if return_potentials else plan
    f, g, kmat, u, v, _ = _sinkhorn_solve(
        cost, m, n, eps, iters, tol, absorb_every, g_init
    )
    # the last block's kernel and scalings ARE the plan — rebuilding it as
    # exp((f+g−C)/reg) would spend one more full exp pass over C for the
    # same values (round-3 exp-pass accounting, docs/notes.md)
    plan = u[:, None] * kmat * v[None, :]
    if return_potentials:
        return plan, (f, g)
    return plan


def _sinkhorn_start(cost, eps: float, g_init):
    """Initial dual pair.  Cold (``g_init=None``): the hard c-transform
    pair — ``f⁰_i = min_j C_ij``, ``g⁰_j = min_i (C_ij − f⁰_i)``.  Warm: the
    SOFT (entropic) c-transform of the carried g — one exact log-domain
    Sinkhorn half-iteration.  The hard min would land O(reg·log n) off the
    entropic fixpoint even from a perfect ``g_init`` (measured ~10 polish
    iterations at the north star); the soft transform of an optimal g IS
    the fixpoint, so the ``tol`` exit fires on the first block.  Safety
    matches the cold start: after the ``f⁰`` update every row of
    ``exp((f⁰+g−C)/reg)`` sums to exactly its marginal ``a_i = 1/m``, so no
    row can start underflowed for any ``g_init``.  The second (``g⁰``)
    tightening pass is kept deliberately: skipping it was probed in round
    3 (one fewer exp pass over C) but an *arbitrary* ``g_init`` — the
    safety contract — can then drive the tol exit to fire at a
    non-solution (the garbage-init regression test catches exactly this);
    the column-side pin is what makes the start safe, not just warm."""
    dt = cost.dtype
    m, n = cost.shape
    if g_init is None:
        f0 = jnp.min(cost, axis=1)                # (m,) nearest-target cost
        g0 = jnp.min(cost - f0[:, None], axis=0)  # (n,) c-transform of f0
        return f0, g0
    reg = eps * jnp.maximum(jnp.mean(cost), jnp.finfo(dt).tiny)
    gi = g_init.astype(dt)
    lse = jax.nn.logsumexp
    f0 = reg * jnp.log(jnp.asarray(1.0 / m, dt)) - reg * lse(
        (gi[None, :] - cost) / reg, axis=1
    )
    g0 = reg * jnp.log(jnp.asarray(1.0 / n, dt)) - reg * lse(
        (f0[:, None] - cost) / reg, axis=0
    )
    return f0, g0


def _sinkhorn_scaling_loop(f0, g0, make_kernel_ops, fold_scale, m, n,
                           iters, tol, absorb_every, dt,
                           carry_kmat: bool = True):
    """The absorbed-scaling loop shared by the XLA path (below), the fused
    Pallas path, and the streaming Pallas path (ops/pallas_ot.py) — ONE
    copy of the block structure, tol-exit statistic, and u/v clamps,
    parametrised over the absorbed-kernel matvecs:

    ``make_kernel_ops(f, g) -> (mv, rmv, kmat)`` where ``mv(v) ≈ K @ v``
    and ``rmv(u) ≈ Kᵀ @ u`` against the absorbed kernel
    ``K = exp((f + g − C)·inv_reg)``.  ``kmat`` is the materialised kernel
    when one exists (dense exp over a cost matrix, or the fused
    VMEM-streaming ``kexp`` build) and is threaded through the loop carry
    so the LAST block's kernel survives for the matvec-finish gradient;
    streaming callers whose matvecs rebuild tiles from coordinates pass
    ``kmat=None`` with ``carry_kmat=False`` and the loop carries only the
    potentials (O(n·d) memory — no kernel-sized buffer ever exists).
    ``fold_scale`` sets the potential units (``reg`` in cost units,
    ``1.0`` in reg-rescaled units).

    Returns ``(f, g, kmat, u, v)`` when ``carry_kmat`` — ``plan =
    u·kmat·v`` entrywise, exactly (``f = f_pre + fold_scale·log u`` folds
    the same factors the product applies), so consumers need no further
    pass over the cost — and ``(f, g)`` otherwise.  Requires
    ``iters >= 1``.
    """
    if absorb_every <= 0:
        raise ValueError(f"absorb_every must be positive, got {absorb_every}")
    if iters < 1:
        raise ValueError(f"the scaling loop needs iters >= 1, got {iters}")
    tiny = jnp.finfo(dt).tiny
    a = jnp.asarray(1.0 / m, dt)
    b = jnp.asarray(1.0 / n, dt)

    def run_block(f, g, k_iters: int):
        """``k_iters`` scaling iterations against the absorbed kernel;
        returns folded potentials, the block's (kmat, u, v) payload, and
        the last iteration's ``log v`` sup-change (the convergence
        statistic)."""
        mv, rmv, kmat = make_kernel_ops(f, g)

        def one(v):
            u = a / jnp.maximum(mv(v), tiny)
            return u, b / jnp.maximum(rmv(u), tiny)

        v = lax.fori_loop(
            0, k_iters - 1, lambda _, v: one(v)[1], jnp.ones((n,), dt)
        )
        u, new_v = one(v)
        delta = jnp.max(jnp.abs(jnp.log(new_v) - jnp.log(v)))
        payload = (kmat, u, new_v) if carry_kmat else ()
        return (f + fold_scale * jnp.log(u), g + fold_scale * jnp.log(new_v),
                payload, delta)

    absorb_every = min(absorb_every, iters)  # short runs stay exact
    blocks, rem = divmod(iters, absorb_every)
    payload0 = (
        (jnp.zeros((m, n), dt), jnp.ones((m,), dt), jnp.ones((n,), dt))
        if carry_kmat
        else ()
    )
    if tol is None:
        def body(_, carry):
            f, g, _ = carry
            f, g, payload, _ = run_block(f, g, absorb_every)
            return f, g, payload

        f, g, payload = lax.fori_loop(0, blocks, body, (f0, g0, payload0))
        if rem:
            f, g, payload, _ = run_block(f, g, rem)
    else:
        thresh = jnp.asarray(tol, dt)
        total = blocks + (1 if rem else 0)

        def cond(carry):
            i, delta = carry[0], carry[-1]
            return (i < total) & (delta > thresh)

        def body(carry):
            i, f, g, _, _ = carry
            # uniform block length keeps one compiled body; the cap may
            # overshoot ``iters`` by < absorb_every on the last block
            f, g, payload, delta = run_block(f, g, absorb_every)
            return i + 1, f, g, payload, delta

        _, f, g, payload, _ = lax.while_loop(
            cond, body,
            (0, f0, g0, payload0, jnp.asarray(jnp.inf, dt)),
        )
    if carry_kmat:
        kmat, u, v = payload
        return f, g, kmat, u, v
    return f, g


def _sinkhorn_solve(cost, m, n, eps, iters, tol, absorb_every, g_init):
    """XLA-path solve over a materialised ``cost``: the shared scaling loop
    with a dense-exp kernel builder, in cost units."""
    dt = cost.dtype
    tiny = jnp.finfo(dt).tiny
    reg = eps * jnp.maximum(jnp.mean(cost), tiny)
    f0, g0 = _sinkhorn_start(cost, eps, g_init)

    def make_ops(f, g):
        kmat = jnp.exp((f[:, None] + g[None, :] - cost) / reg)
        return (lambda v: kmat @ v), (lambda u: kmat.T @ u), kmat

    f, g, kmat, u, v = _sinkhorn_scaling_loop(
        f0, g0, make_ops, reg, m, n, iters, tol, absorb_every, dt,
    )
    return f, g, kmat, u, v, reg


#: ``impl='auto'`` uses the fused Pallas solve (ops/pallas_ot.py) at/above
#: this many pairwise cost entries on TPU small-d problems; below it the
#: kernels' launch overheads aren't worth the one saved distance-build pass
#: (the measured win at the north-star shard shape is 1.10× — docs/notes.md).
FUSED_SINKHORN_MIN_PAIRS = 1 << 20

#: Above this many pairs ``impl='auto'`` switches to the O(n·d)-memory
#: streaming solve (ops/pallas_ot.py:sinkhorn_grad_streaming): 2²⁸ pairs is
#: a 1 GB f32 kernel matrix *per shard* — materialising one per vmap lane
#: (8 GB at S=8) is the HBM cliff the streaming path exists to avoid; below
#: it the materialised solvers are strictly faster.  Note the materialised
#: paths transiently hold ~2 kernel-sized buffers near a block boundary
#: (the loop-carried kmat plus the newly built one, on top of the cost
#: matrix), so their true OOM threshold sits somewhat below what a
#: single-kmat estimate suggests — the cliff constant is deliberately
#: conservative.  The rescue applies to
#: the streaming path's own domain only (f32, d ≤ SMALL_D); ineligible
#: problems past the cliff fall through to the materialised XLA path with
#: an explicit warning (they will likely OOM on a TPU — cast to f32 /
#: reduce d, or force ``impl='xla'`` on a large-memory host).
FUSED_SINKHORN_STREAM_MIN_PAIRS = 1 << 28


def _resolve_sinkhorn_route(x, y, impl: str):
    """Shared implementation gate of :func:`wasserstein_grad_sinkhorn` and
    :func:`sinkhorn_dual_advance`: picks ``'xla'`` / ``'fused'`` /
    ``'streaming'`` (with the streaming-rescue and forced-pallas precision
    warnings) so the two entries cannot drift on routing.  Returns
    ``(route, on_tpu)``."""
    if impl not in ("auto", "xla", "pallas"):
        raise ValueError(f"unknown sinkhorn impl {impl!r}")
    if impl == "xla":
        return "xla", False
    from dist_svgd_tpu.ops.pallas_svgd import SMALL_D, pallas_available

    on_tpu = pallas_available()
    small_d = x.shape[1] <= SMALL_D
    pairs = x.shape[0] * y.shape[0]
    big = pairs >= FUSED_SINKHORN_MIN_PAIRS
    # the fused path is f32-internal; honor other dtypes via XLA
    f32 = (x.dtype == jnp.float32 and y.dtype == jnp.float32)
    if (impl != "pallas" and on_tpu
            and pairs >= FUSED_SINKHORN_STREAM_MIN_PAIRS
            and not (small_d and f32)):
        # forced 'pallas' is exempt: it routes small-d inputs to the
        # streaming path itself (f32-internal), so the materialised-XLA
        # OOM prediction below would be wrong guidance there
        import warnings

        warnings.warn(
            f"sinkhorn solve with {pairs:.2e} cost entries (dtype "
            f"{x.dtype}, d={x.shape[1]}) is past the streaming-rescue "
            "threshold but ineligible for the O(n*d) streaming path "
            "(f32, d <= SMALL_D only); the materialised XLA solve "
            "will likely exhaust TPU HBM — cast to float32 / reduce d, "
            "or force impl='xla' deliberately on a large-memory host",
            stacklevel=3,
        )
    if impl == "pallas" or (on_tpu and small_d and big and f32):
        if not small_d:
            raise ValueError(
                f"impl='pallas' requires d <= {SMALL_D}, got {x.shape[1]}"
            )
        wider_than_f32 = any(
            jnp.issubdtype(a.dtype, jnp.floating)
            and jnp.finfo(a.dtype).bits > 32
            for a in (x, y)
        )
        if impl == "pallas" and wider_than_f32:
            # sub-f32 inputs (bf16/f16) lose nothing to the f32-internal
            # solve — only genuinely wider dtypes warrant the warning
            import warnings

            warnings.warn(
                f"impl='pallas' computes internally in float32 but got "
                f"{x.dtype}/{y.dtype} inputs; the result is cast back "
                "but carries f32 precision — use impl='xla' (or 'auto', "
                "which routes non-f32 there) for full-precision solves",
                stacklevel=3,
            )
        if pairs >= FUSED_SINKHORN_STREAM_MIN_PAIRS:
            # past the HBM cliff: never materialise the kernel matrix
            return "streaming", on_tpu
        return "fused", on_tpu
    return "xla", on_tpu


def wasserstein_grad_sinkhorn(particles, previous, eps: float = 0.05,
                              iters: int = 200, tol: float | None = None,
                              absorb_every: int = 10,
                              g_init=None, return_g: bool = False,
                              impl: str = "auto"):
    """W2 gradient from the Sinkhorn plan — same formula as the LP path:
    ``grad_i = Σ_j P_ij (x_i − y_j) = x_i · rowsum_i − P @ y``, computed
    without materialising the ``(m, n, d)`` difference tensor *or the plan
    itself*: with the last block's ``(kmat, u, v)`` the plan is
    ``diag(u)·kmat·diag(v)``, so ``rowsum = u ⊙ (kmat @ v)`` and ``P @ y =
    u ⊙ (kmat @ (v ⊙ y))`` are two cheap matvecs against the
    already-materialised kernel instead of a fresh exp pass over ``C``
    (round-3 exp-pass accounting, docs/notes.md).

    ``g_init`` / ``return_g`` thread the dual potential ``g`` through for
    warm-starting consecutive solves (see :func:`sinkhorn_plan`); only ``g``
    needs carrying — ``f`` is re-derived as its soft c-transform each
    solve.

    ``impl``: ``'auto'`` (the fused Pallas solve — cost tiles recomputed in
    VMEM, no C matrix — on TPU for d ≤ SMALL_D at
    ``FUSED_SINKHORN_MIN_PAIRS``+ sizes, measured 1.10× at the north star;
    the XLA path otherwise), ``'xla'``, or ``'pallas'`` (force; runs the
    Pallas interpreter off-TPU — slow, for testing).  Identical semantics
    either way (tests/test_pallas_ot.py).  The Pallas solvers are
    f32-internal: ``'auto'`` routes non-f32 inputs to the XLA path, but a
    *forced* ``'pallas'`` computes in f32 and casts the result back —
    a ``UserWarning`` flags the precision loss on f64 inputs."""
    if impl not in ("auto", "xla", "pallas"):
        raise ValueError(f"unknown sinkhorn impl {impl!r}")
    x = particles
    y = previous
    if iters == 0:
        # bare-start edge: gradient from the start-pair plan (no block ran,
        # so there is no (kmat, u, v) to finish from)
        plan, (_, g) = sinkhorn_plan(
            x, y, eps=eps, iters=0, absorb_every=absorb_every,
            g_init=g_init, return_potentials=True,
        )
        grad = x * jnp.sum(plan, axis=1)[:, None] - plan @ y
        return (grad, g) if return_g else grad
    route, on_tpu = _resolve_sinkhorn_route(x, y, impl)
    if route != "xla":
        from dist_svgd_tpu.ops.pallas_ot import (
            sinkhorn_grad_fused,
            sinkhorn_grad_streaming,
        )

        fn = sinkhorn_grad_streaming if route == "streaming" else sinkhorn_grad_fused
        return fn(
            x, y, eps=eps, iters=iters, tol=tol,
            absorb_every=absorb_every, g_init=g_init, return_g=return_g,
            interpret=not on_tpu,
        )
    cost = squared_distances(x, y)
    _, g, kmat, u, v, _ = _sinkhorn_solve(
        cost, x.shape[0], y.shape[0], eps, iters, tol, absorb_every, g_init
    )
    # both matvecs feed the gradient directly: HIGHEST, not the MXU's
    # default bf16 passes (same reasoning as ops/kernels.py:squared_distances)
    row = u * jnp.matmul(
        kmat, v[:, None], precision=jax.lax.Precision.HIGHEST
    )[:, 0]
    py = u[:, None] * jnp.matmul(
        kmat, v[:, None] * y, precision=jax.lax.Precision.HIGHEST
    )
    grad = x * row[:, None] - py
    if return_g:
        return grad, g
    return grad


def sinkhorn_dual_advance(particles, previous, eps: float = 0.05,
                          iters: int = 200, tol: float | None = None,
                          absorb_every: int = 10, g_init=None,
                          impl: str = "auto"):
    """Advance the Sinkhorn dual potential ``g`` by up to ``iters`` scaling
    iterations WITHOUT the gradient finish — the resumable half of
    :func:`wasserstein_grad_sinkhorn`, as a first-class entry.

    The carried ``g`` already makes *consecutive* solves resumable (each
    call restarts from the soft c-transform pair of ``g_init``); this entry
    makes that a within-step chunk: a host loop splits one logical solve of
    ``I`` iterations into bounded dispatches of ``max_passes_per_dispatch``
    — ``g = sinkhorn_dual_advance(x, y, iters=passes, g_init=g)`` repeated,
    with only the terminal chunk paying the gradient finish
    (``wasserstein_grad_sinkhorn(..., g_init=g, return_g=True)``).  This is
    what ``DistSampler.run_steps(dispatch_budget=...)`` uses to keep every
    W2 dispatch under the TPU tunnel's execution watchdog at large n,
    replacing the ad-hoc protocol of shrinking ``sinkhorn_iters`` to bound
    the *whole step's* dispatch.

    Each resume costs the two soft-c-transform start passes; the start pair
    is one exact log-domain iteration from ``g_init``, so a split solve
    sits a few effective iterations *ahead* of the unsplit one, never
    behind — at convergence split ≡ unsplit (tests/test_chunked.py).  With
    ``tol`` set, chunks after convergence collapse to the start passes
    alone (the streaming path's ``delta0`` early exit).

    Returns ``g`` in cost units, ready to feed back as ``g_init``.
    """
    if impl not in ("auto", "xla", "pallas"):
        raise ValueError(f"unknown sinkhorn impl {impl!r}")
    x, y = particles, previous
    route, on_tpu = _resolve_sinkhorn_route(x, y, impl)
    if iters == 0:
        if route == "streaming":
            # the bare start pair without ever materialising C — at
            # streaming sizes the dense sinkhorn_plan path below would
            # build exactly the matrix this route exists to avoid
            from dist_svgd_tpu.ops.pallas_ot import _solve_setup

            _, _, _, g0, _, reg, *_ = _solve_setup(
                x, y, eps, g_init, not on_tpu)
            return (g0 * reg).astype(x.dtype)
        _, (_, g) = sinkhorn_plan(
            x, y, eps=eps, iters=0, absorb_every=absorb_every,
            g_init=g_init, return_potentials=True,
        )
        return g
    if route != "xla":
        from dist_svgd_tpu.ops.pallas_ot import (
            sinkhorn_grad_fused,
            sinkhorn_grad_streaming,
        )

        fn = sinkhorn_grad_streaming if route == "streaming" else sinkhorn_grad_fused
        return fn(
            x, y, eps=eps, iters=iters, tol=tol,
            absorb_every=absorb_every, g_init=g_init, duals_only=True,
            interpret=not on_tpu,
        )
    cost = squared_distances(x, y)
    _, g, _, _, _, _ = _sinkhorn_solve(
        cost, x.shape[0], y.shape[0], eps, iters, tol, absorb_every, g_init
    )
    return g
