"""Wasserstein-2 / JKO proximal term.

The reference adds an optional W2 gradient to each SVGD step
(dsvgd/distsampler.py:103-129, applied at :190-198): solve the discrete-OT
linear program between the current particles ``x`` (weights 1/m) and the
previous step's particles ``y`` (weights 1/n) with cost ``‖x_i − y_j‖²``, then

    w_grad_i = Σ_j  plan_ij · (x_i − y_j).

Two solvers are provided:

- :func:`wasserstein_grad_lp` — exact-parity path: the same dense LP the
  reference builds, solved on the **host** with ``scipy.optimize.linprog``.
  O((m+n)·m·n) constraint matrix — the reference's single biggest scalability
  cliff (SURVEY.md §3.3); kept for fidelity and as the oracle for tests.
- :func:`wasserstein_grad_sinkhorn` — TPU-native fast path: entropic OT via
  absorption-stabilised Sinkhorn scaling (matvec blocks between log-domain
  absorptions — see :func:`sinkhorn_plan`), fully jittable and fusable
  into the sharded step (fixed-count loop, or a ``lax.while_loop`` bounded
  by ``iters`` when the ``tol`` early exit is enabled — the ``DistSampler``
  default).  Converges to the LP plan as ``eps → 0``; tested against the
  LP on small problems.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from dist_svgd_tpu.ops.kernels import squared_distances


def wasserstein_grad_lp(particles, previous) -> np.ndarray:
    """Exact discrete-OT W2 gradient via the host LP (reference parity).

    Builds the same flattened cost/equality system as the reference
    (dsvgd/distsampler.py:111-127): ``c`` is the row-major flattened squared
    distance matrix, the first ``m`` rows of ``A_eq`` constrain row sums to
    ``1/m``, the next ``n`` rows constrain column sums to ``1/n``.  scipy's
    modern default (HiGHS) replaces the scipy-1.1-era simplex; both return a
    vertex solution (a matching when ``m == n``).
    """
    import scipy.optimize

    x = np.asarray(particles, dtype=np.float64)
    y = np.asarray(previous, dtype=np.float64)
    m, d = x.shape
    n = y.shape[0]

    diffs = x[:, None, :] - y[None, :, :]  # (m, n, d)
    c = np.sum(diffs**2, axis=2).reshape(-1)  # row-major flatten

    a_rows = np.kron(np.eye(m), np.ones((1, n)))  # row-sum constraints
    a_cols = np.kron(np.ones((1, m)), np.eye(n))  # column-sum constraints
    a_eq = np.vstack([a_rows, a_cols])
    b_eq = np.concatenate([np.full(m, 1.0 / m), np.full(n, 1.0 / n)])

    res = scipy.optimize.linprog(c, A_eq=a_eq, b_eq=b_eq)
    if res.x is None:  # pragma: no cover - defensive
        raise RuntimeError(f"OT linear program failed: {res.message}")
    plan = res.x.reshape(m, n)
    return np.sum(plan[:, :, None] * diffs, axis=1)


def sinkhorn_plan(x, y, eps: float = 0.05, iters: int = 200,
                  tol: float | None = None, absorb_every: int = 10,
                  g_init=None, return_potentials: bool = False):
    """Entropic-OT transport plan between uniform measures on ``x`` and ``y``.

    ``eps`` is *relative*: the entropic regulariser is ``eps · mean(C)``,
    making the solver scale-free across targets.

    Implementation is **absorption-stabilised scaling** (Schmitzer-style):
    blocks of ``absorb_every`` plain Sinkhorn matvec iterations
    (``u ← a/(K v)``, ``v ← b/(Kᵀ u)`` — two streamed multiply-reduce
    passes, no transcendentals) between log-domain absorptions that fold
    ``reg·log u`` / ``reg·log v`` into the dual potentials and rebuild the
    kernel (one ``exp`` pass per block).  Measured 2.3× faster than
    all-log-domain updates at the 10k-particle north-star shard shape at
    plan agreement ~1e-8 (docs/notes.md).  The potentials start at the
    exact c-transform warm start ``f⁰_i = min_j C_ij``,
    ``g⁰_j = min_i (C_ij − f⁰_i)``, which makes the max entry of every row
    *and* every column of the initial log-kernel exactly zero (for the
    row-wise argmin ``j*``, ``g⁰_{j*} = 0`` since ``C_{ij*} − f⁰_i = 0``,
    so the row's best entry is ``0``; columns by construction) — no
    outlier row can start underflowed, however far away it sits, for two
    cheap min passes over ``C``.  **The warm start is the correctness
    guard**: a zero-init run on the same clamp-and-absorb code corrupts a
    far outlier's row outright (measured NaN/zero row mass and a zero W2
    gradient at the regression config tests/test_ot.py pins — the clamp
    only prevents division by zero within a block; repeated absorption of
    a clamped-dead row is not a general no-NaN guarantee, and the
    ``~87·reg``-per-absorption recovery walk cannot cover a far outlier's
    cost within any realistic ``iters`` budget).

    ``tol=None`` runs exactly ``iters`` iterations (compile-time-constant
    loop).  A float ``tol`` adds an early exit (``lax.while_loop`` over
    uniform absorption blocks, checked at block ends — the cap may
    overshoot ``iters`` by up to ``absorb_every − 1`` iterations on the
    final block): stop once the sup-norm change of ``log v`` per iteration
    drops below ``tol``.  Log-scaling units are the right ones — plan
    entries are stable to ~``tol`` relatively, and the equivalent
    dual-potential precision is ``tol·reg`` in cost units, so the exit
    *tracks the precision intent encoded in eps* (a tiny-``eps`` run
    converges further before exiting).  Note the exit bounds the
    *per-iteration* change only; the distance to the fixpoint is the
    geometric tail ~``delta/(1 − rate)``, so a non-contractive oscillating
    tail could in principle exit early — in practice the scaling iteration
    is contractive and the tests hold with a small atol margin.  Measured
    from the cold start at eps=0.05: ``tol=1e-2`` is reached in ~25
    iterations at the north-star shard shape (1250 × 10000) and ~75 at a
    small 200² problem, while eps=0.01 runs use the full 200 default — the
    adaptive exit serves all of these without a tuning knob (docs/notes.md).

    ``g_init`` warm-starts the solve from a previous dual potential ``g``
    (cost units, shape ``(n,)``): the start is then the **soft (entropic)
    c-transform pair of** ``g_init`` — one exact log-domain Sinkhorn
    iteration, ``f⁰_i = reg·log a_i − reg·logsumexp_j((g_init_j − C_ij)/
    reg)`` and ``g⁰`` likewise from ``f⁰``.  Two properties: (1) the soft
    transform of an *optimal* ``g`` IS the entropic fixpoint (a hard min
    would land O(reg·log n) off it — measured ~10 residual polish
    iterations at the north star, vs ~0 soft), so from a near-optimal
    carry the ``tol`` exit fires on the first block; (2) safety for *any*
    ``g_init`` — after the ``f⁰`` update every row of
    ``exp((f⁰+g⁰′−C)/reg)`` sums to exactly its marginal, so no row can
    start underflowed (the guarantee the cold c-transform start provides,
    in soft form).  Across consecutive SVGD steps the particles move by
    O(ε·φ), making the previous step's ``g`` that near-optimal carry
    (measured 4.4× over the cold start at the north star, docs/notes.md).

    ``return_potentials=True`` returns ``(plan, (f, g))`` — feed ``g`` back
    as the next solve's ``g_init``.
    """
    if absorb_every <= 0:
        raise ValueError(f"absorb_every must be positive, got {absorb_every}")
    m, n = x.shape[0], y.shape[0]
    cost = squared_distances(x, y)
    dt = cost.dtype
    tiny = jnp.finfo(dt).tiny
    mean_c = jnp.maximum(jnp.mean(cost), tiny)
    reg = eps * mean_c
    a = jnp.asarray(1.0 / m, dt)
    b = jnp.asarray(1.0 / n, dt)

    def run_block(f, g, k_iters: int):
        """``k_iters`` scaling iterations against the absorbed kernel;
        returns the new potentials and the last iteration's ``log v``
        sup-change (the convergence statistic)."""
        kmat = jnp.exp((f[:, None] + g[None, :] - cost) / reg)

        def one(v):
            u = a / jnp.maximum(kmat @ v, tiny)
            return u, b / jnp.maximum(kmat.T @ u, tiny)

        v = lax.fori_loop(
            0, k_iters - 1, lambda _, v: one(v)[1], jnp.ones((n,), dt)
        )
        u, new_v = one(v)
        delta = jnp.max(jnp.abs(jnp.log(new_v) - jnp.log(v)))
        return f + reg * jnp.log(u), g + reg * jnp.log(new_v), delta

    if g_init is None:
        f0 = jnp.min(cost, axis=1)                # (m,) nearest-target cost
        g0 = jnp.min(cost - f0[:, None], axis=0)  # (n,) c-transform of f0
    else:
        # SOFT (entropic) c-transform pair of the carried g — one exact
        # log-domain Sinkhorn iteration.  The hard min would land
        # O(reg·log n) off the entropic fixpoint even from a perfect
        # g_init (measured ~10 polish iterations at the north star); the
        # soft transform of an optimal g IS the fixpoint, so the tol exit
        # fires on the first block.  Safety matches the cold start: after
        # the f0 update every row of exp((f0+g−C)/reg) sums to exactly
        # its marginal a_i = 1/m, so no row can start underflowed for any
        # g_init.
        gi = g_init.astype(dt)
        lse = jax.nn.logsumexp
        f0 = reg * jnp.log(a) - reg * lse((gi[None, :] - cost) / reg, axis=1)
        g0 = reg * jnp.log(b) - reg * lse((f0[:, None] - cost) / reg, axis=0)
    if iters:
        absorb_every = min(absorb_every, iters)  # short runs stay exact
    blocks, rem = divmod(iters, absorb_every)
    if tol is None:
        def body(_, carry):
            f, g = carry
            f, g, _ = run_block(f, g, absorb_every)
            return f, g

        f, g = lax.fori_loop(0, blocks, body, (f0, g0))
        if rem:
            f, g, _ = run_block(f, g, rem)
    else:
        thresh = jnp.asarray(tol, dt)
        total = blocks + (1 if rem else 0)

        def cond(carry):
            i, _, _, delta = carry
            return (i < total) & (delta > thresh)

        def body(carry):
            i, f, g, _ = carry
            # uniform block length keeps one compiled body; the cap may
            # overshoot ``iters`` by < absorb_every on the last block
            f, g, delta = run_block(f, g, absorb_every)
            return i + 1, f, g, delta

        _, f, g, _ = lax.while_loop(
            cond, body, (0, f0, g0, jnp.asarray(jnp.inf, dt))
        )
    plan = jnp.exp((f[:, None] + g[None, :] - cost) / reg)
    if return_potentials:
        return plan, (f, g)
    return plan


def wasserstein_grad_sinkhorn(particles, previous, eps: float = 0.05,
                              iters: int = 200, tol: float | None = None,
                              absorb_every: int = 10,
                              g_init=None, return_g: bool = False):
    """W2 gradient from the Sinkhorn plan — same formula as the LP path:
    ``grad_i = Σ_j P_ij (x_i − y_j) = x_i · rowsum_i − P @ y``, computed
    without materialising the ``(m, n, d)`` difference tensor.

    ``g_init`` / ``return_g`` thread the dual potential ``g`` through for
    warm-starting consecutive solves (see :func:`sinkhorn_plan`); only ``g``
    needs carrying — ``f`` is re-derived as its c-transform each solve."""
    out = sinkhorn_plan(particles, previous, eps=eps, iters=iters, tol=tol,
                        absorb_every=absorb_every,
                        g_init=g_init, return_potentials=return_g)
    plan, pots = out if return_g else (out, None)
    row = jnp.sum(plan, axis=1)
    grad = particles * row[:, None] - plan @ previous
    if return_g:
        return grad, pots[1]
    return grad
