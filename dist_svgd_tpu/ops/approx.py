"""Sub-quadratic φ: random-feature and Nyström kernel approximations.

Every φ backend in :mod:`dist_svgd_tpu.ops.svgd` / ``pallas_svgd`` evaluates
the exact RBF Gram matrix — O(n²) pairwise interactions per step, the
scalability wall between the measured 2M-particle rows and the 10M+ regime
(ROADMAP item 2; PAPER.md §0's fixed-bandwidth RBF is what makes the
closed forms below available).  This module provides two drop-in φ
approximations with the **same** ``phi_fn(updated, interacting, scores)``
signature as the exact backends, so everything built on that seam —
mesh sharding, ring/gather exchange, dispatch-budget chunking, the W2
proximal term — composes unchanged through ``resolve_phi_fn``:

- **Random Fourier features** (Rahimi & Recht 2007): ``k(x, y) =
  exp(-‖x−y‖²/h) = E_w[cos(wᵀ(x−y))]`` with ``w ~ N(0, (2/h)·I)``.  With a
  shared R-frequency bank the SVGD drive term collapses to two
  feature-space matmuls through the ``(2R, d)`` summary ``Φ(X)ᵀS`` and the
  repulsive term to one more through the analytic feature gradient —
  O((m+k)·R·d) total, the ``(m, k)`` Gram matrix never exists.  Error
  ~O(1/√R), dialled by ``num_features``.
- **Nyström landmarks**: ``k̂(x, y) = k(x, Z) (K_ZZ + λI)⁻¹ k(Z, y)`` over
  an evenly-strided L-point landmark set Z re-selected from each call's
  interaction set (so landmarks track the moving particles with no carried
  state).  Both φ terms factor through Cholesky solves against the (L, L)
  landmark system (the Woodbury/normal-equations factor) — O(n·L·d + L³),
  with the exact-recovery property k̂ → k as L → m.

Both are **linear in the interaction set**, which is what makes the ring
exchange's hop-accumulated φ (``parallel/exchange.py``) and the chunked
dispatch executors correct without modification: the sum of per-block
approximate φ contributions is the approximate φ of the (blockwise-
approximated) whole.  Under the ring, each hop approximates its visiting
block with that block's own features/landmarks — same O(n/S) per-device
memory story as the exact ring.

Bandwidth discipline: the closed forms above are functions of ONE static
bandwidth.  ``kernel='median'`` therefore resolves the bandwidth *before*
the bank/landmark machinery is built (the samplers order it that way), and
``AdaptiveRBF`` (``kernel='median_step'``) is refused for ``'rff'`` at the
default ``rff_redraw='run'`` — the bank is drawn once at a frozen
bandwidth, and per-step drift would silently decalibrate it.
``rff_redraw='step'`` (round 18) lifts that refusal: the bank is re-drawn
**inside the compiled program every step** from ``fold_in(bank_root, t)``,
so under the adaptive rescaling identity each step's fresh bandwidth-1
bank estimates the step's own median-bandwidth kernel — and the per-step
randomness is independent across steps (no frozen-bank error correlation).
A redraw-per-step φ needs the step index: its ``phi_fn`` carries
``needs_step = True`` and the samplers bind ``t`` via :func:`bind_phi_step`
at the one place each step program knows its absolute index (the same
``(root, t)`` fold the minibatch stream uses, so chunk boundaries and
reshards are invisible to the bank stream too).  ``'nystrom'`` composes
with the adaptive bandwidth through the exact rescaling identity
(landmarks are re-selected and re-factored per call anyway).
"""

from __future__ import annotations

import math
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from dist_svgd_tpu.ops.kernels import RBF, squared_distances

APPROX_METHODS = ("rff", "nystrom")

#: RFF bank lifetimes: one bank per run (a compile-time constant) or a
#: fresh bank per step (``fold_in(bank_root, t)`` inside the program).
RFF_REDRAW_MODES = ("run", "step")

#: ``state_dict`` encoding of the approximation method (orbax/tensorstore
#: cannot serialise unicode arrays — same convention as ``W2_PAIRING_CODES``).
APPROX_METHOD_CODES = APPROX_METHODS

#: ``'auto'`` crossover factor: the approximate φ is preferred once the
#: exact Gram pair count ``k·m`` exceeds ``factor × (k+m) × F`` feature
#: evaluations (F = 2·num_features for RFF — cos and sin banks — and
#: num_landmarks for Nyström).  1.0 is the flop-balance point; the measured
#: CPU walls cross within ~2× of it at every probed shape (docs/notes.md
#: round-17 crossover table), and below it the exact kernel is both faster
#: AND exact, so ties go to exact.
APPROX_CROSSOVER_FACTOR = 1.0


class KernelApprox:
    """Static configuration of a sub-quadratic φ approximation.

    Args:
        method: ``'rff'`` or ``'nystrom'``.
        num_features: RFF frequency count R (the bank holds R cos + R sin
            features).  The accuracy dial: φ error ~O(1/√R).
        num_landmarks: Nyström landmark count L (strided from each call's
            interaction set).  Exact at L = m.
        ridge: Tikhonov jitter on the (L, L) landmark system — keeps the
            Cholesky factor well-posed when the smooth RBF spectrum makes
            K_ZZ numerically rank-deficient in f32 (measured: 1e-6 NaNs
            the factor from L=1024, 1e-5 from L=2048; 1e-4 is stable
            through L=4096 at ≤ 3e-4 added relative φ error —
            docs/notes.md round 17).
        key: PRNG key the RFF bank is drawn from (``utils/rng.py:
            approx_bank_key``).  The samplers derive it from the run seed;
            direct ``resolve_phi_fn`` users must supply it for ``'rff'``.
        rff_redraw: ``'run'`` (default — one bank per run, an eager
            compile-time constant shared by every shard and step) or
            ``'step'`` (the bank is re-drawn inside the compiled program
            each step from ``fold_in(key, t)`` — ``key`` becomes the *bank
            root*; the resulting φ carries ``needs_step = True`` and must
            be bound with :func:`bind_phi_step`).  ``'step'`` is what
            composes with the per-step median bandwidth
            (``kernel='median_step'``); it costs one (R, d) normal draw
            per step inside the program.

    Instances are static configuration (close over them, like
    :class:`~dist_svgd_tpu.ops.kernels.RBF`); :meth:`cache_token` is the
    hashable identity compile caches key on.
    """

    def __init__(self, method: str, num_features: int = 2048,
                 num_landmarks: int = 1024, ridge: float = 1e-4, key=None,
                 rff_redraw: str = "run"):
        if method not in APPROX_METHODS:
            raise ValueError(
                f"unknown kernel_approx method {method!r} "
                f"(expected one of {APPROX_METHODS})"
            )
        if num_features < 1:
            raise ValueError(f"num_features must be >= 1, got {num_features}")
        if num_landmarks < 1:
            raise ValueError(f"num_landmarks must be >= 1, got {num_landmarks}")
        if ridge < 0:
            raise ValueError(f"ridge must be >= 0, got {ridge}")
        if rff_redraw not in RFF_REDRAW_MODES:
            raise ValueError(
                f"unknown rff_redraw {rff_redraw!r} "
                f"(expected one of {RFF_REDRAW_MODES})"
            )
        if rff_redraw != "run" and method != "rff":
            raise ValueError(
                f"rff_redraw={rff_redraw!r} applies to method='rff' only "
                f"(got method={method!r}: Nyström landmarks re-factor every "
                "call already)"
            )
        self.method = method
        self.num_features = int(num_features)
        self.num_landmarks = int(num_landmarks)
        self.ridge = float(ridge)
        self.key = key
        self.rff_redraw = rff_redraw

    @property
    def feature_count(self) -> int:
        """Per-row feature work F the crossover compares against ``k·m``."""
        return (2 * self.num_features if self.method == "rff"
                else self.num_landmarks)

    @property
    def accuracy_dial(self) -> int:
        """The method's accuracy parameter (R or L)."""
        return (self.num_features if self.method == "rff"
                else self.num_landmarks)

    def with_key(self, key) -> "KernelApprox":
        """A copy bound to ``key`` (the samplers bind the per-run bank key
        here; idempotent when the key is unchanged)."""
        out = KernelApprox(self.method, self.num_features,
                           self.num_landmarks, self.ridge, key,
                           self.rff_redraw)
        return out

    def cache_token(self):
        """Hashable identity for compile caches (the key by value, not by
        array object — two samplers at the same seed share programs)."""
        kb = (None if self.key is None
              else np.asarray(self.key).tobytes())
        return (self.method, self.num_features, self.num_landmarks,
                self.ridge, kb, self.rff_redraw)

    def __repr__(self) -> str:  # pragma: no cover
        dial = (f"num_features={self.num_features}" if self.method == "rff"
                else f"num_landmarks={self.num_landmarks}")
        return f"KernelApprox({self.method!r}, {dial})"

    def __eq__(self, other) -> bool:
        return (isinstance(other, KernelApprox)
                and other.cache_token() == self.cache_token())

    def __hash__(self) -> int:
        return hash(self.cache_token())


def as_kernel_approx(spec: Union[None, str, KernelApprox]
                     ) -> Optional[KernelApprox]:
    """Normalise the samplers' ``kernel_approx=`` argument: ``None`` passes
    through, the strings ``'rff'``/``'nystrom'`` take the default dials, a
    :class:`KernelApprox` instance is used as-is."""
    if spec is None or isinstance(spec, KernelApprox):
        return spec
    if isinstance(spec, str):
        return KernelApprox(spec)
    raise ValueError(
        f"kernel_approx must be None, 'rff', 'nystrom', or a KernelApprox "
        f"instance, got {spec!r}"
    )


def is_gram_free(phi_impl, approx_active: bool) -> bool:
    """Whether the resolved φ backend avoids materializing the n×n Gram
    matrix in device memory — the declaration the program auditor's XP001
    rule arms on (``analysis/audit.py``).

    True for the Pallas kernel (the Gram tile lives in VMEM only, never
    HBM — BENCH_r05's whole premise) and for an *active* rff/nystrom
    approximation (O(n·R) / O(n·L) features by construction).  The exact
    XLA φ legitimately materializes (m, n) blocks and must NOT declare —
    a false declaration turns the baseline red, which is the point: the
    declaration is a contract, not a hint."""
    return bool(approx_active) or str(phi_impl).startswith("pallas")


def approx_preferred(k_eff: int, m: int, feature_count: int) -> bool:
    """The ``'auto'`` crossover: approximate once the exact pair count beats
    the feature work (:data:`APPROX_CROSSOVER_FACTOR`).  ``k_eff`` is the
    effective output-row count ``k × batch_hint`` — under vmap emulation all
    lanes run as one batched kernel, and scaling k by the lane count makes
    the decision a function of the GLOBAL shape, so 1-shard and 8-shard
    runs of the same problem pick the same backend (shard invariance)."""
    return k_eff * m >= APPROX_CROSSOVER_FACTOR * (k_eff + m) * feature_count


def default_error_budget(approx: KernelApprox, d: int) -> float:
    """The auto-resolved relative-φ-error ceiling the small-n pin (and the
    ``large_n_approx`` bench gate) holds the approximation to, as a
    function of the accuracy dial and the feature dimension.

    RFF: each kernel entry carries ~1/√R standard error, and the φ
    drive/repulse sums cancel more strongly as d grows (pairwise distances
    concentrate, so the *relative* residual inflates ~√d) — the calibrated
    envelope is ``3.5·√d/√R``, measured at ≤ 0.8× of itself across seeds
    0–2, n ∈ {256..2048}, d ∈ {3, 8, 20}, R ∈ {256..8192} on the
    canonical transient probe (:func:`error_pin_probe`; the calibration
    table is reproduced by tests/test_approx.py).  Nyström converges much
    faster on smooth RBF spectra (exact at L = m); ``2·√d/√L`` envelopes
    the same measurements.

    The budget is defined for the **transient** (non-equilibrium) φ the
    probe generates.  At convergence φ → 0 and any approximation's
    *relative* residual grows without bound while the absolute update
    shrinks with it — gauge readers (``record_phi_residual``) should trend
    the raw residual, not alarm on it alone."""
    if approx.method == "rff":
        return 3.5 * math.sqrt(d) / math.sqrt(approx.num_features)
    return 2.0 * math.sqrt(d) / math.sqrt(approx.num_landmarks)


def error_pin_probe(n: int, d: int, seed: int = 0):
    """The canonical small-n configuration the error budget is pinned on:
    a broad, off-center ensemble (``2.5·N(0,1) + 1.5``) against a
    standard-normal target score — the transient regime where φ is O(1)
    mass transport, which is what the approximation must get right (an
    at-equilibrium probe has φ ≈ 0 and no meaningful relative error).
    Returns ``(particles, scores, kernel)`` with the kernel at the probe's
    own median-heuristic bandwidth — the regime the samplers run."""
    from dist_svgd_tpu.ops.kernels import median_bandwidth
    from dist_svgd_tpu.utils.rng import as_key

    key = as_key(seed)
    x = 2.5 * jax.random.normal(key, (n, d), dtype=jnp.float32) + 1.5
    return x, -x, RBF(float(median_bandwidth(x)))


# --------------------------------------------------------------------- #
# random Fourier features


def rff_frequencies(key, num_features: int, d: int, bandwidth: float,
                    dtype=jnp.float32) -> jax.Array:
    """The shared frequency bank ``W`` (R, d): iid ``N(0, (2/h)·I)`` rows,
    the spectral measure of ``exp(-‖δ‖²/h)``.  Drawn from ``key`` alone —
    every shard (and every resumed run) derives the identical bank."""
    base = jax.random.normal(key, (num_features, d), dtype=dtype)
    return base * float(np.sqrt(2.0 / float(bandwidth)))


def phi_rff(updated: jax.Array, interacting: jax.Array, scores: jax.Array,
            freqs: jax.Array) -> jax.Array:
    """Feature-space φ̂* — drop-in for ``ops.svgd.phi`` at O((m+k)·R·d).

    With ``Φ(x) = R^{-1/2}[cos(Wx); sin(Wx)]`` (so ``ΦᵀΦ`` is the unbiased
    kernel estimate):

    - drive  ``Σ_j k̂(x_j, y)·s_j = Φ(y)ᵀ(Φ(X)ᵀS)`` — the ``(2R, d)``
      summary is computed once over the interaction set;
    - repulse ``Σ_j ∇_{x_j}k̂(x_j, y) = (1/R)·[sin(Wy)⊙Σcos − cos(Wy)⊙Σsin]·W``
      — the analytic feature gradient summed over the set (the ∇K term in
      closed form; no autodiff, no (m, k, d) tensor).

    Never materialises any (m, k) array; the largest temporaries are the
    (m, R)/(k, R) feature blocks.
    """
    m = interacting.shape[0]
    num_features = freqs.shape[0]
    hi = jax.lax.Precision.HIGHEST
    w = freqs.astype(jnp.promote_types(updated.dtype, jnp.float32))
    # HIGHEST on the projection: phase errors pass through cos/sin at unit
    # gain, same argument as the exact path's distance matmul
    xw = jnp.matmul(interacting, w.T, precision=hi)   # (m, R)
    yw = jnp.matmul(updated, w.T, precision=hi)       # (k, R)
    cx, sx = jnp.cos(xw), jnp.sin(xw)
    cy, sy = jnp.cos(yw), jnp.sin(yw)
    a_cos = jnp.matmul(cx.T, scores, precision=hi)    # (R, d)
    a_sin = jnp.matmul(sx.T, scores, precision=hi)
    drive = (jnp.matmul(cy, a_cos, precision=hi)
             + jnp.matmul(sy, a_sin, precision=hi))
    sum_c = jnp.sum(cx, axis=0)                       # (R,)
    sum_s = jnp.sum(sx, axis=0)
    repulse = jnp.matmul(sy * sum_c[None, :] - cy * sum_s[None, :], w,
                         precision=hi)
    return (drive + repulse) / (num_features * m)


# --------------------------------------------------------------------- #
# Nyström landmarks


def nystrom_landmark_indices(m: int, num_landmarks: int) -> np.ndarray:
    """Evenly-strided landmark indices into an ``m``-row interaction set —
    the same ceil-stride subsample convention as ``median_bandwidth``
    (deterministic, layout-free, no carried state).  At ``L ≥ m`` every row
    is a landmark and the approximation is exact (up to the ridge)."""
    if num_landmarks >= m:
        return np.arange(m)
    stride = -(-m // num_landmarks)  # ceil: at most num_landmarks rows
    return np.arange(0, m, stride)


def phi_nystrom(updated: jax.Array, interacting: jax.Array,
                scores: jax.Array, bandwidth: float, num_landmarks: int,
                ridge: float = 1e-4) -> jax.Array:
    """Landmark-factored φ̂* — drop-in for ``ops.svgd.phi`` at O(n·L·d + L³).

    Landmarks Z are the strided rows of THIS call's interaction set, so
    they track the particle flow step by step with no carried state (and a
    resharded resume re-derives them from the checkpointed particles).
    Both φ terms route through one Cholesky factor of ``K_ZZ + λI``:

    - drive  ``k(y, Z)·(K_ZZ+λI)⁻¹·(K_XZᵀ S)``;
    - repulse ``k(y, Z)·(K_ZZ+λI)⁻¹·G`` with ``G_l = Σ_j ∇_{x_j}k(x_j, z_l)
      = -(2/h)(K_XZᵀX − diag(colsum)·Z)_l`` — the analytic RBF gradient
      summed in closed form (ops/svgd.py's repulse identity, applied at
      the landmarks).
    """
    m = interacting.shape[0]
    idx = jnp.asarray(nystrom_landmark_indices(m, num_landmarks))
    z = jnp.take(interacting, idx, axis=0)            # (L, d)
    inv_h = 1.0 / float(bandwidth)
    kzz = jnp.exp(-squared_distances(z, z) * inv_h)
    kzz = kzz + ridge * jnp.eye(z.shape[0], dtype=kzz.dtype)
    kxz = jnp.exp(-squared_distances(interacting, z) * inv_h)  # (m, L)
    kyz = jnp.exp(-squared_distances(updated, z) * inv_h)      # (k, L)
    hi = jax.lax.Precision.HIGHEST
    cf = jax.scipy.linalg.cho_factor(kzz)
    drive_c = jax.scipy.linalg.cho_solve(
        cf, jnp.matmul(kxz.T, scores, precision=hi))           # (L, d)
    colsum = jnp.sum(kxz, axis=0)                              # (L,)
    grad_sum = -(2.0 * inv_h) * (
        jnp.matmul(kxz.T, interacting, precision=hi) - colsum[:, None] * z
    )
    rep_c = jax.scipy.linalg.cho_solve(cf, grad_sum)
    return jnp.matmul(kyz, drive_c + rep_c, precision=hi) / m


# --------------------------------------------------------------------- #
# φ-backend construction (the resolve_phi_fn plug-in)


def bind_phi_step(phi_fn, t):
    """Bind the absolute step index ``t`` into a redraw-per-step φ
    (``phi_fn.needs_step``); a no-op passthrough for every other backend.
    The samplers call this at the one place each step program knows its
    absolute index — the same spot the minibatch key folds ``(root, t)`` —
    so chunked, scanned, and resumed executions all fold the identical
    bank stream."""
    if getattr(phi_fn, "needs_step", False):
        return lambda y, x, s: phi_fn(y, x, s, t=t)
    return phi_fn


def make_approx_phi_fn(kernel: RBF, approx: KernelApprox):
    """Build the approximate ``phi_fn(updated, interacting, scores)`` for a
    fixed-bandwidth RBF kernel.  The RFF bank is derived lazily per feature
    dimension from the spec's key at trace time (a concrete key ⇒ the bank
    is an eager constant baked into the compiled program, shared by every
    shard/lane); Nyström needs no bank.

    ``rff_redraw='step'`` instead returns a φ with ``needs_step = True``
    whose signature is ``phi_fn(updated, interacting, scores, t=...)``:
    the bank is drawn inside the traced program from ``fold_in(key, t)``
    (``key`` is the *bank root*), so every step uses a fresh, independent
    bank at zero recompiles (``t`` is a traced scan operand, not a Python
    scalar).  Bind the step index with :func:`bind_phi_step`."""
    if not isinstance(kernel, RBF):
        raise ValueError(
            "kernel_approx requires an RBF kernel (the feature and landmark "
            f"closed forms are RBF-specific), got {kernel!r}"
        )
    bw = kernel.bandwidth
    if approx.method == "nystrom":
        num_l, ridge = approx.num_landmarks, approx.ridge

        def nystrom_fn(y, x, s):
            return phi_nystrom(y, x, s, bw, num_l, ridge)

        return nystrom_fn
    if approx.key is None:
        raise ValueError(
            "kernel_approx='rff' needs the bank key: bind one with "
            "KernelApprox.with_key(utils.rng.approx_bank_key(seed)) — the "
            "samplers derive it from the run seed automatically"
        )
    key, num_f = approx.key, approx.num_features
    if approx.rff_redraw == "step":

        def rff_step_fn(y, x, s, t=None):
            if t is None:
                raise ValueError(
                    "rff_redraw='step' needs the step index: bind it with "
                    "ops.approx.bind_phi_step(phi_fn, t) before calling"
                )
            freqs = rff_frequencies(jax.random.fold_in(key, t), num_f,
                                    x.shape[1], bw)
            return phi_rff(y, x, s, freqs)

        rff_step_fn.needs_step = True
        return rff_step_fn
    banks = {}

    def rff_fn(y, x, s):
        d = x.shape[1]
        freqs = banks.get(d)
        if freqs is None:
            # the key is concrete, so the draw is forced to compile-time
            # eval: a concrete constant even when first touched inside a
            # jit/scan trace — cached, embedded in every program, zero
            # per-step cost, the ONE bank every shard shares
            with jax.ensure_compile_time_eval():
                freqs = rff_frequencies(key, num_f, d, bw)
            banks[d] = freqs
        return phi_rff(y, x, s, freqs)

    return rff_fn


# --------------------------------------------------------------------- #
# residual probe + gauges (the svgd_diag_* posterior-health channel)


def phi_rel_error(exact, approx) -> float:
    """Global relative L2 (Frobenius) error of an approximate φ against the
    exact one — the single number the error budget bounds."""
    exact = np.asarray(exact, dtype=np.float64)
    approx = np.asarray(approx, dtype=np.float64)
    denom = float(np.linalg.norm(exact))
    return float(np.linalg.norm(approx - exact) / max(denom, 1e-30))


def phi_residual_report(particles, scores, kernel: RBF,
                        approx: KernelApprox, max_points: int = 512,
                        step: int = 0) -> dict:
    """Measure the feature-space φ residual on an evenly-strided subsample
    of the current ensemble: exact φ vs the configured approximation, both
    over the same ≤``max_points`` rows.  O(max_points²) — the diagnostics
    subsample discipline, so the probe stays off the hot path at any n.

    Returns ``{phi_approx_rel_err, phi_approx_budget, phi_approx_within_
    budget, phi_approx_dial, n_eval}`` — plain floats, gauge-ready."""
    from dist_svgd_tpu.ops.svgd import phi as phi_exact

    particles = jnp.asarray(particles)
    scores = jnp.asarray(scores)
    n = particles.shape[0]
    if n > max_points:
        stride = -(-n // max_points)
        particles = particles[::stride]
        scores = scores[::stride]
    # a redraw-per-step spec probes the bank of ``step`` (the fold a live
    # run would use at that index); run-lifetime banks ignore the binding
    approx_fn = bind_phi_step(make_approx_phi_fn(kernel, approx), step)
    exact = phi_exact(particles, particles, scores, kernel)
    est = approx_fn(particles, particles, scores)
    err = phi_rel_error(exact, est)
    budget = default_error_budget(approx, int(particles.shape[1]))
    return {
        "phi_approx_rel_err": err,
        "phi_approx_budget": budget,
        "phi_approx_within_budget": float(err <= budget),
        "phi_approx_dial": float(approx.accuracy_dial),
        "n_eval": int(particles.shape[0]),
    }


def record_phi_residual(report: dict, registry=None) -> None:
    """Publish a :func:`phi_residual_report` as ``svgd_diag_*`` gauges so
    drift guards and SLOs watch approximation health the same way they
    watch KSD/ESS (a ``svgd_diag_phi_approx_within_budget`` gauge at 0 is
    the alarm condition; the raw residual rides alongside for trending)."""
    from dist_svgd_tpu.telemetry import metrics as _metrics

    reg = registry if registry is not None else _metrics.default_registry()
    helps = {
        "phi_approx_rel_err":
            "relative L2 error of the approximate phi vs exact (subsample)",
        "phi_approx_budget": "declared approximation error ceiling",
        "phi_approx_within_budget": "1 when the residual is inside budget",
        "phi_approx_dial": "accuracy dial (RFF features / landmarks)",
    }
    for name, help_text in helps.items():
        reg.gauge(f"svgd_diag_{name}", help_text).set(report[name])
    reg.counter("svgd_diag_phi_residual_total",
                "approximation residual probes completed").inc()
