"""Bayesian logistic regression — the reference's flagship model
(experiments/logreg.py:36-58).

Particle layout (experiments/logreg.py:37,53-54): ``theta = (log α, w)`` with
``d = 1 + n_features``; priors ``α ~ Gamma(1, 1)`` and ``w | α ~ N(0, I/α)``;
likelihood ``-Σ_i log(1 + exp(-t_i · x_i·w))`` on the (local) data slice.

Closed forms used (identical to the torch distributions the reference calls):
- ``Gamma(1,1).log_prob(α) = -α`` (note: evaluated at α, no log-α Jacobian —
  replicating the reference's parameterisation exactly).
- ``MVN(0, I/α).log_prob(w) = ½k·log α − ½k·log 2π − ½α‖w‖²``.
- the likelihood's ``log(1 + exp(-z))`` is computed as ``logaddexp(0, -z)``
  (stable; equal in exact arithmetic to experiments/logreg.py:57).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

_LOG_2PI = math.log(2.0 * math.pi)


def logreg_logp(theta: jax.Array, data: Tuple[jax.Array, jax.Array]) -> jax.Array:
    """Log joint density for one particle on a data slice.

    Args:
        theta: ``(1 + k,)`` particle — ``theta[0] = log α``, ``theta[1:] = w``.
        data: ``(x, t)`` with ``x`` of shape ``(N, k)`` and labels ``t`` of
            shape ``(N,)`` or ``(N, 1)`` in ``{-1, +1}``.
    """
    x, t = data
    t = t.reshape(-1)
    alpha = jnp.exp(theta[0])
    w = theta[1:]
    k = w.shape[0]
    lp = -alpha  # Gamma(1,1) prior on α
    lp += 0.5 * k * theta[0] - 0.5 * k * _LOG_2PI - 0.5 * alpha * jnp.dot(w, w)
    z = (x @ w) * t
    lp += -jnp.sum(jnp.logaddexp(0.0, -z))
    return lp


def make_logreg_logp(x_train: jax.Array, t_train: jax.Array):
    """Closure over a fixed dataset, for the single-device / replicated case
    (mirrors the reference's ``lambda x: logp(rank, x)``,
    experiments/logreg.py:68)."""
    x_train = jnp.asarray(x_train)
    t_train = jnp.asarray(t_train).reshape(-1)

    def logp(theta, data=None):
        if data is None:
            data = (x_train, t_train)
        return logreg_logp(theta, data)

    return logp


def logreg_likelihood(theta: jax.Array, data: Tuple[jax.Array, jax.Array]) -> jax.Array:
    """Likelihood term only: ``-Σ_i log(1 + exp(-t_i·x_i·w))``
    (experiments/logreg.py:57)."""
    x, t = data
    w = theta[1:]
    z = (x @ w) * t.reshape(-1)
    return -jnp.sum(jnp.logaddexp(0.0, -z))


def logreg_prior(theta: jax.Array) -> jax.Array:
    """Prior terms only: ``Gamma(1,1)`` on ``α = exp(θ₀)`` (no log-α
    Jacobian — reference parameterisation) and ``N(0, I/α)`` on ``w``
    (experiments/logreg.py:38-39,55-56)."""
    alpha = jnp.exp(theta[0])
    w = theta[1:]
    k = w.shape[0]
    return -alpha + 0.5 * k * theta[0] - 0.5 * k * _LOG_2PI - 0.5 * alpha * jnp.dot(w, w)


def make_logreg_split():
    """``(likelihood, prior)`` pair for the samplers' ``log_prior=`` path, so
    minibatch/importance scaling touches only the data term (mirrors
    ``bnn.make_bnn_split``).  ``likelihood + prior == logreg_logp`` exactly."""
    return logreg_likelihood, logreg_prior


def posterior_predictive_prob(particles: jax.Array, x_test: jax.Array) -> jax.Array:
    """Per-particle predictive probabilities ``σ(x_test · w)``.

    Reference quirk replicated (experiments/logreg_plots.py:44-48,
    SURVEY.md §7.4): the α component is decoded but *unused* — prediction
    only uses ``w = theta[1:]``.

    Returns ``(n_particles, n_test)``.
    """
    w = particles[:, 1:]
    return jax.nn.sigmoid(x_test @ w.T).T


def ensemble_test_accuracy(particles, x_test, t_test) -> jax.Array:
    """Posterior-predictive-mean test accuracy, reference semantics
    (experiments/logreg_plots.py:42-57): average σ(x·w) over particles,
    threshold at 0.5, compare against ``t > 0``."""
    probs = jnp.mean(posterior_predictive_prob(particles, x_test), axis=0)
    pred = probs > 0.5
    truth = jnp.asarray(t_test).reshape(-1) > 0
    return jnp.mean(pred == truth)
