"""Model log-densities (user-supplied closures in the reference; shipped here
as a library of JAX-traceable builders)."""

from dist_svgd_tpu.models import bnn
from dist_svgd_tpu.models.gmm import make_gmm_logp, gmm_logp
from dist_svgd_tpu.models.logreg import (
    make_logreg_logp,
    posterior_predictive_prob,
)

__all__ = [
    "bnn",
    "make_gmm_logp",
    "gmm_logp",
    "make_logreg_logp",
    "posterior_predictive_prob",
]
