"""1-D Gaussian-mixture target — the reference's sanity-check model
(experiments/gmm.py:14-21).

Reference quirk, replicated deliberately (SURVEY.md §7.4): the comment at
experiments/gmm.py:20 describes the mixture as ``1/3·p1 + 2/3·p2`` but the
code weights *both* components 1/3.  Unnormalised densities are fine for
scores (reference notes.md:1-8), and we replicate the CODE, not the comment.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax.numpy as jnp
from jax.scipy.special import logsumexp

_LOG_SQRT_2PI = 0.5 * math.log(2.0 * math.pi)


def _normal_logpdf(x, loc, scale):
    z = (x - loc) / scale
    return -0.5 * z * z - jnp.log(scale) - _LOG_SQRT_2PI


def make_gmm_logp(
    means: Sequence[float] = (-2.0, 2.0),
    scales: Sequence[float] = (1.0, 1.0),
    weights: Sequence[float] = (1.0 / 3.0, 1.0 / 3.0),
):
    """Build ``logp(theta)`` for a (possibly unnormalised) Gaussian mixture.

    ``theta`` has shape ``(d,)``; dimensions are treated independently and
    summed, so ``d=1`` reproduces the reference exactly.  The reference's
    ``log(Σ_i w_i exp(logpdf_i))`` (experiments/gmm.py:19-21) is computed in
    the numerically-stable logsumexp form — identical in exact arithmetic.
    """
    # keep plain tuples here and convert inside logp: building device arrays
    # at closure-construction time would initialise the XLA backend on
    # module import (the parity instance below), which breaks the multi-host
    # contract that jax.distributed.initialize() is the first JAX call.
    # Under jit the conversions are trace-time constants — zero runtime cost.
    means_t, scales_t, weights_t = tuple(means), tuple(scales), tuple(weights)

    def logp(theta, data=None):
        del data  # no dataset — the target density is the model
        means_a = jnp.asarray(means_t)
        scales_a = jnp.asarray(scales_t)
        log_w = jnp.log(jnp.asarray(weights_t))
        comp = log_w[:, None] + _normal_logpdf(theta[None, :], means_a[:, None], scales_a[:, None])
        return jnp.sum(logsumexp(comp, axis=0))

    return logp


#: Reference-parity instance: mixture 1/3·N(-2,1) + 1/3·N(2,1)
#: (experiments/gmm.py:16-21 — code weights, not comment weights).
gmm_logp = make_gmm_logp()
