"""Two-layer Bayesian neural-network regression (weight-vector SVGD).

BASELINE.json config 5: "2-layer Bayesian NN regression (UCI), 500 particles,
weight-vector SVGD".  The reference repo has no NN model, but SURVEY.md §2.3
notes the whole weight vector is treated as one particle dimension ``d`` — no
intra-model sharding required — so this slots into the existing samplers as
just another user-supplied ``logp`` closure (reference design:
dsvgd/sampler.py:7-17).

Model (the standard SVGD BNN setup of Liu & Wang 2016, §5):

    hidden  h(x)   = relu(x W1 + b1)            (n_hidden units)
    output  ŷ(x)   = h(x) w2 + b2               (scalar regression)
    y | x, w, γ    ~ N(ŷ(x), 1/γ)
    w (all weights and biases) | λ ~ N(0, 1/λ)
    γ ~ Gamma(a0, b0),  λ ~ Gamma(a0, b0)       (a0 = 1, b0 = 0.1)

Particle layout — one flat ``(d,)`` vector per particle:

    theta = [vec(W1) | b1 | w2 | b2 | log γ | log λ]
    d = n_features·n_hidden + n_hidden + n_hidden + 1 + 2

The precisions are carried in log-space so particles live on an unconstrained
Euclidean space (SVGD's RBF kernel assumes this); the prior density includes
the change-of-variables Jacobian ``+ log γ`` / ``+ log λ``.  (The reference's
logreg model omits the Jacobian for its ``log α`` coordinate — a documented
quirk we replicate *there* (models/logreg.py) but not here, since the BNN has
no reference counterpart to stay warty-compatible with.)
"""

from __future__ import annotations

import math
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

_LOG_2PI = math.log(2.0 * math.pi)

#: Gamma hyperpriors on the likelihood precision γ and weight precision λ
#: (shape a0, rate b0) — the Liu & Wang 2016 BNN values.
A0 = 1.0
B0 = 0.1


class BNNParams(NamedTuple):
    """Unpacked view of one flat particle."""

    w1: jax.Array  # (n_features, n_hidden)
    b1: jax.Array  # (n_hidden,)
    w2: jax.Array  # (n_hidden,)
    b2: jax.Array  # ()
    log_gamma: jax.Array  # () — likelihood precision
    log_lambda: jax.Array  # () — weight-prior precision


def num_params(n_features: int, n_hidden: int = 50) -> int:
    """Flat particle dimensionality ``d``."""
    return n_features * n_hidden + n_hidden + n_hidden + 1 + 2


def unpack(theta: jax.Array, n_features: int, n_hidden: int = 50) -> BNNParams:
    """Split a flat ``(d,)`` particle into named network parameters."""
    k = n_features * n_hidden
    w1 = theta[:k].reshape(n_features, n_hidden)
    b1 = theta[k : k + n_hidden]
    w2 = theta[k + n_hidden : k + 2 * n_hidden]
    b2 = theta[k + 2 * n_hidden]
    return BNNParams(w1, b1, w2, b2, theta[-2], theta[-1])


def predict(theta: jax.Array, x: jax.Array, n_features: int, n_hidden: int = 50) -> jax.Array:
    """Network output ``ŷ`` for one particle; ``x`` is ``(N, n_features)``,
    result ``(N,)``."""
    p = unpack(theta, n_features, n_hidden)
    h = jax.nn.relu(x @ p.w1 + p.b1)
    return h @ p.w2 + p.b2


def _log_gamma_prior(log_prec: jax.Array) -> jax.Array:
    """``log Gamma(prec; A0, B0) + log_prec`` — density of the *log*-precision
    (change-of-variables Jacobian included)."""
    prec = jnp.exp(log_prec)
    # log Γ(A0)⁻¹ b0^a0 prec^(a0-1) e^(-b0 prec), with Γ(1) = 1
    return A0 * math.log(B0) - math.lgamma(A0) + (A0 - 1.0) * log_prec - B0 * prec + log_prec


def bnn_logp(
    theta: jax.Array,
    data: Tuple[jax.Array, jax.Array],
    n_features: int,
    n_hidden: int = 50,
) -> jax.Array:
    """Log joint density of one particle on a data slice ``(x, y)``.

    ``x``: ``(N, n_features)`` standardized features; ``y``: ``(N,)`` targets.
    The likelihood is a *sum* over rows, so the minibatch/data-sharding
    machinery's ``N_global/N_local`` (and ``N/B``) scaling is unbiased for it
    exactly as for the logreg model (dsvgd/distsampler.py:96-99 convention).
    """
    x, y = data
    y = y.reshape(-1)
    p = unpack(theta, n_features, n_hidden)
    gamma = jnp.exp(p.log_gamma)
    lam = jnp.exp(p.log_lambda)
    n_weights = theta.shape[0] - 2

    pred = predict(theta, x, n_features, n_hidden)
    n_rows = y.shape[0]
    lp = 0.5 * n_rows * (p.log_gamma - _LOG_2PI) - 0.5 * gamma * jnp.sum((pred - y) ** 2)

    w = theta[:-2]
    lp += 0.5 * n_weights * (p.log_lambda - _LOG_2PI) - 0.5 * lam * jnp.dot(w, w)
    lp += _log_gamma_prior(p.log_gamma) + _log_gamma_prior(p.log_lambda)
    return lp


def make_bnn_logp(n_features: int, n_hidden: int = 50):
    """``logp(theta, data)`` closure for the samplers' ``data=`` path."""

    def logp(theta, data):
        return bnn_logp(theta, data, n_features, n_hidden)

    return logp


def make_bnn_split(n_features: int, n_hidden: int = 50):
    """``(likelihood, prior)`` pair for the samplers' ``log_prior=`` path,
    so only the data term is minibatch-scaled (models the exact posterior
    under stochastic scores — see Sampler docstring)."""

    def likelihood(theta, data):
        x, y = data
        y = y.reshape(-1)
        p = unpack(theta, n_features, n_hidden)
        gamma = jnp.exp(p.log_gamma)
        pred = predict(theta, x, n_features, n_hidden)
        n_rows = y.shape[0]
        return 0.5 * n_rows * (p.log_gamma - _LOG_2PI) - 0.5 * gamma * jnp.sum(
            (pred - y) ** 2
        )

    def prior(theta):
        p = unpack(theta, n_features, n_hidden)
        lam = jnp.exp(p.log_lambda)
        w = theta[:-2]
        n_weights = theta.shape[0] - 2
        lp = 0.5 * n_weights * (p.log_lambda - _LOG_2PI) - 0.5 * lam * jnp.dot(w, w)
        return lp + _log_gamma_prior(p.log_gamma) + _log_gamma_prior(p.log_lambda)

    return likelihood, prior


def init_particles(
    key: jax.Array, n: int, n_features: int, n_hidden: int = 50, dtype=jnp.float32
) -> jax.Array:
    """Initial ``(n, d)`` particle array.

    Network weights ~ N(0, 1/(fan_in+1)) (the Liu & Wang init); log-precisions
    start at log of a Gamma(A0, B0) draw.
    """
    d = num_params(n_features, n_hidden)
    kw, kg, kl = jax.random.split(key, 3)
    theta = jax.random.normal(kw, (n, d), dtype=dtype)
    k = n_features * n_hidden
    scale = jnp.concatenate(
        [
            jnp.full((k + n_hidden,), 1.0 / math.sqrt(n_features + 1.0)),
            jnp.full((n_hidden + 1,), 1.0 / math.sqrt(n_hidden + 1.0)),
            jnp.zeros((2,)),
        ]
    ).astype(dtype)
    theta = theta * scale
    loggam = jnp.log(jax.random.gamma(kg, A0, (n,), dtype=dtype) / B0)
    loglam = jnp.log(jax.random.gamma(kl, A0, (n,), dtype=dtype) / B0)
    theta = theta.at[:, -2].set(loggam).at[:, -1].set(loglam)
    return theta


# --------------------------------------------------------------------- #
# Evaluation (ensemble posterior predictive)


def ensemble_rmse(
    particles: jax.Array,
    x_test: jax.Array,
    y_test: jax.Array,
    n_features: int,
    n_hidden: int = 50,
    y_mean: float = 0.0,
    y_std: float = 1.0,
) -> jax.Array:
    """RMSE of the posterior-predictive mean on the original target scale
    (``y_mean``/``y_std`` undo the driver's target standardization)."""
    preds = jax.vmap(lambda t: predict(t, x_test, n_features, n_hidden))(particles)
    mean_pred = jnp.mean(preds, axis=0) * y_std + y_mean
    truth = jnp.asarray(y_test).reshape(-1)
    return jnp.sqrt(jnp.mean((mean_pred - truth) ** 2))


def ensemble_test_loglik(
    particles: jax.Array,
    x_test: jax.Array,
    y_test: jax.Array,
    n_features: int,
    n_hidden: int = 50,
    y_mean: float = 0.0,
    y_std: float = 1.0,
) -> jax.Array:
    """Average per-point predictive log-likelihood of the particle mixture,
    ``mean_i log (1/n) Σ_p N(y_i; ŷ_p(x_i), 1/γ_p)``, on the original scale."""
    truth = jnp.asarray(y_test).reshape(-1)

    def per_particle(theta):
        pred = predict(theta, x_test, n_features, n_hidden) * y_std + y_mean
        gamma = jnp.exp(theta[-2]) / (y_std**2)  # precision on original scale
        return 0.5 * (jnp.log(gamma) - _LOG_2PI) - 0.5 * gamma * (pred - truth) ** 2

    lls = jax.vmap(per_particle)(particles)  # (n_particles, n_test)
    n = particles.shape[0]
    return jnp.mean(jax.scipy.special.logsumexp(lls, axis=0) - math.log(n))
