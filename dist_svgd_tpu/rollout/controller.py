"""Progressive delivery of posterior generations: shadow → canary → promote.

Today a new generation goes from checkpoint to 100% of traffic in one
atomic ``PredictiveEngine.reload`` swap; the only safety net is the
pre-serve ``ReloadPolicy`` health check, so a generation that passes the
KSD/ESS floors but degrades *live predictions* hits every user at once.
:class:`RolloutController` replaces the cutover with staged exposure
judged on live SLO windows — the production model-rollout discipline:

1. **shadow** — the batcher mirrors a deterministic sampled fraction of
   live requests to the staged candidate *off the client's critical path*
   (a bounded background worker; an over-full mirror queue DROPS, it never
   queues client latency), recording per-request prediction divergence vs
   the incumbent into the ``svgd_rollout_divergence`` histogram.  The
   client answer always comes from the incumbent.
2. **canary stages** — deterministic per-request hash splits send a
   growing fraction (default 1% → 10% → 50% → 100%) of real traffic to the
   candidate.  The split is a pure function of the request key and the
   fraction is a nested threshold, so a request routed to the candidate at
   1% stays on the candidate at every later stage — users never flap
   between generations.  Candidate-served requests carry a
   ``generation="candidate"`` label on every serve metric, so the SLO
   engine judges candidate and incumbent as separate label sets.
3. **promote / rollback** — a stage advances when its windows stay green
   for the hold period with enough data; the candidate promotes to
   incumbent (``engine.promote_candidate`` — the same O(1) pointer
   exchange as a reload's admitted swap, with the outgoing incumbent kept
   resident for ``engine.rollback``).  A breach streak rolls back: the
   candidate is dropped and the split zeroed — the incumbent never stopped
   being resident, so rollback is O(1) and **never touches a checkpoint**.

Control discipline is :class:`~dist_svgd_tpu.serving.autoscale.
AutoscaleController`'s: an injectable clock, the controller's OWN
``SloEngine(mirror_metrics=False)`` and windows (its cadence must not
starve the ``/slo`` endpoint's objective windows), every window primed at
:meth:`~RolloutController.offer` so the first control step judges the
delta since the rollout began, ``step()`` as the whole control iteration
under one lock, a bounded decision log, and ``start()/stop()`` for a
background cadence (drills and tier-1 tests drive ``step(now=...)``
manually and deterministically).

``tools/rollout_drill.py`` measures the loop end to end and emits the
gated ``canary_rollout`` row; ``resilience.faults.BadGenerationAt``
manufactures the deterministic-garbage candidate its rollback phase uses.
"""

from __future__ import annotations

import threading
import time
import zlib
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional, Sequence

import numpy as np

from dist_svgd_tpu.telemetry import metrics as _metrics
from dist_svgd_tpu.telemetry.slo import HistogramWindow, default_rollout_slos

__all__ = ["RolloutPlan", "RolloutController", "DIVERGENCE_BUCKETS"]

#: Bucket lattice for the per-request divergence histogram: powers of two
#: from 1e-6 up to ~1.0 — prediction-space distances, not latencies (a
#: garbage candidate lands in the overflow bucket, which every finite
#: threshold counts as over).
DIVERGENCE_BUCKETS = tuple(1e-6 * 2.0 ** i for i in range(21))

IDLE = "idle"
SHADOW = "shadow"
CANARY = "canary"


def _hash_unit(seed: int, salt: str, key) -> float:
    """Deterministic uniform-ish in [0, 1) from ``(seed, salt, key)`` —
    crc32, NOT Python ``hash()`` (randomized per process, which would make
    replayed traffic split differently every run)."""
    h = zlib.crc32(f"{seed}:{salt}:{key}".encode("utf-8")) & 0xFFFFFFFF
    return h / 4294967296.0


class RolloutPlan:
    """Declarative stage plan + judgement thresholds for one rollout.

    Args:
        shadow_fraction: fraction of live requests mirrored to the
            candidate (shadow stage and onward — the divergence signal
            keeps flowing through the canary stages).
        shadow_min_mirrors: mirrored predictions required before the
            shadow stage may go green (no promotion on an empty window).
        shadow_hold_s: how long shadow must stay green before the first
            canary stage.
        canary_stages: strictly-increasing candidate traffic fractions in
            ``(0, 1]``; the last must be ``1.0`` (full exposure precedes
            promotion).
        stage_hold_s: green hold per canary stage.
        stage_min_requests: candidate-served requests required per canary
            stage before it may advance.
        max_divergence: per-request divergence threshold (mean |candidate
            − incumbent| over the shared output fields).
        divergence_budget: allowed fraction of mirrored requests over
            ``max_divergence`` (the divergence objective's error budget).
        p99_ms / error_budget: candidate-side serve SLOs — p99 latency
            threshold and dispatch-error budget per batch, judged on the
            ``generation="candidate"`` label set only.
        breach_streak: consecutive breaching control steps before
            rollback (1 = roll back the moment a window breaches).
        mirror_inflight_limit: bound on queued+running shadow mirrors;
            beyond it mirrors DROP (counted) — mirroring must never grow
            an unbounded backlog behind a slow candidate.
        on_active: what :meth:`RolloutController.offer` does while a
            rollout is in flight — ``'supersede'`` (drop the current
            candidate, start over with the new one: freshest data wins,
            the streaming cadence) or ``'defer'`` (refuse the offer).
        seed: hash-split seed (one seed per rollout keeps the user→side
            assignment stable for its whole lifetime).
    """

    def __init__(
        self,
        *,
        shadow_fraction: float = 0.25,
        shadow_min_mirrors: int = 32,
        shadow_hold_s: float = 5.0,
        canary_stages: Sequence[float] = (0.01, 0.10, 0.50, 1.0),
        stage_hold_s: float = 5.0,
        stage_min_requests: int = 16,
        max_divergence: float = 0.05,
        divergence_budget: float = 0.01,
        p99_ms: float = 100.0,
        error_budget: float = 0.01,
        breach_streak: int = 1,
        mirror_inflight_limit: int = 4,
        on_active: str = "supersede",
        seed: int = 0x5F6D,
    ):
        if not 0.0 < shadow_fraction <= 1.0:
            raise ValueError(
                f"shadow_fraction must be in (0, 1], got {shadow_fraction}")
        if shadow_min_mirrors < 1:
            raise ValueError(
                f"shadow_min_mirrors must be >= 1, got {shadow_min_mirrors}")
        if shadow_hold_s < 0:
            raise ValueError(
                f"shadow_hold_s must be >= 0, got {shadow_hold_s}")
        stages = tuple(float(f) for f in canary_stages)
        if not stages or any(not 0.0 < f <= 1.0 for f in stages):
            raise ValueError(
                f"canary_stages must be fractions in (0, 1], got {stages}")
        if any(b <= a for a, b in zip(stages, stages[1:])):
            raise ValueError(
                f"canary_stages must be strictly increasing, got {stages}")
        if stages[-1] != 1.0:
            raise ValueError(
                f"the last canary stage must be 1.0 (full exposure "
                f"precedes promotion), got {stages}")
        if stage_hold_s < 0:
            raise ValueError(f"stage_hold_s must be >= 0, got {stage_hold_s}")
        if stage_min_requests < 1:
            raise ValueError(
                f"stage_min_requests must be >= 1, got {stage_min_requests}")
        if max_divergence <= 0:
            raise ValueError(
                f"max_divergence must be positive, got {max_divergence}")
        if not 0.0 < divergence_budget < 1.0:
            raise ValueError(
                f"divergence_budget must be in (0, 1), got {divergence_budget}")
        if p99_ms <= 0:
            raise ValueError(f"p99_ms must be positive, got {p99_ms}")
        if not 0.0 <= error_budget < 1.0:
            raise ValueError(
                f"error_budget must be in [0, 1), got {error_budget}")
        if breach_streak < 1:
            raise ValueError(
                f"breach_streak must be >= 1, got {breach_streak}")
        if mirror_inflight_limit < 1:
            raise ValueError(
                f"mirror_inflight_limit must be >= 1, "
                f"got {mirror_inflight_limit}")
        if on_active not in ("supersede", "defer"):
            raise ValueError(
                f"on_active must be 'supersede' or 'defer', got {on_active!r}")
        self.shadow_fraction = float(shadow_fraction)
        self.shadow_min_mirrors = int(shadow_min_mirrors)
        self.shadow_hold_s = float(shadow_hold_s)
        self.canary_stages = stages
        self.stage_hold_s = float(stage_hold_s)
        self.stage_min_requests = int(stage_min_requests)
        self.max_divergence = float(max_divergence)
        self.divergence_budget = float(divergence_budget)
        self.p99_ms = float(p99_ms)
        self.error_budget = float(error_budget)
        self.breach_streak = int(breach_streak)
        self.mirror_inflight_limit = int(mirror_inflight_limit)
        self.on_active = on_active
        self.seed = int(seed)

    def describe(self) -> Dict[str, Any]:
        return {
            "shadow_fraction": self.shadow_fraction,
            "shadow_min_mirrors": self.shadow_min_mirrors,
            "shadow_hold_s": self.shadow_hold_s,
            "canary_stages": list(self.canary_stages),
            "stage_hold_s": self.stage_hold_s,
            "stage_min_requests": self.stage_min_requests,
            "max_divergence": self.max_divergence,
            "divergence_budget": self.divergence_budget,
            "p99_ms": self.p99_ms,
            "error_budget": self.error_budget,
            "breach_streak": self.breach_streak,
            "mirror_inflight_limit": self.mirror_inflight_limit,
            "on_active": self.on_active,
            "seed": self.seed,
        }


def prediction_divergence(candidate: Dict[str, np.ndarray],
                          incumbent: Dict[str, np.ndarray]) -> float:
    """Mean absolute difference between two prediction dicts over their
    shared output fields (mean over rows and fields).  NaNs propagate —
    a candidate predicting NaN lands in the histogram's overflow bucket,
    which every finite divergence threshold counts as over."""
    keys = sorted(set(candidate) & set(incumbent))
    if not keys:
        return float("nan")
    total = 0.0
    for k in keys:
        total += float(np.mean(np.abs(np.asarray(candidate[k], np.float64)
                                      - np.asarray(incumbent[k], np.float64))))
    return total / len(keys)


class RolloutController:
    """Drives one candidate generation through the stage plan.

    Args:
        engine: the tenant's :class:`~dist_svgd_tpu.serving.engine.
            PredictiveEngine` (candidates stage into its candidate slot).
        plan: the :class:`RolloutPlan` (default knobs otherwise).
        metrics: registry the serve/rollout series live in (default: the
            engine's — pass the batcher's registry when they differ).
        clock: injectable monotonic time source — every hold/streak
            decision reads it, so drills and tests drive the controller
            deterministically (``step(now=...)`` works too).
        logger: optional ``JsonlLogger`` — one record per decision.

    The batcher-facing seams — :meth:`assign` (hash split),
    :meth:`should_mirror`, :meth:`dispatch_candidate`, :meth:`mirror` —
    are cheap reads designed to be called per request/batch; the control
    loop itself lives entirely in :meth:`step`.
    """

    def __init__(self, engine, *, plan: Optional[RolloutPlan] = None,
                 metrics: Optional[_metrics.MetricsRegistry] = None,
                 clock: Callable[[], float] = time.monotonic,
                 logger=None):
        self.engine = engine
        self.plan = plan if plan is not None else RolloutPlan()
        self.metrics = metrics if metrics is not None else engine.registry
        self._clock = clock
        self._logger = logger
        self._lock = threading.Lock()
        self._tlabels = dict(engine._tlabels)

        reg = self.metrics
        self._m_div = reg.histogram(
            "svgd_rollout_divergence",
            "per-mirrored-request prediction divergence, candidate vs "
            "incumbent (mean |Δ| over shared output fields)",
            buckets=DIVERGENCE_BUCKETS)
        self._m_shadow_wall = reg.histogram(
            "svgd_rollout_shadow_wall_s",
            "candidate dispatch wall per shadow mirror (off the client's "
            "critical path)")
        self._m_promote_wall = reg.histogram(
            "svgd_rollout_promote_seconds",
            "offer -> promotion wall per promoted generation")
        self._m_mirrors = reg.counter(
            "svgd_rollout_mirrors_total", "shadow mirrors completed")
        self._m_mirror_dropped = reg.counter(
            "svgd_rollout_mirror_dropped_total",
            "shadow mirrors dropped by the inflight bound (never queued "
            "behind a slow candidate)")
        self._m_mirror_errors = reg.counter(
            "svgd_rollout_mirror_errors_total",
            "shadow mirrors that raised in the candidate dispatch")
        self._m_promotions = reg.counter(
            "svgd_rollout_promotions_total", "candidates promoted to serving")
        self._m_rollbacks = reg.counter(
            "svgd_rollout_rollbacks_total",
            "candidates rolled back by a breaching window")
        self._m_supersedes = reg.counter(
            "svgd_rollout_supersedes_total",
            "in-flight candidates superseded by a newer offer")
        self._m_fraction = reg.gauge(
            "svgd_rollout_fraction",
            "live candidate traffic fraction (hash-split threshold)")
        self._m_stage = reg.gauge(
            "svgd_rollout_stage",
            "rollout stage index (-1 idle, 0 shadow, 1.. canary stages)")

        # the controller's OWN objective windows (mirror_metrics=False:
        # its cadence must not clobber the /slo endpoint's verdict series)
        self._slo = default_rollout_slos(
            reg, p99_ms=self.plan.p99_ms, error_budget=self.plan.error_budget,
            max_divergence=self.plan.max_divergence,
            divergence_budget=self.plan.divergence_budget,
            labels=self._tlabels, mirror_metrics=False,
            clock=lambda: self._clock())
        self._div_window = HistogramWindow(reg, "svgd_rollout_divergence",
                                           labels=self._tlabels)

        # rollout state — all guarded by _lock (assign/should_mirror read
        # the two floats below lock-free: single attribute reads of
        # immutable values, refreshed only inside step()/offer())
        self._state = IDLE
        self._stage_index = -1          # -1 idle/shadow, >=0 canary
        self._split_fraction = 0.0
        self._mirror_fraction = 0.0
        self._tag: Optional[str] = None
        self._generation: Optional[int] = None
        self._watermark: Optional[float] = None
        self._offered_at: Optional[float] = None
        self._stage_entered: Optional[float] = None
        self._breaches = 0
        self._stage_counts: Dict[str, float] = {}
        self._promotions = 0
        self._rollbacks = 0
        self._supersedes = 0
        self._last_rows: Dict[str, Any] = {}
        #: Bounded decision log (stage transitions, promote, rollback).
        self.log: deque = deque(maxlen=64)

        self._mirror_slots = threading.BoundedSemaphore(
            self.plan.mirror_inflight_limit)
        self._executor: Optional[ThreadPoolExecutor] = None
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._m_fraction.set(0.0, **self._tlabels)
        self._m_stage.set(-1, **self._tlabels)

    # ------------------------------------------------------------------ #
    # identity / cheap request-path reads

    @property
    def tenant(self) -> Optional[str]:
        """The tenant this rollout targets (the batcher gates its split
        hook on it — other tenants' traffic never participates)."""
        return self.engine.tenant

    @property
    def state(self) -> str:
        return self._state

    @property
    def active(self) -> bool:
        return self._state != IDLE

    def assign(self, key) -> Optional[str]:
        """Which generation serves the request with this key:
        ``'candidate'`` or ``None`` (incumbent).  A pure deterministic
        hash against the live stage fraction; the threshold is nested, so
        an assignment never flaps backwards as stages widen."""
        f = self._split_fraction
        if f <= 0.0:
            return None
        if f >= 1.0:
            return "candidate"
        return ("candidate"
                if _hash_unit(self.plan.seed, "split", key) < f else None)

    def should_mirror(self, key) -> bool:
        """Whether this (incumbent-served) request's prediction should be
        shadow-mirrored to the candidate."""
        f = self._mirror_fraction
        if f <= 0.0:
            return False
        return _hash_unit(self.plan.seed, "mirror", key) < f

    def dispatch_candidate(self, x, tenant: Optional[str] = None
                           ) -> Dict[str, np.ndarray]:
        """Candidate-side dispatch for a split batch.  Falls back to the
        incumbent when the candidate is gone (a rollback raced a batch
        already queued as candidate) — the client must get an answer
        either way."""
        try:
            return self.engine.predict(x, generation="candidate")
        except RuntimeError:
            return self.engine.predict(x)

    # ------------------------------------------------------------------ #
    # shadow mirroring (off the client's critical path)

    def mirror(self, x, incumbent_out: Dict[str, np.ndarray]) -> bool:
        """Hand one incumbent-served request to the shadow worker: the
        candidate re-predicts it in the background and the divergence
        lands in ``svgd_rollout_divergence``.  Never blocks: an over-full
        mirror queue drops (counted) — the pinned client-latency budget
        is protected by construction, not by luck.  Returns whether the
        mirror was enqueued."""
        if self._state == IDLE:
            return False
        if not self._mirror_slots.acquire(blocking=False):
            self._m_mirror_dropped.inc(**self._tlabels)
            return False
        ex = self._executor
        if ex is None:
            self._mirror_slots.release()
            return False
        # copy: the arrays are slices of the batcher's batch buffer; the
        # mirror outlives the dispatch that produced them
        x = np.array(x, copy=True)
        out = {k: np.array(v, copy=True) for k, v in incumbent_out.items()}
        try:
            ex.submit(self._mirror_task, x, out)
        except RuntimeError:            # executor shut down under us
            self._mirror_slots.release()
            return False
        return True

    def _mirror_task(self, x, incumbent_out) -> None:
        try:
            t0 = time.perf_counter()
            try:
                cand = self.engine.predict(x, generation="candidate")
            except RuntimeError:
                return  # candidate resolved (promoted/dropped) mid-flight
            wall = time.perf_counter() - t0
            div = prediction_divergence(cand, incumbent_out)
            self._m_div.observe(div, **self._tlabels)
            self._m_shadow_wall.observe(wall, **self._tlabels)
            self._m_mirrors.inc(**self._tlabels)
        except Exception:
            self._m_mirror_errors.inc(**self._tlabels)
        finally:
            self._mirror_slots.release()

    # ------------------------------------------------------------------ #
    # lifecycle

    def offer(self, particles, *, tag: Optional[str] = None,
              watermark: Optional[float] = None) -> bool:
        """Stage ``particles`` as a candidate and enter the shadow stage.

        While a rollout is in flight, ``plan.on_active`` decides:
        ``'supersede'`` drops the current candidate and starts over with
        the new one (the streaming supervisor's freshest-data-wins
        default); ``'defer'`` refuses (returns False) — the supervisor
        re-offers on a later segment.  Staging compiles the candidate's
        bucket kernels (off the request path); the first control step
        after ``offer`` judges the window since NOW — every objective
        window is primed here.
        """
        with self._lock:
            now = self._clock()
            if self._state != IDLE:
                if self.plan.on_active == "defer":
                    return False
                self._supersedes += 1
                self._m_supersedes.inc(**self._tlabels)
                self._record("supersede", now, superseded_tag=self._tag)
                self.engine.drop_candidate()
            info = self.engine.stage_candidate(particles, tag=tag)
            self._tag = tag
            self._generation = info["generation_id"]
            self._watermark = watermark
            self._offered_at = now
            self._stage_entered = now
            self._state = SHADOW
            self._stage_index = -1
            self._breaches = 0
            self._stage_counts = {}
            self._set_fractions(0.0, self.plan.shadow_fraction)
            if self._executor is None:
                self._executor = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="rollout-shadow")
            # prime every window: the first step judges the delta from NOW
            self._slo.evaluate()
            self._div_window.poll()
            self._record("offer", now, tag=tag,
                         generation=self._generation)
        return True

    def _set_fractions(self, split: float, mirror: float) -> None:
        self._split_fraction = float(split)
        self._mirror_fraction = float(mirror)
        self._m_fraction.set(float(split), **self._tlabels)
        self._m_stage.set(
            -1 if self._state == IDLE
            else (0 if self._state == SHADOW else self._stage_index + 1),
            **self._tlabels)

    def _record(self, event: str, now: float, **fields) -> None:
        rec = {"t": round(now, 3), "event": event, "state": self._state,
               "stage": self._stage_name(), **fields}
        self.log.append(rec)
        if self._logger is not None:
            try:
                self._logger.log(event=f"rollout_{event}", **rec)
            except Exception:
                pass

    def _stage_name(self) -> str:
        if self._state == IDLE:
            return "idle"
        if self._state == SHADOW:
            return "shadow"
        return f"canary:{self.plan.canary_stages[self._stage_index]:g}"

    # ------------------------------------------------------------------ #
    # the control loop

    def step(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One full control iteration: advance the objective windows,
        judge the current stage, and promote / advance / roll back.
        Returns a decision document (also appended to :attr:`log` when a
        transition happened)."""
        with self._lock:
            now = self._clock() if now is None else now
            if self._state == IDLE:
                return {"state": IDLE, "action": "none"}
            doc = self._slo.evaluate()
            rows = doc["objectives"]
            self._last_rows = {
                name: {k: row.get(k) for k in
                       ("status", "burn_rate", "window_count")}
                for name, row in rows.items()
            }
            for name, row in rows.items():
                self._stage_counts[name] = (self._stage_counts.get(name, 0)
                                            + (row.get("window_count") or 0))
            breached = [name for name, row in rows.items()
                        if row["status"] == "breach"]
            if breached:
                self._breaches += 1
                if self._breaches >= self.plan.breach_streak:
                    return self._rollback(now, breached)
                self._record("breach", now, objectives=breached,
                             streak=self._breaches)
                return {"state": self._state, "action": "breach",
                        "objectives": breached, "streak": self._breaches}
            self._breaches = 0
            held = now - self._stage_entered
            if self._state == SHADOW:
                mirrors = self._stage_counts.get("shadow_divergence", 0)
                if (held >= self.plan.shadow_hold_s
                        and mirrors >= self.plan.shadow_min_mirrors):
                    return self._advance(now)
                return {"state": SHADOW, "action": "hold",
                        "held_s": round(held, 3), "mirrors": mirrors}
            served = self._stage_counts.get("candidate_p99", 0)
            if (held >= self.plan.stage_hold_s
                    and served >= self.plan.stage_min_requests):
                return self._advance(now)
            return {"state": self._state, "action": "hold",
                    "stage": self._stage_name(),
                    "held_s": round(held, 3), "candidate_requests": served}

    def _advance(self, now: float) -> Dict[str, Any]:
        """Green hold satisfied: enter the next stage (or promote).
        Called only from :meth:`step`, which holds ``self._lock``."""
        if self._state == CANARY and (self._stage_index
                                      == len(self.plan.canary_stages) - 1):
            return self._promote(now)
        self._stage_index += 1  # jaxlint: disable=JL004
        self._state = CANARY  # jaxlint: disable=JL004
        self._stage_entered = now  # jaxlint: disable=JL004
        self._stage_counts = {}  # jaxlint: disable=JL004
        self._set_fractions(self.plan.canary_stages[self._stage_index],
                            self.plan.shadow_fraction)
        self._record("advance", now,
                     fraction=self.plan.canary_stages[self._stage_index])
        return {"state": CANARY, "action": "advance",
                "stage": self._stage_name(),
                "fraction": self._split_fraction}

    def _promote(self, now: float) -> Dict[str, Any]:
        info = self.engine.promote_candidate()
        wall = now - self._offered_at
        self._m_promote_wall.observe(max(wall, 0.0), **self._tlabels)
        self._m_promotions.inc(**self._tlabels)
        self._promotions += 1
        if self._watermark is not None:
            # promotion = this generation now answers ALL traffic: stamp
            # the freshness pair's serving half (tenant series — exact
            # label match for FreshnessObjective — plus the
            # generation-labelled identity series)
            gauge = self.metrics.gauge(
                "svgd_serving_watermark",
                "event-time data watermark of the served ensemble")
            gauge.set(self._watermark, **self._tlabels)
            gauge.set(self._watermark,
                      generation=str(info["generation_id"]), **self._tlabels)
        # resets under step()'s lock (the only caller)
        tag = self._tag
        watermark = self._watermark
        self._state = IDLE  # jaxlint: disable=JL004
        self._stage_index = -1  # jaxlint: disable=JL004
        self._tag = None  # jaxlint: disable=JL004
        self._generation = None  # jaxlint: disable=JL004
        self._watermark = None  # jaxlint: disable=JL004
        self._set_fractions(0.0, 0.0)
        self._record("promote", now, tag=tag,
                     generation=info["generation_id"],
                     promote_s=round(wall, 3))
        return {"state": IDLE, "action": "promote", "tag": tag,
                "generation": info["generation_id"],
                "watermark": watermark,
                "promote_s": round(wall, 3)}

    def _rollback(self, now: float, reasons) -> Dict[str, Any]:
        """Breach streak: drop the candidate and zero the split — the
        still-resident incumbent keeps serving.  O(1); no checkpoint is
        ever read on this path (regression-pinned)."""
        self.engine.drop_candidate()
        self._m_rollbacks.inc(**self._tlabels)
        self._rollbacks += 1
        # resets under step()'s lock (the only caller)
        tag = self._tag
        stage = self._stage_name()
        self._state = IDLE  # jaxlint: disable=JL004
        self._stage_index = -1  # jaxlint: disable=JL004
        self._tag = None  # jaxlint: disable=JL004
        self._generation = None  # jaxlint: disable=JL004
        self._watermark = None  # jaxlint: disable=JL004
        self._breaches = 0  # jaxlint: disable=JL004
        self._set_fractions(0.0, 0.0)
        self._record("rollback", now, tag=tag, at_stage=stage,
                     objectives=list(reasons))
        return {"state": IDLE, "action": "rollback", "tag": tag,
                "at_stage": stage, "objectives": list(reasons)}

    # ------------------------------------------------------------------ #
    # background cadence / teardown

    def start(self, interval_s: float = 0.25) -> "RolloutController":
        """Run :meth:`step` on a background cadence (drills/tests drive
        ``step()`` manually instead)."""
        if self._thread is None:
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._loop, args=(float(interval_s),),
                name="rollout-controller", daemon=True)
            self._thread.start()
        return self

    def _loop(self, interval_s: float) -> None:
        while not self._stop_evt.is_set():
            try:
                self.step()
            except Exception:
                # one bad control step must not kill the cadence — the
                # rollout stays in its current stage until the next step
                pass
            self._stop_evt.wait(interval_s)

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def close(self) -> None:
        """Stop the cadence and the shadow worker (any in-flight mirror
        finishes; an idle rollout stays idle)."""
        self.stop()
        ex, self._executor = self._executor, None
        if ex is not None:
            ex.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------ #

    def status(self) -> Dict[str, Any]:
        """JSON-friendly controller document (the ``/rollout``-style
        introspection surface)."""
        with self._lock:
            return {
                "state": self._state,
                "stage": self._stage_name(),
                "fraction": self._split_fraction,
                "mirror_fraction": self._mirror_fraction,
                "tag": self._tag,
                "candidate_generation": self._generation,
                "serving_generation": self.engine.stats()["generation_id"],
                "breach_streak": self._breaches,
                "stage_counts": dict(self._stage_counts),
                "last_objectives": dict(self._last_rows),
                "promotions": self._promotions,
                "rollbacks": self._rollbacks,
                "supersedes": self._supersedes,
                "plan": self.plan.describe(),
                "recent": list(self.log)[-8:],
            }
