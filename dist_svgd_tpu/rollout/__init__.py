"""Progressive delivery of posterior generations.

Shadow traffic → staged canary hash-splits → SLO-gated automatic
promotion, with O(1) rollback to the still-resident incumbent.  See
:mod:`dist_svgd_tpu.rollout.controller`.
"""

from dist_svgd_tpu.rollout.controller import (
    DIVERGENCE_BUCKETS,
    RolloutController,
    RolloutPlan,
    prediction_divergence,
)

__all__ = [
    "DIVERGENCE_BUCKETS",
    "RolloutController",
    "RolloutPlan",
    "prediction_divergence",
]
