"""Streaming-ingest training: the batch trainer turned online.

:class:`StreamingSupervisor` layers continuous ingest over
:class:`~dist_svgd_tpu.resilience.supervisor.RunSupervisor`'s segmented
drive.  Each stream segment is one pass of a fixed lifecycle, traced as one
cross-thread lane tree (``ingest ⊃ train.segment ⊃ ckpt ⊃ reload``) so
``trace_report`` attributes exactly where freshness is spent:

1. **ingest** — poll the :class:`~dist_svgd_tpu.streaming.source.
   StreamBuffer` for due batches, fold them into the fixed-capacity
   :class:`~dist_svgd_tpu.streaming.source.RowRing` corpus, and swap the
   corpus into the sampler (``Sampler.set_data`` — a traced-argument swap,
   zero recompiles);
2. **drift check** — diagnostics (KSD/ESS, PR 6's detector) on the current
   particles against the NEW data's score; a
   :class:`~dist_svgd_tpu.resilience.guards.GuardViolation` escalates this
   segment from ``steps_per_segment`` incremental steps to a
   ``refit_steps`` full re-fit (counted in ``svgd_stream_refits_total``) —
   drift is never served without retraining against it;
3. **train + ckpt** — extend the absolute step grid and drive the base
   supervisor; every segment ends checkpointed, with the stream cursor /
   watermark / corpus ring riding ``_state_with_meta`` so a kill at ANY
   point resumes bitwise (the ``step_offset`` discipline extended to
   data);
4. **reload** — ``CheckpointHotReloader.poll_once`` publishes the new
   generation to the serving engine; an
   :class:`~dist_svgd_tpu.serving.engine.EnsembleRejected` rolls the
   tenant **back, never forward** (the reloader keeps serving the prior
   generation), and an admitted swap stamps the serving watermark the
   freshness SLO reads.

Freshness (event time → first serve) is observed per segment into
``svgd_freshness_seconds``.  Event times and the supervisor clock must
share one timeline — inject the same (manual or ``time.time``) clock into
the source's ``start_time``, the buffer, and this supervisor, as
``tools/freshness_drill.py`` does.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from dist_svgd_tpu.resilience.guards import (
    GuardConfig,
    GuardViolation,
    check_diagnostics,
)
from dist_svgd_tpu.resilience.supervisor import RunSupervisor
from dist_svgd_tpu.streaming.source import RowRing, StreamBuffer
from dist_svgd_tpu.telemetry import diagnostics as _diagnostics
from dist_svgd_tpu.telemetry import trace as _trace

__all__ = ["StreamingSupervisor"]


class StreamingSupervisor(RunSupervisor):
    """Continuous-ingest driver over a single-device minibatch ``Sampler``.

    Args:
        sampler: a minibatch-mode :class:`~dist_svgd_tpu.sampler.Sampler`
            whose ``data`` spec matches ``ring`` (``(capacity, dim)`` /
            ``(capacity,)``) — construct it from ``ring.data()`` after
            priming, or from zeros before the first ingest.  DistSampler
            streaming needs the sharded-data swap and is not wired yet.
        step_size: SVGD ε (the base supervisor's guard backoff applies).
        buffer: the bounded ingest buffer over the stream source.
        ring: the fixed-capacity corpus the sampler trains on.
        steps_per_segment: incremental steps per stream segment.
        refit_steps: steps of an escalated full re-fit segment after a
            drift trip (default ``10 × steps_per_segment``).
        drift_guard: :class:`~dist_svgd_tpu.resilience.guards.GuardConfig`
            whose *diagnostics* thresholds (``max_ksd`` /
            ``min_ess_frac``…) define a drift breach on new data.  Kept
            separate from the base supervisor's in-run ``guard`` on
            purpose: drift means *the world moved* — the answer is more
            training on the new data, not the numerical guards' rollback
            + step-size backoff.
        drift_diagnostics: :class:`~dist_svgd_tpu.telemetry.diagnostics.
            PosteriorDiagnostics` used for the pre-train drift check
            (score closure defaults to the sampler's own, which reads the
            CURRENT corpus — so the check judges old posterior vs new
            data, exactly the drift question).
        reloader: optional :class:`~dist_svgd_tpu.serving.engine.
            CheckpointHotReloader` watching this supervisor's manager root
            — polled once per segment (serve leg of the lifecycle).
        checkpointing is **required** (``checkpoint_dir`` or ``manager``):
            segments resume from checkpoints by construction.
        Remaining keyword args are :class:`RunSupervisor`'s.
    """

    def __init__(self, sampler, step_size: float, *,
                 buffer: StreamBuffer, ring: RowRing,
                 steps_per_segment: int,
                 refit_steps: Optional[int] = None,
                 drift_guard: Optional[GuardConfig] = None,
                 drift_diagnostics=None,
                 reloader=None,
                 **kwargs):
        if hasattr(sampler, "run_steps"):
            raise TypeError(
                "StreamingSupervisor drives a single-device minibatch "
                "Sampler; DistSampler streaming is not wired yet"
            )
        if getattr(sampler, "_batch_size", None) is None:
            raise ValueError(
                "StreamingSupervisor requires a minibatch sampler "
                "(batch_size) — full-data scans bake the dataset into the "
                "compiled program and cannot ingest"
            )
        if steps_per_segment < 1:
            raise ValueError(
                f"steps_per_segment must be >= 1, got {steps_per_segment}"
            )
        super().__init__(sampler, num_steps=steps_per_segment,
                         step_size=step_size, **kwargs)
        if self._manager is None:
            raise ValueError(
                "StreamingSupervisor requires checkpointing (checkpoint_dir "
                "or manager): segments publish through checkpoints"
            )
        self._buffer = buffer
        self._ring = ring
        self._steps_per_segment = int(steps_per_segment)
        self._refit_steps = (int(refit_steps) if refit_steps is not None
                             else 10 * self._steps_per_segment)
        if self._refit_steps < self._steps_per_segment:
            raise ValueError(
                f"refit_steps ({self._refit_steps}) must be >= "
                f"steps_per_segment ({self._steps_per_segment})"
            )
        self._drift_guard = drift_guard
        if drift_diagnostics is not None and drift_diagnostics.enabled:
            drift_diagnostics.ensure_score_fn(self._harness.score_fn)
        self._drift_diag = (drift_diagnostics if drift_diagnostics is not None
                            else _diagnostics.DISABLED)
        self._reloader = reloader
        # stream cursor state — rides _state_with_meta so kill→resume is
        # bitwise (the training-side step_offset discipline, for data)
        self._stream_next = 0
        self._stream_watermark: Optional[float] = None
        self._stream_dropped = 0
        self._stream_segments = 0
        self._stream_refits = 0
        reg = self.registry
        self._m_stream_segments = reg.counter(
            "svgd_stream_segments_total", "stream segments completed")
        self._m_stream_refits = reg.counter(
            "svgd_stream_refits_total",
            "segments escalated to a full re-fit by a drift breach")
        self._m_stream_rows = reg.counter(
            "svgd_stream_rows_total", "stream rows ingested into the corpus")
        self._g_corpus = reg.gauge(
            "svgd_stream_corpus_rows", "rows currently held by the corpus")
        self._m_freshness = reg.histogram(
            "svgd_freshness_seconds",
            "event time -> first-serve latency per published segment")
        #: Report of the most recent :meth:`run_stream` call.
        self.stream_report: Optional[dict] = None

    @property
    def drift_guard(self) -> Optional[GuardConfig]:
        """The drift-breach thresholds.  Settable mid-stream: drills and
        experiments run a few unguarded warm-up segments, measure the
        baseline KSD of the healthy posterior, then arm a guard calibrated
        against it (``tools/freshness_drill.py``'s protocol) — a fixed
        a-priori threshold would be wrong on every new model/box pair."""
        return self._drift_guard

    @drift_guard.setter
    def drift_guard(self, guard: Optional[GuardConfig]) -> None:
        self._drift_guard = guard

    # ------------------------------------------------------------------ #
    # checkpoint seam: stream cursor + corpus ride every save

    def _state_with_meta(self) -> dict:
        state = super()._state_with_meta()
        state.update(self._ring.state_dict())
        state["stream_next"] = np.asarray(self._stream_next, dtype=np.int64)
        state["stream_watermark"] = np.asarray(
            self._stream_watermark if self._stream_watermark is not None
            else -np.inf, dtype=np.float64)
        state["stream_dropped"] = np.asarray(self._stream_dropped,
                                             dtype=np.int64)
        return state

    def _apply_resume_state(self, state: dict) -> None:
        super()._apply_resume_state(state)
        ckpt_next = int(state.get("stream_next", -1))
        if ckpt_next < 0:
            return  # non-streaming checkpoint (plain RunSupervisor save)
        if ckpt_next <= self._stream_next:
            # warm per-segment resume: the in-memory stream is at or past
            # the checkpoint (this segment's ingest already happened) —
            # restoring the older corpus would TRAIN ON STALE DATA
            return
        # cold resume (fresh process): rebuild the corpus bitwise from the
        # checkpointed ring and fast-forward the pull cursor past every
        # batch the corpus already holds
        self._ring.load_state_dict(state)
        self._stream_next = ckpt_next
        wm = float(np.asarray(state["stream_watermark"]))
        self._stream_watermark = None if np.isinf(wm) and wm < 0 else wm
        self._stream_dropped = int(state.get("stream_dropped", 0))
        self._buffer.seek(ckpt_next)
        if self._ring.written > 0:
            self.sampler.set_data(self._ring.data())
        self._g_corpus.set(min(self._ring.written, self._ring.capacity))

    # ------------------------------------------------------------------ #

    def ingest(self, now: Optional[float] = None) -> dict:
        """One ingest pass: poll due batches, fold into the ring, swap the
        corpus into the sampler.  Returns ``{batches, rows, watermark}``."""
        self._buffer.poll(now)
        batches = self._buffer.take()
        rows = 0
        for b in batches:
            self._ring.extend(b.x, b.y)
            rows += b.rows
        if batches:
            self._stream_watermark = batches[-1].event_time
            self._m_stream_rows.inc(rows)
            self._g_corpus.set(min(self._ring.written, self._ring.capacity))
            self.sampler.set_data(self._ring.data())
        self._stream_next = self._buffer.next_ordinal
        self._stream_dropped = self._buffer.dropped
        return {"batches": len(batches), "rows": rows,
                "watermark": self._stream_watermark}

    def _check_drift(self) -> Optional[str]:
        """Judge the current posterior against the NEW corpus; returns the
        breach reason (→ escalate to re-fit) or ``None``."""
        if (self._drift_guard is None
                or not self._drift_guard.checks_diagnostics
                or not self._drift_diag.enabled):
            return None
        report = self._drift_diag.compute(
            self._harness.particles, num_shards=self._harness.num_shards,
            step=self._harness.t)
        try:
            check_diagnostics(report, self._drift_guard)
        except GuardViolation as e:
            _trace.instant("stream.drift_trip", {"reason": e.reason,
                                                 "t": self._harness.t})
            self._log(event="drift_trip", t=self._harness.t,
                      reason=e.reason)
            return e.reason
        return None

    def run_segment_once(self, *, resume: bool = False) -> dict:
        """One full stream segment: ingest → drift check → train (+ckpt)
        → hot-reload publish.  ``resume=True`` on the FIRST segment of a
        process restores the newest checkpoint (cold resume — the corpus
        ring and stream cursor come back bitwise); later segments always
        continue warm on the same grid."""
        tracer = _trace.get_tracer()
        tnow = tracer.now if tracer is not None else self._clock
        first = self._stream_segments == 0
        if first and resume:
            # cold resume must land BEFORE the first ingest: the restored
            # ring already holds every checkpointed batch, and the restore
            # seeks the buffer past them — polling first would re-pull and
            # double-ingest, breaking bitwise resume
            state = self._manager.restore_latest()
            if state is not None:
                self._apply_resume_state(state)
        seg_t0 = tnow()

        # -- ingest --------------------------------------------------- #
        ing = self.ingest()
        ing_t1 = tnow()

        t_base = self._harness.t
        # -- drift check (old posterior vs new data) ------------------- #
        # an untrained posterior (t=0) makes every diagnostic scream, so
        # the detector arms once any training has happened (including a
        # cold-resumed trajectory)
        drift = None
        if t_base > 0 and ing["batches"]:
            drift = self._check_drift()
        steps = self._refit_steps if drift else self._steps_per_segment
        if drift:
            self._stream_refits += 1
            self._m_stream_refits.inc()

        # -- train + checkpoint ---------------------------------------- #
        self.num_steps = t_base + steps
        report = self.run(resume=(resume if first else True))
        train_t1 = tnow()
        ck_wall = report["checkpoint_wall_s"]

        # -- publish (hot reload; rejected reloads roll BACK) ----------- #
        reload_step = None
        rejected = False
        rollout_decision = None
        rel_t0 = tnow()
        if self._reloader is not None:
            rejects0 = self._reloader.engine.stats()["reload_rejects"]
            reload_step = self._reloader.poll_once()
            rejected = (self._reloader.engine.stats()["reload_rejects"]
                        > rejects0)
            rollout = getattr(self._reloader, "rollout", None)
            if rollout is not None:
                # progressive delivery (round 21): the supervisor publishes
                # candidates INTO the rollout (poll_once offered above) and
                # drives one control step per segment — deterministic, on
                # the segment cadence, with the supervisor's own liveness
                # (a separate controller thread would race the injectable
                # clocks tier-1 relies on).  Promotion/rollback decisions
                # land here, in the segment record.
                rollout_decision = rollout.step()
        rel_t1 = tnow()

        freshness_s = None
        if (reload_step is not None and self._stream_watermark is not None
                and rollout_decision is None):
            # event time of the newest datum this generation was trained
            # on → the moment it started serving (one shared timeline)
            freshness_s = max(self._clock() - self._stream_watermark, 0.0)
            self._m_freshness.observe(freshness_s)
        elif (rollout_decision is not None
              and rollout_decision.get("action") == "promote"
              and rollout_decision.get("watermark") is not None):
            # rollout-published generations count as served at PROMOTION
            # (candidate traffic is not "served" freshness-wise): the
            # freshness observation uses the promoted generation's own
            # offered watermark, which may trail the live ingest cursor
            freshness_s = max(
                self._clock() - rollout_decision["watermark"], 0.0)
            self._m_freshness.observe(freshness_s)

        self._stream_segments += 1
        self._m_stream_segments.inc()
        if tracer is not None:
            tracer.lane_tree(
                "stream.lifetime", seg_t0, rel_t1,
                tags={"segment": self._stream_segments - 1,
                      "batches": ing["batches"], "steps": steps,
                      "drift": bool(drift), "reload_step": reload_step},
                children=[
                    ("ingest", seg_t0, ing_t1),
                    ("train.segment", ing_t1, train_t1 - ck_wall),
                    ("ckpt", train_t1 - ck_wall, train_t1),
                    ("reload", rel_t0, rel_t1),
                ])
        seg = {
            "segment": self._stream_segments - 1,
            "t": self._harness.t,
            "steps": steps,
            "batches": ing["batches"],
            "rows": ing["rows"],
            "drift": drift,
            "refit": bool(drift),
            "watermark": self._stream_watermark,
            "dropped_total": self._stream_dropped,
            "reload_step": reload_step,
            "reload_rejected": rejected,
            "rollout": rollout_decision,
            "freshness_s": freshness_s,
            "resumed_from": report["resumed_from"],
            "train_status": report["status"],
            "wall_s": report["wall_s"],
        }
        self._log(event="stream_segment", **seg)
        return seg

    def run_stream(self, num_segments: int, *, resume: bool = False) -> dict:
        """Drive ``num_segments`` stream segments; returns (and keeps as
        :attr:`stream_report`) the aggregate report."""
        if num_segments < 1:
            raise ValueError(
                f"num_segments must be >= 1, got {num_segments}"
            )
        segments = []
        for _ in range(num_segments):
            segments.append(self.run_segment_once(resume=resume))
        freshness = [s["freshness_s"] for s in segments
                     if s["freshness_s"] is not None]
        self.stream_report = {
            "segments": len(segments),
            "t": self._harness.t,
            "batches": sum(s["batches"] for s in segments),
            "rows": sum(s["rows"] for s in segments),
            "dropped": self._stream_dropped,
            "refits": self._stream_refits,
            "drift_trips": [s["segment"] for s in segments if s["drift"]],
            "reloads": sum(1 for s in segments
                           if s["reload_step"] is not None),
            "reload_rejections": sum(1 for s in segments
                                     if s["reload_rejected"]),
            "promotions": sum(1 for s in segments
                              if (s["rollout"] or {}).get("action")
                              == "promote"),
            "rollout_rollbacks": sum(1 for s in segments
                                     if (s["rollout"] or {}).get("action")
                                     == "rollback"),
            "watermark": self._stream_watermark,
            "freshness_s": freshness,
            "segment_reports": segments,
        }
        return self.stream_report
