"""Streaming SVGD: continuous-ingest training with an end-to-end
freshness SLO.

The minibatch score is an unbiased estimator over whatever data exists at
step t (Liu & Wang 2016) — this package supplies the plumbing that lets
data arrive continuously without giving up any standing contract:

- :mod:`source` — seeded, clock-injectable stream sources with
  arithmetic event times (drifting generators + a covertype replay
  adapter), a bounded :class:`StreamBuffer` with explicit drop
  accounting, and the fixed-capacity :class:`RowRing` corpus that keeps
  the compiled scan's data shape constant (zero steady-state recompiles);
- :mod:`pipeline` — :class:`StreamingSupervisor`: incremental training
  segments against the growing/shifting corpus, bitwise kill→resume (the
  stream cursor and ring ride every checkpoint), PR 6's KSD/ESS drift
  guard as the retrain *trigger*, and per-segment publication to a live
  serving engine via ``CheckpointHotReloader`` — rejected generations
  roll back, never forward.

``telemetry/slo.py:FreshnessObjective`` turns the ingest/serving
watermark gauge pair into the ``freshness`` SLO served at ``/slo``;
``tools/freshness_drill.py`` measures the whole loop as one gated bench
row.
"""

from dist_svgd_tpu.streaming.pipeline import StreamingSupervisor
from dist_svgd_tpu.streaming.source import (
    CovertypeReplayStream,
    GrowingCorpusStream,
    LabelFlipStream,
    MeanShiftStream,
    RowRing,
    StreamBatch,
    StreamBuffer,
    StreamSource,
)

__all__ = [
    "StreamingSupervisor",
    "StreamSource",
    "StreamBatch",
    "StreamBuffer",
    "RowRing",
    "MeanShiftStream",
    "LabelFlipStream",
    "GrowingCorpusStream",
    "CovertypeReplayStream",
]
