"""Streaming data sources: seeded, clock-injectable, event-time-stamped.

The sampler's minibatch score is an unbiased estimator over *whatever data
exists at step t* (Liu & Wang 2016) — nothing in the math requires a fixed
dataset.  This module supplies the plumbing half of that observation:

- a :class:`StreamSource` base whose batches are a **pure function of
  (seed, ordinal)** — ``batch_at(o)`` replays bitwise, so a killed and
  resumed pipeline reconstructs the exact corpus the uninterrupted one
  held (the training-side ``step_offset`` discipline extended to data);
- **event time** is stamped arithmetically (``start_time + o · period``),
  never read from a wall clock — the injectable clock decides only *when*
  a batch becomes due, so tier-1 tests replay hours of stream in
  milliseconds;
- deterministic **drift**: sources take
  :class:`~dist_svgd_tpu.resilience.faults.DriftAt` windows (ordinal-keyed
  like the fleet faults) and some generators drift intrinsically — either
  way a replayed ordinal reproduces its shift exactly;
- a bounded :class:`StreamBuffer` whose overflow policy is **explicit
  drop-oldest with accounting** (``svgd_stream_dropped_total``): data loss
  is a counter the freshness gate FAILs on, never a silent slice;
- a fixed-capacity :class:`RowRing` corpus so the traced data argument of
  the compiled scan (``Sampler.set_data``) keeps one shape forever — the
  zero-steady-state-recompile contract extended to streaming ingest.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import numpy as np

from dist_svgd_tpu.resilience.faults import DriftAt
from dist_svgd_tpu.telemetry import metrics as _metrics

__all__ = [
    "StreamBatch",
    "StreamSource",
    "MeanShiftStream",
    "LabelFlipStream",
    "GrowingCorpusStream",
    "CovertypeReplayStream",
    "StreamBuffer",
    "RowRing",
]


@dataclass(frozen=True)
class StreamBatch:
    """One event-time-stamped batch: ``x`` features ``(rows, dim)``
    float32, ``y`` labels ``(rows,)`` float64 in {-1, +1} (the covertype
    convention every model in :mod:`~dist_svgd_tpu.models` speaks)."""

    ordinal: int
    event_time: float
    x: np.ndarray
    y: np.ndarray

    @property
    def rows(self) -> int:
        return int(self.x.shape[0])


class StreamSource:
    """Base class: subclasses implement the pure ``_raw_batch(ordinal)``.

    Args:
        batch_rows / dim: fixed batch geometry (constant shapes are what
            keep the downstream compiled scan retrace-free).
        seed: root of every batch's RNG — ``(seed, ordinal)`` seeds a
            fresh generator per batch, so ordinals replay independently.
        period_s: event-time spacing; batch ``o`` carries
            ``event_time = start_time + o · period_s`` and becomes due
            when the (injected) clock reaches it.
        start_time: epoch of ordinal 0 on the caller's clock timeline.
        faults: :class:`~dist_svgd_tpu.resilience.faults.DriftAt`
            windows applied (in order) to every batch whose ordinal they
            cover — deterministic injected distribution shift.
        num_batches: ``None`` for unbounded generators; replay adapters
            set the finite count.
    """

    def __init__(self, *, batch_rows: int, dim: int, seed: int = 0,
                 period_s: float = 1.0, start_time: float = 0.0,
                 faults: Sequence[DriftAt] = (),
                 num_batches: Optional[int] = None):
        if batch_rows < 1:
            raise ValueError(f"batch_rows must be >= 1, got {batch_rows}")
        if dim < 1:
            raise ValueError(f"dim must be >= 1, got {dim}")
        if period_s <= 0:
            raise ValueError(f"period_s must be positive, got {period_s}")
        self.batch_rows = int(batch_rows)
        self.dim = int(dim)
        self.seed = int(seed)
        self.period_s = float(period_s)
        self.start_time = float(start_time)
        self.faults = tuple(faults)
        for f in self.faults:
            if not isinstance(f, DriftAt):
                raise TypeError(
                    f"stream faults must be DriftAt, got {type(f).__name__}"
                )
        self.num_batches = None if num_batches is None else int(num_batches)

    # -- pure per-ordinal surface -------------------------------------- #

    def _raw_batch(self, ordinal: int) -> Tuple[np.ndarray, np.ndarray]:
        raise NotImplementedError  # pragma: no cover - abstract

    def _rng(self, ordinal: int) -> np.random.Generator:
        return np.random.default_rng((self.seed, int(ordinal)))

    def event_time(self, ordinal: int) -> float:
        return self.start_time + int(ordinal) * self.period_s

    def due(self, ordinal: int, now: float) -> bool:
        """Whether batch ``ordinal`` has arrived by clock time ``now``."""
        if self.num_batches is not None and ordinal >= self.num_batches:
            return False
        return self.event_time(ordinal) <= now

    def batch_at(self, ordinal: int) -> StreamBatch:
        """The batch at ``ordinal`` — pure: same (seed, ordinal, faults)
        always yields the identical bytes, drift included."""
        ordinal = int(ordinal)
        if ordinal < 0:
            raise ValueError(f"ordinal must be >= 0, got {ordinal}")
        if self.num_batches is not None and ordinal >= self.num_batches:
            raise IndexError(
                f"ordinal {ordinal} past the bounded source's "
                f"{self.num_batches} batches"
            )
        x, y = self._raw_batch(ordinal)
        for f in self.faults:
            if f.active(ordinal):
                x, y = f.apply(x, y)
        x = np.ascontiguousarray(x, dtype=np.float32)
        y = np.ascontiguousarray(y, dtype=np.float64)
        return StreamBatch(ordinal=ordinal,
                           event_time=self.event_time(ordinal), x=x, y=y)


class _LogisticStreamBase(StreamSource):
    """Shared synthetic geometry: features ~ N(mean_o, I); ±1 labels from
    a fixed ground-truth logistic weight vector drawn once from ``seed``
    (so the *posterior target* is stable and only the covariates/labels
    drift as each generator dictates)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        w_rng = np.random.default_rng((self.seed, 0x5eed))
        self._w = w_rng.normal(size=self.dim).astype(np.float64)

    def _mean(self, ordinal: int) -> float:
        return 0.0

    def _flip_frac(self, ordinal: int) -> float:
        return 0.0

    def _raw_batch(self, ordinal):
        rng = self._rng(ordinal)
        x = (rng.normal(size=(self.batch_rows, self.dim))
             + self._mean(ordinal)).astype(np.float32)
        p = 1.0 / (1.0 + np.exp(-(x.astype(np.float64) @ self._w)))
        y = np.where(rng.random(self.batch_rows) < p, 1.0, -1.0)
        frac = self._flip_frac(ordinal)
        if frac > 0.0:
            k = int(round(min(frac, 1.0) * self.batch_rows))
            if k > 0:
                idx = np.linspace(0, self.batch_rows - 1,
                                  num=k).round().astype(int)
                y[idx] = -y[idx]
        return x, y


class MeanShiftStream(_LogisticStreamBase):
    """Covariate drift: the feature mean moves by ``rate`` per ordinal —
    the slow continuous shift the KSD guard must notice as a
    posterior/data mismatch."""

    def __init__(self, *, rate: float = 0.0, **kwargs):
        super().__init__(**kwargs)
        self.rate = float(rate)

    def _mean(self, ordinal):
        return self.rate * ordinal


class LabelFlipStream(_LogisticStreamBase):
    """Concept drift: a deterministic (strided, RNG-free) fraction of each
    batch's labels is negated, growing by ``rate`` per ordinal up to
    ``max_frac`` — the decision boundary itself degrades."""

    def __init__(self, *, rate: float = 0.0, max_frac: float = 0.5,
                 **kwargs):
        super().__init__(**kwargs)
        if not 0.0 <= max_frac <= 1.0:
            raise ValueError(f"max_frac must be in [0, 1], got {max_frac}")
        self.rate = float(rate)
        self.max_frac = float(max_frac)

    def _flip_frac(self, ordinal):
        return min(self.rate * ordinal, self.max_frac)


class GrowingCorpusStream(_LogisticStreamBase):
    """Stationary generator: every ordinal samples the same distribution —
    no drift, the corpus simply grows as batches accumulate (the
    freshness-without-retrain baseline the drill's no-drift phases use)."""


class CovertypeReplayStream(StreamSource):
    """Replay adapter: serves :func:`~dist_svgd_tpu.utils.datasets.
    load_covertype` as a bounded timestamped stream — consecutive
    ``batch_rows`` slices in row order, one per period.  The dataset loads
    once; ``batch_at`` is a pure slice of it, so replays are bitwise like
    every other source."""

    def __init__(self, *, n_rows: int = 50_000, batch_rows: int = 512,
                 seed: int = 0, period_s: float = 1.0,
                 start_time: float = 0.0, faults: Sequence[DriftAt] = ()):
        from dist_svgd_tpu.utils.datasets import load_covertype

        x, y = load_covertype(n_rows=n_rows, seed=seed)
        self._x = np.ascontiguousarray(np.asarray(x), dtype=np.float32)
        self._y = np.ascontiguousarray(np.asarray(y), dtype=np.float64)
        super().__init__(
            batch_rows=batch_rows, dim=int(self._x.shape[1]), seed=seed,
            period_s=period_s, start_time=start_time, faults=faults,
            num_batches=self._x.shape[0] // int(batch_rows),
        )

    def _raw_batch(self, ordinal):
        lo = ordinal * self.batch_rows
        hi = lo + self.batch_rows
        return self._x[lo:hi].copy(), self._y[lo:hi].copy()


class StreamBuffer:
    """Bounded ingest buffer between a source and the trainer.

    ``poll(now)`` pulls every due, not-yet-pulled batch in ordinal order;
    past ``capacity`` buffered batches the **oldest is dropped**, counted
    in ``svgd_stream_dropped_total`` and :attr:`dropped` — an overloaded
    trainer loses data *loudly* (the freshness gate FAILs on it), never by
    silent truncation.  The ingest watermark (``svgd_stream_watermark``)
    is the newest pulled event time — what the freshness SLO compares the
    serving watermark against.  Thread-safe; the scanner/trainer threads
    and a metrics scrape may interleave freely.
    """

    def __init__(self, source: StreamSource, capacity: int, *,
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 clock=time.time):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.source = source
        self.capacity = int(capacity)
        self._clock = clock
        self._lock = threading.Lock()
        self._buf: deque = deque()
        self.next_ordinal = 0
        self.pulled = 0
        self.dropped = 0
        self.watermark: Optional[float] = None
        reg = registry if registry is not None else _metrics.default_registry()
        self._m_pulled = reg.counter(
            "svgd_stream_batches_total", "batches pulled from the source")
        self._m_dropped = reg.counter(
            "svgd_stream_dropped_total",
            "batches dropped by buffer overflow — stream data LOST")
        self._g_watermark = reg.gauge(
            "svgd_stream_watermark",
            "event time of the newest ingested batch (ingest watermark)")
        self._g_depth = reg.gauge(
            "svgd_stream_buffer_depth", "batches currently buffered")

    def seek(self, ordinal: int) -> None:
        """Fast-forward the pull cursor (cold resume: the checkpointed
        corpus already holds everything before ``ordinal``)."""
        with self._lock:
            self.next_ordinal = max(self.next_ordinal, int(ordinal))

    def poll(self, now: Optional[float] = None) -> int:
        """Pull all due batches; returns how many arrived this poll."""
        now = self._clock() if now is None else now
        pulled = 0
        with self._lock:
            while self.source.due(self.next_ordinal, now):
                batch = self.source.batch_at(self.next_ordinal)
                self.next_ordinal += 1
                self._buf.append(batch)
                pulled += 1
                self.pulled += 1
                self._m_pulled.inc()
                self.watermark = batch.event_time
                self._g_watermark.set(batch.event_time)
                if len(self._buf) > self.capacity:
                    self._buf.popleft()
                    self.dropped += 1
                    self._m_dropped.inc()
            self._g_depth.set(len(self._buf))
        return pulled

    def take(self) -> list:
        """Drain the buffer (ordinal order) — the trainer's ingest step."""
        with self._lock:
            out = list(self._buf)
            self._buf.clear()
            self._g_depth.set(0)
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)


class RowRing:
    """Fixed-capacity row corpus: the traced ``data`` argument of the
    compiled minibatch scan must keep ONE shape forever (a growing array
    would retrace per segment), so the corpus is a ``(capacity, dim)``
    ring — a sliding window once full, cyclically tiled before that.

    The tiling means early minibatches oversample the few rows that exist
    yet (a mild, vanishing duplication bias — the unbiased-minibatch
    estimator is over the *held* corpus either way); once
    ``written >= capacity`` the window is exact.

    Ring state is plain numpy (:meth:`state_dict` /
    :meth:`load_state_dict`), riding the supervisor checkpoint so a
    killed pipeline resumes the corpus bitwise.
    """

    def __init__(self, capacity: int, dim: int):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.dim = int(dim)
        self._x = np.zeros((self.capacity, self.dim), dtype=np.float32)
        self._y = np.zeros((self.capacity,), dtype=np.float64)
        self._pos = 0
        self.written = 0  # total rows ever written

    def extend(self, x: np.ndarray, y: np.ndarray) -> None:
        x = np.asarray(x, dtype=np.float32)
        y = np.asarray(y, dtype=np.float64)
        if x.ndim != 2 or x.shape[1] != self.dim or x.shape[0] != y.shape[0]:
            raise ValueError(
                f"expected x ({x.shape[0]}, {self.dim}) with matching y, "
                f"got x {x.shape} / y {y.shape}"
            )
        n = x.shape[0]
        if n > self.capacity:
            # only the newest `capacity` rows can survive anyway
            x, y = x[-self.capacity:], y[-self.capacity:]
            self.written += n - self.capacity
            n = self.capacity
        i = self._pos
        first = min(n, self.capacity - i)
        self._x[i:i + first] = x[:first]
        self._y[i:i + first] = y[:first]
        rest = n - first
        if rest:
            self._x[:rest] = x[first:]
            self._y[:rest] = y[first:]
        self._pos = (i + n) % self.capacity
        self.written += n

    def data(self) -> Tuple[np.ndarray, np.ndarray]:
        """The constant-shape ``(x, y)`` corpus view (always
        ``(capacity, dim)`` / ``(capacity,)`` copies)."""
        if self.written == 0:
            raise ValueError("RowRing.data() before any rows were written")
        w = min(self.written, self.capacity)
        if w == self.capacity:
            return self._x.copy(), self._y.copy()
        reps = -(-self.capacity // w)
        x = np.tile(self._x[:w], (reps, 1))[:self.capacity]
        y = np.tile(self._y[:w], reps)[:self.capacity]
        return np.ascontiguousarray(x), np.ascontiguousarray(y)

    def state_dict(self) -> dict:
        return {
            "stream_ring_x": self._x.copy(),
            "stream_ring_y": self._y.copy(),
            "stream_ring_pos": np.asarray(self._pos, dtype=np.int64),
            "stream_ring_written": np.asarray(self.written, dtype=np.int64),
        }

    def load_state_dict(self, state: dict) -> None:
        x = np.asarray(state["stream_ring_x"], dtype=np.float32)
        if x.shape != (self.capacity, self.dim):
            raise ValueError(
                f"ring checkpoint shape {x.shape} != configured "
                f"({self.capacity}, {self.dim})"
            )
        self._x = x.copy()
        self._y = np.asarray(state["stream_ring_y"], dtype=np.float64).copy()
        self._pos = int(state["stream_ring_pos"])
        self.written = int(state["stream_ring_written"])
