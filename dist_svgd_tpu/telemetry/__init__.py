"""Unified telemetry: span tracing + metrics registry.

One layer, two complementary views of the same running system:

- :mod:`~dist_svgd_tpu.telemetry.metrics` — thread-safe **registry** of
  counters / gauges / histograms (fixed log-spaced latency buckets) with
  Prometheus text exposition; the serving ``/metrics`` route serves it.
- :mod:`~dist_svgd_tpu.telemetry.trace` — **span tracer**: nestable
  thread-aware spans with optional device fencing, request lane trees,
  XLA-compile instant events; zero-cost no-op while disabled; exports
  Chrome trace-event JSON (Perfetto) and JSONL.  Summarise a trace with
  ``tools/trace_report.py``.

Quickstart (see README "Observability")::

    from dist_svgd_tpu import telemetry

    tracer = telemetry.enable()             # spans now record
    ...serve / train...
    telemetry.disable().export_chrome("trace.json")

    print(telemetry.default_registry().exposition())   # Prometheus text
"""

from dist_svgd_tpu.telemetry.metrics import (
    LATENCY_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
)
from dist_svgd_tpu.telemetry.trace import (
    SpanHandle,
    Tracer,
    disable,
    enable,
    enabled,
    get_tracer,
    instant,
    span,
)

__all__ = [
    "LATENCY_BUCKETS_S",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "default_registry",
    "SpanHandle",
    "Tracer",
    "disable",
    "enable",
    "enabled",
    "get_tracer",
    "instant",
    "span",
]
