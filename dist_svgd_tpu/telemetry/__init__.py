"""Unified telemetry: span tracing, metrics registry, posterior
diagnostics, SLOs, and the crash flight recorder.

One layer, four complementary views of the same running system:

- :mod:`~dist_svgd_tpu.telemetry.metrics` — thread-safe **registry** of
  counters / gauges / histograms (fixed log-spaced latency buckets) with
  Prometheus text exposition; the serving ``/metrics`` route serves it.
- :mod:`~dist_svgd_tpu.telemetry.trace` — **span tracer**: nestable
  thread-aware spans with optional device fencing, request lane trees,
  XLA-compile instant events; zero-cost no-op while disabled; exports
  Chrome trace-event JSON (Perfetto) and JSONL.  Also home of the
  **flight recorder** — a bounded black box that dumps a postmortem
  bundle when a guard trips or a fault fires (``tools/trace_report.py
  --postmortem`` renders it).
- :mod:`~dist_svgd_tpu.telemetry.diagnostics` — **posterior health**:
  jitted, chunk-safe on-device statistics (kernelized Stein discrepancy,
  kernel ESS, collapse indicators, inter-shard divergence) computed every
  K supervised steps and flowed into the registry as ``svgd_diag_*``
  gauges.
- :mod:`~dist_svgd_tpu.telemetry.slo` — **declarative SLOs** (burn rates
  over the registry's histogram windows, gauge ceilings, staleness);
  the serving server exposes the evaluation at ``/slo``.
- :mod:`~dist_svgd_tpu.telemetry.profile` — **dispatch profiler**: while
  enabled, every plan-compiled dispatch is fenced and its wall time
  attributed to its ``plan://<label>`` program identity
  (``svgd_prog_dispatch_seconds{label}`` + rows/bytes counters);
  ``tools/trace_report.py --programs`` renders the top-programs view.
- :mod:`~dist_svgd_tpu.telemetry.usage` — **per-tenant cost metering**:
  monotonic device-seconds / rows / queue-seconds / requests / compiles
  counters fed by the serving path, summarised at ``/usage`` and
  federated fleet-wide by ``serving/fleet.py``.
- :mod:`~dist_svgd_tpu.telemetry.history` — **telemetry history**: a
  bounded on-disk ring of periodic window-delta registry snapshots;
  ``tools/anomaly_report.py`` runs change-point detection over it.

Quickstart (see README "Observability" and "Posterior health")::

    from dist_svgd_tpu import telemetry

    tracer = telemetry.enable()             # spans now record
    ...serve / train...
    telemetry.disable().export_chrome("trace.json")

    print(telemetry.default_registry().exposition())   # Prometheus text
"""

from dist_svgd_tpu.telemetry.metrics import (
    LATENCY_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    combined_exposition,
    default_registry,
    dump_delta,
)
from dist_svgd_tpu.telemetry.trace import (
    TRACE_HEADER,
    FlightRecorder,
    SpanHandle,
    Tracer,
    disable,
    enable,
    enabled,
    flight_recorder,
    get_trace_context,
    get_tracer,
    install_flight_recorder,
    instant,
    mint_trace_id,
    record_flight,
    set_trace_context,
    span,
    uninstall_flight_recorder,
)

__all__ = [
    "LATENCY_BUCKETS_S",
    "TRACE_HEADER",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "combined_exposition",
    "default_registry",
    "dump_delta",
    "get_trace_context",
    "mint_trace_id",
    "set_trace_context",
    "FlightRecorder",
    "SpanHandle",
    "Tracer",
    "disable",
    "enable",
    "enabled",
    "flight_recorder",
    "get_tracer",
    "install_flight_recorder",
    "instant",
    "record_flight",
    "span",
    "uninstall_flight_recorder",
    # lazy (jax-importing) modules — resolved on first attribute access
    "DiagnosticsConfig",
    "PosteriorDiagnostics",
    "ReloadPolicy",
    "ensemble_health",
    "SloEngine",
    "LatencyObjective",
    "RatioObjective",
    "GaugeCeiling",
    "StalenessObjective",
    "FreshnessObjective",
    "default_serving_slos",
    "default_training_slos",
    "default_streaming_slos",
    "DispatchProfiler",
    "enable_profiler",
    "disable_profiler",
    "get_profiler",
    "profiler_enabled",
    "UsageMeter",
    "enable_usage",
    "disable_usage",
    "get_meter",
    "usage_enabled",
    "usage_summary",
    "TelemetryHistory",
    "HistoryRecorder",
]

_LAZY = {
    "DiagnosticsConfig": "diagnostics",
    "PosteriorDiagnostics": "diagnostics",
    "ReloadPolicy": "diagnostics",
    "ensemble_health": "diagnostics",
    "SloEngine": "slo",
    "LatencyObjective": "slo",
    "RatioObjective": "slo",
    "GaugeCeiling": "slo",
    "StalenessObjective": "slo",
    "FreshnessObjective": "slo",
    "default_serving_slos": "slo",
    "default_training_slos": "slo",
    "default_streaming_slos": "slo",
    # profile/usage/history are stdlib+numpy-light, but stay lazy so the
    # eager import surface is exactly what PR 5 left it
    "DispatchProfiler": "profile",
    "enable_profiler": "profile",
    "disable_profiler": "profile",
    "get_profiler": "profile",
    "profiler_enabled": "profile",
    "UsageMeter": "usage",
    "enable_usage": "usage",
    "disable_usage": "usage",
    "get_meter": "usage",
    "usage_enabled": "usage",
    "usage_summary": "usage",
    "TelemetryHistory": "history",
    "HistoryRecorder": "history",
}


def __getattr__(name):
    """PEP 562 lazy re-exports: the diagnostics module imports jax (and
    the kernel ops) at module load — deferring keeps ``import
    dist_svgd_tpu.telemetry`` as light as PR 5 left it for consumers that
    only want the registry or tracer."""
    submodule = _LAZY.get(name)
    if submodule is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    mod = importlib.import_module(f"{__name__}.{submodule}")
    value = getattr(mod, name)
    globals()[name] = value  # cache: subsequent lookups skip __getattr__
    return value
