"""Telemetry history: a bounded on-disk ring of metrics snapshots.

In-process metrics answer "what is happening now"; BENCH.md rows answer
"what did a hand-run drill measure".  Nothing answered "when did this
series start drifting?" — the history layer does.  A
:class:`HistoryRecorder` periodically dumps a
:class:`~dist_svgd_tpu.telemetry.metrics.MetricsRegistry` and writes
**window deltas** (via :func:`~dist_svgd_tpu.telemetry.metrics.
dump_delta`, inheriting its counter reset-clamp: a restarted process
yields a zero window, never a negative one) into a
:class:`TelemetryHistory` — a directory ring of
``telemetry_<seq>.json`` records, oldest pruned past ``capacity`` so a
long-running server cannot grow the directory without bound.

Each record is self-describing::

    {"format": "svgd-telemetry-history-1", "seq": 42, "ts": <clock>,
     "interval_s": <seconds since previous record, 0.0 for the first>,
     "window": <dump_delta document>}

The first record's window is cumulative-since-start (``dump_delta``'s
``prev=None`` convention) with ``interval_s == 0.0`` — rate consumers
skip it.

The recorder is clock-injectable and has **no background thread**:
callers own the cadence (a serving loop calls :meth:`HistoryRecorder.
maybe_record` wherever it already ticks; drills and tests call
:meth:`~HistoryRecorder.record_once` at exact simulated times), which
is what keeps ``tools/anomaly_report.py`` verdicts deterministic on
fixture histories.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Callable, Dict, List, Optional, Tuple

__all__ = [
    "HISTORY_FORMAT",
    "TelemetryHistory",
    "HistoryRecorder",
    "series_values",
    "list_series",
]

HISTORY_FORMAT = "svgd-telemetry-history-1"

_RECORD_RE = re.compile(r"^telemetry_(\d{8})\.json$")


class TelemetryHistory:
    """The directory ring.  ``capacity`` bounds the number of records on
    disk; sequence numbers keep increasing across prunes (and across
    process restarts — the ring re-seats itself on the existing files)."""

    def __init__(self, root: str, capacity: int = 512):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.root = root
        self.capacity = capacity
        os.makedirs(root, exist_ok=True)
        seqs = self._seqs()
        self._next_seq = (seqs[-1] + 1) if seqs else 0

    # ------------------------------------------------------------ #

    def _seqs(self) -> List[int]:
        out = []
        for name in os.listdir(self.root):
            m = _RECORD_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def _path(self, seq: int) -> str:
        return os.path.join(self.root, f"telemetry_{seq:08d}.json")

    def append(self, record: dict) -> str:
        """Write one record (assigning it the next sequence number) and
        prune the oldest past capacity.  Returns the written path."""
        seq = self._next_seq
        self._next_seq += 1
        record = {**record, "seq": seq}
        path = self._path(seq)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(record, fh)
        os.replace(tmp, path)  # readers never see a torn record
        seqs = self._seqs()
        for old in seqs[: max(0, len(seqs) - self.capacity)]:
            try:
                os.remove(self._path(old))
            except OSError:
                pass
        return path

    def paths(self) -> List[str]:
        return [self._path(s) for s in self._seqs()]

    def records(self) -> List[dict]:
        """All records, oldest first (unreadable files skipped)."""
        out = []
        for path in self.paths():
            try:
                with open(path) as fh:
                    out.append(json.load(fh))
            except (OSError, ValueError):
                continue
        return out

    def __len__(self) -> int:
        return len(self._seqs())


class HistoryRecorder:
    """Periodic window snapshots of one registry into one history ring.

    Args:
        registry: the :class:`MetricsRegistry` to snapshot.
        history: the :class:`TelemetryHistory` (or a directory path).
        interval_s: cadence honoured by :meth:`maybe_record`.
        clock: injectable wall clock (records carry its timestamps).
    """

    def __init__(self, registry, history, interval_s: float = 60.0,
                 clock: Callable[[], float] = time.time):
        if isinstance(history, str):
            history = TelemetryHistory(history)
        self.registry = registry
        self.history = history
        self.interval_s = float(interval_s)
        self._clock = clock
        self._prev: Optional[dict] = None
        self._last_ts: Optional[float] = None

    def record_once(self, now: Optional[float] = None) -> dict:
        """Snapshot unconditionally: dump, delta against the previous
        dump (reset-clamped), append to the ring."""
        from dist_svgd_tpu.telemetry.metrics import dump_delta

        now = self._clock() if now is None else now
        cur = self.registry.dump()
        window = dump_delta(self._prev, cur)
        interval = (now - self._last_ts) if self._last_ts is not None else 0.0
        self._prev = cur
        self._last_ts = now
        record = {
            "format": HISTORY_FORMAT,
            "ts": now,
            "interval_s": max(float(interval), 0.0),
            "window": window,
        }
        self.history.append(record)
        return record

    def maybe_record(self, now: Optional[float] = None) -> Optional[dict]:
        """Snapshot iff a full interval elapsed since the last record —
        the call a serving loop drops wherever it already ticks."""
        now = self._clock() if now is None else now
        if self._last_ts is not None and (now - self._last_ts) < self.interval_s:
            return None
        return self.record_once(now=now)


# ------------------------------------------------------------------ #
# series extraction (the anomaly report's read path)
# ------------------------------------------------------------------ #


def _match(series: List[dict], labels: Optional[dict]) -> Optional[dict]:
    want = dict(labels or {})
    for s in series:
        if dict(s.get("labels") or {}) == want:
            return s
    return None


def list_series(records: List[dict]) -> List[Tuple[str, str, Dict[str, str]]]:
    """Every ``(metric, kind, labels)`` series appearing anywhere in the
    history, deterministically ordered — the anomaly report's scan set."""
    seen = {}
    for rec in records:
        for name, entry in (rec.get("window", {}).get("metrics", {})).items():
            kind = entry.get("kind", "")
            for s in entry.get("series", []):
                labels = dict(s.get("labels") or {})
                key = (name, kind, tuple(sorted(labels.items())))
                seen.setdefault(key, (name, kind, labels))
    return [seen[k] for k in sorted(seen, key=lambda k: (k[0], k[1], k[2]))]


def series_values(records: List[dict], metric: str,
                  labels: Optional[dict] = None,
                  stat: Optional[str] = None) -> List[Optional[float]]:
    """One value per record for ``metric`` / ``labels`` (``None`` where
    the record lacks the series).

    stat: for counters/gauges only ``"value"`` (the window delta /
    instantaneous value).  For histograms: ``"count"``, ``"sum"``,
    ``"mean"``, or a quantile ``"p50"``/``"p95"``/``"p99"`` computed from
    the window's raw bucket counts via a scratch registry (the exact
    interpolation live quantiles use).
    """
    out: List[Optional[float]] = []
    for rec in records:
        entry = rec.get("window", {}).get("metrics", {}).get(metric)
        if entry is None:
            out.append(None)
            continue
        kind = entry.get("kind")
        s = _match(entry.get("series", []), labels)
        if s is None:
            out.append(None)
            continue
        if kind in ("counter", "gauge"):
            out.append(float(s.get("value", 0.0) or 0.0))
            continue
        # histogram window
        want = stat or "mean"
        count = int(s.get("count", 0) or 0)
        total = float(s.get("sum", 0.0) or 0.0)
        if want == "count":
            out.append(float(count))
        elif want == "sum":
            out.append(total)
        elif want == "mean":
            out.append(total / count if count else None)
        elif want.startswith("p"):
            if not count:
                out.append(None)
                continue
            from dist_svgd_tpu.telemetry import metrics as _metrics

            scratch = _metrics.MetricsRegistry()
            h = scratch.histogram(metric, entry.get("help", ""),
                                  buckets=entry.get("buckets"))
            h.merge_series(s.get("counts", []), total, count)
            out.append(float(h.quantile(float(want[1:]) / 100.0)))
        else:
            raise ValueError(f"unknown histogram stat {want!r}")
    return out
