"""Declarative SLO engine over the metrics registry.

The registry (PR 5) answers "what are the numbers"; this layer answers
**"are we meeting the objectives"** — the SRE-style formulation (burn rate
against an error budget) evaluated directly on the registry's histogram
buckets and counters, with no external scrape stack:

- :class:`LatencyObjective` — "fraction of requests over ``threshold_s``
  stays within ``1 − target``" evaluated on a latency histogram's
  **window delta** (the observations since the previous evaluation;
  cumulative-since-start on the first).  ``burn_rate`` =
  observed-error-fraction / error-budget — 1.0 is the edge of the budget,
  the standard multi-window burn-rate alerting number.
- :class:`RatioObjective` — bad-event counter over a base counter (or a
  histogram's observation count) across the same window: shed rate per
  request, guard trips per segment.
- :class:`GaugeCeiling` — an instantaneous statistic must stay at or
  under a ceiling: the KSD ceiling on ``svgd_diag_ksd`` is the posterior
  convergence SLO.
- :class:`StalenessObjective` — a unix-timestamp gauge must be newer than
  ``max_age_s`` (freshness-style: diagnostics recency, last hot reload).

:class:`SloEngine` owns the objective list and the per-objective window
state, returns one JSON-friendly evaluation document, and writes its own
verdicts back into the registry (``svgd_slo_burn_rate{slo=...}`` gauges,
``svgd_slo_breaches_total{slo=...}`` counters) so SLO state itself is
scrapeable.  The serving server exposes it at ``/slo``;
``tools/serve_bench.py`` / ``tools/fault_drill.py`` stamp each bench row
with the resulting ``slo_status``, and ``tools/perf_regress.py`` treats a
breaching row as FAIL.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence

from dist_svgd_tpu.telemetry import metrics as _metrics
from dist_svgd_tpu.telemetry.metrics import Counter, Histogram, MetricsRegistry

__all__ = [
    "LatencyObjective",
    "RatioObjective",
    "GaugeCeiling",
    "StalenessObjective",
    "FreshnessObjective",
    "SloEngine",
    "HistogramWindow",
    "CounterWindow",
    "bucket_frac_over",
    "bucket_quantile",
    "default_serving_slos",
    "default_training_slos",
    "default_streaming_slos",
    "default_rollout_slos",
]

OK = "ok"
BREACH = "breach"
NO_DATA = "no_data"


class _Objective:
    """Shared name plumbing; subclasses implement ``evaluate(registry,
    now_s)`` returning a row dict with at least ``status`` and
    ``burn_rate``.  Objectives are stateful (window snapshots) and belong
    to one engine."""

    def __init__(self, name: str):
        if not name:
            raise ValueError("objective needs a non-empty name")
        self.name = name

    def evaluate(self, registry: MetricsRegistry, now_s: float) -> Dict:
        raise NotImplementedError


#: Label keys an aggregate-mode objective skips: a federated registry
#: carries every series twice (``replica=``-labelled + rollup), and
#: summing both would double-count the fleet.
AGGREGATE_EXCLUDE_KEYS = ("replica",)


def _aggregate_label_sets(metric) -> list:
    return [ls for ls in metric.label_sets()
            if not any(k in ls for k in AGGREGATE_EXCLUDE_KEYS)]


def _count_delta(registry: MetricsRegistry, name: str, labels: dict,
                 prev: Dict, key: str,
                 aggregate: bool = False) -> Optional[float]:
    """Windowed total of a Counter (value) or Histogram (observation
    count) since the previous evaluation; ``None`` when the metric was
    never registered.  ``aggregate=True`` sums across every label set
    (minus :data:`AGGREGATE_EXCLUDE_KEYS`) instead of reading one — the
    fleet-SLO mode, where traffic lives in tenant-labelled rollups."""
    metric = registry._metrics.get(name)  # read-only peek, same package
    if metric is None:
        return None
    if isinstance(metric, Counter):
        if aggregate:
            now = float(sum(metric.value(**ls)
                            for ls in _aggregate_label_sets(metric)))
        else:
            now = metric.value(**labels)
    elif isinstance(metric, Histogram):
        if aggregate:
            now = 0.0
            for ls in _aggregate_label_sets(metric):
                series = metric._snapshot(ls)
                if series is not None:
                    now += series.count
        else:
            series = metric._snapshot(labels)
            now = float(series.count) if series is not None else 0.0
    else:
        raise ValueError(f"metric {name!r} is not a counter or histogram")
    before = prev.get(key, 0.0)
    prev[key] = now
    return max(now - before, 0.0)


def bucket_frac_over(bounds, counts, threshold: float) -> float:
    """Fraction of a bucketed distribution's observations OVER ``threshold``:
    whole buckets below it count as under, plus a linear share of the
    bucket the threshold lands in (the same within-bucket interpolation
    ``Histogram.quantile`` uses); the overflow bucket is entirely over any
    finite threshold.  ``counts`` has ``len(bounds) + 1`` entries."""
    total = sum(counts)
    if not total:
        return 0.0
    under = 0.0
    lo = 0.0
    for i, hi in enumerate(bounds):
        c = counts[i]
        if hi <= threshold:
            under += c
        elif lo < threshold:
            under += c * (threshold - lo) / (hi - lo)
        lo = hi
    return max(0.0, 1.0 - under / total)


def bucket_quantile(bounds, counts, q: float) -> float:
    """Interpolated ``q``-quantile of a bucketed distribution (the
    windowed-counts counterpart of ``Histogram.quantile``, which only
    reads cumulative series).  Overflow-bucket hits clamp to the last
    finite bound."""
    total = sum(counts)
    if not total:
        return 0.0
    target = q * total
    seen = 0.0
    lo = 0.0
    for i, hi in enumerate(bounds):
        c = counts[i]
        if seen + c >= target and c > 0:
            return lo + (hi - lo) * (target - seen) / c
        seen += c
        lo = hi
    return lo  # landed in the overflow bucket


class HistogramWindow:
    """Stateful windowed accessor over one histogram series (round 18) —
    the :mod:`~dist_svgd_tpu.serving.autoscale` controller's view of the
    latency/queue-wait distributions *since its previous control step*,
    with the same delta discipline the SLO objectives use but **its own
    window state**: a controller polling at its own cadence must not
    advance (and thereby starve) the ``/slo`` endpoint's objective
    windows.

    :meth:`poll` returns ``{count, frac_over(threshold_s), p99_s, ...}``
    for the observations since the previous poll (cumulative on the
    first); a reset (fresh registry, restarted process) clamps to an
    empty window instead of going negative — the ``dump_delta``
    discipline."""

    def __init__(self, registry: MetricsRegistry, name: str,
                 labels: Optional[dict] = None, aggregate: bool = False):
        self.registry = registry
        self.name = name
        self.labels = dict(labels or {})
        self.aggregate = bool(aggregate)
        self._prev: Optional[List[int]] = None

    def _current(self) -> Optional[List[int]]:
        metric = self.registry._metrics.get(self.name)
        if not isinstance(metric, Histogram):
            return None
        if not self.aggregate:
            series = metric._snapshot(self.labels)
            return list(series.counts) if series is not None else None
        totals: Optional[List[int]] = None
        for ls in _aggregate_label_sets(metric):
            series = metric._snapshot(ls)
            if series is None:
                continue
            if totals is None:
                totals = list(series.counts)
            else:
                totals = [a + b for a, b in zip(totals, series.counts)]
        return totals

    def poll(self, threshold_s: Optional[float] = None) -> Dict:
        metric = self.registry._metrics.get(self.name)
        counts = self._current()
        prev, self._prev = self._prev, counts
        if counts is None or not isinstance(metric, Histogram):
            return {"count": 0, "frac_over": 0.0, "p50_s": 0.0, "p99_s": 0.0}
        if prev is not None and len(prev) == len(counts):
            window = [max(c - p, 0) for c, p in zip(counts, prev)]
        else:
            window = counts
        bounds = metric.buckets
        out = {
            "count": sum(window),
            "p50_s": bucket_quantile(bounds, window, 0.50),
            "p99_s": bucket_quantile(bounds, window, 0.99),
            "frac_over": (bucket_frac_over(bounds, window, threshold_s)
                          if threshold_s is not None else 0.0),
        }
        return out


class CounterWindow:
    """Stateful windowed delta of one counter series (sums across label
    sets with ``aggregate=True`` — minus the federation ``replica``
    identity); resets clamp to zero like every other window here."""

    def __init__(self, registry: MetricsRegistry, name: str,
                 labels: Optional[dict] = None, aggregate: bool = False):
        self.registry = registry
        self.name = name
        self.labels = dict(labels or {})
        self.aggregate = bool(aggregate)
        self._prev: Dict[str, float] = {}

    def poll(self) -> float:
        delta = _count_delta(self.registry, self.name, self.labels,
                             self._prev, "v", aggregate=self.aggregate)
        return float(delta) if delta is not None else 0.0


class LatencyObjective(_Objective):
    """``target`` fraction of observations must land at or under
    ``threshold_s``, judged per evaluation window.

    ``aggregate=True`` sums bucket counts across every label set of the
    histogram (minus :data:`AGGREGATE_EXCLUDE_KEYS`) before windowing —
    the **fleet-SLO mode**: a federated registry holds per-tenant rollup
    series, and the fleet-wide p99 is judged over their exact bucket sum
    (same lattice, so the sum is itself a valid histogram)."""

    def __init__(self, name: str, histogram: str, threshold_s: float,
                 target: float = 0.99, labels: Optional[dict] = None,
                 aggregate: bool = False):
        super().__init__(name)
        if not 0.0 < target < 1.0:
            raise ValueError(f"target must be in (0, 1), got {target}")
        if threshold_s <= 0:
            raise ValueError(f"threshold_s must be positive, got {threshold_s}")
        self.histogram = histogram
        self.threshold_s = float(threshold_s)
        self.target = float(target)
        self.labels = dict(labels or {})
        self.aggregate = bool(aggregate)
        self._prev_counts: Optional[List[int]] = None

    def _current_counts(self, hist: Histogram) -> Optional[List[int]]:
        if not self.aggregate:
            series = hist._snapshot(self.labels)
            return list(series.counts) if series is not None else None
        totals: Optional[List[int]] = None
        for ls in _aggregate_label_sets(hist):
            series = hist._snapshot(ls)
            if series is None:
                continue
            if totals is None:
                totals = list(series.counts)
            else:
                totals = [a + b for a, b in zip(totals, series.counts)]
        return totals

    def _window_counts(self, hist: Histogram) -> Optional[List[int]]:
        counts = self._current_counts(hist)
        if counts is None:
            return None
        prev = self._prev_counts
        self._prev_counts = counts
        if prev is None or len(prev) != len(counts):
            return counts
        return [max(c - p, 0) for c, p in zip(counts, prev)]

    def evaluate(self, registry: MetricsRegistry, now_s: float) -> Dict:
        metric = registry._metrics.get(self.histogram)
        row = {"objective": "latency", "histogram": self.histogram,
               "threshold_ms": round(self.threshold_s * 1e3, 4),
               "target": self.target}
        if not isinstance(metric, Histogram):
            row.update(status=NO_DATA, burn_rate=0.0, window_count=0)
            return row
        counts = self._window_counts(metric)
        total = sum(counts) if counts else 0
        if not total:
            row.update(status=NO_DATA, burn_rate=0.0, window_count=0)
            return row
        # observations at or under the threshold: whole buckets below it
        # plus a linear share of the bucket the threshold lands in
        # (bucket_frac_over — shared with the autoscale HistogramWindow)
        frac_over = bucket_frac_over(metric.buckets, counts,
                                     self.threshold_s)
        budget = 1.0 - self.target
        burn = frac_over / budget
        row.update(
            status=BREACH if burn > 1.0 else OK,
            burn_rate=round(burn, 4),
            frac_over=round(frac_over, 6),
            window_count=total,
        )
        return row


class RatioObjective(_Objective):
    """Windowed ``numerator / denominator`` must stay at or under
    ``max_ratio``.  Either name may be a counter or a histogram (a
    histogram contributes its observation count)."""

    def __init__(self, name: str, numerator: str, denominator: str,
                 max_ratio: float, labels: Optional[dict] = None,
                 aggregate: bool = False):
        super().__init__(name)
        if max_ratio < 0:
            raise ValueError(f"max_ratio must be >= 0, got {max_ratio}")
        self.numerator = numerator
        self.denominator = denominator
        self.max_ratio = float(max_ratio)
        self.labels = dict(labels or {})
        self.aggregate = bool(aggregate)
        self._prev: Dict[str, float] = {}

    def evaluate(self, registry: MetricsRegistry, now_s: float) -> Dict:
        num = _count_delta(registry, self.numerator, self.labels,
                           self._prev, "num", aggregate=self.aggregate)
        den = _count_delta(registry, self.denominator, self.labels,
                           self._prev, "den", aggregate=self.aggregate)
        row = {"objective": "ratio", "numerator": self.numerator,
               "denominator": self.denominator, "max_ratio": self.max_ratio}
        if (num or 0.0) > 0 and not den:
            # bad events with ZERO base events is the outage shape (every
            # request shed → none resolved): an infinite ratio, a breach —
            # never no_data (burn_rate None: unbounded, not a number)
            row.update(status=BREACH, burn_rate=None, ratio=None,
                       window_num=num, window_den=den or 0)
            return row
        if den is None or not den:
            row.update(status=NO_DATA, burn_rate=0.0, window_den=den or 0)
            return row
        ratio = (num or 0.0) / den
        burn = (ratio / self.max_ratio) if self.max_ratio > 0 else (
            0.0 if ratio == 0 else None)  # None: unbounded, not a number
        row.update(
            status=BREACH if ratio > self.max_ratio else OK,
            burn_rate=round(burn, 4) if burn is not None else None,
            ratio=round(ratio, 6),
            window_num=num or 0.0,
            window_den=den,
        )
        return row


class GaugeCeiling(_Objective):
    """The gauge's current value must stay at or under ``ceiling`` —
    instantaneous, not windowed (a gauge is already last-write-wins).
    A gauge that was never written is ``no_data``, not a breach."""

    def __init__(self, name: str, gauge: str, ceiling: float,
                 labels: Optional[dict] = None):
        super().__init__(name)
        if ceiling <= 0:
            raise ValueError(f"ceiling must be positive, got {ceiling}")
        self.gauge = gauge
        self.ceiling = float(ceiling)
        self.labels = dict(labels or {})

    def evaluate(self, registry: MetricsRegistry, now_s: float) -> Dict:
        metric = registry._metrics.get(self.gauge)
        row = {"objective": "gauge_ceiling", "gauge": self.gauge,
               "ceiling": self.ceiling}
        if metric is None or not metric.has(**self.labels):
            row.update(status=NO_DATA, burn_rate=0.0)
            return row
        value = metric.value(**self.labels)
        burn = value / self.ceiling
        # `not <=` so a NaN statistic reads as a breach, never as ok
        row.update(
            status=OK if value <= self.ceiling else BREACH,
            burn_rate=round(burn, 4),
            value=value,
        )
        return row


class StalenessObjective(_Objective):
    """A unix-timestamp gauge must be at most ``max_age_s`` old."""

    def __init__(self, name: str, gauge: str, max_age_s: float,
                 labels: Optional[dict] = None):
        super().__init__(name)
        if max_age_s <= 0:
            raise ValueError(f"max_age_s must be positive, got {max_age_s}")
        self.gauge = gauge
        self.max_age_s = float(max_age_s)
        self.labels = dict(labels or {})

    def evaluate(self, registry: MetricsRegistry, now_s: float) -> Dict:
        metric = registry._metrics.get(self.gauge)
        row = {"objective": "staleness", "gauge": self.gauge,
               "max_age_s": self.max_age_s}
        if metric is None or not metric.has(**self.labels):
            row.update(status=NO_DATA, burn_rate=0.0)
            return row
        age = max(now_s - metric.value(**self.labels), 0.0)
        burn = age / self.max_age_s
        row.update(
            status=BREACH if age > self.max_age_s else OK,
            burn_rate=round(burn, 4),
            age_s=round(age, 3),
        )
        return row


class FreshnessObjective(_Objective):
    """Served predictions must not lag ingested data by more than
    ``max_lag_s`` of **event time** — the streaming pipeline's end-to-end
    SLO (round 20).

    Reads a watermark gauge *pair*: ``ingest_gauge`` (event time of the
    newest ingested batch — ``svgd_stream_watermark``) and
    ``served_gauge`` (event-time watermark of the generation actually
    serving — ``svgd_serving_watermark``, stamped by the hot reloader).
    The lag is ``max(ingest − served, 0)``: a served watermark at or
    ahead of ingest (a replayed stream, an idle source) is perfectly
    fresh, exactly like :class:`StalenessObjective`'s backwards-clock
    clamp.  Either gauge never set → ``no_data`` (a pipeline that has not
    published yet is not breaching)."""

    def __init__(self, name: str, max_lag_s: float, *,
                 ingest_gauge: str = "svgd_stream_watermark",
                 served_gauge: str = "svgd_serving_watermark",
                 labels: Optional[dict] = None):
        super().__init__(name)
        if max_lag_s <= 0:
            raise ValueError(f"max_lag_s must be positive, got {max_lag_s}")
        self.max_lag_s = float(max_lag_s)
        self.ingest_gauge = ingest_gauge
        self.served_gauge = served_gauge
        self.labels = dict(labels or {})

    def evaluate(self, registry: MetricsRegistry, now_s: float) -> Dict:
        ingest = registry._metrics.get(self.ingest_gauge)
        served = registry._metrics.get(self.served_gauge)
        row = {"objective": "freshness", "ingest_gauge": self.ingest_gauge,
               "served_gauge": self.served_gauge,
               "max_lag_s": self.max_lag_s}
        # the served watermark may carry tenant labels while the ingest
        # side is unlabelled (single trainer, many tenants) — each gauge
        # is judged under its own label set
        if (ingest is None or not ingest.has()
                or served is None or not served.has(**self.labels)):
            row.update(status=NO_DATA, burn_rate=0.0)
            return row
        lag = max(ingest.value() - served.value(**self.labels), 0.0)
        burn = lag / self.max_lag_s
        row.update(
            status=BREACH if lag > self.max_lag_s else OK,
            burn_rate=round(burn, 4),
            lag_s=round(lag, 3),
        )
        return row


class SloEngine:
    """Evaluates a fixed objective list against one registry.

    Each :meth:`evaluate` call advances every objective's window (the
    delta since the previous call; cumulative on the first) and returns::

        {"status": "ok"|"breach", "ts": <unix>,
         "objectives": {name: {status, burn_rate, ...}, ...}}

    ``no_data`` objectives never breach the overall status (a fresh server
    with zero traffic is healthy, not failing).  Verdicts are mirrored
    into the registry: ``svgd_slo_burn_rate{slo=name}`` gauges and
    ``svgd_slo_breaches_total{slo=name}`` counters.

    ``mirror_metrics=False`` (round 18) evaluates without writing the
    verdict series — for a SECOND engine over the same registry (the
    autoscale controller runs its own objective windows at its own
    cadence) whose verdicts must not clobber the ``/slo`` endpoint's
    gauges or double-count its breach counters.  :attr:`last` keeps the
    most recent evaluation document and :meth:`burn_rates` exposes its
    per-objective burn numbers — the controller-facing accessors.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 objectives: Sequence[_Objective] = (),
                 clock: Callable[[], float] = time.time,
                 mirror_metrics: bool = True):
        import threading

        self.registry = (registry if registry is not None
                         else _metrics.default_registry())
        self.objectives = list(objectives)
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        self._clock = clock
        self.mirror_metrics = bool(mirror_metrics)
        #: The most recent :meth:`evaluate` document (None before the
        #: first) — readable without advancing any objective window.
        self.last: Optional[Dict] = None
        # the objectives' window snapshots are stateful: concurrent
        # evaluations (two scrapers on /slo — ThreadingHTTPServer runs one
        # thread per request) would double-judge one window and starve the
        # next; one engine lock serialises them
        self._lock = threading.Lock()
        if self.mirror_metrics:
            self._m_burn = self.registry.gauge(
                "svgd_slo_burn_rate", "error-budget burn rate per objective")
            self._m_breaches = self.registry.counter(
                "svgd_slo_breaches_total", "SLO evaluations that breached")

    def evaluate(self) -> Dict:
        with self._lock:
            now = self._clock()
            rows = {}
            worst = OK
            for obj in self.objectives:
                row = obj.evaluate(self.registry, now)
                rows[obj.name] = row
                burn = row.get("burn_rate", 0.0)
                if (self.mirror_metrics
                        and isinstance(burn, (int, float))
                        and burn != float("inf")):
                    self._m_burn.set(burn, slo=obj.name)
                if row["status"] == BREACH:
                    worst = BREACH
                    if self.mirror_metrics:
                        self._m_breaches.inc(slo=obj.name)
            doc = {"status": worst, "ts": round(now, 3), "objectives": rows}
            self.last = doc
        return doc

    def burn_rates(self) -> Dict[str, Optional[float]]:
        """Per-objective burn rates of the most recent evaluation (empty
        before the first) — ``None`` marks an unbounded ratio (bad events
        over a zero base), which callers must treat as the worst case,
        not as zero."""
        if self.last is None:
            return {}
        return {name: row.get("burn_rate")
                for name, row in self.last["objectives"].items()}


def default_serving_slos(registry: MetricsRegistry, *,
                         p99_ms: float = 100.0,
                         shed_budget: float = 0.01,
                         error_budget: float = 0.01,
                         aggregate: bool = False,
                         mirror_metrics: bool = True,
                         clock: Callable[[], float] = time.time) -> SloEngine:
    """The serving server's standard objective set: request p99 under
    ``p99_ms``, sheds under ``shed_budget`` per resolved request, and
    dispatch errors under ``error_budget`` per batch.

    ``aggregate=True`` judges every objective over the **sum across label
    sets** (minus the ``replica`` federation identity) — how the fleet
    router evaluates the same objectives over its federated window, where
    all traffic lives in tenant-labelled rollup series."""
    return SloEngine(registry, [
        LatencyObjective("serve_p99", "svgd_serve_request_latency_seconds",
                         p99_ms / 1e3, target=0.99, aggregate=aggregate),
        RatioObjective("shed_rate", "svgd_serve_shed_total",
                       "svgd_serve_requests_total", shed_budget,
                       aggregate=aggregate),
        RatioObjective("dispatch_errors", "svgd_serve_dispatch_errors_total",
                       "svgd_serve_batches_total", error_budget,
                       aggregate=aggregate),
    ], clock=clock, mirror_metrics=mirror_metrics)


def default_training_slos(registry: MetricsRegistry, *,
                          max_ksd: Optional[float] = None,
                          guard_trip_budget: float = 0.1,
                          diag_max_age_s: Optional[float] = None,
                          clock: Callable[[], float] = time.time) -> SloEngine:
    """The supervised-training objective set: guard trips under
    ``guard_trip_budget`` per segment, optionally a KSD ceiling (the
    posterior-convergence SLO) and a diagnostics-freshness bound."""
    objectives: List[_Objective] = [
        RatioObjective("guard_trip_rate", "svgd_train_guard_trips_total",
                       "svgd_train_segment_seconds", guard_trip_budget),
    ]
    if max_ksd is not None:
        objectives.append(GaugeCeiling("ksd_ceiling", "svgd_diag_ksd", max_ksd))
    if diag_max_age_s is not None:
        objectives.append(StalenessObjective(
            "diag_freshness", "svgd_diag_last_update_ts", diag_max_age_s))
    return SloEngine(registry, objectives, clock=clock)


def default_streaming_slos(registry: MetricsRegistry, *,
                           max_lag_s: float = 60.0,
                           drop_budget: float = 0.0,
                           labels: Optional[dict] = None,
                           mirror_metrics: bool = True,
                           clock: Callable[[], float] = time.time) -> SloEngine:
    """The streaming pipeline's objective set: served predictions within
    ``max_lag_s`` of ingested event time (:class:`FreshnessObjective` over
    the watermark gauge pair), and stream drops within ``drop_budget`` per
    pulled batch (the default budget is ZERO — a dropped batch is lost
    data, the freshness gate's unconditional-FAIL condition)."""
    return SloEngine(registry, [
        FreshnessObjective("freshness", max_lag_s, labels=labels),
        RatioObjective("stream_drop_rate", "svgd_stream_dropped_total",
                       "svgd_stream_batches_total", drop_budget),
    ], clock=clock, mirror_metrics=mirror_metrics)


def default_rollout_slos(registry: MetricsRegistry, *,
                         p99_ms: float = 100.0,
                         error_budget: float = 0.01,
                         max_divergence: float = 0.05,
                         divergence_budget: float = 0.01,
                         labels: Optional[dict] = None,
                         mirror_metrics: bool = True,
                         clock: Callable[[], float] = time.time) -> SloEngine:
    """The progressive-delivery judge: the candidate generation's OWN
    serve windows plus the shadow-divergence window.

    The candidate objectives read the ``generation="candidate"`` label
    set of the standard serve series — the batcher stamps candidate-split
    batches with that label, so the incumbent's traffic never dilutes the
    candidate's verdict (and vice versa).  Divergence reuses
    :class:`LatencyObjective` verbatim: ``svgd_rollout_divergence`` is a
    histogram over prediction-space distances instead of seconds, and
    "``target`` fraction of observations at or under ``threshold``" is
    exactly the divergence-budget judgement (a NaN-predicting candidate
    lands in the overflow bucket, over every finite threshold).  All
    three objectives are ``no_data``-safe: an empty window holds the
    rollout in its current stage rather than promoting or rolling back.
    """
    base = dict(labels or {})
    cand = {**base, "generation": "candidate"}
    return SloEngine(registry, [
        LatencyObjective("candidate_p99", "svgd_serve_request_latency_seconds",
                         p99_ms / 1e3, target=0.99, labels=cand),
        RatioObjective("candidate_errors", "svgd_serve_dispatch_errors_total",
                       "svgd_serve_batches_total", error_budget, labels=cand),
        LatencyObjective("shadow_divergence", "svgd_rollout_divergence",
                         max_divergence, target=1.0 - divergence_budget,
                         labels=base),
    ], clock=clock, mirror_metrics=mirror_metrics)
