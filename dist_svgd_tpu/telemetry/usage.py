"""Per-tenant usage metering: the serving path's cost ledger.

The batcher already *observes* per-tenant latency; what it could not
answer is "what did tenant X cost this hour, fleet-wide?".  This module
adds the accounting half: a process-global :class:`UsageMeter` (the
tracer/profiler switchboard discipline — one module-global read when
disabled) that the serving batcher and engine feed with **monotonic
counters**, labelled per tenant and, when the request pinned one, per
generation:

- ``svgd_usage_device_seconds_total`` — dispatch wall the batch spent
  on device (the batcher's measured window, same number its
  ``svgd_serve_device_time_seconds`` histogram observes),
- ``svgd_usage_rows_total`` — rows served,
- ``svgd_usage_queue_seconds_total`` — summed per-request queue wait,
- ``svgd_usage_requests_total`` — requests completed,
- ``svgd_usage_compiles_total`` — kernel-cache misses (steady state
  should hold this flat; the ``cost_attribution`` drill gates it at 0
  in-window).

Counters mean the whole existing plumbing works unchanged: the PR-9
cardinality guard caps runaway tenant labels at the registry layer,
``dump_delta`` gives reset-clamped windows, and ``MetricsFederation``
scrapes and re-ingests the series both replica-labelled and as a fleet
rollup — so :func:`usage_summary` run on the router's federated
registry answers cost-per-tenant across the fleet with zero new
transport.

Each batch writes exactly one label set (``{}``, ``{tenant}``, or
``{tenant, generation}``) — the same convention as the batcher's
latency labels — so summing disjoint label sets partitions the total:
the tenant-sum-within-1% acceptance check is an accounting identity,
not a tolerance for lost work.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = [
    "DEVICE_SECONDS_TOTAL",
    "ROWS_TOTAL",
    "QUEUE_SECONDS_TOTAL",
    "REQUESTS_TOTAL",
    "COMPILES_TOTAL",
    "DEFAULT_TENANT",
    "UsageMeter",
    "enable_usage",
    "disable_usage",
    "get_meter",
    "usage_enabled",
    "usage_summary",
]

DEVICE_SECONDS_TOTAL = "svgd_usage_device_seconds_total"
ROWS_TOTAL = "svgd_usage_rows_total"
QUEUE_SECONDS_TOTAL = "svgd_usage_queue_seconds_total"
REQUESTS_TOTAL = "svgd_usage_requests_total"
COMPILES_TOTAL = "svgd_usage_compiles_total"

#: Summary key for work not pinned to a tenant (single-model servers) —
#: matches tools/fleet_status.py's display convention.
DEFAULT_TENANT = "(default)"

#: Active meter or None; read once per batch by the serving feeds.
_METER: Optional["UsageMeter"] = None
_LOCK = threading.Lock()


class UsageMeter:
    """Monotonic per-tenant cost counters over one metrics registry.

    Pass the registry the serving server exposes (``/metrics.dump``) so
    the series federate; defaults to the process-wide registry.
    """

    def __init__(self, registry=None):
        from dist_svgd_tpu.telemetry import metrics as _metrics

        self.registry = registry if registry is not None else _metrics.default_registry()
        self._m_device = self.registry.counter(
            DEVICE_SECONDS_TOTAL,
            "Device dispatch wall seconds consumed, by tenant/generation.")
        self._m_rows = self.registry.counter(
            ROWS_TOTAL, "Rows served, by tenant/generation.")
        self._m_queue = self.registry.counter(
            QUEUE_SECONDS_TOTAL,
            "Summed per-request queue wait seconds, by tenant/generation.")
        self._m_requests = self.registry.counter(
            REQUESTS_TOTAL, "Requests completed, by tenant/generation.")
        self._m_compiles = self.registry.counter(
            COMPILES_TOTAL,
            "Serving kernel-cache misses (compiles), by tenant/generation.")

    # feeds ---------------------------------------------------------- #

    def record_batch(self, *, tenant: Optional[str],
                     generation: Optional[str],
                     rows: int, device_s: float, queue_s: float,
                     requests: int) -> None:
        """One completed batch — called by the batcher with its own
        measured device window (so meter and latency histograms agree by
        construction)."""
        tl = {} if tenant is None else {"tenant": str(tenant)}
        gl = tl if generation is None else {**tl, "generation": str(generation)}
        self._m_device.inc(device_s, **gl)
        if rows:
            self._m_rows.inc(rows, **gl)
        if queue_s > 0.0:
            self._m_queue.inc(queue_s, **gl)
        if requests:
            self._m_requests.inc(requests, **gl)

    def record_compile(self, *, tenant: Optional[str] = None,
                       generation: Optional[str] = None) -> None:
        """One serving kernel compile (cache miss)."""
        tl = {} if tenant is None else {"tenant": str(tenant)}
        gl = tl if generation is None else {**tl, "generation": str(generation)}
        self._m_compiles.inc(**gl)


# ------------------------------------------------------------------ #
# switchboard
# ------------------------------------------------------------------ #


def enable_usage(registry=None) -> UsageMeter:
    """Install a process-wide meter (idempotent — disable first to
    re-target another registry)."""
    global _METER
    with _LOCK:
        if _METER is None:
            _METER = UsageMeter(registry=registry)
        return _METER


def disable_usage() -> Optional[UsageMeter]:
    global _METER
    with _LOCK:
        meter, _METER = _METER, None
    return meter


def get_meter() -> Optional[UsageMeter]:
    return _METER


def usage_enabled() -> bool:
    return _METER is not None


# ------------------------------------------------------------------ #
# read side
# ------------------------------------------------------------------ #

_FIELDS = (
    (DEVICE_SECONDS_TOTAL, "device_seconds", float),
    (ROWS_TOTAL, "rows", int),
    (QUEUE_SECONDS_TOTAL, "queue_seconds", float),
    (REQUESTS_TOTAL, "requests", int),
    (COMPILES_TOTAL, "compiles", int),
)


def _zero_row() -> dict:
    return {key: typ(0) for _, key, typ in _FIELDS}


def usage_summary(registry=None) -> dict:
    """Cost accounting read off any registry carrying ``svgd_usage_*``
    series — the live server registry, a scraped dump ingest, or the
    router's federated registry.

    Returns ``{"tenants": {tenant: {device_seconds, rows, queue_seconds,
    requests, compiles, generations: {gen: {...}}}}, "totals": {...},
    "replicas": {rid: {tenant: {...}}}}``.  Tenants/totals come from the
    rollup (non-``replica``-labelled) series so federated registries are
    not double-counted; the per-replica breakdown uses the
    replica-labelled series and is empty on a single server.
    """
    from dist_svgd_tpu.telemetry import metrics as _metrics

    reg = registry if registry is not None else _metrics.default_registry()
    tenants: Dict[str, dict] = {}
    totals = _zero_row()
    replicas: Dict[str, dict] = {}

    for name, key, typ in _FIELDS:
        ctr = reg.get(name)
        if ctr is None:
            continue
        for ls in ctr.label_sets():
            val = typ(ctr.value(**ls))
            if not val:
                continue
            tenant = ls.get("tenant", DEFAULT_TENANT)
            rid = ls.get("replica")
            if rid is not None:
                row = replicas.setdefault(rid, {}).setdefault(
                    tenant, _zero_row())
                row[key] += val
                continue
            trow = tenants.setdefault(
                tenant, {**_zero_row(), "generations": {}})
            trow[key] += val
            totals[key] += val
            gen = ls.get("generation")
            if gen is not None:
                grow = trow["generations"].setdefault(gen, _zero_row())
                grow[key] += val
    return {"tenants": tenants, "totals": totals, "replicas": replicas}
