"""On-device posterior health diagnostics: KSD, kernel ESS, collapse and
shard-divergence indicators.

PR 5's telemetry observes the *system* (latency, queue depth, compiles);
nothing observes whether the *posterior* is healthy.  SVGD with the paper's
fixed-bandwidth RBF kernel can fail silently in ways no NaN check sees:
particles collapse onto each other (the kernel repulsion term underpowered
for the step size), the trajectory stalls far from the target, or — in the
distributed modes — shards drift apart while each one looks locally fine.
This module computes cheap, jitted statistics on the particle array already
resident on the device, every K supervised steps:

- **Kernelized Stein discrepancy** (Liu, Lee & Jordan 2016 — the
  goodness-of-fit companion to SVGD's Liu & Wang 2016): the U-statistic
  ``KSD² = 1/(n(n−1)) Σ_{i≠j} u_p(x_i, x_j)`` with the repo's RBF
  convention ``k(x,y) = exp(−‖x−y‖²/h)`` expanded in closed form
  (``β = 2/h``)::

      u_p(x,y) = k(x,y)·[ ⟨s_x,s_y⟩ + β⟨s_x−s_y, x−y⟩ + βd − β²‖x−y‖² ]

  where ``s_x = ∇log p(x)`` — the same analytic-RBF pieces the φ update
  uses (:mod:`dist_svgd_tpu.ops.kernels`), so no new kernel machinery and
  no ``(n, n, d)`` tensor is ever materialised.  KSD → 0 iff the particle
  measure converges to ``p`` (under the usual conditions), making it the
  one scalar that distinguishes "converged" from "collapsed" — a collapsed
  set has tiny φ updates *and* a large KSD.
- **Kernel-matrix effective sample size**: the participation ratio
  ``ESS = (tr K)² / ‖K‖_F² = n² / Σᵢⱼ Kᵢⱼ²`` of the Gram matrix —
  ``n`` for well-spread particles (K ≈ I), 1 for a fully collapsed set
  (K ≈ 𝟙𝟙ᵀ).  Score-free, so it also guards *serving-side* reloads where
  no ∇log p is available (:class:`ReloadPolicy`).
- **Collapse indicators**: min pairwise distance (exact over all pairs),
  median pairwise distance (sort-free counting bracket on a strided
  subsample — :func:`dist_svgd_tpu.ops.kernels._median_bracket`, the
  adaptive-bandwidth machinery reused), and the per-dimension variance
  floor (one dead dimension = mode collapse the global norm hides).
- **Inter-shard divergence** (``DistSampler``): max over shards of the
  scale-normalised mean / variance discrepancy between a shard's particle
  block and the global set — exchange bugs and shard-local divergence show
  up here steps before anything trips a NaN guard.

Everything pairwise is **chunk-safe**: an ``(n, n)`` interaction is
evaluated as a ``lax.scan`` over fixed-size row blocks against the full
column set (rows padded to the chunk lattice with zero-weight masks), so a
2M-particle diagnostic costs ``row_chunk × n`` live memory, never ``n²``.
All functions are jitted once per (shape, dtype, chunk) — zero steady-state
recompiles, pinned by ``tests/test_diagnostics.py`` under the retrace
sentry.

Results flow into the PR 5 :class:`~dist_svgd_tpu.telemetry.metrics.
MetricsRegistry` as ``svgd_diag_*`` gauges, are emitted as
``train.diagnostics`` spans while the tracer is enabled, and are handed to
the flight recorder (:mod:`~dist_svgd_tpu.telemetry.trace`) so a postmortem
bundle carries the last posterior health picture.  When disabled the
supervisor holds the shared no-op singleton (:data:`DISABLED`) — no
allocation, no clock read, the tracer's zero-cost discipline
(tracemalloc-pinned in ``tests/test_diagnostics.py``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Callable, Dict, Optional, Union

import jax
import jax.numpy as jnp
from jax import lax

from dist_svgd_tpu.ops.kernels import (
    _median_bracket,
    median_bandwidth_approx,
    squared_distances,
)
from dist_svgd_tpu.telemetry import metrics as _metrics
from dist_svgd_tpu.telemetry import trace as _trace

__all__ = [
    "DiagnosticsConfig",
    "PosteriorDiagnostics",
    "ReloadPolicy",
    "DISABLED",
    "ensemble_health",
]

_HIGH = jax.lax.Precision.HIGHEST


def _chunk_layout(n: int, row_chunk: int):
    """Static row-chunk lattice: ``(chunk, n_chunks, pad)`` with
    ``n_chunks · chunk = n + pad``."""
    c = max(1, min(int(row_chunk), n))
    nc = -(-n // c)
    return c, nc, nc * c - n


def _scan_pair_blocks(particles, scores, h, row_chunk):
    """One chunked pass over the ``(n, n)`` pairwise interaction.

    Returns ``(sum_u, sum_k2, min_offdiag_sq)`` where ``sum_u`` is the
    all-pairs (diagonal included) Stein-kernel sum — ``None`` when
    ``scores`` is ``None`` — and the other two are score-free.  Rows are
    scanned in fixed blocks against the full column set; padded rows carry
    zero weight, so the result is exactly the unchunked sum.
    """
    n, d = particles.shape
    dt = particles.dtype
    beta = 2.0 / h
    c, nc, pad = _chunk_layout(n, row_chunk)
    xp = jnp.pad(particles, ((0, pad), (0, 0)))
    wp = jnp.pad(jnp.ones((n,), dt), (0, pad))
    cols = jnp.arange(n)
    with_u = scores is not None
    if with_u:
        sp = jnp.pad(scores, ((0, pad), (0, 0)))
        s_dot_x_cols = jnp.sum(scores * particles, axis=-1)  # (n,)

    def body(carry, blk):
        sum_u, sum_k2, min_sq = carry
        if with_u:
            xb, sb, wb, off = blk
        else:
            xb, wb, off = blk
        sq = squared_distances(xb, particles)  # (c, n)
        k = jnp.exp(-sq / h)
        w = wb[:, None]
        sum_k2 = sum_k2 + jnp.sum(k * k * w)
        if with_u:
            ss = jnp.matmul(sb, scores.T, precision=_HIGH)
            sxr = (jnp.sum(sb * xb, axis=-1)[:, None]
                   - jnp.matmul(sb, particles.T, precision=_HIGH))
            syr = (jnp.matmul(xb, scores.T, precision=_HIGH)
                   - s_dot_x_cols[None, :])
            u = k * (ss + beta * (sxr - syr) + beta * d - beta * beta * sq)
            sum_u = sum_u + jnp.sum(u * w)
        rows = off + jnp.arange(c)
        offdiag = (cols[None, :] != rows[:, None]) & (w > 0)
        min_sq = jnp.minimum(
            min_sq, jnp.min(jnp.where(offdiag, sq, jnp.inf))
        )
        return (sum_u, sum_k2, min_sq), None

    init = (jnp.zeros((), dt) if with_u else None,
            jnp.zeros((), dt), jnp.asarray(jnp.inf, dt))
    xs = xp.reshape(nc, c, d)
    ws = wp.reshape(nc, c)
    offs = jnp.arange(nc) * c
    blocks = (xs, sp.reshape(nc, c, d), ws, offs) if with_u else (xs, ws, offs)
    (sum_u, sum_k2, min_sq), _ = lax.scan(body, init, blocks)
    return sum_u, sum_k2, min_sq


def _resolve_bandwidth(particles, bandwidth, median_bw: bool):
    if median_bw:
        return median_bandwidth_approx(particles)
    return jnp.asarray(bandwidth, particles.dtype)


#: Row cap for the median-distance bracket inside the pairwise pass — the
#: bracket's four broadcast-compare passes dominate everything else above
#: this, and a median order statistic stabilises far below it.
MEDIAN_DIST_POINTS = 256


def _median_dist(particles):
    """Median pairwise distance over a further-capped strided slice: the
    sort-free counting bracket (``ops.kernels._median_bracket``) at 8
    probes — resolution 8⁻⁴ of the distance range, plenty for a health
    gauge at a fraction of the 16-probe bandwidth estimator's cost."""
    p0 = particles.shape[0]
    if p0 > MEDIAN_DIST_POINTS:
        particles = particles[::-(-p0 // MEDIAN_DIST_POINTS)]
    p = particles.shape[0]
    sq = squared_distances(particles, particles)
    # the p diagonal zeros are below any positive threshold: add them to
    # the target rank instead of masking (median_bandwidth_approx's trick)
    target = p + (p * p - p + 1) // 2
    return jnp.sqrt(_median_bracket(sq, target, 8))


@partial(jax.jit, static_argnames=("row_chunk", "median_bw"))
def _ksd_stats(particles, scores, bandwidth, row_chunk, median_bw):
    """One fused dispatch: KSD² (U-statistic) + kernel ESS + min/median
    pairwise distance, chunked.  Fused deliberately — the diagnostics
    cadence pays per-dispatch latency plus a host sync per call, which on
    a ``max_points``-bounded subsample costs more than the statistics
    themselves."""
    n, d = particles.shape
    h = _resolve_bandwidth(particles, bandwidth, median_bw)
    sum_u, sum_k2, min_sq = _scan_pair_blocks(particles, scores, h, row_chunk)
    beta = 2.0 / h
    diag_u = jnp.sum(scores * scores) + n * beta * d  # u(x, x) summed
    ksd_sq = (sum_u - diag_u) / (n * (n - 1))
    return {
        "ksd_sq": ksd_sq,
        "ksd": jnp.sqrt(jnp.maximum(ksd_sq, 0.0)),
        "ess": (n * n) / sum_k2,
        "min_pairwise_dist": jnp.sqrt(min_sq),
        "median_pairwise_dist": _median_dist(particles),
        "bandwidth": h,
    }


@partial(jax.jit, static_argnames=("row_chunk", "median_bw"))
def _kernel_stats(particles, bandwidth, row_chunk, median_bw):
    """Score-free twin of :func:`_ksd_stats` (no KSD term)."""
    n, _ = particles.shape
    h = _resolve_bandwidth(particles, bandwidth, median_bw)
    _, sum_k2, min_sq = _scan_pair_blocks(particles, None, h, row_chunk)
    return {
        "ess": (n * n) / sum_k2,
        "min_pairwise_dist": jnp.sqrt(min_sq),
        "median_pairwise_dist": _median_dist(particles),
        "bandwidth": h,
    }


@jax.jit
def _dim_var_stats(particles):
    """Per-dimension variance floor — O(nd), over the full set.  Only
    dispatched on single-shard runs: :func:`_shard_stats` folds it in
    (the global variance is on its path anyway)."""
    return jnp.min(jnp.var(particles, axis=0))


@partial(jax.jit, static_argnames=("num_shards",))
def _shard_stats(particles, num_shards):
    """Scale-normalised divergence of each contiguous shard block from the
    global particle set (the samplers' block layout: shard s owns rows
    ``[s·per, (s+1)·per)``)."""
    n, d = particles.shape
    blocks = particles.reshape(num_shards, n // num_shards, d)
    mu = jnp.mean(blocks, axis=1)           # (S, d)
    var = jnp.var(blocks, axis=1)           # (S, d)
    gmu = jnp.mean(particles, axis=0)
    gvar = jnp.var(particles, axis=0)
    scale = jnp.sqrt(jnp.sum(gvar)) + 1e-12
    return {
        "shard_mean_div": jnp.max(
            jnp.linalg.norm(mu - gmu[None, :], axis=1)) / scale,
        "shard_var_div": jnp.max(
            jnp.linalg.norm(var - gvar[None, :], axis=1))
        / (jnp.sum(gvar) + 1e-12),
        # the variance floor rides along: gvar is already computed here,
        # saving the single-shard path's separate dispatch
        "min_dim_var": jnp.min(gvar),
    }


def _subsample(particles, max_points: int):
    """Evenly-strided row subsample (the median-bandwidth discipline: an
    O(n²) statistic over more than ``max_points`` rows costs more than the
    step it observes), pulled onto ONE device.

    The full array may be mesh-sharded (``DistSampler``); the O(n)
    statistics stay on that layout, but an O(rows²) pairwise pass over a
    ``max_points``-bounded subsample gains nothing from sharding — and on
    the emulated CPU mesh every cross-device elementwise op costs more
    than the whole statistic.  The gather moves at most
    ``max_points × d`` floats.
    """
    n = particles.shape[0]
    if n > max_points:
        stride = -(-n // max_points)
        particles = particles[::stride]
    try:
        spread = len(particles.sharding.device_set) > 1
    except AttributeError:  # non-Array input (numpy) — already local
        spread = False
    if spread:
        particles = jax.device_put(particles, jax.devices()[0])
    return particles


@dataclass
class DiagnosticsConfig:
    """What to compute, how often, and at what cost ceiling.

    Args:
        every_steps: compute at supervised-step multiples of this (the
            supervisor only checks at segment boundaries, so the effective
            cadence is the first boundary at or past each multiple).
        bandwidth: RBF bandwidth ``h`` for KSD/ESS — a float, or
            ``'median'`` to re-resolve via the sort-free median heuristic
            (:func:`~dist_svgd_tpu.ops.kernels.median_bandwidth_approx`)
            inside the same jitted program on every compute.
        row_chunk: pairwise row-block size — live memory is
            ``row_chunk × rows``, never ``rows²``.
        max_points: cap on the rows entering any O(rows²) statistic (KSD,
            ESS, min/median pairwise distance): past it an evenly-strided
            subsample is evaluated instead (the ``median_bandwidth``
            discipline — a diagnostic must cost less than the steps it
            observes).  Per-dim variance and shard divergence always use
            the full set (they are O(n·d)).  ``ess_frac`` is ESS over the
            *evaluated* rows, so thresholds stay comparable across caps.
        score_fn: ``θ ↦ ∇log p(θ)`` for the KSD term.  ``None`` skips KSD
            (ESS/collapse/shard stats are score-free).  The supervisor
            fills this from a single-device ``Sampler``'s own score closure
            when left unset.
    """

    every_steps: int = 50
    bandwidth: Union[float, str] = 1.0
    row_chunk: int = 1024
    max_points: int = 1024
    score_fn: Optional[Callable] = field(default=None, repr=False)

    def __post_init__(self):
        if self.every_steps < 1:
            raise ValueError(
                f"every_steps must be >= 1, got {self.every_steps}")
        if self.bandwidth != "median" and not float(self.bandwidth) > 0:
            raise ValueError(f"bandwidth must be positive or 'median', "
                             f"got {self.bandwidth}")
        if self.row_chunk < 1:
            raise ValueError(f"row_chunk must be >= 1, got {self.row_chunk}")
        if self.max_points < 2:
            raise ValueError(f"max_points must be >= 2, got {self.max_points}")


class _NoopDiagnostics:
    """Disabled-path singleton: the supervisor's per-boundary check is one
    attribute load + a constant-returning method — no allocation, no clock
    read (tracemalloc-pinned, the tracer's discipline)."""

    __slots__ = ()
    enabled = False
    last_report = None

    def should_run(self, t):
        return False

    def compute(self, particles, scores=None, num_shards=None, step=None):
        return None

    def ensure_score_fn(self, score_fn):
        return self


#: Shared no-op instance — what the supervisor holds when diagnostics are
#: off, so the enabled check costs nothing on the segment path.
DISABLED = _NoopDiagnostics()


class PosteriorDiagnostics:
    """Computes, records, and remembers the posterior health statistics.

    Args:
        config: :class:`DiagnosticsConfig` (default: defaults above).
        registry: metrics registry for the ``svgd_diag_*`` gauges, the
            computation counter, and the compute-wall histogram (default:
            the process-wide registry).
        logger: optional ``JsonlLogger`` — one record per computation.
        wall_clock: unix-time source for the freshness gauge
            (``svgd_diag_last_update_ts`` — what a staleness SLO reads).

    Every computation runs inside a ``train.diagnostics`` span (tagged with
    step and n) while the tracer is enabled, and is handed to the installed
    flight recorder so postmortems carry the last health picture.
    """

    enabled = True

    def __init__(self, config: Optional[DiagnosticsConfig] = None,
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 logger=None, wall_clock: Callable[[], float] = time.time):
        self.config = config or DiagnosticsConfig()
        reg = registry if registry is not None else _metrics.default_registry()
        self.registry = reg
        self._logger = logger
        self._wall_clock = wall_clock
        # instance-held score closure: ensure_score_fn adopts a sampler's
        # closure HERE, never into the caller-owned (possibly shared)
        # config — a config reused across runs must not leak one run's
        # ∇log p into another's KSD
        self._score_fn = self.config.score_fn
        self._scores_jit = None  # built lazily from _score_fn
        self._gauges = {
            name: reg.gauge(f"svgd_diag_{name}", help)
            for name, help in (
                ("ksd", "kernelized Stein discrepancy (U-statistic sqrt)"),
                ("ess", "kernel-matrix effective sample size"),
                ("ess_frac", "kernel ESS over particle count"),
                ("min_pairwise_dist", "smallest inter-particle distance"),
                ("median_pairwise_dist",
                 "median inter-particle distance (strided subsample)"),
                ("min_dim_var", "smallest per-dimension particle variance"),
                ("shard_mean_div",
                 "max scale-normalised shard-mean divergence"),
                ("shard_var_div",
                 "max normalised shard-variance divergence"),
                ("last_step", "step of the newest diagnostics computation"),
                ("last_update_ts",
                 "unix time of the newest diagnostics computation"),
            )
        }
        self._m_computations = reg.counter(
            "svgd_diag_computations_total", "diagnostics passes completed")
        self._m_wall = reg.histogram(
            "svgd_diag_compute_seconds", "wall per diagnostics pass")
        #: Most recent report dict (plain floats), ``None`` before any.
        self.last_report: Optional[Dict] = None

    # ------------------------------------------------------------------ #

    def should_run(self, t: int) -> bool:
        """True when step ``t`` is on the cadence grid (t > 0)."""
        return t > 0 and t % self.config.every_steps == 0

    def ensure_score_fn(self, score_fn: Optional[Callable]) -> "PosteriorDiagnostics":
        """Adopt ``score_fn`` if this instance has none (the supervisor
        wires a single-device sampler's own score closure through here).
        Instance-scoped: the shared config object is never mutated."""
        if self._score_fn is None and score_fn is not None:
            self._score_fn = score_fn
            self._scores_jit = None
        return self

    def _score_array(self, particles):
        if self._score_fn is None:
            return None
        if self._scores_jit is None:
            # one jitted vmap per diagnostics instance: steady-state
            # computes reuse the compiled program (shape-keyed by jit)
            self._scores_jit = jax.jit(jax.vmap(self._score_fn))
        return self._scores_jit(particles)

    def compute(self, particles, scores=None, num_shards: Optional[int] = None,
                step: Optional[int] = None) -> Dict:
        """One full diagnostics pass over ``particles`` (``(n, d)``).

        ``scores`` overrides the config's ``score_fn`` (pass the score
        array a training step already computed); ``num_shards`` > 1 adds
        the inter-shard divergence block.  Returns the report dict of
        plain floats (also kept as :attr:`last_report`).
        """
        cfg = self.config
        particles = jnp.asarray(particles)
        n, d = particles.shape
        if n < 2:
            raise ValueError(f"diagnostics need n >= 2 particles, got {n}")
        t0 = time.perf_counter()
        traced = _trace.enabled()
        with _trace.span("train.diagnostics",
                         {"step": step, "n": n} if traced else None):
            median_bw = cfg.bandwidth == "median"
            bw = 1.0 if median_bw else float(cfg.bandwidth)
            # all O(rows²) statistics run on the capped subsample; the
            # stride is static per n, so every compute at one shape reuses
            # the same compiled programs
            sub = _subsample(particles, cfg.max_points)
            n_eval = sub.shape[0]
            if scores is not None:
                sub_scores = _subsample(jnp.asarray(scores), cfg.max_points)
            else:
                sub_scores = self._score_array(sub)
            if sub_scores is not None:
                pair = _ksd_stats(sub, sub_scores, bw,
                                  cfg.row_chunk, median_bw)
            else:
                pair = _kernel_stats(sub, bw, cfg.row_chunk, median_bw)
            if (num_shards and num_shards > 1
                    and n % num_shards == 0):
                extra = _shard_stats(particles, num_shards)
            else:
                extra = {"min_dim_var": _dim_var_stats(particles)}
            # the float() conversions ARE the fence: every statistic is a
            # scalar fetch, so the span's wall covers device execution
            report = {k: float(v) for block in (pair, extra)
                      for k, v in block.items()}
        report["ess_frac"] = report["ess"] / n_eval
        report["n"] = n
        report["n_eval"] = n_eval
        report["d"] = d
        if step is not None:
            report["step"] = step
        wall = time.perf_counter() - t0
        report["wall_s"] = round(wall, 6)
        self._record(report, wall)
        return report

    def _record(self, report: Dict, wall: float) -> None:
        for name, gauge in self._gauges.items():
            if name == "last_step":
                if "step" in report:
                    gauge.set(report["step"])
            elif name == "last_update_ts":
                gauge.set(self._wall_clock())
            elif name in report:
                gauge.set(report[name])
        self._m_computations.inc()
        self._m_wall.observe(wall)
        self.last_report = report
        _trace.record_flight("diagnostics", **report)
        if self._logger is not None:
            self._logger.log(event="diagnostics", **report)


def ensemble_health(particles, max_points: int = 2048,
                    bandwidth: Union[float, str] = "median",
                    row_chunk: int = 1024) -> Dict:
    """Score-free health snapshot of a particle ensemble — the serving
    side's diagnostic (no ∇log p at serve time).

    Evaluates kernel ESS / min distance / variance floor / median distance
    over an evenly-strided subsample of at most ``max_points`` rows (the
    reported ``ess`` is the subsample's; ``ess_frac`` — ESS over evaluated
    rows — is the scale-free number to threshold).  Used by
    :class:`ReloadPolicy` and ``tools/serve_bench.py``.
    """
    particles = jnp.asarray(particles)
    if particles.ndim != 2 or particles.shape[0] < 2:
        raise ValueError(
            f"ensemble_health needs an (n>=2, d) array, got {particles.shape}"
        )
    sub = _subsample(particles, max_points)
    median_bw = bandwidth == "median"
    bw = 1.0 if median_bw else float(bandwidth)
    pair = _kernel_stats(sub, bw, row_chunk, median_bw)
    report = {k: float(v) for k, v in pair.items()}
    report["min_dim_var"] = float(_dim_var_stats(particles))
    report["n_eval"] = int(sub.shape[0])
    report["ess_frac"] = report["ess"] / sub.shape[0]
    return report


class ReloadPolicy:
    """Serve-side admission check: reject a candidate ensemble whose
    health regressed past thresholds (``PredictiveEngine.reload``).

    All checks are score-free (:func:`ensemble_health`); absolute floors
    apply always, relative checks compare against the currently-served
    ensemble's report.  A ``None`` threshold disables that check.

    Args:
        min_ess_frac: absolute floor on ``ess_frac`` (collapse filter).
        max_ess_drop_frac: max allowed *relative* ESS-fraction drop vs the
            served baseline (0.5 = reject below half the baseline).
        min_dim_var: absolute floor on the per-dimension variance minimum.
        max_points / bandwidth / row_chunk: forwarded to
            :func:`ensemble_health`.
    """

    def __init__(self, min_ess_frac: Optional[float] = 0.01,
                 max_ess_drop_frac: Optional[float] = 0.5,
                 min_dim_var: Optional[float] = None,
                 max_points: int = 2048,
                 bandwidth: Union[float, str] = "median",
                 row_chunk: int = 1024):
        self.min_ess_frac = min_ess_frac
        self.max_ess_drop_frac = max_ess_drop_frac
        self.min_dim_var = min_dim_var
        self.max_points = int(max_points)
        self.bandwidth = bandwidth
        self.row_chunk = int(row_chunk)

    def evaluate(self, particles) -> Dict:
        return ensemble_health(particles, max_points=self.max_points,
                               bandwidth=self.bandwidth,
                               row_chunk=self.row_chunk)

    def judge(self, candidate: Dict, baseline: Optional[Dict]) -> list:
        """Reasons the candidate fails (empty list = admit).  ``not <=`` /
        ``not >=`` comparisons so a NaN statistic rejects instead of
        comparing False."""
        reasons = []
        if (self.min_ess_frac is not None
                and not candidate["ess_frac"] >= self.min_ess_frac):
            reasons.append(
                f"ess_frac {candidate['ess_frac']:.4g} below floor "
                f"{self.min_ess_frac:g}")
        if (self.max_ess_drop_frac is not None and baseline is not None
                and baseline.get("ess_frac", 0) > 0):
            floor = baseline["ess_frac"] * (1.0 - self.max_ess_drop_frac)
            if not candidate["ess_frac"] >= floor:
                reasons.append(
                    f"ess_frac {candidate['ess_frac']:.4g} dropped past "
                    f"{self.max_ess_drop_frac:g} of served baseline "
                    f"{baseline['ess_frac']:.4g}")
        if (self.min_dim_var is not None
                and not candidate["min_dim_var"] >= self.min_dim_var):
            reasons.append(
                f"min_dim_var {candidate['min_dim_var']:.4g} below floor "
                f"{self.min_dim_var:g}")
        return reasons
