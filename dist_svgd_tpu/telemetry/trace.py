"""Span tracer: nestable, thread-aware timing spans with device fencing.

Where the metrics registry answers "how many / how fast on aggregate", the
tracer answers **"where did this slow request / slow segment spend its
time?"** — the Dapper-style causal view (Sigelman et al. 2010) the serving
and resilience paths need to debug convergence-vs-throughput tradeoffs:

- **Thread spans** (:func:`span`) — a context manager pushing onto a
  per-thread stack, so nesting is implicit and free; the span may *fence* a
  device value before stamping its end time (``sp.fence(out)`` →
  ``jax.block_until_ready`` — the honest-wall discipline inherited from
  ``utils/metrics.py:StepTimer``; an unfenced span around an async dispatch
  measures dispatch latency, which is sometimes exactly what you want).
- **Lane trees** (:meth:`Tracer.lane_tree`) — post-hoc span trees with
  explicit timestamps for work whose lifetime crosses threads (a serving
  request is enqueued by a handler thread and resolved by the batch worker).
  Each tree lands on a synthetic "request lane" track chosen so spans on one
  lane never overlap — Perfetto renders concurrent requests side by side.
- **Instant events** (:func:`instant`) — point markers; while the tracer is
  enabled it listens to ``jax.monitoring`` and records every XLA compilation
  as an ``xla_compile`` instant *inside whatever span was active on the
  compiling thread* (the runtime cousin of ``tools/jaxlint``'s
  ``retrace_sentry`` — same event stream, but placed in causal context).

**Zero-cost when disabled**: module-level :func:`span`/:func:`instant` check
one global and return a shared no-op singleton — no allocation, no lock, no
clock read (pinned by ``tests/test_telemetry.py`` with ``tracemalloc``).
Enable with :func:`enable`, stop and export with :func:`disable`.

Exporters: Chrome trace-event JSON (:meth:`Tracer.export_chrome` — load the
file in Perfetto / ``chrome://tracing``; ``tools/trace_report.py``
summarises it) and JSON-lines through the existing ``JsonlLogger`` (pass
``jsonl=`` — one record per completed span, interleaving with the metric
records the component already writes).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Tracer",
    "SpanHandle",
    "FlightRecorder",
    "enable",
    "disable",
    "get_tracer",
    "enabled",
    "span",
    "instant",
    "TRACE_HEADER",
    "mint_trace_id",
    "set_trace_context",
    "get_trace_context",
    "install_flight_recorder",
    "uninstall_flight_recorder",
    "flight_recorder",
    "record_flight",
]


# --------------------------------------------------------------------- #
# cross-process trace context (round 16)
#
# A trace id is the join key that lets one request's spans be stitched
# back together across process boundaries: the fleet router mints one per
# routed request, sends it downstream as the ``X-Fleet-Trace`` header, and
# every hop tags its lane trees with it (``tools/trace_report.py
# --stitch`` does the join).  Within one process the id travels on a
# thread-local so a component deep in the dispatch path (the engine's
# spans under the batcher's lane thread) can tag without plumbing an
# argument through every signature.


#: The HTTP header a trace id crosses process boundaries in.  Defined
#: here — next to the minting and context plumbing — because BOTH sides
#: of the hop (the fleet router sending, the serving server extracting)
#: must spell it identically; each imports this one constant.
TRACE_HEADER = "X-Fleet-Trace"

_MINT_PREFIX = os.urandom(4).hex()  # 32 random bits per process
_MINT_SEQ = itertools.count(1)


def mint_trace_id() -> str:
    """A fresh 16-hex-char trace id: a per-process random 32-bit prefix +
    a process-local sequence.  Unique within a process by construction,
    collision-safe across a fleet via the prefix — and ~30× cheaper than
    per-call ``os.urandom`` (measured 11.8 µs/call on the container: a
    syscall per request is real money on the serve hot path, where every
    traced submit mints)."""
    return f"{_MINT_PREFIX}{next(_MINT_SEQ) & 0xFFFFFFFF:08x}"


_TRACE_CTX = threading.local()


def set_trace_context(trace_id: Optional[str]) -> Optional[str]:
    """Set the calling thread's active trace id (``None`` clears it);
    returns the previous value so callers can restore it — the batcher
    brackets each single-trace dispatch with set/restore."""
    prev = getattr(_TRACE_CTX, "trace", None)
    _TRACE_CTX.trace = trace_id
    return prev


def get_trace_context() -> Optional[str]:
    """The calling thread's active trace id, or ``None``."""
    return getattr(_TRACE_CTX, "trace", None)


class _NoopSpan:
    """Disabled-path singleton: every operation is a no-op returning fast.

    ``__exit__`` takes the three positional exception args explicitly —
    a ``*args`` signature would allocate a tuple per call, and this object
    sits in hot loops of every instrumented component.
    """

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def tag(self, **tags):
        return self

    def fence(self, value):
        return value


_NOOP = _NoopSpan()


class SpanHandle:
    """One live span (enabled path).  Created by :meth:`Tracer.span`."""

    __slots__ = ("_tracer", "name", "tags", "_t0", "_fence")

    def __init__(self, tracer: "Tracer", name: str, tags: Optional[dict]):
        self._tracer = tracer
        self.name = name
        self.tags = tags
        self._t0 = 0.0
        self._fence = None

    def tag(self, **tags) -> "SpanHandle":
        if self.tags is None:
            self.tags = tags
        else:
            self.tags.update(tags)
        return self

    def fence(self, value):
        """Register ``value`` for ``jax.block_until_ready`` at span exit —
        the end timestamp then covers device execution, not just dispatch.
        Returns ``value`` for inline use: ``out = sp.fence(fn(x))``."""
        self._fence = value
        return value

    def __enter__(self) -> "SpanHandle":
        tr = self._tracer
        tr._stack().append(self)
        self._t0 = tr.now()
        return self

    def __exit__(self, exc_type, exc, tb):
        tr = self._tracer
        try:
            if self._fence is not None:
                import jax

                jax.block_until_ready(self._fence)
                self._fence = None
        finally:
            # record + pop even when the fence raises (a failed async
            # dispatch surfaces at the fence): the span must not leak on
            # the thread stack, and the trace should show the span that
            # died
            t1 = tr.now()
            stack = tr._stack()
            if stack and stack[-1] is self:
                stack.pop()
            if exc_type is not None:
                self.tag(error=exc_type.__name__)
            tr._complete(self.name, self._t0, t1, self.tags,
                         threading.get_ident())
        return False


class Tracer:
    """Collects span/instant events; thread-safe; bounded.

    Args:
        clock: monotonic seconds source (``time.perf_counter``); injectable
            for deterministic tests.
        max_events: hard cap on retained events — beyond it new events are
            **dropped and counted** (``dropped_events``), never silently
            grown: a day-long traced run must not OOM the host.
        jsonl: optional ``utils/metrics.py:JsonlLogger`` (anything with a
            ``log(**record)`` method) — one line per completed span/instant.
        registry: metrics registry for the tracer's own health series
            (``svgd_trace_dropped_total``, the ``svgd_trace_lanes`` gauge —
            a saturated trace buffer must be observable without polling
            ``dropped_events``); defaults to the process-wide registry.

    **Process identity (round 16):** every tracer stamps a process header —
    role / name / pid plus a wall-clock↔monotonic anchor (``time.time()``
    sampled at the tracer's monotonic epoch) — into both exporters (the
    Chrome doc's ``otherData.process``, one ``kind="process"`` JSONL
    record), so ``tools/trace_report.py --stitch`` can align timestamps
    from different processes on one wall clock and label each hop.
    :meth:`set_process` names the role (``"router"``/``"replica"``).
    """

    def __init__(self, clock: Callable[[], float] = time.perf_counter,
                 max_events: int = 1_000_000, jsonl=None, registry=None):
        if max_events < 1:
            raise ValueError(f"max_events must be >= 1, got {max_events}")
        from dist_svgd_tpu.telemetry import metrics as _metrics

        self._clock = clock
        # the wall↔monotonic anchor: _anchor_unix is the wall time AT the
        # tracer's monotonic epoch (every event ts is seconds since _t0,
        # so wall(ts) = _anchor_unix + ts at analysis time)
        self._anchor_unix = time.time()
        self._t0 = clock()
        self._max_events = int(max_events)
        self._jsonl = jsonl
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._dropped = 0
        self._lanes: List[float] = []  # per-lane last span end time
        self._thread_names: Dict[int, str] = {}
        self._tls = threading.local()
        self._listener_registered = False
        self._process = {"role": "process",
                         "name": f"pid-{os.getpid()}",
                         "pid": os.getpid()}
        self._process_explicit = False
        reg = registry if registry is not None else _metrics.default_registry()
        self._m_dropped = reg.counter(
            "svgd_trace_dropped_total",
            "trace events dropped past the tracer's max_events cap")
        self._m_lanes = reg.gauge(
            "svgd_trace_lanes",
            "request lane tracks allocated by the tracer (lane pressure)")
        if self._jsonl is not None:
            # the process-identity header rides the JSONL stream first, so
            # a stitcher can label the file before reading any span
            try:
                self._jsonl.log(**self.process_meta())
            except ValueError:
                pass

    # ------------------------------------------------------------------ #
    # process identity

    def set_process(self, role: Optional[str] = None,
                    name: Optional[str] = None,
                    only_if_default: bool = False) -> Dict[str, Any]:
        """Stamp this tracer's process identity (role ``"router"`` /
        ``"replica"`` / ..., a human replica name).  ``only_if_default``
        makes the call a no-op once an explicit identity was set — so a
        component's best-effort self-labelling never clobbers what a
        drill or CLI already declared.  Returns the active meta."""
        with self._lock:
            if not (only_if_default and self._process_explicit):
                if role is not None:
                    self._process["role"] = str(role)
                if name is not None:
                    self._process["name"] = str(name)
                self._process_explicit = True
            proc = dict(self._process)
        if self._jsonl is not None:
            try:
                self._jsonl.log(**self.process_meta())
            except ValueError:
                pass
        return proc

    def process_meta(self) -> Dict[str, Any]:
        """The process-identity header record both exporters carry:
        role/name/pid plus the wall↔monotonic anchor (``anchor_unix_s`` is
        the wall time at trace-timestamp 0.0)."""
        with self._lock:
            proc = dict(self._process)
        return {"kind": "process", **proc,
                "anchor_unix_s": self._anchor_unix,
                "anchor_trace_s": 0.0}

    # ------------------------------------------------------------------ #
    # recording

    def now(self) -> float:
        """Seconds since the tracer started (every event timestamp)."""
        return self._clock() - self._t0

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def active_span(self) -> Optional[SpanHandle]:
        stack = self._stack()
        return stack[-1] if stack else None

    def span(self, name: str, tags: Optional[dict] = None) -> SpanHandle:
        return SpanHandle(self, name, dict(tags) if tags else None)

    def instant(self, name: str, tags: Optional[dict] = None) -> None:
        parent = self.active_span()
        if parent is not None:
            tags = dict(tags) if tags else {}
            tags["in_span"] = parent.name
        self._append({
            "ph": "i", "name": name, "ts": self.now(),
            "tid": threading.get_ident(), "args": tags or None,
        })

    def complete(self, name: str, t0: float, t1: float,
                 tags: Optional[dict] = None, tid=None) -> None:
        """Record an already-timed span (timestamps from :meth:`now`) —
        for callers that measured the interval themselves (``StepTimer``)."""
        self._complete(name, t0, t1, tags,
                       tid if tid is not None else threading.get_ident())

    def _complete(self, name: str, t0: float, t1: float,
                  tags: Optional[dict], tid) -> None:
        self._append({
            "ph": "X", "name": name, "ts": t0, "dur": max(t1 - t0, 0.0),
            "tid": tid, "args": tags or None,
        })

    def _append(self, event: dict) -> None:
        rec = _RECORDER
        if rec is not None:
            # the flight recorder's ring keeps the NEWEST events (deque
            # maxlen) while the tracer's buffer keeps the oldest under its
            # drop cap — a crash postmortem wants what happened just
            # before the end, so feed the ring even past the tracer's cap
            rec._record_trace_event(event)
        tid = event["tid"]
        dropped = False
        with self._lock:
            if isinstance(tid, int) and tid not in self._thread_names:
                cur = threading.current_thread()
                self._thread_names[tid] = (
                    cur.name if cur.ident == tid else f"thread-{tid}"
                )
            if len(self._events) >= self._max_events:
                self._dropped += 1
                dropped = True
            else:
                self._events.append(event)
        if dropped:
            # metric write OUTSIDE the tracer lock (registry has its own);
            # a drop is now a scrapeable counter, not a silent property
            self._m_dropped.inc()
            return
        if self._jsonl is not None:
            rec = {k: v for k, v in event.items() if v is not None}
            rec["kind"] = "span" if event["ph"] == "X" else "instant"
            try:
                self._jsonl.log(**rec)
            except ValueError:
                pass  # logger closed mid-run: keep tracing in memory

    def lane_tree(self, name: str, t0: float, t1: float,
                  tags: Optional[dict] = None,
                  children: Sequence[Tuple] = ()) -> None:
        """Record a parent span plus children with **explicit timestamps**
        (from :meth:`now`, captured by the caller as the work progressed)
        on a synthetic lane track.  Lanes are allocated first-fit by
        start time so spans within one lane never overlap — the Chrome
        viewer then nests each tree unambiguously even when many trees
        (concurrent requests) overlap in wall time.

        ``children``: ``(name, t0, t1)`` or ``(name, t0, t1, tags)`` tuples,
        each clamped inside the parent interval.
        """
        if t1 < t0:
            t0, t1 = t1, t0
        with self._lock:
            lane = None
            for i, last_end in enumerate(self._lanes):
                if last_end <= t0:
                    lane = i
                    break
            new_lane = lane is None
            if new_lane:
                lane = len(self._lanes)
                self._lanes.append(0.0)
            self._lanes[lane] = t1
            n_lanes = len(self._lanes)
        if new_lane:
            # gauge write only when lane pressure actually grows — this
            # sits on every traced request's completion path
            self._m_lanes.set(n_lanes)
        tid = f"lane-{lane:03d}"
        self._complete(name, t0, t1, tags, tid)
        for child in children:
            cname, c0, c1 = child[0], child[1], child[2]
            ctags = child[3] if len(child) > 3 else None
            self._complete(cname, max(c0, t0), min(c1, t1), ctags, tid)

    @property
    def dropped_events(self) -> int:
        with self._lock:
            return self._dropped

    # ------------------------------------------------------------------ #
    # jax compile instants (the retrace_sentry event stream, in context)

    def _on_jax_event(self, event_name: str, *args, **kwargs) -> None:
        if "backend_compile" in event_name:
            self.instant("xla_compile")
        elif "jaxpr_trace" in event_name:
            self.instant("jaxpr_trace")

    def _register_listener(self) -> None:
        if self._listener_registered:
            return
        try:
            from jax._src import monitoring

            monitoring.register_event_duration_secs_listener(
                self._on_jax_event
            )
            self._listener_registered = True
        except Exception:
            pass  # degrade like retrace_sentry: trace without compile marks

    def _unregister_listener(self) -> None:
        if not self._listener_registered:
            return
        try:
            from jax._src import monitoring

            monitoring._unregister_event_duration_listener_by_callback(
                self._on_jax_event
            )
        except Exception:
            pass
        self._listener_registered = False

    # ------------------------------------------------------------------ #
    # export

    def chrome_events(self) -> List[dict]:
        """Chrome trace-event dicts (µs timestamps), ts-sorted, with
        thread/lane name metadata events first."""
        with self._lock:
            events = list(self._events)
            thread_names = dict(self._thread_names)
        out = []
        lanes = sorted({e["tid"] for e in events if isinstance(e["tid"], str)})
        names = dict(thread_names)
        names.update({lane: f"request {lane}" for lane in lanes})
        # stable int tids for chrome: lanes first (they read top-down as
        # request swimlanes), then real threads in first-seen order
        tid_map = {lane: i + 1 for i, lane in enumerate(lanes)}
        base = len(lanes) + 1
        for e in events:
            if e["tid"] not in tid_map:
                tid_map[e["tid"]] = base
                base += 1
        for raw_tid, tid in sorted(tid_map.items(), key=lambda kv: kv[1]):
            out.append({
                "ph": "M", "name": "thread_name", "pid": 1, "tid": tid,
                "args": {"name": str(names.get(raw_tid, raw_tid))},
            })
        for e in sorted(events, key=lambda e: e["ts"]):
            ev = {
                "ph": e["ph"], "name": e["name"], "pid": 1,
                "tid": tid_map[e["tid"]],
                "ts": round(e["ts"] * 1e6, 3),
            }
            if e["ph"] == "X":
                ev["dur"] = round(e["dur"] * 1e6, 3)
            else:
                ev["s"] = "t"
            if e.get("args"):
                ev["args"] = e["args"]
            out.append(ev)
        return out

    def export_chrome(self, path: str) -> int:
        """Write Perfetto-loadable Chrome trace JSON; returns event count.
        ``otherData.process`` carries the process-identity header + clock
        anchor that ``trace_report --stitch`` aligns files on."""
        events = self.chrome_events()
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"process": self.process_meta()}}
        if self.dropped_events:
            doc["otherData"]["dropped_events"] = self.dropped_events
        with open(path, "w") as fh:
            json.dump(doc, fh)
            fh.write("\n")
        return len(events)

    def counts(self) -> Dict[str, int]:
        """Event counts by name (diagnostics and tests)."""
        with self._lock:
            out: Dict[str, int] = {}
            for e in self._events:
                out[e["name"]] = out.get(e["name"], 0) + 1
            return out


# --------------------------------------------------------------------- #
# flight recorder: bounded black box for crash postmortems

class FlightRecorder:
    """Bounded ring buffer of recent spans, instants, explicit records,
    and the last diagnostics report — the training/serving "black box".

    While installed (:func:`install_flight_recorder`) the tracer feeds
    every completed span/instant into the ring (newest kept — a crash
    wants the moments *before* the end, the opposite retention of the
    tracer's own drop-oldest-never buffer), and components add structured
    records off their hot paths via :func:`record_flight`.  On a guard
    trip, an injected fault, or an exhausted restart budget the supervisor
    calls :meth:`dump`, which writes one **postmortem bundle** — JSONL:
    a header line, the registry's metric snapshot, the last diagnostics
    report, then the ring oldest→newest — rendered by
    ``tools/trace_report.py --postmortem``.

    Args:
        capacity: max retained events (ring; oldest evicted).
        dump_dir: where :meth:`dump` writes bundles
          (``postmortem_<seq>_<reason>.jsonl``).
        registry: metrics registry snapshotted into each bundle — every
            bundle carries the numbers (default: the process-wide
            registry).
        clock: unix-time source for event/bundle timestamps.
    """

    def __init__(self, capacity: int = 1024, dump_dir: str = ".",
                 registry=None, clock: Callable[[], float] = time.time):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        import collections

        from dist_svgd_tpu.telemetry import metrics as _metrics

        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=int(capacity))
        self._dump_dir = dump_dir
        self._registry = (registry if registry is not None
                          else _metrics.default_registry())
        self._clock = clock
        self._last_diagnostics: Optional[dict] = None
        self._dumps = 0
        self._m_dumps = self._registry.counter(
            "svgd_flight_dumps_total", "postmortem bundles written")

    # ------------------------------------------------------------------ #

    def record(self, kind: str, **fields) -> None:
        """Append one structured record to the ring.  ``kind='diagnostics'``
        additionally becomes the bundle's last-diagnostics block."""
        entry = {"kind": kind, "ts": self._clock(), **fields}
        with self._lock:
            self._ring.append(entry)
            if kind == "diagnostics":
                self._last_diagnostics = entry

    def _record_trace_event(self, event: dict) -> None:
        """Tracer feed: one completed span/instant (tracer-relative
        timestamps, like the trace exports)."""
        entry = {"kind": "span" if event["ph"] == "X" else "instant",
                 "name": event["name"], "ts": event["ts"]}
        if event["ph"] == "X":
            entry["dur"] = event["dur"]
        if event.get("args"):
            entry["args"] = event["args"]
        with self._lock:
            self._ring.append(entry)

    @property
    def last_diagnostics(self) -> Optional[dict]:
        with self._lock:
            return self._last_diagnostics

    def events(self) -> List[dict]:
        """Ring contents oldest→newest (a copy)."""
        with self._lock:
            return list(self._ring)

    @property
    def dumps(self) -> int:
        with self._lock:
            return self._dumps

    # ------------------------------------------------------------------ #

    def dump(self, reason: str, context: Optional[dict] = None,
             path: Optional[str] = None) -> str:
        """Write one postmortem bundle; returns its path.

        The bundle is JSONL so a truncated write (the crash may be a
        dying process) still yields parseable leading lines: header,
        metrics snapshot, last diagnostics, then ring events.
        """
        import os
        import re

        with self._lock:
            self._dumps += 1
            seq = self._dumps
            events = list(self._ring)
            last_diag = self._last_diagnostics
        if path is None:
            slug = re.sub(r"[^a-zA-Z0-9_.-]+", "_", reason)[:48] or "unknown"
            os.makedirs(self._dump_dir, exist_ok=True)
            path = os.path.join(self._dump_dir,
                                f"postmortem_{seq:03d}_{slug}.jsonl")
        lines = [{"kind": "postmortem", "reason": reason,
                  "ts": self._clock(), "events": len(events),
                  "context": context or {}}]
        try:
            lines.append({"kind": "metrics",
                          "snapshot": self._registry.snapshot()})
        except Exception:  # a half-poisoned registry must not block a dump
            lines.append({"kind": "metrics", "snapshot": None})
        if last_diag is not None:
            lines.append(last_diag)
        lines.extend(events)
        with open(path, "w") as fh:
            for rec in lines:
                fh.write(json.dumps(rec, default=str))
                fh.write("\n")
        self._m_dumps.inc()
        return path


_RECORDER: Optional[FlightRecorder] = None


def install_flight_recorder(recorder: Optional[FlightRecorder] = None,
                            **kwargs) -> FlightRecorder:
    """Install (and return) the process flight recorder.  Idempotent while
    installed — a second call returns the live recorder unchanged (nested
    tooling composes, the tracer-enable convention).  ``kwargs`` build a
    fresh :class:`FlightRecorder` when none is passed."""
    global _RECORDER
    with _SWITCH_LOCK:
        if _RECORDER is None:
            _RECORDER = recorder if recorder is not None else FlightRecorder(
                **kwargs)
        return _RECORDER


def uninstall_flight_recorder() -> Optional[FlightRecorder]:
    """Remove and return the installed recorder (``None`` when absent)."""
    global _RECORDER
    with _SWITCH_LOCK:
        recorder, _RECORDER = _RECORDER, None
    return recorder


def flight_recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def record_flight(kind: str, **fields) -> None:
    """Structured record into the installed recorder; no-op when none.
    Hot paths should guard on :func:`flight_recorder` first — the kwargs
    dict is built at the call site either way."""
    rec = _RECORDER
    if rec is not None:
        rec.record(kind, **fields)


# --------------------------------------------------------------------- #
# module-level switchboard: the zero-cost disabled path

_TRACER: Optional[Tracer] = None
_SWITCH_LOCK = threading.Lock()


def enable(clock: Callable[[], float] = time.perf_counter,
           max_events: int = 1_000_000, jsonl=None,
           registry=None) -> Tracer:
    """Install (and return) the global tracer.  Idempotent while enabled —
    a second ``enable`` returns the live tracer unchanged, so nested
    tooling (serve_bench inside perf_regress) composes."""
    global _TRACER
    with _SWITCH_LOCK:
        if _TRACER is None:
            tracer = Tracer(clock=clock, max_events=max_events, jsonl=jsonl,
                            registry=registry)
            tracer._register_listener()
            _TRACER = tracer
        return _TRACER


def disable() -> Optional[Tracer]:
    """Uninstall and return the global tracer (for export); no-op → None."""
    global _TRACER
    with _SWITCH_LOCK:
        tracer, _TRACER = _TRACER, None
    if tracer is not None:
        tracer._unregister_listener()
    return tracer


def get_tracer() -> Optional[Tracer]:
    return _TRACER


def enabled() -> bool:
    """True while a global tracer is installed.  Hot paths that must build
    tag dicts or capture timestamps guard on this first."""
    return _TRACER is not None


def span(name: str, tags: Optional[dict] = None):
    """Context manager timing ``name`` on the current thread's span stack.
    The shared no-op singleton when tracing is disabled (no allocation)."""
    tracer = _TRACER
    if tracer is None:
        return _NOOP
    return tracer.span(name, tags)


def instant(name: str, tags: Optional[dict] = None) -> None:
    """Point event inside the current span; no-op when disabled."""
    tracer = _TRACER
    if tracer is not None:
        tracer.instant(name, tags)
