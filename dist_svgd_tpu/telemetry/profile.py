"""Per-program dispatch profiling: runtime cost attribution for every
``plan://<label>`` identity the compile seam tracks.

PR 17's program registry gave every ``Plan.compile``/``compile_sharded``
product a durable label and a static program card; this module joins the
*runtime* to those identities.  While a :class:`DispatchProfiler` is
enabled, every dispatch through an ``analysis/registry.py`` wrapper is
fenced (``jax.block_until_ready``) and its wall time observed into a
``svgd_prog_dispatch_seconds{label=...}`` histogram, alongside
dispatch / rows / bytes counters sized from the entry's first-call aval
snapshot (the same avals the program card is lowered from).  The answer
to "where do the device-seconds go, per program, right now?" becomes one
registry read — ``tools/trace_report.py --programs`` renders it.

Cost discipline (the PR-5 tracer contract, applied here):

- **Disabled is the default and costs one module-global read** per
  dispatch — ``analysis/registry.py`` reads ``_PROFILER`` and calls the
  compiled program directly when it is ``None``.  No object is
  allocated on that path; :func:`measure` returns a shared zero-alloc
  no-op singleton (pinned by a tracemalloc test like the tracer's).
- **Enabled fences every tracked dispatch.**  That is the point — the
  observed wall is device wall, not async-dispatch wall — and the cost
  is the fence: serving already host-fetches results (its fence is
  free), while training chunk pipelines serialise at chunk boundaries
  for the duration.  The A/B overhead on the serve path is gated <= 3%
  by ``tools/perf_regress.py`` (``profiler_overhead`` row).
- **Fence exactly once.**  The profiler leaves a thread-local note
  identifying the output it just fenced; :func:`fence` (used by
  ``utils/metrics.StepTimer.mark`` and the distributed sampler's
  dispatch runner) consumes the note and skips the redundant
  ``block_until_ready`` when handed that same object.

The profiler has no background thread and takes no locks on the hot
path: per-entry label dicts and rows/bytes sizes are computed once and
cached on the :class:`~dist_svgd_tpu.analysis.registry.ProgramEntry`
itself (keyed by profiler identity, so a fresh enable re-derives them),
and the metric objects do their own locking.

Usage::

    from dist_svgd_tpu.telemetry import profile

    prof = profile.enable_profiler(registry=metrics_registry)
    ...dispatch work...
    profile.disable_profiler()
    print(profile.summary(metrics_registry))   # {label: {seconds, ...}}
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

__all__ = [
    "DISPATCH_SECONDS",
    "DISPATCHES_TOTAL",
    "DISPATCH_ROWS_TOTAL",
    "DISPATCH_BYTES_TOTAL",
    "DispatchProfiler",
    "enable_profiler",
    "disable_profiler",
    "get_profiler",
    "profiler_enabled",
    "fence",
    "measure",
    "summary",
    "attributed_seconds",
]

#: Metric names (one label: ``label`` = the plan/program label).
DISPATCH_SECONDS = "svgd_prog_dispatch_seconds"
DISPATCHES_TOTAL = "svgd_prog_dispatches_total"
DISPATCH_ROWS_TOTAL = "svgd_prog_dispatch_rows_total"
DISPATCH_BYTES_TOTAL = "svgd_prog_dispatch_bytes_total"

#: The active profiler, or None.  Read (not called) on every tracked
#: dispatch — keep it a plain module global so the disabled path is one
#: attribute load + identity check.
_PROFILER: Optional["DispatchProfiler"] = None
_LOCK = threading.Lock()

#: Thread-local fence bookkeeping: ``(id(out), type(out))`` of the last
#: output this thread's profiler fenced, consumed (cleared) by the first
#: :func:`fence` call handed the same object.  id() alone could collide
#: after garbage collection; pairing with the concrete type and
#: overwriting on every profiled dispatch bounds the window to "the
#: dispatch this thread just timed", which is exactly the double-fence
#: being deduplicated.
_TLS = threading.local()

# jax is imported lazily (module attribute, not bound function, so test
# spies that monkeypatch ``jax.block_until_ready`` are honoured) to keep
# ``import dist_svgd_tpu.telemetry`` as light as PR 5 left it.
_jax = None


def _block_until_ready(value: Any) -> Any:
    global _jax
    if _jax is None:
        import jax

        _jax = jax
    return _jax.block_until_ready(value)


# ------------------------------------------------------------------ #
# sizing helpers: rows / bytes from the entry's first-call avals
# ------------------------------------------------------------------ #


def _entry_sizes(entry) -> tuple:
    """(rows, bytes) for one dispatch of ``entry``, from its aval
    snapshot — the same shapes the PR-17 program card is lowered from.

    rows: leading dim of the first traced argument's first array leaf
    (the batch/ensemble axis by plan convention).  bytes: total traced
    input payload.  (0, 0) when the snapshot is missing or unsizable.
    """
    avals = entry.avals
    if avals is None:
        return (0, 0)
    static = set(entry.static_argnums)
    rows = 0
    nbytes = 0
    try:
        import jax

        for i, a in enumerate(avals):
            if i in static:
                continue
            for leaf in jax.tree_util.tree_leaves(a):
                shape = getattr(leaf, "shape", None)
                dtype = getattr(leaf, "dtype", None)
                if shape is None or dtype is None:
                    continue
                if rows == 0 and len(shape) >= 1:
                    rows = int(shape[0])
                nbytes += int(
                    np.prod(shape, dtype=np.int64) * np.dtype(dtype).itemsize)
    except Exception:
        return (0, 0)
    return (rows, nbytes)


# ------------------------------------------------------------------ #
# the profiler
# ------------------------------------------------------------------ #


class DispatchProfiler:
    """Fence + attribute every tracked dispatch to its program label.

    Args:
        registry: the :class:`~dist_svgd_tpu.telemetry.metrics.
            MetricsRegistry` to write ``svgd_prog_*`` series into
            (default: the process-wide registry, so serving ``/metrics``
            picks the series up with no extra wiring).
        clock: injectable monotonic clock (tests).
    """

    def __init__(self, registry=None, clock: Callable[[], float] = time.perf_counter):
        from dist_svgd_tpu.telemetry import metrics as _metrics

        self.registry = registry if registry is not None else _metrics.default_registry()
        self._clock = clock
        self._hist = self.registry.histogram(
            DISPATCH_SECONDS,
            "Fenced wall seconds of one compiled-program dispatch, by plan label.")
        self._dispatches = self.registry.counter(
            DISPATCHES_TOTAL, "Profiled dispatches, by plan label.")
        self._rows = self.registry.counter(
            DISPATCH_ROWS_TOTAL,
            "Leading-axis rows dispatched (first traced arg), by plan label.")
        self._bytes = self.registry.counter(
            DISPATCH_BYTES_TOTAL,
            "Traced input bytes dispatched, by plan label.")

    # hot path ------------------------------------------------------ #

    def call(self, entry, compiled: Callable, args, kwargs):
        """Run one dispatch fenced, attributing its wall to ``entry``.

        Called by the ``analysis/registry.py`` wrapper *after* aval
        capture, so ``entry.avals`` is already populated on the first
        profiled call.  The per-entry cache (label dict + sizes) is
        keyed by profiler identity — a disable/enable cycle with a new
        registry re-derives it; the benign write race on the cache slot
        is idempotent.
        """
        t0 = self._clock()
        out = compiled(*args, **kwargs)
        _block_until_ready(out)
        wall = self._clock() - t0
        _TLS.fenced = (id(out), type(out))

        cache = entry.prof_cache
        if cache is None or cache[0] is not self:
            rows, nbytes = _entry_sizes(entry)
            cache = (self, {"label": entry.label}, rows, nbytes)
            entry.prof_cache = cache
        _, labels, rows, nbytes = cache
        self._hist.observe(wall, **labels)
        self._dispatches.inc(**labels)
        if rows:
            self._rows.inc(rows, **labels)
        if nbytes:
            self._bytes.inc(nbytes, **labels)
        return out


# ------------------------------------------------------------------ #
# switchboard (the tracer's enable/disable discipline)
# ------------------------------------------------------------------ #


def enable_profiler(registry=None,
                    clock: Callable[[], float] = time.perf_counter,
                    ) -> DispatchProfiler:
    """Install a process-wide profiler (idempotent: an already-active
    profiler is returned unchanged — disable first to re-target)."""
    global _PROFILER
    with _LOCK:
        if _PROFILER is None:
            _PROFILER = DispatchProfiler(registry=registry, clock=clock)
        return _PROFILER


def disable_profiler() -> Optional[DispatchProfiler]:
    """Uninstall and return the active profiler (``None`` if idle).
    Clears this thread's pending fence note so a stale object id cannot
    suppress a later legitimate fence."""
    global _PROFILER
    with _LOCK:
        prof, _PROFILER = _PROFILER, None
    _TLS.fenced = None
    return prof


def get_profiler() -> Optional[DispatchProfiler]:
    return _PROFILER


def profiler_enabled() -> bool:
    return _PROFILER is not None


# ------------------------------------------------------------------ #
# fence-once
# ------------------------------------------------------------------ #


def fence(value: Any) -> Any:
    """``jax.block_until_ready(value)`` — unless the active profiler
    already fenced this very object on this thread, in which case the
    note is consumed and the redundant device round-trip skipped.

    Drop-in for the fence sites that may wrap a profiled dispatch
    (``StepTimer.mark``, the distributed sampler's dispatch runner):
    with the profiler off this is exactly ``block_until_ready``; with it
    on, each dispatch is fenced exactly once.
    """
    if value is None:
        return None
    note = getattr(_TLS, "fenced", None)
    if note is not None and note[0] == id(value) and note[1] is type(value):
        _TLS.fenced = None
        return value
    return _block_until_ready(value)


# ------------------------------------------------------------------ #
# manual attribution spans
# ------------------------------------------------------------------ #


class _NoopMeasure:
    """Shared do-nothing measure — the disabled :func:`measure` path
    allocates nothing (tracemalloc-pinned, like the tracer's no-op
    span)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NOOP_MEASURE = _NoopMeasure()


class _Measure:
    """Context manager attributing a hand-labelled block's fenced wall
    to the profiler's metrics — for host-side cost that never flows
    through a tracked plan dispatch (tools, custom loops)."""

    __slots__ = ("_prof", "_labels", "_t0")

    def __init__(self, prof: DispatchProfiler, label: str):
        self._prof = prof
        self._labels = {"label": label}
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = self._prof._clock()
        return self

    def __exit__(self, exc_type, exc, tb):
        prof = self._prof
        wall = prof._clock() - self._t0
        prof._hist.observe(wall, **self._labels)
        prof._dispatches.inc(**self._labels)
        return False


def measure(label: str):
    """A with-block whose wall is attributed to ``label`` like a
    dispatch (no fence — the caller decides what readiness means for a
    host-side block).  Zero-alloc shared no-op while disabled."""
    prof = _PROFILER
    if prof is None:
        return _NOOP_MEASURE
    return _Measure(prof, label)


# ------------------------------------------------------------------ #
# read side
# ------------------------------------------------------------------ #


def summary(registry=None, label_prefix: str = "") -> Dict[str, dict]:
    """Per-program attribution read off any registry holding
    ``svgd_prog_*`` series (live, scraped, or federated): ``{label:
    {seconds, dispatches, mean_ms, rows, bytes}}``, restricted to
    ``label_prefix`` when given.  Federated replica-labelled series are
    skipped so fleet totals are not double-counted (the rollup series
    carry the fleet view)."""
    from dist_svgd_tpu.telemetry import metrics as _metrics

    reg = registry if registry is not None else _metrics.default_registry()
    hist = reg.get(DISPATCH_SECONDS)
    out: Dict[str, dict] = {}
    if hist is None:
        return out
    for ls in hist.label_sets():
        if "replica" in ls:
            continue
        label = ls.get("label", "")
        if not label.startswith(label_prefix):
            continue
        # read at microsecond scale: Histogram.summary rounds to 4
        # decimals, which truncates a µs-scale dispatch wall at scale 1.0
        s = hist.summary(scale=1e6, **ls)
        if not s["count"]:
            continue
        row = out.setdefault(label, {
            "seconds": 0.0, "dispatches": 0, "mean_ms": 0.0,
            "rows": 0, "bytes": 0,
        })
        row["seconds"] += float(s["sum"]) / 1e6
        row["dispatches"] += int(s["count"])
    for name, key in ((DISPATCH_ROWS_TOTAL, "rows"),
                      (DISPATCH_BYTES_TOTAL, "bytes")):
        ctr = reg.get(name)
        if ctr is None:
            continue
        for ls in ctr.label_sets():
            if "replica" in ls:
                continue
            label = ls.get("label", "")
            if label in out:
                out[label][key] += int(ctr.value(**ls))
    for row in out.values():
        if row["dispatches"]:
            row["mean_ms"] = 1e3 * row["seconds"] / row["dispatches"]
    return out


def attributed_seconds(registry=None, label_prefix: str = "") -> float:
    """Total fenced dispatch wall attributed under ``label_prefix`` —
    the numerator of the ``cost_attribution`` coverage gate."""
    return float(sum(r["seconds"]
                     for r in summary(registry, label_prefix).values()))
