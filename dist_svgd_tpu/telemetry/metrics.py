"""Thread-safe metrics registry: counters, gauges, histograms.

The repo grew four half-connected observability substitutes (``JsonlLogger``,
``StepTimer``, ``retrace_sentry``, the serving ``/metrics`` ad-hoc dicts) and
none of them aggregates: every component keeps private counters behind its own
lock with its own naming.  This registry is the one shared sink —

- **Counter** — monotonically increasing totals (requests, sheds, restarts);
- **Gauge** — last-write-wins instantaneous values (queue depth, particles);
- **Histogram** — fixed **log-spaced** latency buckets (powers of two from
  0.1 ms to ~26 s — :data:`LATENCY_BUCKETS_S`), cumulative-bucket semantics,
  with quantile estimates by linear interpolation inside the crossing bucket
  (the standard Prometheus ``histogram_quantile`` estimate: exact bucket
  counts, approximate within-bucket position);

all label-aware (``counter.inc(route="/predict", status=200)``), all guarded
by ONE registry lock (the write path is a dict upsert — at serving rates the
lock is uncontended; the exposition path snapshots under the lock and formats
outside it, the same discipline as ``MicroBatcher.stats``).

**Label-cardinality guard** (round 14): per-tenant labels make unbounded
cardinality a real leak — a buggy or adversarial label value (a request id,
a timestamp) would grow a metric's series dict and its exposition without
bound.  Every metric therefore bounds its distinct label sets
(``max_label_sets``, default :data:`DEFAULT_MAX_LABEL_SETS`, configurable
per registry and per metric); once the bound is reached, *new* label sets
aggregate into a reserved rollup series whose label values are all
:data:`OTHER_LABEL_VALUE` (``{tenant="other"}``) with a one-time
``RuntimeWarning`` per metric.  Already-admitted series keep updating —
the guard caps growth, it never drops data.

Exposition is Prometheus text format 0.0.4 (:meth:`MetricsRegistry.
exposition`) — the serving server's ``/metrics`` serves it directly — plus a
JSON-friendly :meth:`~MetricsRegistry.snapshot` for BENCH-style rows.

A process-wide default registry (:func:`default_registry`) is what
instrumented components write to when not handed an explicit one; tests and
benches that need isolation construct their own ``MetricsRegistry()`` and
pass it down.
"""

from __future__ import annotations

import math
import re
import threading
import warnings
from typing import Dict, Iterable, Optional, Tuple

__all__ = [
    "DEFAULT_MAX_LABEL_SETS",
    "DUMP_FORMAT",
    "LATENCY_BUCKETS_S",
    "OTHER_LABEL_VALUE",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "combined_exposition",
    "default_registry",
    "dump_delta",
]

#: Wire-format tag of :meth:`MetricsRegistry.dump` (the full-fidelity
#: snapshot the fleet federation scrapes at ``/metrics.dump``).
DUMP_FORMAT = "svgd-metrics-dump-1"

#: Default per-metric bound on distinct label sets — generous for the
#: repo's own labels (tenants × lanes × routes stay well under it) while
#: capping a genuine cardinality leak at a fixed exposition size.
DEFAULT_MAX_LABEL_SETS = 128

#: Reserved label value the overflow rollup series carries for every label
#: name of the set that overflowed (``{tenant="other"}``).
OTHER_LABEL_VALUE = "other"

#: Fixed log-spaced latency buckets (seconds): powers of two from 0.1 ms up
#: to ~26 s, 19 buckets.  One shared lattice for every latency histogram so
#: cross-metric quantiles are comparable and exposition size is bounded.
LATENCY_BUCKETS_S: Tuple[float, ...] = tuple(
    1e-4 * 2.0 ** i for i in range(19)
)

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: dict) -> _LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _escape_label(value: str) -> str:
    """Label-value escaping per the text exposition format 0.0.4:
    backslash, double-quote, and line feed — in that order, so an
    already-escaped sequence is never double-mangled."""
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(value: str) -> str:
    """HELP-text escaping: only backslash and line feed — the format
    leaves double quotes literal in HELP lines (they are not quoted), so
    escaping them there corrupts the docstring a scraper shows."""
    return value.replace("\\", "\\\\").replace("\n", "\\n")


def _format_labels(key: _LabelKey, extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    items = key + extra
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label(v)}"' for k, v in items)
    return "{" + body + "}"


def _format_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Metric:
    """Shared name/help/lock plumbing.  Subclasses store per-label-set state
    in ``_series`` and render themselves into exposition lines."""

    kind = "untyped"

    def __init__(self, name: str, help: str, lock: threading.Lock,
                 max_label_sets: int = DEFAULT_MAX_LABEL_SETS):
        if max_label_sets < 1:
            raise ValueError(
                f"metric {name!r} needs max_label_sets >= 1, "
                f"got {max_label_sets}"
            )
        self.name = name
        self.help = help
        self.max_label_sets = int(max_label_sets)
        self._lock = lock
        self._series: Dict[_LabelKey, object] = {}
        self._overflowed = False

    def _admit(self, key: _LabelKey) -> Tuple[_LabelKey, bool]:
        """Cardinality guard (call under the lock): an already-known label
        set or one under the bound is admitted as-is; a NEW set past the
        bound maps to the reserved rollup key (same label names, every
        value :data:`OTHER_LABEL_VALUE`).  Returns ``(key, warn)`` where
        ``warn`` is True exactly once per metric — the caller emits the
        warning after releasing the lock."""
        if key in self._series or len(self._series) < self.max_label_sets:
            return key, False
        rollup = tuple((k, OTHER_LABEL_VALUE) for k, _ in key)
        warn = not self._overflowed
        self._overflowed = True
        return rollup, warn

    def _warn_overflow(self) -> None:
        warnings.warn(
            f"metric {self.name!r} exceeded max_label_sets="
            f"{self.max_label_sets}: further new label sets aggregate into "
            f'the reserved {{...="{OTHER_LABEL_VALUE}"}} rollup series',
            RuntimeWarning,
            stacklevel=3,
        )

    def _header(self) -> list:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {_escape_help(self.help)}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        return lines

    def has(self, **labels) -> bool:
        """True once this label set has been written (distinguishes a
        never-set gauge from one legitimately at 0 — the SLO engine's
        ``no_data`` vs ``ok``)."""
        with self._lock:
            return _label_key(labels) in self._series

    def label_sets(self) -> list:
        """Every written label set, as dicts — the introspection surface
        federation/status tooling enumerates series with (pair it with
        ``value(**labels)`` / ``summary(**labels)``)."""
        with self._lock:
            return [dict(k) for k in self._series]


class Counter(_Metric):
    """Monotonic total.  ``inc(amount=1, **labels)``."""

    kind = "counter"

    def inc(self, amount: float = 1, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({amount})")
        with self._lock:
            key, warn = self._admit(_label_key(labels))
            self._series[key] = self._series.get(key, 0) + amount
        if warn:
            self._warn_overflow()

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0))

    def _render(self) -> list:
        with self._lock:
            series = dict(self._series)
        lines = self._header()
        for key in sorted(series):
            lines.append(
                f"{self.name}{_format_labels(key)} {_format_value(series[key])}"
            )
        if not series:
            lines.append(f"{self.name} 0")
        return lines


class Gauge(_Metric):
    """Instantaneous value.  ``set(v, **labels)`` / ``inc`` / ``dec``."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            key, warn = self._admit(_label_key(labels))
            self._series[key] = float(value)
        if warn:
            self._warn_overflow()

    def inc(self, amount: float = 1, **labels) -> None:
        with self._lock:
            key, warn = self._admit(_label_key(labels))
            self._series[key] = self._series.get(key, 0.0) + amount
        if warn:
            self._warn_overflow()

    def dec(self, amount: float = 1, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))

    def _render(self) -> list:
        with self._lock:
            series = dict(self._series)
        lines = self._header()
        for key in sorted(series):
            lines.append(
                f"{self.name}{_format_labels(key)} {_format_value(series[key])}"
            )
        if not series:
            lines.append(f"{self.name} 0")
        return lines


class _HistSeries:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative) counts
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    """Fixed-bucket histogram.  ``observe(value, **labels)``; quantiles by
    interpolation inside the crossing bucket (:meth:`quantile`)."""

    kind = "histogram"

    def __init__(self, name: str, help: str, lock: threading.Lock,
                 buckets: Optional[Iterable[float]] = None,
                 max_label_sets: int = DEFAULT_MAX_LABEL_SETS):
        super().__init__(name, help, lock, max_label_sets=max_label_sets)
        bounds = tuple(buckets) if buckets is not None else LATENCY_BUCKETS_S
        if not bounds or any(b <= a for a, b in zip(bounds, bounds[1:])):
            raise ValueError(
                f"histogram {name} needs strictly increasing buckets, "
                f"got {bounds}"
            )
        self.buckets = bounds  # upper bounds; +Inf is implicit

    def observe(self, value: float, **labels) -> None:
        with self._lock:
            key, warn = self._admit(_label_key(labels))
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = _HistSeries(len(self.buckets) + 1)
            i = 0
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    break
            else:
                i = len(self.buckets)  # overflow (+Inf) bucket
            series.counts[i] += 1
            series.sum += value
            series.count += 1
        if warn:
            self._warn_overflow()

    def merge_series(self, counts: Iterable[int], sum: float, count: int,
                     **labels) -> None:
        """Add one dumped series (raw per-bucket counts + sum + count) into
        this histogram — **exact** because every registry shares the same
        fixed bucket lattice; a mismatched bucket count raises (the
        federation surfaces it as a scrape error, never a silent skew)."""
        counts = list(counts)
        if len(counts) != len(self.buckets) + 1:
            raise ValueError(
                f"histogram {self.name}: cannot merge {len(counts)} bucket "
                f"counts into {len(self.buckets) + 1} buckets"
            )
        with self._lock:
            key, warn = self._admit(_label_key(labels))
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = _HistSeries(len(self.buckets) + 1)
            for i, c in enumerate(counts):
                series.counts[i] += c
            series.sum += sum
            series.count += count
        if warn:
            self._warn_overflow()

    def _snapshot(self, labels: dict) -> Optional[_HistSeries]:
        with self._lock:
            series = self._series.get(_label_key(labels))
            if series is None:
                return None
            out = _HistSeries(len(series.counts))
            out.counts = list(series.counts)
            out.sum = series.sum
            out.count = series.count
            return out

    def quantile(self, q: float, **labels) -> float:
        """Estimated ``q``-quantile (seconds for latency histograms): find
        the bucket where the cumulative count crosses ``q·total``, linearly
        interpolate inside it.  0.0 with no observations; the last finite
        bound when the crossing lands in the overflow bucket."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        series = self._snapshot(labels)
        if series is None or series.count == 0:
            return 0.0
        rank = q * series.count
        cum = 0
        for i, c in enumerate(series.counts):
            prev_cum = cum
            cum += c
            if cum >= rank and c > 0:
                if i >= len(self.buckets):  # overflow bucket: no upper bound
                    return self.buckets[-1]
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i]
                frac = (rank - prev_cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        return self.buckets[-1]

    def summary(self, scale: float = 1.0, **labels) -> dict:
        """``{count, sum, p50, p95, p99}`` (values × ``scale`` — pass 1e3
        for milliseconds) for one label set — the BENCH-row form."""
        series = self._snapshot(labels)
        count = series.count if series else 0
        return {
            "count": count,
            "sum": round((series.sum if series else 0.0) * scale, 4),
            "p50": round(self.quantile(0.50, **labels) * scale, 4),
            "p95": round(self.quantile(0.95, **labels) * scale, 4),
            "p99": round(self.quantile(0.99, **labels) * scale, 4),
        }

    def _render(self) -> list:
        with self._lock:
            series = {k: (list(s.counts), s.sum, s.count)
                      for k, s in self._series.items()}
        lines = self._header()
        for key in sorted(series):
            counts, total, count = series[key]
            cum = 0
            for bound, c in zip(self.buckets, counts):
                cum += c
                lines.append(
                    f"{self.name}_bucket"
                    f"{_format_labels(key, (('le', _format_value(bound)),))}"
                    f" {cum}"
                )
            lines.append(
                f"{self.name}_bucket{_format_labels(key, (('le', '+Inf'),))}"
                f" {count}"
            )
            lines.append(
                f"{self.name}_sum{_format_labels(key)} {_format_value(total)}"
            )
            lines.append(f"{self.name}_count{_format_labels(key)} {count}")
        if not series:
            lines.append(f"{self.name}_count 0")
        return lines


_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


class MetricsRegistry:
    """Get-or-create registry of named metrics with one shared lock.

    Re-requesting a name returns the existing metric (instrumented classes
    can be constructed many times per process — a second ``MicroBatcher``
    aggregates into the same counters, the Prometheus convention); asking
    for the same name as a different metric kind raises.

    ``max_label_sets`` is the registry-wide default cardinality bound per
    metric (see the module docstring); the per-metric ``max_label_sets=``
    on :meth:`counter`/:meth:`gauge`/:meth:`histogram` overrides it **at
    creation** — a later get-or-create of the same name returns the
    existing metric with its original bound.
    """

    def __init__(self, max_label_sets: int = DEFAULT_MAX_LABEL_SETS):
        if max_label_sets < 1:
            raise ValueError(
                f"max_label_sets must be >= 1, got {max_label_sets}"
            )
        self.max_label_sets = int(max_label_sets)
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       max_label_sets: Optional[int] = None,
                       **kwargs) -> _Metric:
        if not _NAME_OK.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        bound = (self.max_label_sets if max_label_sets is None
                 else max_label_sets)
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = self._metrics[name] = cls(name, help, self._lock,
                                                   max_label_sets=bound,
                                                   **kwargs)
            elif type(metric) is not cls:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(metric).__name__}, requested {cls.__name__}"
                )
            return metric

    def counter(self, name: str, help: str = "",
                max_label_sets: Optional[int] = None) -> Counter:
        return self._get_or_create(Counter, name, help,
                                   max_label_sets=max_label_sets)

    def gauge(self, name: str, help: str = "",
              max_label_sets: Optional[int] = None) -> Gauge:
        return self._get_or_create(Gauge, name, help,
                                   max_label_sets=max_label_sets)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Iterable[float]] = None,
                  max_label_sets: Optional[int] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets,
                                   max_label_sets=max_label_sets)

    def exposition(self) -> str:
        """Prometheus text format 0.0.4; one block per metric, names sorted
        (deterministic output — the golden test relies on it)."""
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        lines = []
        for metric in metrics:
            lines.extend(metric._render())
        return "\n".join(lines) + ("\n" if lines else "")

    def get(self, name: str) -> Optional[_Metric]:
        """The metric registered under ``name`` (None when absent) — the
        read-only peek the SLO engine and the fleet federation use."""
        with self._lock:
            return self._metrics.get(name)

    def dump(self) -> dict:
        """Full-fidelity JSON-safe snapshot — unlike :meth:`snapshot`,
        histograms keep their **raw per-bucket counts**, so two dumps from
        registries sharing the fixed bucket lattice merge *exactly*
        (:meth:`ingest`).  This is the fleet federation's wire format
        (served at ``/metrics.dump``)."""
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        out: dict = {"format": DUMP_FORMAT, "metrics": {}}
        for metric in metrics:
            entry: dict = {"kind": metric.kind, "help": metric.help}
            with metric._lock:
                if isinstance(metric, Histogram):
                    entry["buckets"] = list(metric.buckets)
                    entry["series"] = [
                        {"labels": dict(k), "counts": list(s.counts),
                         "sum": s.sum, "count": s.count}
                        for k, s in metric._series.items()
                    ]
                else:
                    entry["series"] = [{"labels": dict(k), "value": v}
                                       for k, v in metric._series.items()]
            out["metrics"][metric.name] = entry
        return out

    def ingest(self, dump: dict, labels: Optional[dict] = None,
               skip_gauges: bool = False) -> None:
        """Merge a :meth:`dump` document into this registry.

        Counters and histogram series **add** (repeated ingests accumulate
        — pass per-scrape *deltas* from :func:`dump_delta` for federation
        semantics); gauges **set** (last write wins — instantaneous values
        do not sum meaningfully, so a federation rollup passes
        ``skip_gauges=True`` on its unlabelled pass).  ``labels`` adds
        extra label pairs to every ingested series (the federation's
        ``replica=`` identity); they route through the cardinality guard
        like any other label set."""
        extra = dict(labels or {})
        for name, entry in dump.get("metrics", {}).items():
            kind = entry.get("kind")
            help_ = entry.get("help", "")
            series = entry.get("series", [])
            if kind == "counter":
                m = self.counter(name, help_)
                for s in series:
                    m.inc(s.get("value", 0) or 0,
                          **{**(s.get("labels") or {}), **extra})
            elif kind == "gauge":
                if skip_gauges:
                    continue
                m = self.gauge(name, help_)
                for s in series:
                    m.set(s.get("value", 0.0) or 0.0,
                          **{**(s.get("labels") or {}), **extra})
            elif kind == "histogram":
                m = self.histogram(name, help_, buckets=entry.get("buckets"))
                dumped = entry.get("buckets")
                if dumped is not None and tuple(dumped) != tuple(m.buckets):
                    # get-or-create returned an EXISTING histogram whose
                    # lattice the buckets= argument cannot change: merging
                    # same-length-but-different-boundary lattices would
                    # silently skew every quantile — refuse instead (the
                    # federation surfaces it as a scrape error)
                    raise ValueError(
                        f"histogram {name!r}: dump buckets {dumped} do not "
                        f"match this registry's lattice {list(m.buckets)}")
                for s in series:
                    m.merge_series(s.get("counts", []),
                                   s.get("sum", 0.0) or 0.0,
                                   s.get("count", 0) or 0,
                                   **{**(s.get("labels") or {}), **extra})
            else:
                raise ValueError(
                    f"dump entry {name!r} has unknown kind {kind!r}")

    def snapshot(self) -> dict:
        """JSON-friendly dump: counters/gauges as scalars (labelled series
        keyed ``name{k="v"}``), histograms as their ms-scaled summaries."""
        with self._lock:
            metrics = dict(self._metrics)
        out = {}
        for name, metric in sorted(metrics.items()):
            if isinstance(metric, Histogram):
                with metric._lock:
                    keys = list(metric._series)
                for key in keys:
                    label = name + _format_labels(key)
                    out[label] = metric.summary(scale=1e3, **dict(key))
            else:
                with metric._lock:
                    series = dict(metric._series)
                for key, value in series.items():
                    out[name + _format_labels(key)] = value
        return out


def _series_by_labels(entry: dict) -> Dict[_LabelKey, dict]:
    return {_label_key(s.get("labels") or {}): s
            for s in entry.get("series", [])}


def dump_delta(prev: Optional[dict], cur: dict) -> dict:
    """The per-series window delta between two :meth:`MetricsRegistry.dump`
    documents of ONE source registry — what a federation ingests per
    scrape.

    Counters and histograms yield **non-negative deltas**: a series whose
    total went *down* means the source process restarted (counters reset
    to zero), and the delta **clamps to zero** — the same window-reset
    discipline ``telemetry/slo.py`` applies (``max(now - before, 0)``), so
    federated rates dip to zero across a restart instead of going
    negative.  Gauges pass through current values unchanged (last write
    wins at ingest).  ``prev=None`` (the first scrape) yields ``cur``
    whole — cumulative-since-start, the first-window convention."""
    if prev is None:
        return cur
    out: dict = {"format": cur.get("format", DUMP_FORMAT), "metrics": {}}
    prev_metrics = prev.get("metrics", {})
    for name, entry in cur.get("metrics", {}).items():
        kind = entry.get("kind")
        pentry = prev_metrics.get(name)
        if kind == "gauge" or pentry is None or pentry.get("kind") != kind:
            out["metrics"][name] = entry
            continue
        prev_series = _series_by_labels(pentry)
        new_series = []
        for s in entry.get("series", []):
            p = prev_series.get(_label_key(s.get("labels") or {}))
            if kind == "counter":
                base = (p.get("value", 0) or 0) if p else 0
                delta = max((s.get("value", 0) or 0) - base, 0)
                new_series.append({"labels": s.get("labels") or {},
                                   "value": delta})
            else:  # histogram
                cur_counts = list(s.get("counts", []))
                cur_count = s.get("count", 0) or 0
                if p is None:
                    new_series.append(dict(s))
                    continue
                prev_counts = list(p.get("counts", []))
                if len(prev_counts) != len(cur_counts):
                    new_series.append(dict(s))
                    continue
                if (cur_count < (p.get("count", 0) or 0)
                        or any(c < q for c, q in zip(cur_counts,
                                                     prev_counts))):
                    # whole-series reset: ANY decrease — total count OR a
                    # single bucket — clamps the entire window to zero.
                    # (A restart masked by growth can keep the total count
                    # rising while individual buckets shrink; per-bucket
                    # clamping there would emit a delta whose bucket sum
                    # disagrees with its count — an inconsistent
                    # histogram skewing every federated quantile.)
                    new_series.append({"labels": s.get("labels") or {},
                                       "counts": [0] * len(cur_counts),
                                       "sum": 0.0, "count": 0})
                    continue
                new_series.append({
                    "labels": s.get("labels") or {},
                    "counts": [c - q
                               for c, q in zip(cur_counts, prev_counts)],
                    "sum": max((s.get("sum", 0.0) or 0.0)
                               - (p.get("sum", 0.0) or 0.0), 0.0),
                    "count": cur_count - (p.get("count", 0) or 0),
                })
        delta_entry = {"kind": kind, "help": entry.get("help", ""),
                       "series": new_series}
        if kind == "histogram" and "buckets" in entry:
            delta_entry["buckets"] = entry["buckets"]
        out["metrics"][name] = delta_entry
    return out


def combined_exposition(*registries: MetricsRegistry) -> str:
    """One Prometheus text document over several registries (the fleet
    router's ``/metrics``: its own series + the federated fleet view).

    A metric name appearing in several registries renders as ONE block
    (two blocks under one name would be a malformed exposition): the
    earlier registry contributes its header and samples, later registries
    **append the series the block doesn't already carry** — so a name both
    processes emit (a router that traces has its own
    ``svgd_trace_dropped_total`` while the federation holds the replicas'
    ``{replica=...}`` series of the same name) keeps every distinct
    series visible instead of dropping the federated view wholesale.  On
    an identical series identity the earlier registry wins (the router's
    unlabelled series means *this process*; a same-name unlabelled rollup
    from elsewhere is ambiguous and defers).  A later registry whose
    metric has a different *kind* under the name is skipped entirely."""
    blocks: Dict[str, dict] = {}
    order: list = []
    for reg in registries:
        with reg._lock:
            metrics = [reg._metrics[k] for k in sorted(reg._metrics)]
        for metric in metrics:
            rendered = metric._render()
            headers = [ln for ln in rendered if ln.startswith("# ")]
            samples = [ln for ln in rendered if not ln.startswith("# ")]
            block = blocks.get(metric.name)
            if block is None:
                blocks[metric.name] = {
                    "kind": metric.kind, "headers": headers,
                    "samples": list(samples),
                    "series": {ln.rsplit(" ", 1)[0] for ln in samples},
                }
                order.append(metric.name)
                continue
            if block["kind"] != metric.kind:
                continue
            for ln in samples:
                sid = ln.rsplit(" ", 1)[0]
                if sid not in block["series"]:
                    block["series"].add(sid)
                    block["samples"].append(ln)
    lines: list = []
    for name in order:
        block = blocks[name]
        lines.extend(block["headers"])
        lines.extend(block["samples"])
    return "\n".join(lines) + ("\n" if lines else "")


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry instrumented components default to."""
    return _DEFAULT
