"""SPMD parallelism: mesh utilities, exchange strategies, multi-host setup."""

from dist_svgd_tpu.parallel.mesh import AXIS, make_mesh, bind_shard_fn
from dist_svgd_tpu.parallel.plan import Plan, make_plan
from dist_svgd_tpu.parallel.exchange import (
    ALL_PARTICLES,
    ALL_SCORES,
    PARTITIONS,
    make_shard_step,
)
from dist_svgd_tpu.parallel import multihost

__all__ = [
    "AXIS",
    "make_mesh",
    "bind_shard_fn",
    "Plan",
    "make_plan",
    "ALL_PARTICLES",
    "ALL_SCORES",
    "PARTITIONS",
    "make_shard_step",
    "multihost",
]
