"""SPMD parallelism: mesh utilities and particle/score exchange strategies."""

from dist_svgd_tpu.parallel.mesh import AXIS, make_mesh, bind_shard_fn
from dist_svgd_tpu.parallel.exchange import (
    ALL_PARTICLES,
    ALL_SCORES,
    PARTITIONS,
    make_shard_step,
)

__all__ = [
    "AXIS",
    "make_mesh",
    "bind_shard_fn",
    "ALL_PARTICLES",
    "ALL_SCORES",
    "PARTITIONS",
    "make_shard_step",
]
