"""The three particle/score exchange strategies, as one fused per-shard step.

Reference semantics (dsvgd/distsampler.py:131-170,172-205 — SURVEY.md §2.3):

- ``all_particles`` — every shard gathers the full particle set
  (``dist.all_gather`` → ``lax.all_gather``) and computes scores for *all* n
  particles using only its **local data slice**, importance-scaled by
  ``N_global / N_local`` (dsvgd/distsampler.py:96-99).
- ``all_scores``    — after the particle gather, per-shard local-data scores
  for all n particles are summed across shards (``dist.all_reduce(SUM)`` →
  ``lax.psum``), yielding the **exact global score**; no extra scaling
  (the reference's open TODO at dsvgd/distsampler.py:93 — the SUM already
  globalises the estimate).
- ``partitions``    — ring migration: each rank hands its particle block to
  rank+1 and adopts the block from rank−1, then interacts **only within the
  owned block** (dsvgd/distsampler.py:131-150, interaction set :85-87).

The ``partitions`` mode is re-derived for SPMD: instead of migrating particle
blocks between devices (mutable ownership ranges don't exist under pjit),
each device keeps its particle block pinned and the **data-shard assignment
rotates** — block ``b`` at step ``t`` is updated against data slice
``(b + t) mod S``, which is exactly the pairing the reference's ring produces
(owner of block ``b`` at step ``t`` is rank ``(b + t) mod S``, whose data is
slice ``(b + t) mod S``).  The global particle array therefore stays in
logical order at all times.  Like the reference — where every rank loads the
full dataset and slices its block (experiments/logreg.py:28,41-51) — the
dataset is by default replicated across devices and sliced per-shard with
``lax.dynamic_slice``; ``shard_data=True`` instead shards the data rows over
the mesh (for datasets that don't fit per-device HBM; ``all_*`` modes only,
since ``partitions`` needs a different slice each step).

Each strategy is one jit-compiled function; XLA overlaps the collective with
the score/kernel compute.

**Ring execution** (``ring=True``): the long-context analog (SURVEY.md §5).
For large n the all-gather materialises the full ``(n, d)`` set and an
``(n, n/S)`` Gram block per device.  The ring implementation instead rotates
particle blocks hop-by-hop around the mesh with ``lax.ppermute`` — the exact
motif of ring attention's KV rotation — and accumulates each visiting block's
φ contribution into a running ``(n/S, d)`` array, so per-device memory is
O(n/S · d + (n/S)²) regardless of S:

- ``all_particles`` + ring: one pass; each hop scores the *visiting* block on
  the device's local data (importance-scaled), reproducing the gather mode's
  semantics (every rank scores all particles on its own slice,
  dsvgd/distsampler.py:96-99) exactly — same math, different reduction order.
- ``all_scores`` + ring: two passes.  Pass 1 rotates each block through every
  device, accumulating local-data score contributions so each block arrives
  home carrying the exact global score (the ``psum`` result, reference
  dsvgd/distsampler.py:160-170).  Pass 2 rotates (block, score) pairs and
  accumulates φ.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from dist_svgd_tpu.ops.approx import bind_phi_step as _bind_phi_step
from dist_svgd_tpu.ops.kernels import (
    RBF,
    AdaptiveRBF,
    median_bandwidth_approx_masked,
)
from dist_svgd_tpu.ops.pallas_svgd import resolve_phi_fn
from dist_svgd_tpu.parallel.mesh import AXIS
from dist_svgd_tpu.utils.rng import draw_minibatch

ALL_PARTICLES = "all_particles"
ALL_SCORES = "all_scores"
PARTITIONS = "partitions"

MODES = (ALL_PARTICLES, ALL_SCORES, PARTITIONS)


def _slice_data(data, start: jax.Array, size: int):
    """Per-shard data slice: every leaf is sliced ``[start : start+size]``
    along axis 0 (the reference's contiguous block convention,
    experiments/logreg.py:41-51)."""
    if data is None:
        return None
    return jax.tree_util.tree_map(
        lambda a: lax.dynamic_slice_in_dim(a, start, size, axis=0), data
    )


def _ring_perm(num_shards: int):
    """Send-to-next-rank permutation — the reference's ring direction
    (rank → rank+1, dsvgd/distsampler.py:134-143)."""
    return [(j, (j + 1) % num_shards) for j in range(num_shards)]


def ring_hops_per_step(mode: str, num_shards: int) -> dict:
    """``{'hops': H, 'arrays_per_hop': A}``: how many ``lax.ppermute``
    rotations one ring-mode step issues, and how many arrays each rotates —
    the static comm profile drivers multiply by the mesh's DCN-boundary
    crossing count (``parallel/multihost.py:dcn_boundary_crossings``) to
    report slow-network traffic per step.

    Counts mirror the hop primitives exactly, terminal-chunk elision
    included: the ``all_particles`` single pass runs S hops with a
    rotation-free tail (S−1 rotations of 1 array,
    :func:`_ring_phi_local_scores`); ``all_scores`` adds a score pass of S
    full rotations of 2 arrays before its S−1-rotation φ pass
    (:func:`_ring_phi_exact_scores`); ``partitions`` never rotates.
    """
    S = int(num_shards)
    if mode == PARTITIONS or S < 2:
        return {"hops": 0, "arrays_per_hop": 0}
    if mode == ALL_PARTICLES:
        return {"hops": S - 1, "arrays_per_hop": 1}
    if mode == ALL_SCORES:
        return {"hops": (S - 1) + S, "arrays_per_hop": 2}
    raise ValueError(f"unknown exchange mode {mode!r}")


def _shard_data_resolver(mode, num_shards, n_local_data, shard_data):
    """Shared per-shard data resolution: ``resolve(data, t, r) -> data_local``.

    Encodes the one place the ``partitions`` data-rank rotation lives
    (block ``b`` at step ``t`` pairs with data slice ``(b + t) mod S`` — the
    re-derivation of the reference's ring migration, module docstring), so
    the Jacobi core and the Gauss–Seidel sweep cannot diverge on it.
    """
    def resolve(data, t, r):
        if shard_data:
            return data
        if mode == PARTITIONS:
            data_rank = (r + t.astype(r.dtype)) % num_shards
        else:
            data_rank = r
        return _slice_data(data, data_rank * n_local_data, n_local_data)

    return resolve


def _ring_local_hops(y_block, carry, score_of, phi_fn, num_shards,
                     num_hops: int, rotate_last: bool):
    """Advance ``num_hops`` (accumulate, rotate) hops of the single-pass
    (``all_particles``) ring φ from an explicit carry ``(visiting, acc)``.

    The carry is the *resumable* state of the hop loop — the visiting block
    and the partial φ accumulator — so a full S-hop pass can be executed as
    one call (the monolithic :func:`_ring_phi_local_scores`) or split
    ``hops_per_dispatch`` at a time across host-driven dispatches (the
    chunked step executor, :func:`make_chunked_ring_step_fns`), with bitwise-
    identical accumulation order either way.  ``rotate_last=False`` elides
    the final hop's ppermute — a wasted inter-device transfer XLA cannot
    elide — and is only valid on the pass's terminal chunk."""
    perm = _ring_perm(num_shards)

    def body(i, c):
        visiting, acc = c
        acc = acc + phi_fn(y_block, visiting, score_of(visiting))
        return lax.ppermute(visiting, AXIS, perm), acc

    loop_hops = num_hops if rotate_last else num_hops - 1
    visiting, acc = lax.fori_loop(0, loop_hops, body, carry)
    if not rotate_last:
        acc = acc + phi_fn(y_block, visiting, score_of(visiting))
    return visiting, acc


def _ring_phi_local_scores(y_block, score_of, phi_fn, num_shards):
    """Single-pass ring φ with ``all_particles`` semantics: the visiting block
    is scored by *this* device's ``score_of`` (local data, importance-scaled,
    prior included).  Equal block sizes let each hop contribute
    ``phi(y, visiting, s)`` (already normalised by the block size) so the mean
    over hops is the global-mean φ.  One monolithic S-hop pass of
    :func:`_ring_local_hops` (S−1 rotations + a rotation-free tail)."""
    _, acc = _ring_local_hops(
        y_block, (y_block, jnp.zeros_like(y_block)), score_of, phi_fn,
        num_shards, num_shards, rotate_last=False,
    )
    return acc / num_shards


def _ring_exact_score_hops(carry, lik_score_of, num_shards, num_hops: int):
    """Advance ``num_hops`` hops of the ``all_scores`` ring's score pass from
    the carry ``(visiting, vscores)`` — each hop adds this device's
    local-data likelihood score of the visiting block to its travelling
    accumulator, then rotates both.  All S hops rotate (the pass must end
    with every block home), so chunks compose without a tail variant."""
    perm = _ring_perm(num_shards)

    def body(i, c):
        visiting, vscores = c
        vscores = vscores + lik_score_of(visiting)
        return (
            lax.ppermute(visiting, AXIS, perm),
            lax.ppermute(vscores, AXIS, perm),
        )

    return lax.fori_loop(0, num_hops, body, carry)


def _ring_exact_phi_hops(y_block, carry, phi_fn, num_shards, num_hops: int,
                         rotate_last: bool):
    """Advance ``num_hops`` hops of the ``all_scores`` ring's φ pass from the
    carry ``(visiting, vscores, acc)`` — the (block, score)-pair rotation
    with the partial φ accumulator.  ``rotate_last=False`` (terminal chunk
    only) elides the final two transfers, as in :func:`_ring_local_hops`."""
    perm = _ring_perm(num_shards)

    def body(i, c):
        visiting, vscores, acc = c
        acc = acc + phi_fn(y_block, visiting, vscores)
        return (
            lax.ppermute(visiting, AXIS, perm),
            lax.ppermute(vscores, AXIS, perm),
            acc,
        )

    loop_hops = num_hops if rotate_last else num_hops - 1
    visiting, vscores, acc = lax.fori_loop(0, loop_hops, body, carry)
    if not rotate_last:
        acc = acc + phi_fn(y_block, visiting, vscores)
    return visiting, vscores, acc


def _ring_phi_exact_scores(y_block, lik_score_of, prior_score_of, phi_fn, num_shards):
    """Two-pass ring φ with ``all_scores`` semantics.  Pass 1 carries each
    block once around the ring, summing per-device local-data likelihood
    scores into an accumulator that travels with it — after S hops the block
    is home with the exact global score (the ``lax.psum`` result, modulo
    summation order); the prior gradient (identity when the prior lives
    inside ``logp``) is then added once.  Pass 2 rotates (block, score) pairs
    and accumulates φ.  Both passes are monolithic full-S calls of the
    resumable hop primitives (:func:`_ring_exact_score_hops` /
    :func:`_ring_exact_phi_hops`)."""
    visiting, vscores = _ring_exact_score_hops(
        (y_block, jnp.zeros_like(y_block)), lik_score_of, num_shards,
        num_shards,
    )
    vscores = vscores + prior_score_of(visiting)
    _, _, acc = _ring_exact_phi_hops(
        y_block, (visiting, vscores, jnp.zeros_like(y_block)), phi_fn,
        num_shards, num_shards, rotate_last=False,
    )
    return acc / num_shards


def _ring_median_bandwidth(block, num_shards: int, max_points: int):
    """The gather path's per-step median bandwidth, computed under the ring
    exchange without materialising the global set.

    ``median_bandwidth_approx`` on the gathered global array subsamples
    ``global[::stride]`` with ``stride = ceil(n / max_points)`` — and the
    rows of shard ``r``'s block whose *global* index ``r·s + j`` is a
    multiple of ``stride`` are exactly that set's slice through the shard.
    Each shard gathers its (ragged, padded-to-``cap``) slice with a validity
    mask and every shard computes the same masked median
    (:func:`~dist_svgd_tpu.ops.kernels.median_bandwidth_approx_masked`) —
    identical point set, thresholds, and rank as the gather path, so
    ring ≡ gather holds for ``median_step`` exactly, at O(max_points·d)
    per-device memory instead of O(n·d).
    """
    s = block.shape[0]
    n = s * num_shards
    stride = -(-n // max_points) if n > max_points else 1
    p = -(-n // stride)          # global subsample size (static)
    cap = -(-s // stride)        # max rows any one shard contributes
    r = lax.axis_index(AXIS)
    # first local row whose global index is a stride multiple: (−r·s) mod
    off = (-r * s) % stride
    idx = off + stride * jnp.arange(cap, dtype=jnp.int32)
    valid = idx < s
    rows = jnp.take(block, jnp.minimum(idx, s - 1), axis=0)
    rows = jnp.where(valid[:, None], rows, jnp.zeros((), block.dtype))
    sub = lax.all_gather(rows, AXIS, tiled=True)      # (S·cap, d)
    vmask = lax.all_gather(valid, AXIS, tiled=True)   # (S·cap,)
    return median_bandwidth_approx_masked(sub, vmask, p, n)


def _builder_prelude(logp, kernel, phi_impl, log_prior, batch_size,
                     n_local_data, phi_batch_hint=1, kernel_approx=None):
    """Shared construction of every step builder's numeric machinery —
    one definition so the per-step, Gauss-Seidel, lagged, and W2 builders
    cannot drift on score/prior/φ semantics.  ``phi_batch_hint`` feeds the
    φ 'auto' thresholds (how many lanes run as one batched kernel —
    ops/pallas_svgd.py:resolve_phi_fn); ``kernel_approx`` selects the
    sub-quadratic feature/landmark φ (``ops/approx.py``) — a drop-in
    ``phi_fn`` with the same signature, so every exchange/chunk path
    downstream is approximation-agnostic."""
    if batch_size is not None and not 0 < batch_size <= n_local_data:
        raise ValueError(
            f"batch_size {batch_size} not in (0, {n_local_data}] local rows"
        )
    phi_fn = resolve_phi_fn(kernel, phi_impl, phi_batch_hint, kernel_approx)
    batched_score = jax.vmap(jax.grad(logp, argnums=0), in_axes=(0, None))
    if log_prior is not None:
        batched_prior = jax.vmap(jax.grad(log_prior))
    else:
        batched_prior = lambda thetas: jnp.zeros_like(thetas)
    return phi_fn, batched_score, batched_prior


def make_shard_step(
    logp: Callable,
    kernel,
    mode: str,
    num_shards: int,
    n_local_data: int,
    score_scale: float,
    ring: bool = False,
    shard_data: bool = False,
    batch_size: Optional[int] = None,
    log_prior: Optional[Callable] = None,
    phi_impl: str = "xla",
    update_rule: str = "jacobi",
    phi_batch_hint: int = 1,
    kernel_approx=None,
) -> Callable:
    """Build the per-shard SVGD step for one exchange strategy.

    Args:
        logp: ``logp(theta, data_local)`` scalar log-density; ``data_local``
            is the shard's data slice (or ``None`` for data-free targets).
        kernel: kernel object/callable for :func:`dist_svgd_tpu.ops.svgd.phi`.
        mode: one of :data:`MODES`.
        num_shards: mesh size S.
        n_local_data: rows per data shard (``N_global // S``, remainder
            dropped — reference drop policy, experiments/logreg.py:35).
        score_scale: ``N_global / N_local`` importance factor applied when
            scores are *not* exchanged (dsvgd/distsampler.py:96-99); pass 1.0
            for data-free targets.
        ring: use the ``ppermute`` ring-rotation implementation of the
            ``all_*`` exchange (module docstring) instead of
            ``all_gather``/``psum`` — same semantics, O(n/S) per-device
            memory.  Ignored for ``partitions`` (already block-local).
        shard_data: the step's ``data`` argument is this shard's slice (data
            sharded over the mesh) rather than the replicated full set.
            Unsupported in ``partitions`` mode, whose rotating data-rank
            assignment needs access to every slice.
        batch_size: per-step per-shard minibatch size B: each shard draws B
            of its ``n_local_data`` rows without replacement (its own fold of
            the step key) and scales the data-dependent score by
            ``n_local_data / B`` — an unbiased estimate of its full-slice
            score, so every exchange mode's downstream combination
            (psum / importance scale) is unchanged (writeup.tex:214-231).
        log_prior: optional ``log_prior(theta)``.  When given, ``logp`` is
            treated as pure likelihood; the prior gradient is added once,
            after the minibatch scale / psum / importance scale (so it is
            neither minibatch-amplified nor summed S times — unlike the
            reference, whose in-logp prior is importance-scaled,
            dsvgd/distsampler.py:96-99, and psum-multiplied in all_scores).
        phi_impl: φ backend — ``'auto'`` / ``'xla'`` / ``'pallas'`` /
            ``'pallas_bf16'``; see
            :func:`dist_svgd_tpu.ops.pallas_svgd.resolve_phi_fn`.
        update_rule: ``'jacobi'`` (vectorised, TPU-native default — all
            kernels/scores at pre-update values) or ``'gauss_seidel'`` (the
            reference's literal in-place sweep, dsvgd/distsampler.py:194-200:
            each shard sweeps its own block *inside its local view*, particle
            ``i+1`` seeing particle ``i``'s new value, with per-pair scores
            re-evaluated fresh at current positions — except in ``all_scores``
            mode, whose exchanged scores are frozen at their pre-update
            all-reduced values for the whole step, reference :160-170).
            ``lax.scan``-sequential, O(n_loc) score re-batches per step — for
            small-n parity verification, not throughput.

    Returns:
        ``step(block, data, w_grad_block, t, key, step_size, h) -> new_block``
        written against block-local shapes and the named axis
        :data:`~dist_svgd_tpu.parallel.mesh.AXIS`; bind it with
        :func:`~dist_svgd_tpu.parallel.mesh.bind_shard_fn`.

        ``w_grad_block`` is the per-shard Wasserstein/JKO gradient (zeros when
        disabled), added as ``δ += h·w_grad`` before ``θ += ε·δ`` exactly as
        the reference does (dsvgd/distsampler.py:194-200).  ``t`` is the
        1-based step counter driving the ``partitions`` rotation.
    """
    if update_rule == "gauss_seidel":
        if kernel_approx is not None:
            raise ValueError(
                "kernel_approx requires update_rule='jacobi': the "
                "Gauss-Seidel sweep exists for literal reference parity, "
                "which an approximate kernel cannot provide"
            )
        # the GS sweep's phi calls are single-row (1, m) probes inside a
        # lax.scan, not equal batched lane blocks -- the batching-amortised
        # thresholds the hint encodes do not apply (and would route the
        # degenerate shape to a 94%-padded pallas tile); keep the per-call
        # gate
        return _build_gs_step(
            logp, kernel, mode, num_shards, n_local_data, score_scale,
            ring, shard_data, batch_size, log_prior, phi_impl,
        )
    if update_rule != "jacobi":
        raise ValueError(f"unknown update_rule {update_rule!r}")
    core = _build_core(
        logp, kernel, mode, num_shards, n_local_data, score_scale,
        ring, shard_data, batch_size, log_prior, phi_impl, phi_batch_hint,
        kernel_approx,
    )

    def step(block, data, w_grad_block, t, key, step_size, h):
        delta, _ = core(block, data, t, key)
        delta = delta + h * w_grad_block
        return block + step_size * delta

    return step


def _build_gs_step(
    logp, kernel, mode, num_shards, n_local_data, score_scale,
    ring, shard_data, batch_size, log_prior, phi_impl, phi_batch_hint=1,
):
    """The literal Gauss–Seidel per-shard step (see ``make_shard_step``).

    Matches the oracle's distributed-GS semantics (tests/_oracle.py,
    reference dsvgd/distsampler.py:194-200): each shard holds a private view
    (the gathered global set in exchanged modes, its own block in
    ``partitions``), sweeps its owned rows in place, and commits only its own
    block — other shards' rows in the view stay at pre-exchange values.
    """
    if mode not in MODES:
        raise ValueError(f"unknown exchange mode {mode!r}")
    if ring:
        raise ValueError(
            "update_rule='gauss_seidel' requires exchange_impl='gather' "
            "(the sweep mutates a materialised local view)"
        )
    if batch_size is not None:
        raise ValueError("minibatching supports only the jacobi update rule")
    if shard_data and mode == PARTITIONS:
        raise ValueError("shard_data is unsupported in partitions mode")

    phi_fn, batched_score, batched_prior = _builder_prelude(
        logp, kernel, phi_impl, log_prior, batch_size, n_local_data,
        phi_batch_hint,
    )

    resolve_data = _shard_data_resolver(mode, num_shards, n_local_data, shard_data)

    def step(block, data, w_grad_block, t, key, step_size, h):
        r = lax.axis_index(AXIS)
        s = block.shape[0]
        data_local = resolve_data(data, t, r)

        if mode == PARTITIONS:
            view = block
            lo = jnp.zeros((), dtype=jnp.int32)
        else:
            view = lax.all_gather(block, AXIS, tiled=True)
            lo = r.astype(jnp.int32) * s

        if mode == ALL_SCORES:
            # exchanged scores are frozen at pre-update values for the whole
            # step (the all_reduce happens once, reference :160-170)
            frozen = lax.psum(batched_score(view, data_local), AXIS)
            frozen = frozen + batched_prior(view)

        def body(v, i):
            if mode == ALL_SCORES:
                scores = frozen
            else:
                # fresh per-pair scores at *current* positions (the
                # reference's _dlogp(xj)-per-pair, dsvgd/distsampler.py:96-99)
                scores = score_scale * batched_score(v, data_local)
                scores = scores + batched_prior(v)
            y = lax.dynamic_slice_in_dim(v, lo + i, 1, axis=0)
            delta = phi_fn(y, v, scores)[0] + h * w_grad_block[i]
            v = lax.dynamic_update_slice_in_dim(
                v, (y[0] + step_size * delta)[None], lo + i, axis=0
            )
            return v, None

        view, _ = lax.scan(body, view, jnp.arange(s, dtype=jnp.int32))
        return lax.dynamic_slice_in_dim(view, lo, s, axis=0)

    return step


def _build_core(
    logp, kernel, mode, num_shards, n_local_data, score_scale,
    ring, shard_data, batch_size, log_prior, phi_impl, phi_batch_hint=1,
    kernel_approx=None,
):
    """Shared exchange+φ computation: ``core(block, data, t, key) ->
    (delta, interacting)`` where ``interacting`` is the pre-update all-gather
    of the particle set in the gather-impl ``all_*`` modes (the array the
    reference's Wasserstein snapshot is built from, dsvgd/distsampler.py:202-
    203) and ``None`` otherwise."""
    if mode not in MODES:
        raise ValueError(f"unknown exchange mode {mode!r}")
    if shard_data and mode == PARTITIONS:
        raise ValueError("shard_data is unsupported in partitions mode")
    # ring + median_step: the per-call adaptive φ would take a per-hop
    # median (each hop sees only the visiting block) — instead resolve the
    # bandwidth ONCE per step from the gathered strided subsample
    # (_ring_median_bandwidth: the gather path's exact subsample, so
    # ring ≡ gather holds) and wrap the bandwidth-1 backend in the same
    # rescaling identity resolve_phi_fn applies.
    ring_adaptive = ring and isinstance(kernel, AdaptiveRBF) and mode != PARTITIONS
    phi_fn, batched_score, batched_prior = _builder_prelude(
        logp, RBF(1.0) if ring_adaptive else kernel, phi_impl, log_prior,
        batch_size, n_local_data, phi_batch_hint, kernel_approx,
    )

    resolve_data = _shard_data_resolver(mode, num_shards, n_local_data, shard_data)

    def core(block, data, t, key):
        r = lax.axis_index(AXIS)
        data_local = resolve_data(data, t, r)
        # redraw-per-step RFF (ops/approx.py): fold the bank from the
        # absolute step index, here — the one spot every execution shape
        # (eager, scanned, scan-chunked) knows t, so the bank stream is
        # chunk- and reshard-invariant like the minibatch stream
        phi_step = _bind_phi_step(phi_fn, t)

        # One minibatch per shard per step, shared across every use of this
        # shard's data within the step (keeps ring ≡ gather exactly).
        mb_scale = jnp.asarray(1.0, dtype=block.dtype)
        if batch_size is not None:
            data_local, scale = draw_minibatch(
                jax.random.fold_in(key, r), data_local, n_local_data, batch_size
            )
            mb_scale = jnp.asarray(scale, dtype=block.dtype)

        def lik_score_of(thetas):
            return mb_scale * batched_score(thetas, data_local)

        interacting = None
        if mode == PARTITIONS:
            scores = score_scale * lik_score_of(block) + batched_prior(block)
            delta = phi_step(block, block, scores)
        elif ring:
            hop_phi = phi_step
            if ring_adaptive:
                h = _ring_median_bandwidth(
                    block, num_shards, kernel.max_points
                )
                sh = jnp.sqrt(h.astype(block.dtype))
                # φ_h(y; x, s) = φ₁(y/√h; x/√h, √h·s)/√h, per hop — linear
                # in the hop accumulation, so the summed ring φ carries the
                # same identity (resolve_phi_fn's AdaptiveRBF wrapper)
                hop_phi = lambda y, x, s_: phi_step(y / sh, x / sh, s_ * sh) / sh
            if mode == ALL_SCORES:
                delta = _ring_phi_exact_scores(
                    block, lik_score_of, batched_prior, hop_phi, num_shards
                )
            else:
                score_of = lambda th: score_scale * lik_score_of(th) + batched_prior(th)
                delta = _ring_phi_local_scores(block, score_of, hop_phi, num_shards)
        else:
            interacting = lax.all_gather(block, AXIS, tiled=True)
            local_scores = lik_score_of(interacting)
            if mode == ALL_SCORES:
                scores = lax.psum(local_scores, AXIS)
            else:
                scores = score_scale * local_scores
            scores = scores + batched_prior(interacting)
            delta = phi_step(block, interacting, scores)

        return delta, interacting

    return core


def make_chunked_ring_step_fns(
    logp: Callable,
    kernel,
    mode: str,
    num_shards: int,
    n_local_data: int,
    score_scale: float,
    shard_data: bool = False,
    batch_size: Optional[int] = None,
    log_prior: Optional[Callable] = None,
    phi_impl: str = "xla",
    phi_batch_hint: int = 1,
    kernel_approx=None,
) -> dict:
    """Per-shard pieces of the ring-φ SVGD step for **host-driven bounded-
    dispatch execution** — the chunked step executor behind
    ``DistSampler.run_steps(dispatch_budget=...)``.

    The monolithic ring step runs all S ppermute hops inside one jitted
    dispatch; at large n that single dispatch exceeds the TPU tunnel's
    execution watchdog (the measured 2M-particle ceiling, docs/notes.md
    large-n table).  This builder instead exposes the step's natural seams
    as separately bindable per-shard functions whose carries are exactly the
    resumable hop-loop state (:func:`_ring_local_hops` /
    :func:`_ring_exact_score_hops` / :func:`_ring_exact_phi_hops`), so a
    host loop can chain ``hops_per_dispatch``-hop dispatches with the
    partial accumulator, visiting block, and travelling scores threaded
    through a serializable carry — the same accumulation order as the
    monolithic pass, hence trajectories allclose (tests/test_chunked.py),
    at the measured ~0.2 ms marginal cost per chained dispatch
    (docs/notes.md dispatch-relay table).

    Returns a dict of builders:

    - ``'local_hops'``: ``factory(num_hops, rotate_last) -> fn(block,
      visiting, acc, data, t, key) -> (visiting, acc)`` — ``all_particles``
      hop chunks.  Scores are recomputed per hop from the dispatch's own
      ``(data, t, key)`` arguments; the per-shard minibatch draw folds the
      same ``(key, r)`` in every chunk, so all chunks of a step see the
      step's ONE minibatch, exactly like the monolithic pass.
    - ``'score_hops'`` (``all_scores``): ``factory(num_hops) ->
      fn(visiting, vscores, data, t, key) -> (visiting, vscores)`` —
      score-pass chunks (every hop rotates; chunks compose freely).
    - ``'exact_phi_hops'`` (``all_scores``): ``factory(num_hops,
      rotate_last) -> fn(block, visiting, vscores, acc) -> (visiting,
      vscores, acc)`` — φ-pass chunks over the (block, score) pairs.
    - ``'add_prior'``: ``fn(visiting, vscores) -> vscores`` — the once-per-
      step prior add between the two ``all_scores`` passes.  Row-wise
      elementwise, so the executor applies it to the merged global arrays
      directly (no collective inside).
    - ``'finish'``: ``fn(block, acc, w_grad_block, step_size, h) ->
      new_block`` — hop-mean normalisation plus the update (row-wise
      elementwise, like ``add_prior``).

    ``rotate_last=False`` is the terminal-chunk variant (elides the final
    hop's wasted ppermute, matching the monolithic tail).  Jacobi only (the
    ring has no Gauss–Seidel variant); fixed-bandwidth kernels only —
    ``median_step``'s per-step bandwidth would need its own gathered-
    subsample dispatch; resolve ``'median'`` once at construction instead.
    """
    if mode not in (ALL_PARTICLES, ALL_SCORES):
        raise ValueError(
            f"chunked ring stepping is defined for the all_* modes, got {mode!r}"
        )
    if isinstance(kernel, AdaptiveRBF):
        raise ValueError(
            "chunked ring stepping requires a fixed-bandwidth kernel: "
            "kernel='median_step' resolves per step from a gathered "
            "subsample the bounded-dispatch chain does not carry — use "
            "kernel='median' (resolved once at construction) instead"
        )
    phi_fn, batched_score, batched_prior = _builder_prelude(
        logp, kernel, phi_impl, log_prior, batch_size, n_local_data,
        phi_batch_hint, kernel_approx,
    )
    if getattr(phi_fn, "needs_step", False) and mode == ALL_SCORES:
        raise ValueError(
            "chunked all_scores ring stepping does not thread the step "
            "index through its φ-pass chunks (exact_phi_hops carries only "
            "the rotating (block, score, acc) state), which "
            "rff_redraw='step' needs for its per-step bank fold — use "
            "rff_redraw='run', kernel_approx='nystrom', or the "
            "all_particles mode"
        )
    resolve_data = _shard_data_resolver(mode, num_shards, n_local_data, shard_data)

    def lik_score_env(dtype, data, t, key):
        """The step's per-shard likelihood-score closure, reconstructed
        identically in every chunk dispatch from the step's ``(data, t,
        key)`` — one minibatch per shard per step (the same ``(key, r)``
        fold the monolithic core draws)."""
        r = lax.axis_index(AXIS)
        data_local = resolve_data(data, t, r)
        mb_scale = jnp.asarray(1.0, dtype=dtype)
        if batch_size is not None:
            data_local, scale = draw_minibatch(
                jax.random.fold_in(key, r), data_local, n_local_data, batch_size
            )
            mb_scale = jnp.asarray(scale, dtype=dtype)
        return lambda thetas: mb_scale * batched_score(thetas, data_local)

    def local_hops(num_hops: int, rotate_last: bool):
        def fn(block, visiting, acc, data, t, key):
            lik = lik_score_env(block.dtype, data, t, key)
            score_of = lambda th: score_scale * lik(th) + batched_prior(th)
            return _ring_local_hops(
                block, (visiting, acc), score_of,
                _bind_phi_step(phi_fn, t), num_shards,
                num_hops, rotate_last,
            )

        return fn

    def score_hops(num_hops: int):
        def fn(visiting, vscores, data, t, key):
            lik = lik_score_env(visiting.dtype, data, t, key)
            return _ring_exact_score_hops(
                (visiting, vscores), lik, num_shards, num_hops
            )

        return fn

    def exact_phi_hops(num_hops: int, rotate_last: bool):
        def fn(block, visiting, vscores, acc):
            return _ring_exact_phi_hops(
                block, (visiting, vscores, acc), phi_fn, num_shards,
                num_hops, rotate_last,
            )

        return fn

    def add_prior(visiting, vscores):
        return vscores + batched_prior(visiting)

    def finish(block, acc, w_grad_block, step_size, h):
        delta = acc / num_shards + h * w_grad_block
        return block + step_size * delta

    return {
        "local_hops": local_hops,
        "score_hops": score_hops,
        "exact_phi_hops": exact_phi_hops,
        "add_prior": add_prior,
        "finish": finish,
    }


def make_shard_step_lagged(
    logp: Callable,
    kernel,
    num_shards: int,
    n_local_data: int,
    score_scale: float,
    exchange_every: int,
    shard_data: bool = False,
    batch_size: Optional[int] = None,
    log_prior: Optional[Callable] = None,
    phi_impl: str = "xla",
    phi_batch_hint: int = 1,
    record: bool = False,
    kernel_approx=None,
) -> Callable:
    """Lagged (stale) ``all_particles`` exchange: one ``lax.all_gather``
    per ``exchange_every`` SVGD steps instead of per step.

    The reference *timed* this variant ("8-laggedlocal", its ``notes.md:134``
    — 226 s vs 59 s for the per-step exchange at its headline config) but
    never shipped an implementation (SURVEY.md §2.3).  Semantics here (the
    "lagged-remote, live-local" reading the name implies): at each refresh
    the shard snapshots the gathered global set; for the following
    ``exchange_every`` steps its interaction set is that stale snapshot
    with the shard's **own block patched live** (``dynamic_update_slice``),
    scores re-evaluated on local data each step at the current view.  The
    collective count — the quantity lagging exists to cut — drops
    ``exchange_every``-fold; between refreshes shards drift like the
    reference's per-rank processes would between its hypothetical lagged
    syncs.  Same fixed point as ``all_particles`` (stale and fresh sets
    coincide once particles stop moving).

    One call = ``exchange_every`` SVGD steps (a static inner ``lax.scan`` —
    no data-dependent control flow, works identically under shard_map and
    vmap emulation).  ``t`` is the first sub-step's 1-based counter; the
    per-sub-step minibatch keys fold ``(key, i)`` so every sub-step draws a
    fresh batch.  ``all_scores`` is excluded: its exchanged quantity *is*
    the per-step psum, so a lagged variant would freeze scores at stale
    positions — a different (and degenerate) algorithm.

    Returns ``macro(block, data, w_grad_block, t, key, step_size, h) ->
    new_block`` — the standard per-shard step signature (``w_grad_block``
    must be zeros: the W2 term's previous-snapshot bookkeeping is defined
    per step, not per refresh).

    ``record=True`` instead returns ``(new_block, hist)`` with ``hist`` the
    ``(exchange_every, s, d)`` stack of this shard's **pre-update** block
    per sub-step (the reference history convention, SURVEY.md §7.4) — the
    inner scan's per-iteration carry, emitted for free.  Stacked across
    shards this is the exact global pre-update state at every sub-step:
    each shard's live block IS the authoritative value of its rows (the
    stale gathered copies other shards hold are interaction inputs, not
    state).
    """
    if exchange_every < 1:
        raise ValueError(f"exchange_every must be >= 1, got {exchange_every}")
    phi_fn, batched_score, batched_prior = _builder_prelude(
        logp, kernel, phi_impl, log_prior, batch_size, n_local_data,
        phi_batch_hint, kernel_approx,
    )
    resolve_data = _shard_data_resolver(
        ALL_PARTICLES, num_shards, n_local_data, shard_data
    )

    def macro(block, data, w_grad_block, t, key, step_size, h):
        del w_grad_block, h  # W2 is per-step bookkeeping; rejected upstream
        r = lax.axis_index(AXIS)
        s = block.shape[0]
        stale = lax.all_gather(block, AXIS, tiled=True)  # the ONE collective
        lo = r.astype(jnp.int32) * s
        data_local = resolve_data(data, t, r)

        def body(blk, i):
            view = lax.dynamic_update_slice_in_dim(stale, blk, lo, axis=0)
            dl, mb_scale = data_local, jnp.asarray(1.0, dtype=blk.dtype)
            if batch_size is not None:
                dl, scale = draw_minibatch(
                    jax.random.fold_in(jax.random.fold_in(key, i), r),
                    data_local, n_local_data, batch_size,
                )
                mb_scale = jnp.asarray(scale, dtype=blk.dtype)
            scores = score_scale * mb_scale * batched_score(view, dl)
            scores = scores + batched_prior(view)
            # sub-step i of this macro is absolute step t + i (t is the
            # first sub-step's counter) — the redraw-per-step bank folds it
            delta = _bind_phi_step(phi_fn, t + i)(blk, view, scores)
            return blk + step_size * delta, (blk if record else None)

        blk, hist = lax.scan(
            body, block, jnp.arange(exchange_every, dtype=jnp.int32)
        )
        if record:
            return blk, hist  # (exchange_every, s, d) pre-update snapshots
        return blk

    return macro


def make_shard_step_sinkhorn_w2(
    logp: Callable,
    kernel,
    mode: str,
    num_shards: int,
    n_local_data: int,
    score_scale: float,
    shard_data: bool = False,
    batch_size: Optional[int] = None,
    log_prior: Optional[Callable] = None,
    phi_impl: str = "xla",
    sinkhorn_eps: float = 0.05,
    sinkhorn_iters: int = 200,
    sinkhorn_tol: Optional[float] = None,
    sinkhorn_warm_start: bool = True,
    phi_batch_hint: int = 1,
    update_rule: str = "jacobi",
    w2_pairing: str = "global",
    ring: bool = False,
    kernel_approx=None,
) -> Callable:
    """Per-shard SVGD step with the Wasserstein/JKO term computed **inside
    the step** from carried previous-snapshot state, so whole W2 trajectories
    can run under one ``lax.scan`` (``DistSampler.run_steps``).

    ``w2_pairing='block'`` (exchanged modes, S > 1) swaps the W2 term's
    reference-warty global pairing for the ``partitions``-style one while φ
    still interacts with the gathered global set: each shard snapshots only
    the block it just updated and pairs its block against the snapshot of
    block ``(b+1) mod S`` (the same ``ppermute`` roll ``partitions`` uses).
    The carried state drops from a per-shard ``(n, d)`` snapshot — four
    lane-padded ``(n, 128)``-float buffers deep in the scan, the measured
    memory cliff past n = 400k (docs/notes.md round-4 table) — to ``(n/S,
    d)``, and each solve from ``(n/S, n)`` to ``(n/S, n/S)``.
    ``DistSampler`` auto-routes to this above
    :data:`~dist_svgd_tpu.distsampler.W2_GLOBAL_PAIRING_MAX_N` particles.

    Replicates the reference's exact (warty) snapshot semantics
    (dsvgd/distsampler.py:103-129,186-205; distsampler.py module docstring):

    - exchanged modes: each shard's ``previous`` is the pre-update all-gather
      with only its *own* block post-update; the W2 gradient pairs the
      shard's pre-update block against that full snapshot;
    - ``partitions``: each shard snapshots the block it just updated, and the
      next step pairs device ``b``'s block against the snapshot of block
      ``(b+1) mod S`` (a ``lax.ppermute`` of the carried snapshots — the
      device-side form of the host path's ``np.roll(previous, -1)``).

    Exchange implementation: the *global* pairing is gather-only — its
    snapshot IS the gathered set, which the ring implementation exists to
    avoid materialising.  Under ``w2_pairing='block'`` the snapshot is the
    own block, so ``ring=True`` composes (round 5): blockwise ppermute φ
    accumulation + block-sized W2 state — the fully O(n/S)-memory exchanged
    W2 step (Jacobi only, like every ring path).

    Returns ``step(block, prev, g_dual, data, t, key, step_size, h, w_on) ->
    (new_block, new_prev, new_g)`` where ``prev``/``new_prev`` and
    ``g_dual``/``new_g`` carry a leading length-1 axis (the per-shard slice
    of the global ``(S, ., d)`` snapshot / ``(S, .)`` dual stacks) and
    ``w_on`` is 0.0 on a first-ever step (reference: no W2 until a previous
    snapshot exists, dsvgd/distsampler.py:186-188) and 1.0 after.

    ``g_dual`` is the previous step's Sinkhorn dual potential ``g``, fed as
    the next solve's warm start (:func:`dist_svgd_tpu.ops.ot.sinkhorn_plan`:
    particles move O(ε·φ) per step, so the carried ``g`` is near-optimal and
    the ``tol`` exit terminates in a block or two).  The pairing each shard's
    solve works on — its own evolving block against a fixed logical
    snapshot slot (the mixed gathered snapshot in exchanged modes; block
    ``(b+1) mod S``'s snapshot in ``partitions``, via the per-step
    ``ppermute`` roll) — is the *same* every step, so the carried ``g``
    always describes the measure it will warm-start against.  On a
    ``w_on == 0`` step the solve's output duals are zeroed, so the first
    real solve starts from zeroed duals (the safe soft-transform start)
    instead of inheriting potentials fitted to the zeros placeholder
    snapshot.  ``sinkhorn_warm_start=False`` restores the
    cold c-transform start on every step (the A/B baseline —
    tools/w2_bench.py).

    ``update_rule='gauss_seidel'`` composes the W2 term with the literal
    GS sweep exactly as the eager path does (``DistSampler.make_step``):
    the W2 gradient is solved once per step from the pre-sweep block
    against the carried snapshot and held fixed while the sweep applies it
    row by row (``δ_i = φ(..) + h·w_grad_i`` — the reference's placement,
    dsvgd/distsampler.py:194-200); the snapshot is then built from the
    pre-sweep gather with the swept own block patched in, the same warty
    rule.  Gather implementation, no minibatch (the GS builder's own
    constraints).
    """
    from dist_svgd_tpu.ops.ot import wasserstein_grad_sinkhorn

    if update_rule == "gauss_seidel":
        if kernel_approx is not None:
            raise ValueError(
                "kernel_approx requires update_rule='jacobi' (the GS sweep "
                "exists for literal reference parity)"
            )
        gs_step = _build_gs_step(
            logp, kernel, mode, num_shards, n_local_data, score_scale,
            False, shard_data, batch_size, log_prior, phi_impl,
        )
        core = None
    elif update_rule == "jacobi":
        gs_step = None
        core = _build_core(
            logp, kernel, mode, num_shards, n_local_data, score_scale,
            ring, shard_data, batch_size, log_prior, phi_impl, phi_batch_hint,
            kernel_approx,
        )
    else:
        raise ValueError(f"unknown update_rule {update_rule!r}")
    if w2_pairing not in ("global", "block"):
        raise ValueError(f"unknown w2_pairing {w2_pairing!r}")
    # prev_for[b] = previous[(b+1) % S]  (np.roll(prev, -1) device-side)
    roll_perm = [(j, (j - 1) % num_shards) for j in range(num_shards)]
    # block-sized snapshots + (b+1)-roll: partitions natively, or the
    # exchanged modes under w2_pairing='block' (docstring)
    block_pair = (mode == PARTITIONS or w2_pairing == "block") and num_shards > 1
    if ring and mode != PARTITIONS and not block_pair and num_shards > 1:
        # S == 1 is exempt: every pairing degenerates to the same thing
        # there (the snapshot is the whole post-update array), handled by
        # the interacting-is-None branch in the step
        raise ValueError(
            "the scanned W2 step under exchange_impl='ring' requires the "
            "block pairing (w2_pairing='block'): the global pairing's "
            "snapshot is the gathered set the ring exists to avoid"
        )

    def step(block, prev, g_dual, data, t, key, step_size, h, w_on):
        prev = prev[0]
        if block_pair:
            prev_for = lax.ppermute(prev, AXIS, roll_perm)
        else:
            prev_for = prev
        w_grad, g_out = wasserstein_grad_sinkhorn(
            block, prev_for, eps=sinkhorn_eps, iters=sinkhorn_iters,
            tol=sinkhorn_tol,
            g_init=g_dual[0] if sinkhorn_warm_start else None,
            return_g=True,
        )
        w_grad = w_on * w_grad
        if gs_step is not None:
            # the sweep applies h·w_grad per row itself; the snapshot needs
            # the pre-sweep gather (the sweep's internal gather of the same
            # pre-update block — XLA CSEs the duplicate collective).  Block
            # pairing snapshots only the own block, so no extra gather
            interacting = (
                None if (mode == PARTITIONS or block_pair)
                else lax.all_gather(block, AXIS, tiled=True)
            )
            new = gs_step(block, data, w_grad, t, key, step_size, h)
        else:
            delta, interacting = core(block, data, t, key)
            new = block + step_size * (delta + h * w_grad)
        if mode == PARTITIONS or block_pair or interacting is None:
            # block-sized snapshot, or the S=1 ring degenerate case where
            # the "global" snapshot is exactly the whole post-update array
            new_prev = new
        else:
            r = lax.axis_index(AXIS)
            new_prev = lax.dynamic_update_slice_in_dim(
                interacting, new, r * block.shape[0], axis=0
            )
        return new, new_prev[None], (w_on * g_out)[None]

    return step
