"""The three particle/score exchange strategies, as one fused per-shard step.

Reference semantics (dsvgd/distsampler.py:131-170,172-205 — SURVEY.md §2.3):

- ``all_particles`` — every shard gathers the full particle set
  (``dist.all_gather`` → ``lax.all_gather``) and computes scores for *all* n
  particles using only its **local data slice**, importance-scaled by
  ``N_global / N_local`` (dsvgd/distsampler.py:96-99).
- ``all_scores``    — after the particle gather, per-shard local-data scores
  for all n particles are summed across shards (``dist.all_reduce(SUM)`` →
  ``lax.psum``), yielding the **exact global score**; no extra scaling
  (the reference's open TODO at dsvgd/distsampler.py:93 — the SUM already
  globalises the estimate).
- ``partitions``    — ring migration: each rank hands its particle block to
  rank+1 and adopts the block from rank−1, then interacts **only within the
  owned block** (dsvgd/distsampler.py:131-150, interaction set :85-87).

The ``partitions`` mode is re-derived for SPMD: instead of migrating particle
blocks between devices (mutable ownership ranges don't exist under pjit),
each device keeps its particle block pinned and the **data-shard assignment
rotates** — block ``b`` at step ``t`` is updated against data slice
``(b + t) mod S``, which is exactly the pairing the reference's ring produces
(owner of block ``b`` at step ``t`` is rank ``(b + t) mod S``, whose data is
slice ``(b + t) mod S``).  The global particle array therefore stays in
logical order at all times.  Like the reference — where every rank loads the
full dataset and slices its block (experiments/logreg.py:28,41-51) — the
dataset is replicated across devices and sliced per-shard with
``lax.dynamic_slice``; a sharded-data path with ``ppermute`` rotation is the
planned optimisation for datasets that don't fit per-device HBM.

Each strategy is one jit-compiled function; XLA overlaps the collective with
the score/kernel compute.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from dist_svgd_tpu.ops.svgd import phi
from dist_svgd_tpu.parallel.mesh import AXIS

ALL_PARTICLES = "all_particles"
ALL_SCORES = "all_scores"
PARTITIONS = "partitions"

MODES = (ALL_PARTICLES, ALL_SCORES, PARTITIONS)


def _slice_data(data, start: jax.Array, size: int):
    """Per-shard data slice: every leaf is sliced ``[start : start+size]``
    along axis 0 (the reference's contiguous block convention,
    experiments/logreg.py:41-51)."""
    if data is None:
        return None
    return jax.tree_util.tree_map(
        lambda a: lax.dynamic_slice_in_dim(a, start, size, axis=0), data
    )


def make_shard_step(
    logp: Callable,
    kernel,
    mode: str,
    num_shards: int,
    n_local_data: int,
    score_scale: float,
) -> Callable:
    """Build the per-shard SVGD step for one exchange strategy.

    Args:
        logp: ``logp(theta, data_local)`` scalar log-density; ``data_local``
            is the shard's data slice (or ``None`` for data-free targets).
        kernel: kernel object/callable for :func:`dist_svgd_tpu.ops.svgd.phi`.
        mode: one of :data:`MODES`.
        num_shards: mesh size S.
        n_local_data: rows per data shard (``N_global // S``, remainder
            dropped — reference drop policy, experiments/logreg.py:35).
        score_scale: ``N_global / N_local`` importance factor applied when
            scores are *not* exchanged (dsvgd/distsampler.py:96-99); pass 1.0
            for data-free targets.

    Returns:
        ``step(block, data_full, w_grad_block, t, step_size, h) -> new_block``
        written against block-local shapes and the named axis
        :data:`~dist_svgd_tpu.parallel.mesh.AXIS`; bind it with
        :func:`~dist_svgd_tpu.parallel.mesh.bind_shard_fn`.

        ``w_grad_block`` is the per-shard Wasserstein/JKO gradient (zeros when
        disabled), added as ``δ += h·w_grad`` before ``θ += ε·δ`` exactly as
        the reference does (dsvgd/distsampler.py:194-200).  ``t`` is the
        1-based step counter driving the ``partitions`` rotation.
    """
    if mode not in MODES:
        raise ValueError(f"unknown exchange mode {mode!r}")

    score_fn = jax.grad(logp, argnums=0)
    batched_score = jax.vmap(score_fn, in_axes=(0, None))

    def step(block, data_full, w_grad_block, t, step_size, h):
        r = lax.axis_index(AXIS)
        if mode == PARTITIONS:
            data_rank = (r + t.astype(r.dtype)) % num_shards
        else:
            data_rank = r
        data_local = _slice_data(data_full, data_rank * n_local_data, n_local_data)

        if mode == PARTITIONS:
            interacting = block
            scores = score_scale * batched_score(block, data_local)
        else:
            interacting = lax.all_gather(block, AXIS, tiled=True)
            local_scores = batched_score(interacting, data_local)
            if mode == ALL_SCORES:
                scores = lax.psum(local_scores, AXIS)
            else:
                scores = score_scale * local_scores

        delta = phi(block, interacting, scores, kernel)
        delta = delta + h * w_grad_block
        return block + step_size * delta

    return step
