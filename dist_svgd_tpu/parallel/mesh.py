"""Device-mesh utilities.

The reference's communication layer is per-process ``torch.distributed`` with
explicit rank bookkeeping (SURVEY.md §2.4).  The TPU-native replacement is a
1-D ``jax.sharding.Mesh`` over the particle axis: ownership ranges become
sharding specs, and the three exchange collectives become
``lax.all_gather`` / ``lax.psum`` / ``lax.ppermute`` inside one jitted step.

Two interchangeable backends execute the same per-shard function:

- **shard_map** over a real device mesh (TPU ICI, or
  ``--xla_force_host_platform_device_count`` CPU devices in tests);
- **vmap with a named axis** — an exact single-device emulation used when the
  host has fewer devices than shards (e.g. benchmarking 8-shard semantics on
  the one real TPU chip).  JAX collectives are semantically identical under
  ``vmap(axis_name=...)``, so both backends run the *same* code path.

Multi-host: call ``jax.distributed.initialize()`` before ``make_mesh`` and the
same program spans DCN-connected hosts via global arrays (SURVEY.md §2.4).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # newer jax: public export, replication check named check_vma
    from jax import shard_map as _shard_map

    _SHARD_MAP_CHECK_KW = "check_vma"
except ImportError:  # older jax (< 0.5): experimental home, check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

    _SHARD_MAP_CHECK_KW = "check_rep"

#: True on jax versions whose shard_map still lives in jax.experimental —
#: a proxy for the old XLA pipeline whose sharding propagation crashes
#: (SIGABRT, ``TileAssignment::Reshape`` check failure) when a collective-
#: derived scalar feeds a ``ppermute`` loop body, the exact dataflow of the
#: ring exchange's per-step median bandwidth.  ``DistSampler`` refuses that
#: configuration on these versions with a clear error instead of letting
#: the compiler kill the process (tests/test_adaptive_bandwidth.py runs it
#: under the vmap emulation there, which is unaffected).
SHARD_MAP_LEGACY = _SHARD_MAP_CHECK_KW == "check_rep"

#: Name of the particle-sharding mesh axis used throughout the framework.
AXIS = "shards"


def make_mesh(num_shards: int, devices: Optional[Sequence] = None) -> Optional[Mesh]:
    """Build a 1-D mesh of ``num_shards`` devices, or ``None`` when the host
    does not have enough devices (callers then use the vmap emulation backend).
    """
    if devices is None:
        devices = jax.devices()
    if num_shards == 1:
        return None
    if len(devices) < num_shards:
        return None
    return Mesh(np.asarray(devices[:num_shards]), (AXIS,))


def bind_shard_fn(
    fn: Callable,
    num_shards: int,
    mesh: Optional[Mesh],
    in_specs: Sequence[Optional[int]],
    out_specs: Sequence[Optional[int]],
) -> Callable:
    """Bind a per-shard function to a mesh (shard_map) or emulate it (vmap).

    ``fn`` is written once against block-local shapes and the named axis
    :data:`AXIS`.  Each spec entry is ``None`` (replicated — whole value seen
    by every shard, pytrees allowed) or an int axis index along which the
    *global* value is split into ``num_shards`` equal blocks.  The bound
    callable always takes/returns global arrays, so callers are oblivious to
    the backend.
    """
    in_specs = tuple(in_specs)
    out_specs = tuple(out_specs)
    single_out = len(out_specs) == 1

    if mesh is not None:
        def to_p(s):
            return P() if s is None else P(*([None] * s + [AXIS]))

        sm_out = to_p(out_specs[0]) if single_out else tuple(to_p(s) for s in out_specs)
        return _shard_map(
            fn,
            mesh=mesh,
            in_specs=tuple(to_p(s) for s in in_specs),
            out_specs=sm_out,
            **{_SHARD_MAP_CHECK_KW: False},
        )

    vf = jax.vmap(
        fn,
        in_axes=in_specs,
        out_axes=out_specs[0] if single_out else out_specs,
        axis_name=AXIS,
        axis_size=num_shards,
    )

    def _split_leaf(a, s):
        shape = a.shape
        assert shape[s] % num_shards == 0, (shape, s, num_shards)
        return a.reshape(shape[:s] + (num_shards, shape[s] // num_shards) + shape[s + 1:])

    def split(a, s):
        if s is None or a is None:
            return a
        return jax.tree_util.tree_map(lambda x: _split_leaf(x, s), a)

    def merge(o, s):
        if s is None or o is None:
            return o
        return jax.tree_util.tree_map(
            lambda x: x.reshape(x.shape[:s] + (x.shape[s] * x.shape[s + 1],) + x.shape[s + 2:]),
            o,
        )

    def wrapped(*args):
        outs = vf(*[split(a, s) for a, s in zip(args, in_specs)])
        if single_out:
            return merge(outs, out_specs[0])
        return tuple(merge(o, s) for o, s in zip(outs, out_specs))

    return wrapped
