"""Multi-host (DCN) execution support.

The reference scales past one machine with ``torch.distributed`` TCP
rendezvous: env vars ``MASTER_ADDR``/``MASTER_PORT``/``WORLD_SIZE`` plus an
explicit per-process rank (experiments/logreg.py:94-103,129-140 — SURVEY.md
§2.4).  The TPU-native counterpart keeps the same operational shape — one
process per host, one rendezvous — but after :func:`initialize` the SPMD
program itself is unchanged: ``jax.distributed.initialize`` makes every
host's chips visible as one global device list, a :class:`~jax.sharding.Mesh`
spans them, and the very same jitted step (``parallel/exchange.py``) runs
with XLA routing each collective hop over ICI within a host and DCN between
hosts.  No rank bookkeeping survives into user code.

Mesh ordering matters for collective cost: :func:`make_particle_mesh` orders
the 1-D particle axis **granule-major** — all chips of one DCN granule (a
TPU slice on multi-slice jobs; a process on CPU federations), then the next
— via ``mesh_utils.create_hybrid_device_mesh``, so the ``partitions``/ring
``lax.ppermute`` crosses DCN exactly once per granule boundary per hop and
all other traffic rides ICI — the minimum possible DCN load for a ring.
Within a single ICI domain (one slice, however many hosts) there is no DCN
and the natural device order is used.

Array placement: a multi-host global array cannot be built from one host's
``jnp.asarray`` (each process only holds its addressable shards).
:func:`make_global_particles` assembles the global ``(n, d)`` particle array
from each process's local rows via ``jax.make_array_from_process_local_data``;
:func:`process_local_rows` tells a process which logical block that is.  On a
single process both degrade to the trivial case, so drivers are written once.
"""

from __future__ import annotations

import os
import warnings
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dist_svgd_tpu.parallel.mesh import AXIS


def _version_tuple(version: str) -> Tuple[int, ...]:
    parts = []
    for piece in version.split(".")[:3]:
        digits = ""
        for ch in piece:
            if not ch.isdigit():
                break
            digits += ch
        if not digits:
            break
        parts.append(int(digits))
    return tuple(parts)


def multiprocess_gap(
    num_processes: Optional[int] = None, platform: Optional[str] = None
) -> Optional[str]:
    """One-line reason an explicit ``num_processes``-way rendezvous cannot
    work in this runtime, or None when it can.

    The known gap: jax < 0.5 has no multi-process collectives on the CPU
    backend — rendezvous *succeeds* and the failure surfaces mid-run as
    XLA's "Multiprocess computations aren't implemented on the CPU backend"
    inside the first jitted collective.  Detecting it up front lets
    :func:`initialize` (and drivers) refuse cleanly before any work is done.
    ``platform`` defaults to the configured platform (``jax.config`` /
    ``JAX_PLATFORMS``) so the probe stays legal before device init.
    """
    if num_processes is None or num_processes <= 1:
        return None
    if platform is None:
        platform = (
            getattr(jax.config, "jax_platforms", None)
            or os.environ.get("JAX_PLATFORMS", "")
            or ""
        )
        platform = platform.split(",")[0].strip().lower()
    if platform != "cpu":
        return None
    if _version_tuple(jax.__version__) >= (0, 5):
        return None
    return (
        f"jax {jax.__version__} cannot run multi-process collectives on the "
        f"CPU backend (needs jax>=0.5); refusing the {num_processes}-process "
        "rendezvous up front"
    )


def _distributed_initialized() -> bool:
    """``jax.distributed.is_initialized()`` with a fallback for jax versions
    that predate the public probe (< 0.5): the distributed client lives on
    ``jax._src.distributed.global_state`` there."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    from jax._src import distributed as _dist

    state = getattr(_dist, "global_state", None)
    return state is not None and getattr(state, "client", None) is not None


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **kwargs,
) -> bool:
    """Join the multi-host job — the counterpart of the reference's
    ``dist.init_process_group('tcp', init_method='env://')``
    (experiments/logreg.py:96).

    With no arguments, JAX auto-detects cluster environments (TPU pods, GKE,
    SLURM); arguments mirror the reference's explicit
    ``MASTER_ADDR:PORT`` / world-size / rank rendezvous.  Must be the first
    JAX call in the process (JAX's own ``jax.distributed`` contract — nothing
    here may touch a device before the rendezvous).  Idempotent: returns
    False (no-op) when the runtime is already initialized or when this is a
    plainly single-process run (no coordinator given, no cluster detected),
    True when initialization happened.  An explicit ``coordinator_address``
    that cannot be honored always raises.
    """
    if _distributed_initialized():
        return False
    gap = multiprocess_gap(num_processes)
    if gap is not None:
        # refuse a doomed explicit multi-process request up front (the PR-1
        # clean-refusal pattern) instead of letting XLA fail mid-run
        raise RuntimeError(gap)
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )
        return True
    except ValueError as e:
        if coordinator_address is not None:
            raise RuntimeError(
                f"multi-host initialize({coordinator_address=}) failed: {e}"
            ) from e
        # Only the no-cluster-to-auto-detect case (jax: "coordinator_address
        # should be defined") may degrade to single-process; any other
        # ValueError means a present-but-malformed cluster env, and running
        # on would give every worker an independent exchange-free job with
        # wrong results.
        if "coordinator_address" not in str(e):
            raise
        warnings.warn(
            f"jax.distributed found no cluster to auto-detect ({e}); "
            "continuing single-process.",
            RuntimeWarning,
            stacklevel=2,
        )
        return False
    except RuntimeError as e:
        # Only the "must be called before any JAX calls …" too-late case may
        # degrade to single-process; a detected cluster whose rendezvous
        # *fails* (connection refused, timeout — XlaRuntimeError subclasses)
        # must abort, or every worker would silently run an independent
        # exchange-free job with wrong results.
        too_late = ("before any JAX calls" in str(e)        # newer jax
                    or "before any JAX computations" in str(e))  # < 0.5
        if coordinator_address is not None or not too_late:
            raise
        warnings.warn(
            "jax.distributed could not auto-initialize (the XLA backend is "
            "already started); continuing single-process. Call "
            "multihost.initialize() before any other JAX use.",
            RuntimeWarning,
            stacklevel=2,
        )
        return False


def make_particle_mesh(
    num_shards: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """1-D particle mesh over every chip in the job, **granule-major**
    (module docstring: slice-major on TPU multi-slice jobs, process-major on
    CPU federations, natural order within one ICI domain).

    ``num_shards`` defaults to the global device count (one shard per chip —
    the normal multi-host configuration).  The ordering makes mesh-adjacent
    shards ICI-adjacent, so each ring hop crosses DCN only at granule
    boundaries.
    """
    if devices is None:
        devices = jax.devices()
    if num_shards is None:
        num_shards = len(devices)
    if num_shards > len(devices):
        raise ValueError(f"need {num_shards} devices, have {len(devices)}")

    # Where is the DCN boundary?  On TPU multi-slice jobs it is the slice
    # (hosts *within* a slice are still ICI-connected, so they need no
    # special ordering); CPU federations expose no real slices (every
    # process reports slice_index 0), so there the process boundary is the
    # slow network.  A single granule means a single fast domain — plain
    # device order, no hybrid mesh needed.
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    n_procs = len({d.process_index for d in devices})
    if len(slice_ids) > 1:
        granule_of = lambda d: d.slice_index
        process_is_granule = False
    elif n_procs > 1 and devices[0].platform == "cpu":
        granule_of = lambda d: d.process_index
        process_is_granule = True
    elif n_procs > 1:
        # one ICI domain spanning several processes (single-slice multi-host
        # TPU): no DCN to order around, but a subset must still take an equal
        # block from every process — devices[:num_shards] could exclude whole
        # processes, which would own zero shards and fail far from here
        per_p = num_shards // n_procs
        by_p: dict = {}
        for d in devices:
            by_p.setdefault(d.process_index, []).append(d)
        if per_p * n_procs != num_shards or any(
            len(v) < per_p for v in by_p.values()
        ):
            raise ValueError(
                f"num_shards {num_shards} cannot take an equal share of the "
                f"{n_procs} processes' devices "
                f"({ {p: len(v) for p, v in by_p.items()} })"
            )
        subset = [d for p in sorted(by_p) for d in by_p[p][:per_p]]
        return Mesh(np.asarray(subset), (AXIS,))
    else:
        return Mesh(np.asarray(devices[:num_shards]), (AXIS,))

    from jax.experimental import mesh_utils

    groups: dict = {}
    for d in devices:
        groups.setdefault(granule_of(d), []).append(d)
    n_g = len(groups)
    per_g = num_shards // n_g
    if per_g * n_g != num_shards:
        raise ValueError(
            f"num_shards {num_shards} must be a multiple of the {n_g} "
            "DCN granules (slices/processes)"
        )
    short = {g: len(v) for g, v in groups.items() if len(v) < per_g}
    if short:
        raise ValueError(
            f"need {per_g} devices per granule for num_shards {num_shards}, "
            f"but granules {short} have fewer"
        )

    def take(group):
        """Equal per-process share of a granule's subset — ``group[:per_g]``
        could take all of one host's chips and none of another's, leaving
        processes that own zero shards (they would fail far away, in
        ``process_local_rows``, with an empty indices map)."""
        by_p: dict = {}
        for d in group:
            by_p.setdefault(d.process_index, []).append(d)
        per_p = per_g // len(by_p)
        if per_p * len(by_p) != per_g or any(
            len(v) < per_p for v in by_p.values()
        ):
            raise ValueError(
                f"cannot take an equal {per_g}-device share of a granule's "
                f"processes ({ {p: len(v) for p, v in by_p.items()} })"
            )
        return [d for p in sorted(by_p) for d in by_p[p][:per_p]]

    subset = [d for g in sorted(groups) for d in take(groups[g])]
    dev_array = mesh_utils.create_hybrid_device_mesh(
        (per_g,), (n_g,), devices=subset,
        process_is_granule=process_is_granule,
    )
    return Mesh(dev_array, (AXIS,))


def process_local_rows(n_global: int, mesh: Mesh) -> Tuple[int, int]:
    """(start, count) of the logical particle rows this process's chips own
    under ``P(AXIS)`` row sharding — what the reference computes per rank as
    ``rank * particles_per_shard`` ownership ranges (dsvgd/distsampler.py:46-49),
    derived here from the sharding itself."""
    sharding = NamedSharding(mesh, P(AXIS))
    idx_map = sharding.addressable_devices_indices_map((n_global,))
    spans = sorted(
        (
            0 if sl.start is None else sl.start,
            n_global if sl.stop is None else sl.stop,
        )
        for sl, *_ in idx_map.values()
    )
    lo, hi = spans[0][0], spans[-1][1]
    cur = lo
    for a, b in spans:
        if a > cur:
            raise ValueError(
                "this process's addressable rows are not one contiguous "
                "block — the mesh interleaves processes; build it with "
                "make_particle_mesh (granule-major ordering)"
            )
        cur = max(cur, b)
    return lo, hi - lo


def make_global_particles(
    local_rows, mesh: Mesh, n_global: Optional[int] = None
) -> jax.Array:
    """Assemble the global row-sharded ``(n, d)`` particle array from this
    process's block of rows (``process_local_rows`` tells which).

    ``n_global`` is the global row count — pass the same ``n`` given to
    :func:`process_local_rows` (required when ``n`` does not divide evenly
    across processes, where per-process counts differ and cannot be inferred
    from the local block alone).  Defaults to assuming equal blocks.

    Single-process this is just ``device_put`` with the row sharding; multi-
    host it is the only correct way to build the array — no host holds all
    rows, so drivers must never ``jnp.asarray`` a global particle set.
    """
    local_rows = np.asarray(local_rows)
    sharding = NamedSharding(mesh, P(AXIS))
    if jax.process_count() == 1:
        # same contract as the multi-host path: one process owns all rows
        if n_global is not None and n_global != local_rows.shape[0]:
            raise ValueError(
                f"n_global {n_global} != local rows {local_rows.shape[0]} "
                "on a single-process run"
            )
        return jax.device_put(local_rows, sharding)
    if n_global is None:
        n_global = local_rows.shape[0] * jax.process_count()
    return jax.make_array_from_process_local_data(
        sharding, local_rows, global_shape=(n_global,) + local_rows.shape[1:]
    )


def host_addressable_block(arr, axis: int = 0) -> Tuple[np.ndarray, int]:
    """``(rows, start)``: a host copy of this process's contiguous
    addressable block of a global array along ``axis`` (the whole array and
    ``start=0`` when it is fully addressable — numpy inputs included).

    The checkpoint counterpart of :func:`make_global_particles`:
    ``np.asarray`` on a multi-process global array raises (other processes'
    shards are not addressable), so per-process state saving goes through
    this instead (``DistSampler.state_dict``).
    """
    if not isinstance(arr, jax.Array) or arr.is_fully_addressable:
        return np.asarray(arr), 0
    spans = {}
    for s in arr.addressable_shards:
        sl = s.index[axis]
        key = (sl.start or 0, sl.stop)
        if key not in spans:  # replicated shards repeat the same span
            spans[key] = s.data
    ordered = sorted(spans)
    start = ordered[0][0]
    cur = start
    for a, b in ordered:
        if a != cur:
            raise ValueError(
                "this process's addressable shards are not one contiguous "
                f"block along axis {axis} (spans {ordered}); build the mesh "
                "with make_particle_mesh (granule-major ordering)"
            )
        cur = b
    return (
        np.concatenate([np.asarray(spans[k]) for k in ordered], axis=axis),
        start,
    )


def make_global_from_local(
    local, mesh: Mesh, global_shape: Tuple[int, ...]
) -> jax.Array:
    """Assemble a ``P(AXIS)``-sharded global array of ``global_shape`` from
    this process's axis-0 block (``process_local_rows(global_shape[0],
    mesh)`` tells which) —
    :func:`make_global_particles` for arrays of any rank (e.g. the
    Wasserstein ``previous`` snapshot stack)."""
    local = np.asarray(local)
    sharding = NamedSharding(mesh, P(AXIS))
    if jax.process_count() == 1:
        if local.shape != tuple(global_shape):
            raise ValueError(
                f"single-process local block {local.shape} != global "
                f"{tuple(global_shape)}"
            )
        return jax.device_put(local, sharding)
    return jax.make_array_from_process_local_data(
        sharding, local, global_shape=tuple(global_shape)
    )


def replicate(value, mesh: Mesh) -> jax.Array:
    """Place a host value replicated on every chip of the mesh (the multi-host
    equivalent of the reference's every-rank-loads-the-full-dataset pattern,
    experiments/logreg.py:28)."""
    return jax.device_put(np.asarray(value), NamedSharding(mesh, P()))


def mesh_process_layout(mesh: Mesh) -> Tuple[int, Tuple[int, ...]]:
    """``(process_count, per-process shard counts)`` of a particle mesh —
    the granule layout the topology manifest stamps so a restore can verify
    it reassembles the same global shape the saves came from.

    The counts are ordered by ``process_index`` (mesh order under
    :func:`make_particle_mesh`'s granule-major placement), so the tuple is
    identical in every process — safe to stamp into replicated manifest
    entries (``assemble_full_state`` requires those to be bitwise equal
    across per-process files)."""
    counts: dict = {}
    for d in mesh.devices.flat:
        counts[d.process_index] = counts.get(d.process_index, 0) + 1
    return len(counts), tuple(counts[p] for p in sorted(counts))


def dcn_boundary_crossings(mesh_or_devices) -> int:
    """Number of ring-adjacent device pairs (wrap included) that sit on
    different DCN granules — how many of a ring pass's hops ride the slow
    network instead of ICI.

    Granule = TPU slice on multi-slice jobs, process otherwise (the same
    boundary rule :func:`make_particle_mesh` orders around).  Granule-major
    ordering makes this exactly the granule count — the minimum for a ring;
    an interleaved mesh scores higher, which is the point of measuring it.
    """
    if isinstance(mesh_or_devices, Mesh):
        devs = list(mesh_or_devices.devices.flat)
    else:
        devs = list(mesh_or_devices)
    if len(devs) < 2:
        return 0
    slice_ids = {getattr(d, "slice_index", None) for d in devs}
    if len(slice_ids) > 1:
        granule = lambda d: d.slice_index
    else:
        granule = lambda d: d.process_index
    return sum(
        granule(devs[i]) != granule(devs[(i + 1) % len(devs)])
        for i in range(len(devs))
    )
