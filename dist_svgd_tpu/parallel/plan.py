"""Unified sharding **Plan**: one compile entrypoint for mesh-sharded and
single-device execution (ROADMAP item 5's seed, grown for serving first).

The training side already shards per-particle work across the mesh
(``bind_shard_fn``'s shard_map/vmap backends), but the serving engine's
predictive kernels were plain single-device ``jax.jit`` — the mesh that
trains 2M particles idled at serve time.  A :class:`Plan` closes that gap
the pjit-preferring way (SNIPPETS.md [2]): when a mesh is given, compile
with **explicit in/out shardings** (replicated request batches in, a
particle-sharded ensemble closed over, replicated outputs back out — the
particle-axis reduction becomes one cross-shard ``psum`` XLA inserts);
when no mesh is given, fall back to today's single-device ``jit`` so the
CPU tier-1 path is byte-for-byte the old behavior.

Placement follows the ``shard_params`` / ``get_naive_sharding`` pattern
(SNIPPETS.md [3]): :meth:`Plan.shard_ensemble` is a ``jax.device_put``
with ``NamedSharding(mesh, PartitionSpec(AXIS, ...))`` on the particle
axis.  jax 0.4.x rejects uneven shardings outright, so a particle count
the mesh doesn't divide falls back to replication with a warning rather
than failing the cold start — serving an ensemble beats serving an error.

Buffer donation rides the same entrypoint (ROADMAP item 2):
``donate_argnums`` passes straight through to ``jit`` so steady-state
dispatch inputs stop re-allocating per call.  Donation is declared per
*compiled program*; on backends where a donated buffer cannot alias an
output (CPU, and reduction kernels whose outputs are smaller than their
inputs) XLA just frees it early and warns.  For a *deliberate* donation
that nag carries no signal, so :meth:`Plan.compile` suppresses it —
scoped to the first (lowering) call of each donating program, never as a
process-global filter, so a future training-loop donation that wants the
warning as a tuning signal can keep it (``quiet_donation=False``).

Since round 17 the **training carries donate through here too** (ROADMAP
item 1's last slice): both samplers pass ``donate_argnums`` at their
:meth:`Plan.compile_sharded` scan/chunk sites — particles on every
scanned run, the W2 snapshot + Sinkhorn dual stacks in the W2 scan, and
the intra-step executors' accumulator carries — gated by their
``donate_carries`` flag and pinned bitwise against the undonated path
(``tools/profile_step_floor.py --donate-ab``).

Round 22: every compiled program is additionally **tracked** through
:mod:`dist_svgd_tpu.analysis.registry` — the seam the program auditor
hangs off.  Call sites pass ``label=`` (a stable audit name) and
``audit=`` (declarations like ``gram_free``/``pinned_f32`` that arm the
XP rules); untagged sites still register under the function's name so the
card inventory covers *every* entrypoint, not just the annotated ones.
Tracking costs one bool check per steady-state dispatch and holds only a
weakref to the compiled program.
"""

from __future__ import annotations

import threading
import warnings
from typing import Any, Callable, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dist_svgd_tpu.parallel.mesh import AXIS, make_mesh

_DONATION_NAG = "Some donated buffers were not usable"

__all__ = ["Plan", "make_plan", "nondividing_replicate_warning"]


def nondividing_replicate_warning(n: int, num_shards: int) -> str:
    """The ONE warning text for the replicate-instead-of-shard fallback.

    Emitted by :meth:`Plan.shard_ensemble` at engine construction AND by
    ``utils/checkpoint.py:reshard_state`` when an elastic resume targets a
    shard count that does not divide the particle count — the same
    degradation (correct but no longer distributed) must read the same in
    logs wherever it happens."""
    return (
        f"ensemble of {n} particles is not divisible by "
        f"{num_shards} shards; replicating instead of sharding "
        "(serving stays correct, the mesh win is lost)"
    )


def _quiet_first_call(fn: Callable) -> Callable:
    """Suppress the not-usable-donation nag around ``fn``'s first call.

    The warning is emitted at lowering time — exactly once per compiled
    program — so only the first invocation needs the filter; steady-state
    calls pay one bool check.  Concurrent cold callers serialise on a
    private lock (compiles serialise on jax's internals anyway), keeping
    the ``catch_warnings`` window single-threaded.
    """
    state = {"lowered": False}
    guard = threading.Lock()

    def wrapped(*args):
        if state["lowered"]:
            return fn(*args)
        with guard:
            with warnings.catch_warnings():
                warnings.filterwarnings("ignore", message=_DONATION_NAG)
                out = fn(*args)
            state["lowered"] = True
            return out

    # keep the registry identity visible through the donation shim so
    # cost tooling (telemetry/profile.py, program cards) can reach the
    # ProgramEntry from whichever callable the caller ends up holding
    entry = getattr(fn, "program_entry", None)
    if entry is not None:
        wrapped.program_entry = entry  # type: ignore[attr-defined]

    return wrapped


def _track(compiled: Callable, fn: Callable, *, kind: str, num_shards: int,
           donate_argnums, static_argnums,
           label: Optional[str], audit: Optional[dict]) -> Callable:
    """Register ``compiled`` with the process program registry (lazy
    import: analysis is a pure observer — a broken/absent analysis package
    must never take the compile path down with it)."""
    try:
        from dist_svgd_tpu.analysis.registry import default_registry
    except Exception:
        return compiled
    return default_registry().track(
        compiled,
        label=label or getattr(fn, "__name__", None) or "plan_fn",
        kind=kind,
        num_shards=num_shards,
        donate_argnums=donate_argnums,
        static_argnums=static_argnums,
        meta=audit,
    )


class Plan:
    """A compile + placement recipe bound to one (optional) device mesh.

    Args:
        mesh: a 1-D particle-axis :class:`~jax.sharding.Mesh` (axis name
            :data:`~dist_svgd_tpu.parallel.mesh.AXIS`), or ``None`` for
            single-device execution.  Build one with
            :func:`~dist_svgd_tpu.parallel.mesh.make_mesh` or use
            :func:`make_plan`.
    """

    def __init__(self, mesh: Optional[Mesh] = None):
        if mesh is not None and AXIS not in mesh.axis_names:
            raise ValueError(
                f"plan mesh must carry the {AXIS!r} axis, got {mesh.axis_names}"
            )
        self.mesh = mesh

    # ------------------------------------------------------------------ #
    # identity

    @property
    def num_shards(self) -> int:
        """Devices on the particle axis (1 when single-device)."""
        return self.mesh.shape[AXIS] if self.mesh is not None else 1

    @property
    def is_sharded(self) -> bool:
        return self.mesh is not None

    def __repr__(self) -> str:
        return f"Plan(num_shards={self.num_shards})"

    def describe(self) -> dict:
        """JSON-friendly identity for stats()/bench rows."""
        return {
            "sharded": self.is_sharded,
            "num_shards": self.num_shards,
            "devices": ([str(d) for d in self.mesh.devices.flat]
                        if self.mesh is not None else None),
        }

    # ------------------------------------------------------------------ #
    # shardings

    def replicated(self) -> Optional[NamedSharding]:
        """Every-device-sees-everything placement (request batches,
        outputs); ``None`` without a mesh (plain jit semantics)."""
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, P())

    def particle_sharding(self, ndim: int = 2) -> Optional[NamedSharding]:
        """Leading-axis (particle) sharding for an ``ndim``-dim array."""
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, P(AXIS, *([None] * (ndim - 1))))

    # ------------------------------------------------------------------ #
    # placement

    def shard_ensemble(self, particles) -> jax.Array:
        """Place an ``(n, d)`` ensemble on the plan's devices, sharded
        along the particle axis (``get_naive_sharding`` discipline).

        Without a mesh this is a no-op pass-through (``jnp.asarray``) —
        single-device callers keep their uncommitted-array behavior.
        jax 0.4.x cannot shard a dimension the mesh doesn't divide; such
        an ensemble is **replicated** instead, with a warning (correct,
        just not distributed — reshape or repad upstream to win it back).
        """
        import jax.numpy as jnp

        arr = jnp.asarray(particles)
        if self.mesh is None:
            return arr
        if arr.shape[0] % self.num_shards:
            warnings.warn(
                nondividing_replicate_warning(arr.shape[0], self.num_shards),
                UserWarning,
                stacklevel=2,
            )
            return jax.device_put(arr, self.replicated())
        return jax.device_put(arr, self.particle_sharding(arr.ndim))

    def replicate(self, value) -> Any:
        """Place a value replicated on every plan device (no-op without
        a mesh) — pre-placing dispatch inputs keeps ``donate_argnums``
        usable (a buffer that must first be resharded cannot be donated).
        """
        if self.mesh is None:
            return value
        return jax.device_put(value, self.replicated())

    # ------------------------------------------------------------------ #
    # compile

    def compile(
        self,
        fn: Callable,
        *,
        donate_argnums: Union[int, Sequence[int], Tuple] = (),
        static_argnums: Union[int, Sequence[int], Tuple] = (),
        quiet_donation: bool = True,
        label: Optional[str] = None,
        audit: Optional[dict] = None,
    ) -> Callable:
        """Compile ``fn`` under this plan.

        With a mesh: ``jit`` with explicit shardings — every argument
        replicated in, every output replicated back out (the pjit layer
        of SNIPPETS.md [2]); arrays ``fn`` closes over keep their own
        committed shardings (a :meth:`shard_ensemble`'d ensemble stays
        particle-sharded and XLA partitions the reduction).  Without a
        mesh: plain ``jax.jit`` — the exact pre-plan behavior.
        ``donate_argnums``/``static_argnums`` pass through either way.

        ``quiet_donation`` (default True) suppresses XLA's not-usable-
        donation warning around the donating program's lowering call —
        a deliberate donation of a reduction input can never alias an
        output, and the nag would fire once per compiled bucket.  Pass
        False to keep the warning (e.g. when tuning donation on a
        training loop where "not usable" is the regression signal).

        ``label``/``audit`` feed the program registry (module docstring):
        ``label`` names the card, ``audit`` carries the XP-rule
        declarations (``gram_free``, ``pinned_f32``, ``expect_donation``,
        ``particles_arg``, ``allow_f64``).
        """
        if self.mesh is None:
            compiled = jax.jit(fn, donate_argnums=donate_argnums,
                               static_argnums=static_argnums)
        else:
            repl = self.replicated()
            compiled = jax.jit(
                fn,
                in_shardings=repl,
                out_shardings=repl,
                donate_argnums=donate_argnums,
                static_argnums=static_argnums,
            )
        compiled = _track(compiled, fn, kind="compile",
                          num_shards=self.num_shards,
                          donate_argnums=donate_argnums,
                          static_argnums=static_argnums,
                          label=label, audit=audit)
        if quiet_donation and donate_argnums not in ((), None):
            compiled = _quiet_first_call(compiled)
        return compiled

    def spec_sharding(self, spec: Optional[int]) -> Optional[NamedSharding]:
        """Sharding for one ``bind_shard_fn``-style spec entry: ``None`` →
        replicated, an int ``s`` → split along axis ``s`` (trailing axes
        replicated, so one spec serves pytree leaves of mixed rank).
        ``None`` is returned without a mesh (plain-jit semantics)."""
        if self.mesh is None:
            return None
        if spec is None:
            return self.replicated()
        return NamedSharding(self.mesh, P(*([None] * spec), AXIS))

    def compile_sharded(
        self,
        fn: Callable,
        in_specs: Optional[Sequence[Optional[int]]] = None,
        out_specs: Optional[Sequence[Optional[int]]] = None,
        *,
        donate_argnums: Union[int, Sequence[int], Tuple] = (),
        static_argnums: Union[int, Sequence[int], Tuple] = (),
        label: Optional[str] = None,
        audit: Optional[dict] = None,
    ) -> Callable:
        """Compile a *training* step/scan program under this plan — the
        sampler half of the unified compile entrypoint (ROADMAP item 5:
        serving compiled through :meth:`compile` since PR 7; the samplers
        route here so one explicit-sharding path serves any mesh size, and
        an elastic resume at a new shard count recompiles once through the
        same entrypoint instead of growing a private jit per call site).

        ``in_specs`` / ``out_specs`` use ``bind_shard_fn``'s convention
        (``None`` replicated, int = global split axis); with a mesh they
        become explicit ``in_shardings``/``out_shardings`` (the particle
        array stays particle-sharded in and out — unlike :meth:`compile`,
        whose replicated surfaces are serving semantics), without one —
        or with ``in_specs=None`` for programs whose placement the bound
        function already owns — this is plain ``jax.jit``, byte-for-byte
        the pre-plan behavior.

        ``label``/``audit`` feed the program registry exactly as in
        :meth:`compile`.
        """
        if self.mesh is None or in_specs is None:
            compiled = jax.jit(fn, donate_argnums=donate_argnums,
                               static_argnums=static_argnums)
        else:
            if out_specs is None:
                raise ValueError("out_specs is required when in_specs is given")
            out_specs = tuple(out_specs)
            out_sh = (self.spec_sharding(out_specs[0])
                      if len(out_specs) == 1
                      else tuple(self.spec_sharding(s) for s in out_specs))
            compiled = jax.jit(
                fn,
                in_shardings=tuple(self.spec_sharding(s) for s in in_specs),
                out_shardings=out_sh,
                donate_argnums=donate_argnums,
                static_argnums=static_argnums,
            )
        compiled = _track(compiled, fn, kind="compile_sharded",
                          num_shards=self.num_shards,
                          donate_argnums=donate_argnums,
                          static_argnums=static_argnums,
                          label=label, audit=audit)
        if donate_argnums not in ((), None):
            compiled = _quiet_first_call(compiled)
        return compiled


def make_plan(num_shards: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Plan:
    """Build a :class:`Plan` over ``num_shards`` devices.

    ``num_shards=None`` uses every visible device; ``1`` (or a host with
    fewer devices than asked) yields the single-device plan — the same
    graceful degradation ``make_mesh`` gives the samplers, so one code
    path serves laptops and pods.
    """
    if devices is None:
        devices = jax.devices()
    if num_shards is None:
        num_shards = len(devices)
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    return Plan(make_mesh(num_shards, devices))
