"""Static program analysis of compiled plans (the hardware-independent
half of the perf contract).

``registry`` records every ``Plan.compile``/``compile_sharded`` product
with the avals of its first call; ``audit`` re-lowers each recorded
program and distills a **program card** (collective inventory, donation
verdict, n×n detector, dtype story) plus findings under the jaxlint-style
**XP001–XP005** rule family; ``stablehlo`` holds the text-level parsers
both lean on.  ``tools/program_audit.py`` gates the cards against a
committed baseline; ``tests/test_program_audit.py`` enforces the
zero-finding baseline in tier-1.
"""

from dist_svgd_tpu.analysis.audit import (
    COLLECTIVE_PRIMS,
    ProgramCard,
    XP_RULES,
    audit_entry,
    audit_registry,
    xp_findings,
)
from dist_svgd_tpu.analysis.registry import (
    ProgramEntry,
    ProgramRegistry,
    default_registry,
    use_registry,
)

__all__ = [
    "COLLECTIVE_PRIMS",
    "ProgramCard",
    "ProgramEntry",
    "ProgramRegistry",
    "XP_RULES",
    "audit_entry",
    "audit_registry",
    "default_registry",
    "use_registry",
    "xp_findings",
]
