"""Program cards + the XP rule family: static audit of compiled plans.

A **program card** is the audit summary of one compiled entrypoint (one
:class:`~dist_svgd_tpu.analysis.registry.ProgramEntry`): the collective
inventory from walking its jaxpr, the donation/aliasing verdict and
buffer inventory from its lowered StableHLO text, and the dtype story of
inputs vs internals.  Cards are pure data (``as_dict`` round-trips to
JSON) so ``tools/program_audit.py`` can diff them against a committed
baseline on the 2-core CPU box — the hardware-independent proof ROADMAP
items 1–2 kept stalling on.

Findings ride the jaxlint ``Finding`` machinery (same dataclass, same
allowlist) under the **XP** rule family — program-level rules, distinct
from the AST-level JL family because there is no source line to hang a
disable comment on; the path is the pseudo-URL ``plan://<label>`` and the
allowlist (path-suffix matching) is the blessing mechanism:

- **XP001 materialized-nxn** — a program whose call site *declared*
  ``gram_free`` (Pallas φ, or an active rff/nystrom kernel approximation)
  lowered a tensor with two axes equal to the particle count: the Gram
  matrix the whole design exists to avoid is back in HBM.  Exact-φ
  programs legitimately materialize (m, n) tiles and never declare.
- **XP002 collective-in-unsharded-plan** — a plan with ``num_shards == 1``
  lowered cross-device collectives (psum/all_gather/...): either the mesh
  plumbing regressed or a shard_map leaked into the single-device path.
- **XP003 donation-dropped** — ``donate_argnums`` was declared and at
  least one donated leaf has a shape/dtype-matching output to alias, yet
  the lowered module carries fewer aliasing/donor markers than those
  matches: jax dropped the donation silently (the classic "warning
  suppressed, win lost" regression).  Also fires when the call site's
  ``expect_donation`` meta says the program is *supposed* to donate but
  ``donate_argnums`` arrived empty — the "``donate_carries`` stripped"
  red path.  Structurally unaliasable donations (reduction kernels whose
  outputs match no donated input — the serving engine's deliberate case)
  are exempt by construction.
- **XP004 f64-promotion** — f64 tensors materialize inside a program none
  of whose inputs (arguments *or* closed-over constants) are f64: a
  weak-type leak doubled the bandwidth bill.  Tier-1 runs with x64
  enabled, so this keys on the promotion, not on f64 existing.
- **XP005 bf16-pollution** — a program whose call site pinned f32
  (``pinned_f32`` meta) lowered bf16 internals with no bf16 input: the
  low-precision path bled into the pinned one.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dist_svgd_tpu.analysis import stablehlo as shlo
from dist_svgd_tpu.analysis.registry import ProgramEntry, ProgramRegistry

try:  # the repo checkout: share jaxlint's Finding + allowlist machinery
    from tools.jaxlint.core import Finding
except Exception:  # standalone package install without tools/ on the path
    @dataclasses.dataclass(frozen=True)
    class Finding:  # type: ignore[no-redef]
        path: str
        line: int
        rule: str
        message: str

        def format(self) -> str:
            return f"{self.path}:{self.line}: {self.rule} {self.message}"

        def as_dict(self) -> dict:
            return dataclasses.asdict(self)


__all__ = [
    "COLLECTIVE_PRIMS",
    "Finding",
    "ProgramCard",
    "XP_RULES",
    "audit_entry",
    "audit_registry",
    "xp_findings",
]

XP_RULES: Dict[str, str] = {
    "XP001": "materialized n×n buffer in a gram-free-declared program",
    "XP002": "cross-shard collective lowered in a single-shard plan",
    "XP003": "donation declared but aliasing dropped / stripped",
    "XP004": "silent f32→f64 promotion (f64 internals, no f64 input)",
    "XP005": "bf16 pollution of a pinned-f32 program",
}

#: jaxpr primitives that move bytes across the mesh axis.
COLLECTIVE_PRIMS = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "pbroadcast", "reduce_scatter", "psum_scatter",
}

_HLO_DTYPE = {
    "float64": "f64", "float32": "f32", "float16": "f16",
    "bfloat16": "bf16", "int64": "i64", "int32": "i32", "int16": "i16",
    "int8": "i8", "bool": "i1", "uint64": "ui64", "uint32": "ui32",
    "uint16": "ui16", "uint8": "ui8", "complex64": "c64",
    "complex128": "c128",
}


def _hlo_dtype(dt: Any) -> str:
    return _HLO_DTYPE.get(np.dtype(dt).name, np.dtype(dt).name)


def _aval_sig(a: Any) -> str:
    return f"{_hlo_dtype(a.dtype)}[{','.join(str(d) for d in a.shape)}]"


@dataclasses.dataclass
class ProgramCard:
    """One compiled program's audit summary (see module docstring)."""

    label: str
    kind: str                      # 'compile' | 'compile_sharded'
    num_shards: int
    input_signature: str           # "f64[24,3],f64[],i32[]" — the card key
    input_dtypes: List[str]        # args + closed-over consts, sorted
    internal_dtypes: List[str]
    collectives: Dict[str, int]            # prim name -> count
    collective_payload_bytes: Dict[str, int]  # mesh axis -> bytes moved
    donated_leaves: int
    aliasable_leaves: int
    donation_markers: int
    donation_ok: bool
    n_particles: Optional[int]
    nxn_buffers: int
    largest_intermediate_bytes: int
    peak_live_bytes_est: int
    meta: Dict[str, Any]

    @property
    def key(self) -> str:
        """Stable identity across runs: label + first-call signature
        (one serving label covers many buckets — each bucket is its own
        card)."""
        return f"{self.label}({self.input_signature})"

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["key"] = self.key
        return d


# ------------------------------------------------------------------ #
# jaxpr walking

def _sub_jaxprs(value: Any):
    items = value if isinstance(value, (list, tuple)) else (value,)
    for item in items:
        if hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
            yield item.jaxpr          # ClosedJaxpr
        elif hasattr(item, "eqns"):
            yield item                # raw Jaxpr


def _walk_eqns(jaxpr, visit) -> None:
    for eqn in jaxpr.eqns:
        visit(eqn)
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                _walk_eqns(sub, visit)


def _collective_axes(params: dict) -> Tuple[str, ...]:
    axes = params.get("axes", params.get("axis_name", ()))
    if isinstance(axes, (str, int)):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def collective_inventory(closed_jaxpr) -> Tuple[Dict[str, int], Dict[str, int]]:
    """(op -> count, mesh axis -> payload bytes) over the whole program,
    sub-jaxprs (pjit/shard_map/scan bodies) included.  Payload counts each
    collective's *input* bytes once per occurrence in the program text —
    a scanned collective is one occurrence (per-step traffic, which is
    what the card gates; total-step traffic is run-length dependent)."""
    counts: Dict[str, int] = {}
    payload: Dict[str, int] = {}

    def visit(eqn):
        name = eqn.primitive.name
        if name not in COLLECTIVE_PRIMS:
            return
        counts[name] = counts.get(name, 0) + 1
        nbytes = 0
        for v in eqn.invars:
            aval = getattr(v, "aval", None)
            if aval is not None and hasattr(aval, "shape"):
                nbytes += int(np.prod(aval.shape, dtype=np.int64)
                              * np.dtype(aval.dtype).itemsize)
        for axis in _collective_axes(eqn.params) or ("<unnamed>",):
            payload[axis] = payload.get(axis, 0) + nbytes

    _walk_eqns(closed_jaxpr.jaxpr, visit)
    return counts, payload


# ------------------------------------------------------------------ #
# card construction

def _flat_avals(entry: ProgramEntry, argnums: Sequence[int]) -> List[Any]:
    import jax

    out: List[Any] = []
    args = entry.call_args()
    for i in argnums:
        if i < len(args) and i not in entry.static_argnums:
            out.extend(jax.tree_util.tree_leaves(args[i]))
    return out


def _greedy_alias_matches(donated: List[Any], outputs: List[Any]) -> int:
    """How many donated leaves have a shape+dtype-matching output buffer to
    alias (each output matches at most once) — the count of aliasing
    markers a donation-preserving lowering must carry."""
    pool: Dict[Tuple, int] = {}
    for o in outputs:
        k = (tuple(o.shape), np.dtype(o.dtype).name)
        pool[k] = pool.get(k, 0) + 1
    hits = 0
    for d in donated:
        k = (tuple(d.shape), np.dtype(d.dtype).name)
        if pool.get(k, 0) > 0:
            pool[k] -= 1
            hits += 1
    return hits


def audit_entry(entry: ProgramEntry) -> Optional[ProgramCard]:
    """Build the card for one registry entry; ``None`` when the program
    died (weakref cleared) or was never called (no avals to re-lower
    with).  Re-lowering is trace-time work on the entry's captured avals —
    it never executes the program."""
    import jax

    fn = entry.ref()
    if fn is None or not entry.captured:
        return None
    args = entry.call_args()

    closed = jax.make_jaxpr(
        fn, static_argnums=entry.static_argnums or ())(*args)
    counts, payload = collective_inventory(closed)

    text = ""
    if hasattr(fn, "lower"):
        text = fn.lower(*args).as_text()

    traced = [i for i in range(len(args)) if i not in entry.static_argnums]
    in_leaves = _flat_avals(entry, traced)
    const_avals = [jax.ShapeDtypeStruct(np.shape(c), c.dtype)
                   for c in closed.consts if hasattr(c, "dtype")]
    input_dtypes = sorted({_hlo_dtype(a.dtype)
                           for a in in_leaves + const_avals})
    donated = _flat_avals(entry, entry.donate_argnums)
    aliasable = _greedy_alias_matches(donated, list(closed.out_avals))
    markers = shlo.donation_marker_count(text)

    p_arg = entry.meta.get("particles_arg", 0)
    n = None
    if p_arg is not None:  # None = no particle-shaped argument (W2 stacks)
        p_leaves = _flat_avals(entry, (int(p_arg),))
        if p_leaves and len(p_leaves[0].shape) >= 1:
            n = int(p_leaves[0].shape[0])

    return ProgramCard(
        label=entry.label,
        kind=entry.kind,
        num_shards=entry.num_shards,
        input_signature=",".join(_aval_sig(a) for a in in_leaves),
        input_dtypes=input_dtypes,
        internal_dtypes=sorted(shlo.internal_dtypes(text)),
        collectives=dict(sorted(counts.items())),
        collective_payload_bytes=dict(sorted(payload.items())),
        donated_leaves=len(donated),
        aliasable_leaves=aliasable,
        donation_markers=markers,
        donation_ok=(markers >= aliasable),
        n_particles=n,
        nxn_buffers=(shlo.nxn_buffer_count(text, n) if n else 0),
        largest_intermediate_bytes=shlo.largest_tensor_bytes(text),
        peak_live_bytes_est=shlo.peak_live_bytes(text),
        meta=dict(entry.meta),
    )


# ------------------------------------------------------------------ #
# the XP rules (pure on the card — red paths are unit-testable without
# recompiling anything)

def xp_findings(card: ProgramCard) -> List[Finding]:
    path = f"plan://{card.label}"
    meta = card.meta
    out: List[Finding] = []

    if meta.get("gram_free") and card.nxn_buffers > 0:
        out.append(Finding(path, 0, "XP001", (
            f"program declares gram_free but lowers {card.nxn_buffers} "
            f"{card.n_particles}x{card.n_particles} buffer(s) — the Gram "
            "matrix is materialized"
        )))

    if card.num_shards == 1 and card.collectives:
        inv = ", ".join(f"{k}x{v}" for k, v in card.collectives.items())
        out.append(Finding(path, 0, "XP002", (
            f"single-shard plan lowers cross-device collectives ({inv})"
        )))

    if card.donated_leaves and card.donation_markers < card.aliasable_leaves:
        out.append(Finding(path, 0, "XP003", (
            f"donation declared for {card.donated_leaves} leaf/leaves with "
            f"{card.aliasable_leaves} aliasable output match(es), but the "
            f"lowering carries only {card.donation_markers} aliasing/donor "
            "marker(s) — donation silently dropped"
        )))
    elif meta.get("expect_donation") and not card.donated_leaves:
        out.append(Finding(path, 0, "XP003", (
            "call site expects carry donation (expect_donation meta) but "
            "donate_argnums arrived empty — donation stripped"
        )))

    if (not meta.get("allow_f64") and "f64" in card.internal_dtypes
            and "f64" not in card.input_dtypes):
        out.append(Finding(path, 0, "XP004", (
            "f64 tensors materialize inside a program with no f64 input — "
            "weak-type promotion doubled the bandwidth"
        )))

    if (meta.get("pinned_f32") and "bf16" in card.internal_dtypes
            and "bf16" not in card.input_dtypes):
        out.append(Finding(path, 0, "XP005", (
            "bf16 internals in a pinned-f32 program with no bf16 input"
        )))
    return out


def audit_registry(registry: ProgramRegistry, *, label_prefix: str = "",
                   ) -> Tuple[List[ProgramCard], List[Finding]]:
    """Cards + findings for every live, called entry — deduplicated by
    card key (rebuilt kernels for the same label+signature audit once)."""
    cards: Dict[str, ProgramCard] = {}
    for entry in registry.entries(captured_only=True,
                                  label_prefix=label_prefix):
        card = audit_entry(entry)
        if card is not None and card.key not in cards:
            cards[card.key] = card
    ordered = [cards[k] for k in sorted(cards)]
    findings: List[Finding] = []
    for card in ordered:
        findings.extend(xp_findings(card))
    return ordered, findings
