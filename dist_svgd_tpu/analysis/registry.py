"""Program registry: every ``Plan.compile``/``compile_sharded`` product,
observable after the fact.

The auditor (``analysis/audit.py``, ``tools/program_audit.py``,
``tests/test_program_audit.py``) needs two things the compile seam alone
cannot give it: the *set* of programs a run actually compiled, and the
argument avals each was first called with (re-lowering needs concrete
shapes; the compile call itself only sees a Python callable).  So
``Plan`` routes every compiled program through :meth:`ProgramRegistry.
track`, which records an entry and returns a wrapper that snapshots the
first call's ``ShapeDtypeStruct`` tree, then gets out of the way (one
bool check plus one profiler-global read per steady-state dispatch —
the same discipline as plan.py's ``_quiet_first_call``).  The same
wrapper is the runtime hook for ``telemetry/profile.py``: when a
dispatch profiler is enabled, every call is routed through it so fenced
wall time lands on this entry's ``plan://<label>`` identity.

Memory discipline, because this rides *every* compile across a ~600-test
tier-1 run:

- the entry holds a **weakref** to the jit object — the registry never
  extends the life of a compiled executable or the ensemble it closes
  over; a dead entry is skipped by :meth:`entries` and pruned on the next
  :meth:`track`.
- the entry count is **bounded** (FIFO eviction past ``capacity``) so a
  pathological compile loop cannot grow the registry without bound.

Tests and the audit tool that want a private view swap the process
default with :func:`use_registry` (a context manager) — the seam in
plan.py always asks :func:`default_registry` at compile time, never
caches it.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np

# the dispatch profiler's switchboard (telemetry.profile never imports
# analysis, so this edge is acyclic); the steady-state cost while
# profiling is disabled is one module-global read per dispatch
from dist_svgd_tpu.telemetry import profile as _profile

__all__ = [
    "ProgramEntry",
    "ProgramRegistry",
    "default_registry",
    "use_registry",
]


def _as_tuple(v: Union[int, Sequence[int], Tuple]) -> Tuple[int, ...]:
    if v is None:
        return ()
    if isinstance(v, int):
        return (v,)
    return tuple(v)


def _aval_of(x: Any) -> jax.ShapeDtypeStruct:
    dtype = getattr(x, "dtype", None)
    if dtype is None:
        dtype = np.result_type(x)
    return jax.ShapeDtypeStruct(np.shape(x), dtype)


class ProgramEntry:
    """One compiled program: identity, compile-time declarations, and the
    first call's aval snapshot (``None`` until called / if uncapturable).

    ``meta`` carries the call site's audit declarations (the ``audit=``
    kwarg of ``Plan.compile``): ``gram_free`` (the program *claims* no n×n
    Gram materialization — arms XP001), ``pinned_f32`` (arms XP005),
    ``expect_donation`` (XP003's stripped-donation check),
    ``particles_arg`` (which positional arg carries the ``(n, d)``
    ensemble; default 0), ``allow_f64`` (disarms XP004).
    """

    __slots__ = ("seq", "label", "kind", "num_shards", "donate_argnums",
                 "static_argnums", "meta", "ref", "avals", "calls",
                 "prof_cache")

    def __init__(self, seq: int, label: str, kind: str, num_shards: int,
                 donate_argnums: Tuple[int, ...],
                 static_argnums: Tuple[int, ...],
                 meta: Optional[dict], ref: "weakref.ref"):
        self.seq = seq
        self.label = label
        self.kind = kind
        self.num_shards = num_shards
        self.donate_argnums = donate_argnums
        self.static_argnums = static_argnums
        self.meta = dict(meta or {})
        self.ref = ref
        self.avals: Optional[Tuple[Any, ...]] = None
        self.calls = 0
        # (profiler, label dict, rows, bytes) cached by the dispatch
        # profiler on its first profiled call; identity-keyed so a new
        # profiler epoch re-derives it (see telemetry/profile.py)
        self.prof_cache: Optional[tuple] = None

    # -------------------------------------------------------------- #

    @property
    def alive(self) -> bool:
        return self.ref() is not None

    @property
    def captured(self) -> bool:
        return self.avals is not None

    def call_args(self) -> Tuple[Any, ...]:
        """The first call, re-playable against ``lower``/``make_jaxpr``:
        traced positions as ``ShapeDtypeStruct``, static positions as the
        raw Python values the call passed."""
        if self.avals is None:
            raise ValueError(f"program {self.label!r} was never called")
        return self.avals

    def describe(self) -> dict:
        return {
            "label": self.label,
            "kind": self.kind,
            "num_shards": self.num_shards,
            "donate_argnums": list(self.donate_argnums),
            "static_argnums": list(self.static_argnums),
            "meta": dict(self.meta),
            "captured": self.captured,
            "alive": self.alive,
            "calls": self.calls,
        }


class ProgramRegistry:
    """Bounded, thread-safe store of :class:`ProgramEntry` (see module
    docstring for the lifetime rules)."""

    def __init__(self, capacity: int = 256):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._capacity = capacity
        self._lock = threading.Lock()
        self._entries: List[ProgramEntry] = []
        self._seq = itertools.count()

    # -------------------------------------------------------------- #

    def track(self, compiled: Callable, *, label: str, kind: str,
              num_shards: int = 1,
              donate_argnums: Union[int, Sequence[int], Tuple] = (),
              static_argnums: Union[int, Sequence[int], Tuple] = (),
              meta: Optional[dict] = None) -> Callable:
        """Register ``compiled`` and return the aval-capturing wrapper the
        caller should hand out in its place.

        The wrapper delegates every call; the first positional-only call
        additionally snapshots arg avals into the entry.  A call with
        kwargs (no plan call site uses them) skips capture rather than
        guessing at jit's kwarg flattening.
        """
        static = _as_tuple(static_argnums)
        try:
            ref = weakref.ref(compiled)
        except TypeError:
            # unweakrefable callable (builtins, some C wrappers): keep a
            # strong ref — rare enough that the leak rule above tolerates it
            ref = (lambda c=compiled: c)
        with self._lock:
            entry = ProgramEntry(
                next(self._seq), label, kind, num_shards,
                _as_tuple(donate_argnums), static, meta, ref,
            )
            self._entries = [e for e in self._entries if e.alive]
            self._entries.append(entry)
            if len(self._entries) > self._capacity:
                del self._entries[: len(self._entries) - self._capacity]

        state = {"captured": False}
        guard = threading.Lock()

        def dispatch(*args, **kwargs):
            if not state["captured"]:
                with guard:
                    if not state["captured"]:
                        if not kwargs:
                            try:
                                entry.avals = tuple(
                                    args[i] if i in static
                                    else jax.tree_util.tree_map(
                                        _aval_of, args[i])
                                    for i in range(len(args))
                                )
                            except Exception:
                                entry.avals = None
                        state["captured"] = True
            entry.calls += 1
            prof = _profile._PROFILER
            if prof is None:
                return compiled(*args, **kwargs)
            return prof.call(entry, compiled, args, kwargs)

        dispatch.program_entry = entry  # type: ignore[attr-defined]
        return dispatch

    # -------------------------------------------------------------- #

    def entries(self, *, captured_only: bool = False,
                label_prefix: str = "") -> List[ProgramEntry]:
        """Live entries, registration order (a snapshot — safe to iterate
        while other threads compile)."""
        with self._lock:
            snap = list(self._entries)
        return [e for e in snap
                if e.alive
                and (not captured_only or e.captured)
                and e.label.startswith(label_prefix)]

    def clear(self) -> None:
        with self._lock:
            self._entries = []

    def __len__(self) -> int:
        return len(self.entries())


_default = ProgramRegistry()
_default_lock = threading.Lock()


def default_registry() -> ProgramRegistry:
    """The process-wide registry ``Plan`` tracks through (re-read at every
    compile — :func:`use_registry` swaps take effect immediately)."""
    with _default_lock:
        return _default


@contextlib.contextmanager
def use_registry(registry: Optional[ProgramRegistry] = None):
    """Swap the process default for a scope (tests / the audit tool):
    compiles inside the ``with`` land in the scoped registry, the prior
    default is restored on exit.  Process-global: concurrent *other*
    threads' compiles land in the scoped registry too — fine for the
    single-threaded contexts this is built for, documented so nobody
    treats it as thread-local."""
    global _default
    reg = registry if registry is not None else ProgramRegistry()
    with _default_lock:
        prev, _default = _default, reg
    try:
        yield reg
    finally:
        with _default_lock:
            _default = prev
