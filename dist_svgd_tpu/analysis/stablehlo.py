"""Text-level StableHLO facts for the program auditor (no mlir bindings).

Everything here parses the string ``jitted.lower(*avals).as_text()``
returns — deliberately: the in-tree jax 0.4.x MLIR python bindings are
private and version-fragile, while the *textual* StableHLO form of the
three facts the auditor needs has been stable across every jax this repo
has run on:

- **tensor types** — ``tensor<8x128xf32>`` literals carry shape + dtype;
  from them we derive the materialized-buffer inventory, the largest
  intermediate, the n×n detector, and the internal-dtype set.
- **donation markers** — jax records input→output aliasing either as
  ``tf.aliasing_output = k`` (plain jit, shape-matched alias) or as
  ``jax.buffer_donor = true`` (sharded / deferred donation).  A donated
  argument that XLA could not alias carries *no* marker at all — that
  silence is exactly the "donate_argnums set but aliasing silently
  dropped" failure XP003 exists to catch, so the marker *count* is the
  signal, not the marker text.
- **peak live bytes** — a linear-scan liveness estimate over the ``@main``
  body: each SSA value goes live at its defining line and dies after its
  last textual use.  It ignores control-flow region overlap and XLA's
  later fusion/rematerialization, so it is an *estimate* — good enough to
  flag a program whose live set jumped from O(n·d) to O(n²), which is the
  regression the card gates on (exact HBM numbers stay a TPU-profiler
  job).
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = [
    "DTYPE_BYTES",
    "donation_marker_count",
    "internal_dtypes",
    "iter_tensor_types",
    "main_body_lines",
    "nxn_buffer_count",
    "peak_live_bytes",
    "tensor_bytes",
]

#: ``tensor<8x128xf32>`` / ``tensor<f64>`` / ``tensor<4xi1>`` — shape dims
#: then one element-type token.  Dynamic (``?``) dims never appear in this
#: repo's programs (every plan is shape-bucketed); a type containing one
#: simply does not match and is ignored.
_TENSOR_RE = re.compile(r"tensor<((?:\d+x)*)([a-z][a-z0-9]*)>")

_SSA_RE = re.compile(r"%[A-Za-z0-9_#.:]+")

DTYPE_BYTES: Dict[str, int] = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "i64": 8, "i32": 4, "i16": 2, "i8": 1, "i1": 1,
    "ui64": 8, "ui32": 4, "ui16": 2, "ui8": 1,
    "c64": 8, "c128": 16,
}


def iter_tensor_types(text: str) -> Iterable[Tuple[Tuple[int, ...], str]]:
    """Every ``(shape, dtype)`` tensor-type literal in ``text``, in order
    (duplicates included — counts matter for the buffer inventory)."""
    for m in _TENSOR_RE.finditer(text):
        dims = m.group(1)
        shape = tuple(int(d) for d in dims.split("x") if d) if dims else ()
        yield shape, m.group(2)


def tensor_bytes(shape: Tuple[int, ...], dtype: str) -> int:
    n = 1
    for d in shape:
        n *= d
    return n * DTYPE_BYTES.get(dtype, 4)


def donation_marker_count(text: str) -> int:
    """Input→output aliasing annotations present in the lowered module —
    both spellings (see module docstring).  0 for a program that donates
    nothing *or* whose donation XLA silently dropped."""
    return text.count("tf.aliasing_output") + text.count("jax.buffer_donor")


def main_body_lines(text: str) -> List[str]:
    """The op lines of the first ``func.func`` body (``@main`` in every
    jax lowering), without the signature line — the signature's argument
    types are *inputs*, not internals, and must not pollute the
    internal-dtype / liveness scans."""
    lines = text.splitlines()
    out: List[str] = []
    depth = 0
    started = False
    for line in lines:
        if not started:
            if "func.func" in line:
                started = True
                depth = line.count("{") - line.count("}")
            continue
        depth += line.count("{") - line.count("}")
        if depth <= 0:
            break
        out.append(line)
    return out


def internal_dtypes(text: str) -> Set[str]:
    """Element dtypes of tensors materialized *inside* the main body
    (signature/input types excluded)."""
    found: Set[str] = set()
    for line in main_body_lines(text):
        for _shape, dtype in iter_tensor_types(line):
            found.add(dtype)
    return found


def nxn_buffer_count(text: str, n: int) -> int:
    """Distinct body lines materializing a tensor with >= 2 axes equal to
    ``n`` — the n×n (Gram-shaped) detector.  ``n < 2`` never matches
    (axis-1 collisions are meaningless)."""
    if n < 2:
        return 0
    count = 0
    for line in main_body_lines(text):
        for shape, _dtype in iter_tensor_types(line):
            if sum(1 for d in shape if d == n) >= 2:
                count += 1
                break  # one hit per line: a line = one op's result
    return count


def largest_tensor_bytes(text: str) -> int:
    best = 0
    for line in main_body_lines(text):
        for shape, dtype in iter_tensor_types(line):
            best = max(best, tensor_bytes(shape, dtype))
    return best


def _result_types(line: str) -> List[Tuple[Tuple[int, ...], str]]:
    """Tensor types of the values a body line *defines*.  For functional
    types (``... : (tensor<a>) -> tensor<b>``) only the arrow's right side
    counts; otherwise every tensor literal after the last ``:`` does."""
    if "->" in line:
        seg = line.rsplit("->", 1)[1]
    elif ":" in line:
        seg = line.rsplit(":", 1)[1]
    else:
        return []
    return list(iter_tensor_types(seg))


def peak_live_bytes(text: str) -> int:
    """Linear-scan liveness estimate over the main body (see module
    docstring for what this deliberately ignores)."""
    lines = main_body_lines(text)
    defs: List[Tuple[int, List[str], int]] = []  # (line_idx, names, bytes)
    last_use: Dict[str, int] = {}
    for i, line in enumerate(lines):
        head, _, _tail = line.partition("=")
        names = _SSA_RE.findall(head) if "=" in line else []
        for tok in _SSA_RE.findall(line):
            last_use[tok] = i
        if names:
            size = sum(tensor_bytes(s, d) for s, d in _result_types(line))
            defs.append((i, names, size))
    # death line per defined value group
    peak = live = 0
    deaths: Dict[int, int] = {}  # line -> bytes released after it
    for i, names, size in defs:
        die = max((last_use.get(nm, i) for nm in names), default=i)
        deaths[die] = deaths.get(die, 0) + size
    idx = 0
    for i, _line in enumerate(lines):
        while idx < len(defs) and defs[idx][0] == i:
            live += defs[idx][2]
            idx += 1
        peak = max(peak, live)
        live -= deaths.get(i, 0)
    return peak
