"""dist_svgd_tpu — a TPU-native framework for distributed Stein Variational
Gradient Descent (SVGD).

Brand-new JAX/XLA/pjit design with the capabilities of the reference
implementation `Sandy4321/dist-svgd` (see SURVEY.md):

- `Sampler`        — single-device SVGD sampler (reference: dsvgd/sampler.py:6-74)
- `DistSampler`    — sharded SVGD over a TPU mesh with three exchange modes
                     (reference: dsvgd/distsampler.py:8-205)
- `ops`            — fused kernel/φ/step primitives (jit/vmap, analytic ∇k)
                     and the Wasserstein/JKO term (host LP + on-device Sinkhorn)
- `models`         — GMM and Bayesian logistic regression log-densities
- `parallel`       — mesh utilities + SPMD exchange strategies
- `serving`        — posterior-predictive serving of checkpointed ensembles
                     (micro-batched engine + HTTP front end + checkpoint
                     hot reload; import `dist_svgd_tpu.serving` explicitly
                     — not loaded here)
- `resilience`     — fault-tolerant training: supervised segmented runs
                     with periodic/signal checkpointing, bitwise-exact
                     resume, retry/backoff, numerical guards, and a
                     deterministic fault-injection harness (import
                     `dist_svgd_tpu.resilience` explicitly)
- `telemetry`      — unified observability: thread-safe metrics registry
                     (counters/gauges/histograms, Prometheus exposition)
                     + span tracer (nestable thread-aware spans, Chrome
                     trace / JSONL export, zero-cost while disabled);
                     train, resilience, and serving are instrumented with
                     it (import `dist_svgd_tpu.telemetry` explicitly)
- `utils`          — datasets, history recording, RNG helpers

Where the reference evaluates k(x, y) and its autograd one particle-pair at a
time in Python loops, this framework computes each SVGD step as a single fused
XLA program over an HBM-resident (n, d) particle array and shards particles
across a `jax.sharding.Mesh` with `lax.all_gather` / `lax.psum` /
`lax.ppermute` collectives.
"""

from dist_svgd_tpu.sampler import Sampler
from dist_svgd_tpu.distsampler import DistSampler
from dist_svgd_tpu.ops.approx import KernelApprox
from dist_svgd_tpu.ops.kernels import (
    RBF,
    AdaptiveRBF,
    median_bandwidth,
    median_bandwidth_approx,
)

__version__ = "0.0.1"  # matches pyproject.toml (reference packaging: setup.py v0.0.1)

__all__ = [
    "Sampler",
    "DistSampler",
    "RBF",
    "AdaptiveRBF",
    "KernelApprox",
    "median_bandwidth",
    "median_bandwidth_approx",
    "__version__",
]
