"""Single-device SVGD sampler.

TPU-native counterpart of the reference's ``Sampler``
(dsvgd/sampler.py:6-74): same public shape —
``Sampler(d, logp, kernel).sample(n, num_iter, step_size)`` returning a
pandas DataFrame with columns ``timestep / particle / value`` — but the whole
run is one jitted ``lax.scan`` over a fused Jacobi step instead of a Python
double loop with two autograd graphs per particle pair.

History follows the reference's exact timestep convention: a snapshot *before*
each update at timesteps ``0..num_iter-1`` plus one final post-update snapshot
at ``num_iter`` (dsvgd/sampler.py:62-73, SURVEY.md §7.4).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dist_svgd_tpu.ops.kernels import RBF
from dist_svgd_tpu.ops.svgd import phi, svgd_step_sequential
from dist_svgd_tpu.utils.history import history_to_dataframe
from dist_svgd_tpu.utils.rng import as_key, init_particles


class Sampler:
    """Model-agnostic SVGD sampler.

    Args:
        d: particle dimensionality.
        logp: scalar log-density ``logp(theta)`` with ``theta`` of shape
            ``(d,)`` — a user-supplied JAX-traceable closure, mirroring the
            reference's model-agnostic design (dsvgd/sampler.py:7-17).
        kernel: :class:`RBF` instance or scalar kernel callable; defaults to
            the reference's ``RBF(bandwidth=1)``.
        update_rule: ``'jacobi'`` (vectorised, TPU-native default) or
            ``'gauss_seidel'`` (the reference's sequential in-place sweep via
            ``lax.scan``, for small-n parity — SURVEY.md §3.2).
    """

    def __init__(
        self,
        d: int,
        logp: Callable,
        kernel=None,
        update_rule: str = "jacobi",
    ):
        if update_rule not in ("jacobi", "gauss_seidel"):
            raise ValueError(f"unknown update_rule {update_rule!r}")
        self._d = d
        self._logp = logp
        self._kernel = kernel if kernel is not None else RBF(1.0)
        self._update_rule = update_rule
        self._score_fn = jax.grad(logp)
        self._compiled = {}

    # ------------------------------------------------------------------ #

    def _run_fn(self, num_iter: int, record: bool):
        """Build (and cache) the jitted scan over `num_iter` steps."""
        cache_key = (num_iter, record)
        if cache_key in self._compiled:
            return self._compiled[cache_key]

        batched_score = jax.vmap(self._score_fn)
        kernel = self._kernel
        update_rule = self._update_rule

        def one_step(parts, step_size):
            if update_rule == "jacobi":
                scores = batched_score(parts)
                return parts + step_size * phi(parts, parts, scores, kernel)
            return svgd_step_sequential(parts, self._score_fn, step_size, kernel)

        @partial(jax.jit, static_argnums=())
        def run(particles, step_size):
            def body(parts, _):
                new = one_step(parts, step_size)
                if record:
                    return new, parts  # pre-update snapshot (reference convention)
                return new, None

            final, hist = lax.scan(body, particles, None, length=num_iter)
            return final, hist

        self._compiled[cache_key] = run
        return run

    # ------------------------------------------------------------------ #

    def run(
        self,
        n: int,
        num_iter: int,
        step_size: float,
        seed=0,
        record: bool = True,
        initial_particles: Optional[jax.Array] = None,
        dtype=None,
    ):
        """Raw-array variant of :meth:`sample`.

        Returns ``(final_particles, history)`` where ``history`` is a
        ``(num_iter + 1, n, d)`` device array (pre-update snapshots plus the
        final state) or ``None`` when ``record=False``.  ``dtype`` defaults to
        the dtype of ``initial_particles`` when given, else float32.
        """
        if initial_particles is not None:
            particles = jnp.asarray(initial_particles, dtype=dtype)
        else:
            particles = init_particles(as_key(seed), n, self._d, dtype=dtype or jnp.float32)
        run = self._run_fn(num_iter, record)
        final, hist = run(particles, jnp.asarray(step_size, dtype=particles.dtype))
        if record:
            hist = jnp.concatenate([hist, final[None]], axis=0)
        return final, hist

    def sample(
        self,
        n: int,
        num_iter: int,
        step_size: float,
        seed=0,
        initial_particles: Optional[jax.Array] = None,
    ):
        """Generate samples using SVGD — reference API (dsvgd/sampler.py:42-74).

        Returns a pandas DataFrame with columns ``timestep`` (0..num_iter),
        ``particle`` (0..n), ``value`` (numpy ``(d,)`` vector).
        """
        _, hist = self.run(
            n, num_iter, step_size, seed=seed, record=True,
            initial_particles=initial_particles,
        )
        return history_to_dataframe(np.asarray(hist))
