"""Single-device SVGD sampler.

TPU-native counterpart of the reference's ``Sampler``
(dsvgd/sampler.py:6-74): same public shape —
``Sampler(d, logp, kernel).sample(n, num_iter, step_size)`` returning a
pandas DataFrame with columns ``timestep / particle / value`` — but the whole
run is one jitted ``lax.scan`` over a fused Jacobi step instead of a Python
double loop with two autograd graphs per particle pair.

History follows the reference's exact timestep convention: a snapshot *before*
each update at timesteps ``0..num_iter-1`` plus one final post-update snapshot
at ``num_iter`` (dsvgd/sampler.py:62-73, SURVEY.md §7.4).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dist_svgd_tpu.ops.approx import (
    approx_preferred,
    as_kernel_approx,
    bind_phi_step,
    is_gram_free,
)
from dist_svgd_tpu.ops.kernels import RBF, AdaptiveRBF
from dist_svgd_tpu.ops.svgd import svgd_step_sequential
from dist_svgd_tpu.parallel.plan import Plan
from dist_svgd_tpu.telemetry import profile as _profile
from dist_svgd_tpu.telemetry import trace as _trace
from dist_svgd_tpu.utils.history import history_to_dataframe
from dist_svgd_tpu.utils.rng import as_key, draw_minibatch, init_particles, minibatch_key


class Sampler:
    """Model-agnostic SVGD sampler.

    Args:
        d: particle dimensionality.
        logp: scalar log-density ``logp(theta)`` with ``theta`` of shape
            ``(d,)`` — a user-supplied JAX-traceable closure, mirroring the
            reference's model-agnostic design (dsvgd/sampler.py:7-17).  When
            ``data`` is given the signature is ``logp(theta, data_batch)``
            instead.
        kernel: :class:`RBF` instance or scalar kernel callable; defaults to
            the reference's ``RBF(bandwidth=1)``.  The string ``'median'``
            selects an RBF whose bandwidth is resolved **per run** from the
            initial particles via the median heuristic
            (:func:`~dist_svgd_tpu.ops.kernels.median_bandwidth`, Liu & Wang
            2016 eq. 13) — each distinct resolved bandwidth compiles its own
            scan program.  The string ``'median_step'`` (equivalently an
            :class:`~dist_svgd_tpu.ops.kernels.AdaptiveRBF` instance)
            instead re-resolves the bandwidth from the **current** particles
            on every step, inside the jitted scan
            (:func:`~dist_svgd_tpu.ops.kernels.median_bandwidth_approx`; one
            compiled program regardless of how the bandwidth evolves) —
            Jacobi update rule only.
        update_rule: ``'jacobi'`` (vectorised, TPU-native default) or
            ``'gauss_seidel'`` (the reference's sequential in-place sweep via
            ``lax.scan``, for small-n parity — SURVEY.md §3.2).
        data: optional pytree of arrays with a common leading data axis,
            passed to ``logp`` (full, or a per-step minibatch when
            ``batch_size`` is set).
        batch_size: per-step minibatch size B.  Each step draws B rows
            without replacement (fresh fold of the run's seed) and scales the
            data-dependent score by ``N / B`` — an unbiased stochastic score,
            the writeup's minibatch approximation (writeup.tex:214-231,
            BASELINE.json config 4).  Requires ``data``.
        log_prior: optional ``log_prior(theta)``.  When given, ``logp`` is
            treated as pure likelihood: only it is minibatch-scaled and the
            prior gradient is added once, unscaled.  When omitted the ``N/B``
            factor scales the whole ``logp`` gradient — the reference's
            importance-scaling convention, which scales its prior term too
            (dsvgd/distsampler.py:96-99).
        phi_impl: ``'auto'`` (Pallas fused-tile φ on TPU with an RBF kernel
            at Gram-bound sizes, XLA otherwise — see
            ``ops.pallas_svgd.resolve_phi_fn``), ``'xla'``, or ``'pallas'``
            (force; requires an RBF kernel).
        kernel_approx: ``None`` (exact Gram φ), ``'rff'``, ``'nystrom'``,
            or a :class:`~dist_svgd_tpu.ops.approx.KernelApprox` — the
            sub-quadratic φ (``ops/approx.py``) with its explicit
            ``num_features``/``num_landmarks`` accuracy dial.  With
            ``phi_impl='auto'`` the (n, R) crossover picks exact vs
            approximate per :meth:`run` call from that run's n (exact is
            faster AND exact below it); ``'xla'`` forces the
            approximation.  The RFF bank derives from each run's ``seed``
            (``utils/rng.py:approx_bank_key``) at the bandwidth frozen by
            then — ``kernel='median'`` resolves *before* the bank is
            built, and ``'median_step'`` + ``'rff'`` is refused in one
            line at the default ``rff_redraw='run'``
            (``KernelApprox('rff', rff_redraw='step')`` lifts it: the
            bank re-folds from ``(bank_root, t)`` every step inside the
            program; ``'nystrom'`` composes either way).  Jacobi only.
        donate_carries: donate the scan carry (the particle array) to XLA
            at every run/chunk dispatch — no per-dispatch re-allocation;
            bitwise-identical results (``tools/profile_step_floor.py
            --donate-ab``).  A caller-supplied ``initial_particles`` array
            is defensively copied first, so caller buffers are never
            invalidated.
    """

    def __init__(
        self,
        d: int,
        logp: Callable,
        kernel=None,
        update_rule: str = "jacobi",
        data=None,
        batch_size: Optional[int] = None,
        log_prior: Optional[Callable] = None,
        phi_impl: str = "auto",
        kernel_approx=None,
        donate_carries: bool = True,
    ):
        if update_rule not in ("jacobi", "gauss_seidel"):
            raise ValueError(f"unknown update_rule {update_rule!r}")
        if batch_size is not None and data is None:
            raise ValueError("batch_size requires data")
        if batch_size is not None and update_rule != "jacobi":
            raise ValueError("minibatching supports only the jacobi update rule")
        self._d = d
        self._logp = logp
        self._median_kernel = kernel == "median"
        if self._median_kernel:
            kernel = RBF(1.0)  # placeholder until run() resolves the bandwidth
        if kernel == "median_step":
            kernel = AdaptiveRBF()
        if update_rule != "jacobi" and isinstance(kernel, AdaptiveRBF):
            # the gauss_seidel sweep evaluates the kernel directly
            # (svgd_step_sequential), which a per-step-median marker cannot
            # do — and the sweep exists for reference parity, which has no
            # adaptive bandwidth
            raise ValueError(
                "kernel='median_step' requires update_rule='jacobi'"
            )
        self._kernel = kernel if kernel is not None else RBF(1.0)
        self._update_rule = update_rule
        self._data = None if data is None else jax.tree_util.tree_map(jnp.asarray, data)
        self._n_rows = (
            jax.tree_util.tree_leaves(self._data)[0].shape[0]
            if self._data is not None
            else 0
        )
        self._batch_size = batch_size
        if batch_size is not None and not 0 < batch_size <= self._n_rows:
            raise ValueError(
                f"batch_size {batch_size} not in (0, {self._n_rows}] rows"
            )
        self._log_prior = log_prior

        from dist_svgd_tpu.ops.pallas_svgd import resolve_phi_fn

        if phi_impl.startswith("pallas") and update_rule != "jacobi":
            # the gauss_seidel sweep never calls φ through self._phi, so a
            # forced pallas choice would silently no-op
            raise ValueError(f"phi_impl={phi_impl!r} requires update_rule='jacobi'")
        self._phi_impl = phi_impl
        self._donate = bool(donate_carries)
        self._approx = as_kernel_approx(kernel_approx)
        self._approx_active = False
        if self._approx is not None:
            if update_rule != "jacobi":
                raise ValueError(
                    "kernel_approx requires update_rule='jacobi': the "
                    "Gauss-Seidel sweep exists for literal reference "
                    "parity, which an approximate kernel cannot provide"
                )
            # validate through the ONE policy seam (pallas/AdaptiveRBF+rff
            # refusals); the real bank key arrives with run()'s seed
            from dist_svgd_tpu.utils.rng import approx_bank_key

            va = self._approx
            if va.method == "rff" and va.key is None:
                va = va.with_key(approx_bank_key(0))
            resolve_phi_fn(self._kernel, phi_impl, 1, va)
        self._phi = self._resolve_phi()
        if data is None:
            if log_prior is not None:
                full = lambda theta: logp(theta) + log_prior(theta)
            else:
                full = logp
        else:
            if log_prior is not None:
                full = lambda theta: logp(theta, self._data) + log_prior(theta)
            else:
                full = lambda theta: logp(theta, self._data)
        self._score_fn = jax.grad(full)
        # the single-device plan (ROADMAP item 5: one compile entrypoint
        # for serving and BOTH samplers) — Plan(None).compile is plain jit,
        # byte-for-byte the pre-plan behavior
        self._plan = Plan(None)
        self._compiled = {}
        #: Execution report of the most recent :meth:`run` call (mode,
        #: dispatch counts, steps per dispatch) — see ``DistSampler.
        #: last_run_stats`` for the sharded counterpart.
        self.last_run_stats = None

    # ------------------------------------------------------------------ #

    @property
    def kernel_approx(self):
        """The resolved :class:`~dist_svgd_tpu.ops.approx.KernelApprox`
        (RFF bank key bound once a run has derived it), or ``None``."""
        return self._approx

    @property
    def kernel_approx_active(self) -> bool:
        """Whether the most recent :meth:`run`'s φ used the approximate
        backend (the per-run (n, R) crossover under ``phi_impl='auto'``;
        always true with ``'xla'`` + ``kernel_approx``)."""
        return self._approx is not None and self._approx_active

    def _phi_token(self):
        """The part of the compile-cache key that tracks the φ closure's
        identity beyond the kernel bandwidth (approximation spec + pinned
        crossover decision)."""
        if self._approx is None:
            return None
        return (self._approx.cache_token(), self._approx_active)

    def _resolve_phi(self):
        """Rebuild the φ backend from the current kernel + approximation
        state.  With the approximation pinned active the builder sees the
        always-approximate combination; inactive (or unconfigured), the
        exact configuration — one decision per run, like DistSampler's
        global-shape pin."""
        from dist_svgd_tpu.ops.pallas_svgd import resolve_phi_fn

        if self._approx is not None and self._approx_active:
            return resolve_phi_fn(self._kernel, "xla", 1, self._approx)
        return resolve_phi_fn(self._kernel, self._phi_impl)

    def _pin_approx(self, n: int, seed) -> None:
        """Per-run approximation resolution: bind the run's RFF bank key
        (``approx_bank_key(seed)``) and pin the (n, R) crossover, then
        rebuild φ if either changed.  No-op for exact samplers."""
        if self._approx is None:
            return
        from dist_svgd_tpu.utils.rng import approx_bank_key

        changed = False
        if self._approx.method == "rff":
            bkey = approx_bank_key(seed)
            if (self._approx.key is None
                    or not np.array_equal(np.asarray(self._approx.key),
                                          np.asarray(bkey))):
                self._approx = self._approx.with_key(bkey)
                changed = True
        active = (approx_preferred(n, n, self._approx.feature_count)
                  if self._phi_impl == "auto" else True)
        if active != self._approx_active:
            self._approx_active = active
            changed = True
        if changed or self._phi is None:
            self._phi = self._resolve_phi()

    def approx_residual(self, particles=None, max_points: int = 512,
                        seed=0, registry=None) -> dict:
        """Measure the configured approximation's φ residual (exact vs
        approximate φ over a strided ≤``max_points`` subsample, scores from
        this sampler's own ``∇log p``) and publish it as
        ``svgd_diag_phi_approx_*`` gauges — the posterior-health channel
        for approximate runs.  ``particles`` defaults to a fresh
        ``init_particles`` draw at ``max_points`` (pre-run probing);
        pass the current ensemble to probe a live run."""
        from dist_svgd_tpu.ops.approx import (
            phi_residual_report,
            record_phi_residual,
        )

        if self._approx is None:
            raise ValueError(
                "approx_residual needs kernel_approx (exact runs have no "
                "approximation residual to measure)"
            )
        if particles is None:
            particles = init_particles(as_key(seed), max_points, self._d)
        particles = jnp.asarray(particles)
        if particles.shape[0] > max_points:
            stride = -(-particles.shape[0] // max_points)
            particles = particles[::stride]
        # probe-local spec: binding a bank key for a never-run sampler must
        # NOT rebind the live run's bank or re-pin its crossover (the probe
        # subsample's tiny shape would flip 'active' and rebuild phi)
        spec = self._approx
        if spec.method == "rff" and spec.key is None:
            from dist_svgd_tpu.utils.rng import approx_bank_key

            spec = spec.with_key(approx_bank_key(seed))
        scores = jax.vmap(self._score_fn)(particles)
        if isinstance(self._kernel, RBF):
            kernel = self._kernel
        else:  # AdaptiveRBF: probe at the current per-step median bandwidth
            from dist_svgd_tpu.ops.kernels import median_bandwidth_approx

            kernel = RBF(float(median_bandwidth_approx(particles)))
        report = phi_residual_report(particles, scores, kernel, spec,
                                     max_points=max_points)
        report["active"] = bool(self._approx_active)
        record_phi_residual(report, registry=registry)
        return report

    def _minibatch_scores(self, parts, key, data=None):
        """Stochastic scores: N/B-scaled batch-likelihood gradient (+ unscaled
        prior gradient when ``log_prior`` is separate).  ``data`` is a traced
        argument of the jitted scan, NOT a closure constant — baking the
        dataset in at trace time would silently train on stale rows after
        :meth:`set_data` (the streaming path's whole point).  Eager callers
        may omit it to score against the live corpus."""
        if data is None:
            data = self._data
        batch, scale = draw_minibatch(key, data, self._n_rows, self._batch_size)
        scores = scale * jax.vmap(jax.grad(self._logp), in_axes=(0, None))(parts, batch)
        if self._log_prior is not None:
            scores = scores + jax.vmap(jax.grad(self._log_prior))(parts)
        return scores

    def set_data(self, data) -> None:
        """Swap the minibatch dataset in place (streaming ingest).

        Requires minibatch mode and a replacement with the **identical**
        pytree structure, leaf shapes, and dtypes — the compiled scan takes
        data as a traced argument, so a shape-stable swap reuses the cached
        executable with zero recompiles (streaming sources keep shapes
        fixed via a capacity-bound ring for exactly this reason).  The
        eager diagnostics score (``_score_fn``) reads ``self._data`` at
        call time, so post-swap KSD/ESS judge the posterior against the
        NEW data."""
        if self._batch_size is None:
            raise ValueError("set_data requires minibatch mode (batch_size)")
        new = jax.tree_util.tree_map(jnp.asarray, data)
        old_spec = jax.tree_util.tree_map(
            lambda a: (a.shape, a.dtype), self._data)
        new_spec = jax.tree_util.tree_map(lambda a: (a.shape, a.dtype), new)
        if old_spec != new_spec:
            raise ValueError(
                f"set_data requires an identical data spec (shape/dtype "
                f"pytree) — a changed spec would retrace the scan; got "
                f"{new_spec} vs current {old_spec}"
            )
        self._data = new

    def _resolve_median_kernel(self, particles) -> None:
        """``kernel='median'``: bind an RBF at the median-heuristic bandwidth
        of this run's initial particles (idempotent per bandwidth — the
        compile cache below is keyed by it)."""
        from dist_svgd_tpu.ops.kernels import median_bandwidth

        h = float(median_bandwidth(particles))
        if self._kernel != RBF(h):
            # bandwidth freeze ordering: the kernel is rebound BEFORE φ is
            # re-resolved, so an RFF bank is always constructed at the
            # frozen median bandwidth, never the placeholder
            self._kernel = RBF(h)
            self._phi = self._resolve_phi()

    def freeze_median_kernel(self, particles) -> float:
        """Resolve ``kernel='median'`` from ``particles`` NOW and pin the
        resulting bandwidth for every later :meth:`run` call.

        A segmented drive (``resilience.RunSupervisor``, or any manual
        chunking via repeated ``initial_particles`` calls) must not let each
        segment re-resolve the bandwidth from its own start state — that
        would optimise a different kernel per segment and break
        resume-exactness.  Returns the pinned bandwidth (record it in resume
        state; re-pin with ``freeze_median_kernel`` is idempotent).  No-op
        (returns the current bandwidth) for fixed-bandwidth kernels; raises
        for ``'median_step'``, whose per-step re-resolution lives inside the
        jitted scan and is already segment-invariant."""
        if isinstance(self._kernel, AdaptiveRBF):
            raise ValueError(
                "kernel='median_step' re-resolves inside the scan and needs "
                "no freezing"
            )
        if self._median_kernel:
            self._resolve_median_kernel(jnp.asarray(particles))
            self._median_kernel = False
        return float(self._kernel.bandwidth)

    def pin_kernel_bandwidth(self, bandwidth: float) -> None:
        """Bind a fixed ``RBF(bandwidth)`` and disable any pending
        ``kernel='median'`` per-run resolution — the restore path of
        :meth:`freeze_median_kernel` (a resumed supervised run re-pins the
        bandwidth recorded in its checkpoint instead of re-resolving from
        the resumed particles)."""
        self._median_kernel = False
        if self._kernel != RBF(float(bandwidth)):
            self._kernel = RBF(float(bandwidth))
            self._phi = self._resolve_phi()

    def _run_fn(self, num_iter: int, record: bool):
        """Build (and cache) the jitted scan over `num_iter` steps."""
        cache_key = (num_iter, record, self._kernel.bandwidth
                     if isinstance(self._kernel, RBF) else None,
                     self._phi_token())
        if cache_key in self._compiled:
            return self._compiled[cache_key]

        batched_score = jax.vmap(self._score_fn)
        kernel = self._kernel
        update_rule = self._update_rule
        minibatch = self._batch_size is not None

        phi_fn = self._phi

        def one_step(parts, step_size, step_key, step_idx, data):
            # redraw-per-step RFF folds its bank from the same absolute
            # index the minibatch key uses (ops/approx.py:bind_phi_step) —
            # a no-op wrapper for every other φ backend
            phi_t = bind_phi_step(phi_fn, step_idx)
            if minibatch:
                scores = self._minibatch_scores(parts, step_key, data)
                return parts + step_size * phi_t(parts, parts, scores)
            if update_rule == "jacobi":
                scores = batched_score(parts)
                return parts + step_size * phi_t(parts, parts, scores)
            return svgd_step_sequential(parts, self._score_fn, step_size, kernel)

        def scan_body(particles, step_size, batch_key, i0, data):
            # i0 offsets the per-step key fold so a budget-chunked run
            # (dispatch_budget) draws the SAME minibatch stream as one
            # monolithic scan — chunk boundaries are invisible to the RNG
            def body(parts, i):
                new = one_step(parts, step_size,
                               jax.random.fold_in(batch_key, i0 + i),
                               i0 + i, data)
                if record:
                    return new, parts  # pre-update snapshot (reference convention)
                return new, None

            final, hist = lax.scan(body, particles, jnp.arange(num_iter))
            return final, hist

        if minibatch:
            # minibatch mode traces the dataset as a real argument so
            # set_data swaps rows without invalidating this cache entry —
            # a closure-captured dataset would be baked into the
            # executable as a constant (stale-data hazard)
            def scan_run(particles, step_size, batch_key, i0, data):
                return scan_body(particles, step_size, batch_key, i0, data)
        else:
            def scan_run(particles, step_size, batch_key, i0):
                return scan_body(particles, step_size, batch_key, i0, None)

        # carry donation (ROADMAP item 1): the particle buffer aliases the
        # output at every dispatch — run() owns/copies the input, so no
        # caller buffer is ever invalidated
        run = self._plan.compile(
            scan_run, donate_argnums=(0,) if self._donate else (),
            label="sampler.scan",
            audit=dict(
                gram_free=is_gram_free(self._phi_impl,
                                       self.kernel_approx_active),
                expect_donation=self._donate,
            ))
        self._compiled[cache_key] = run
        return run

    # ------------------------------------------------------------------ #

    def run(
        self,
        n: int,
        num_iter: int,
        step_size: float,
        seed=0,
        record: bool = True,
        initial_particles: Optional[jax.Array] = None,
        dtype=None,
        dispatch_budget: Optional[float] = None,
        pairs_per_sec: Optional[float] = None,
        step_offset: int = 0,
    ):
        """Raw-array variant of :meth:`sample`.

        Returns ``(final_particles, history)`` where ``history`` is a
        ``(num_iter + 1, n, d)`` array (pre-update snapshots plus the
        final state) or ``None`` when ``record=False``.  ``dtype`` defaults to
        the dtype of ``initial_particles`` when given, else float32.

        ``step_offset`` is the absolute index of this call's first step in a
        longer logical run: it offsets the per-step minibatch key fold (and
        nothing else), so a segmented drive — ``resilience.RunSupervisor``,
        or manual resume via ``initial_particles`` — draws the exact
        minibatch stream the monolithic run would.  Without ``batch_size``
        it is inert.

        Recorded histories are **HBM-budget chunked** automatically: when the
        ``(num_iter, n, d)`` pre-update stack would exceed
        :data:`~dist_svgd_tpu.utils.history.RECORD_HBM_BUDGET_BYTES`
        (TPU lane padding counted — each snapshot is physically
        ``n × max(d, 128)`` floats), the run splits into
        :func:`~dist_svgd_tpu.utils.history.record_chunk_steps`-sized scan
        dispatches whose history chunks are fetched to host while the next
        chunk's scan runs (the D2H copy overlaps compute on hosts with an
        async transfer engine).  Whenever the run chunks with
        ``record=True`` the returned history is a **host** ``np.ndarray``
        (holding it on device would defeat the budget); monolithic runs
        return the device array as before.

        ``dispatch_budget`` (seconds) splits the run into multiple scan
        dispatches of at most that estimated duration (pair throughput from
        ``pairs_per_sec``, default :data:`dist_svgd_tpu.distsampler.
        DISPATCH_PAIRS_PER_SEC`) — the built-in form of the chunked-record
        pattern below, with both of its caveats handled internally: the
        per-step minibatch key fold is offset per chunk so the stream
        equals the monolithic one, and chunk histories concatenate without
        duplicate rows.  A single step that exceeds the budget cannot be
        subdivided on one device (no hop seam — warn and run one step per
        dispatch; the ``DistSampler`` ring executor is the tool past that
        boundary).  Each call writes :attr:`last_run_stats`.

        Memory note: the history HBM budget above is enforced
        automatically — callers no longer chunk recorded runs by hand.  A
        manual segmented drive (repeated calls with ``initial_particles``,
        e.g. for checkpointed resume) should keep ``seed`` FIXED and pass
        ``step_offset=steps_done`` so the minibatch key stream continues
        the monolithic one exactly (``resilience/supervisor.py`` does
        this); when recording manually, drop each chunk's trailing history
        row before concatenating (it is the chunk's final state, which
        reappears as the next chunk's first pre-update snapshot).
        """
        if initial_particles is not None:
            if self._donate:
                # the scan donates its particle input; copy so the CALLER's
                # buffer survives (one (n, d) copy per run, not per dispatch)
                particles = jnp.array(initial_particles, dtype=dtype)
            else:
                particles = jnp.asarray(initial_particles, dtype=dtype)
        else:
            particles = init_particles(as_key(seed), n, self._d, dtype=dtype or jnp.float32)
        if self._median_kernel:
            self._resolve_median_kernel(particles)
        # bandwidth is frozen by here; the RFF bank (if any) builds at it
        self._pin_approx(n, seed)
        eps = jnp.asarray(step_size, dtype=particles.dtype)
        bkey = minibatch_key(seed)
        steps_per_dispatch = num_iter
        if dispatch_budget is not None:
            if dispatch_budget <= 0:
                raise ValueError(
                    f"dispatch_budget must be positive, got {dispatch_budget}"
                )
            from dist_svgd_tpu.distsampler import DISPATCH_PAIRS_PER_SEC

            pps = float(pairs_per_sec if pairs_per_sec is not None
                        else DISPATCH_PAIRS_PER_SEC)
            t_step = float(n) * float(n) / pps
            if t_step > dispatch_budget:
                import warnings

                warnings.warn(
                    f"one {n}-particle step (~{t_step:.1f} s at {pps:.2e} "
                    f"pairs/s) exceeds dispatch_budget={dispatch_budget} s "
                    "and the single-device step has no internal seam to "
                    "split at; running one step per dispatch — shard over "
                    "DistSampler's ring executor to chunk inside a step",
                    stacklevel=2,
                )
            steps_per_dispatch = max(1, min(num_iter, int(dispatch_budget // max(t_step, 1e-30))))
        if record:
            # HBM-budget history chunking (generalised out of the logreg
            # driver, round 8) — runtime module-attr lookup so tests can
            # monkeypatch the sizing
            from dist_svgd_tpu.utils import history as _history

            steps_per_dispatch = min(
                steps_per_dispatch, _history.record_chunk_steps(n, self._d)
            )
        # the minibatch scan takes the dataset as a traced trailing arg
        # (set_data swaps rows without a retrace); full-data modes keep the
        # 4-arg signature
        extra = ((self._data,) if self._batch_size is not None else ())
        if steps_per_dispatch >= num_iter:
            run = self._run_fn(num_iter, record)
            with _trace.span("train.step_chunk",
                             {"steps": num_iter, "execution": "monolithic",
                              "fenced": _profile.profiler_enabled()}
                             if _trace.enabled() else None):
                final, hist = run(particles, eps, bkey,
                                  jnp.asarray(step_offset, jnp.int32), *extra)
            self.last_run_stats = {
                "execution": "monolithic", "num_steps": num_iter,
                "num_dispatches": 1,
                "dispatches_per_step": round(1 / max(num_iter, 1), 4),
                "steps_per_dispatch": num_iter,
            }
            if record:
                hist = jnp.concatenate([hist, final[None]], axis=0)
            return final, hist
        from dist_svgd_tpu.distsampler import _chunk_sizes

        hists = []
        final = particles
        done = 0
        pending = None  # previous chunk's device history: its D2H fetch is
        # issued only after the NEXT chunk's dispatch, so on a host with an
        # async transfer engine the copy rides the transfer engine while
        # that chunk computes (the logreg driver's round-5 overlap pattern)
        sizes = _chunk_sizes(num_iter, steps_per_dispatch)
        for csize in sizes:  # ≤ 2 distinct sizes → ≤ 2 compiled programs
            run = self._run_fn(csize, record)
            # unfenced span: chained chunk dispatches keep pipelining, so
            # the span shows dispatch latency (the trailing host concat
            # carries the execution wall) — unless the dispatch profiler
            # is on, which fences every plan dispatch for per-program
            # attribution and serialises the chunk chain for the duration
            # (the span's `fenced` tag says which regime recorded it)
            with _trace.span("train.step_chunk",
                             {"steps": csize,
                              "fenced": _profile.profiler_enabled()}
                             if _trace.enabled() else None):
                final, hist = run(final, eps, bkey,
                                  jnp.asarray(step_offset + done, jnp.int32),
                                  *extra)
            if record:
                if pending is not None:
                    hists.append(np.asarray(pending))
                pending = hist
            done += csize
        self.last_run_stats = {
            "execution": "scan_chunks", "num_steps": num_iter,
            "num_dispatches": len(sizes),
            "dispatches_per_step": round(len(sizes) / num_iter, 4),
            "steps_per_dispatch": steps_per_dispatch,
        }
        hist = None
        if record:
            if pending is not None:
                hists.append(np.asarray(pending))
            # host concatenation: a chunked recorded run exists because the
            # stack does NOT fit the HBM budget (or dispatch budget) whole
            hist = np.concatenate(hists + [np.asarray(final)[None]], axis=0)
        return final, hist

    def sample(
        self,
        n: int,
        num_iter: int,
        step_size: float,
        seed=0,
        initial_particles: Optional[jax.Array] = None,
    ):
        """Generate samples using SVGD — reference API (dsvgd/sampler.py:42-74).

        Returns a pandas DataFrame with columns ``timestep`` (0..num_iter),
        ``particle`` (0..n), ``value`` (numpy ``(d,)`` vector).
        """
        _, hist = self.run(
            n, num_iter, step_size, seed=seed, record=True,
            initial_particles=initial_particles,
        )
        return history_to_dataframe(np.asarray(hist))
