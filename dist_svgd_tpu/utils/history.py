"""Particle-history recording.

The reference accumulates one pandas row per (timestep, particle) with the
particle value as a numpy vector, snapshotted *before* each update plus one
final post-update snapshot (dsvgd/sampler.py:62-73, experiments/logreg.py:78-87
— SURVEY.md §7.4 timestep convention).  The TPU-native samplers record the
whole history as a stacked device array inside ``lax.scan`` and convert to the
reference's DataFrame schema once, on the host, at the end.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import pandas as pd


def history_to_dataframe(
    history: np.ndarray,
    timesteps: Optional[Sequence[int]] = None,
    particle_ids: Optional[Sequence[int]] = None,
    include_particle_column: bool = True,
) -> pd.DataFrame:
    """Convert a ``(T, n, d)`` history array to the reference DataFrame schema.

    Columns: ``timestep`` (int), ``particle`` (int, optional — the reference's
    distributed driver records only timestep/value, experiments/logreg.py:81),
    ``value`` (numpy ``(d,)`` vector), matching ``dsvgd/sampler.py:66,74``.
    """
    history = np.asarray(history)
    T, n, d = history.shape
    if timesteps is None:
        timesteps = np.arange(T)
    if particle_ids is None:
        particle_ids = np.arange(n)
    rows = {
        "timestep": np.repeat(np.asarray(timesteps), n),
        "particle": np.tile(np.asarray(particle_ids), T),
        # one reshape, not a T×n Python double loop (millions of iterations
        # at 10k particles × 500 steps); row (t, i) of the reshape IS
        # history[t, i], so the schema is unchanged
        "value": list(history.reshape(T * n, d)),
    }
    if not include_particle_column:
        del rows["particle"]
    return pd.DataFrame(rows)
