"""Particle-history recording.

The reference accumulates one pandas row per (timestep, particle) with the
particle value as a numpy vector, snapshotted *before* each update plus one
final post-update snapshot (dsvgd/sampler.py:62-73, experiments/logreg.py:78-87
— SURVEY.md §7.4 timestep convention).  The TPU-native samplers record the
whole history as a stacked device array inside ``lax.scan`` and convert to the
reference's DataFrame schema once, on the host, at the end.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import pandas as pd

#: Upper bound on steps per recorded dispatch, and the HBM budget that sizes
#: the actual chunk (:func:`record_chunk_steps`).  Chunking bounds the device
#: history buffer at (chunk, n, d) instead of (niter, n, d) and caps the
#: number of compiled scan programs at two (the chunk length plus one
#: remainder length).  Round 5 (in the logreg driver), generalised into the
#: samplers in round 8: the chunk is sized from the budget, not fixed — a
#: fixed 500 held a ~25 GB lane-padded history stack at n=100k (each
#: (n, d≤128) f32 snapshot is physically n×128 floats on TPU), OOMing the
#: history path long before the step.
RECORD_CHUNK_MAX = 500
RECORD_HBM_BUDGET_BYTES = 2 << 30  # 2 GiB for history; steps keep the rest


def record_chunk_steps(n: int, d: int) -> int:
    """Steps per recorded dispatch such that the on-device pre-update
    history stack stays within :data:`RECORD_HBM_BUDGET_BYTES`.

    TPU tiles every trailing-2-D f32 page to (8, 128), so one (n, d)
    snapshot costs ``n × max(d, 128) × 4`` bytes regardless of small d —
    the lane padding is the whole story at d=3 (docs/notes.md lane-dense
    OT operands note).  Clamped to [1, RECORD_CHUNK_MAX].  Shared by
    ``Sampler.run`` and ``DistSampler.run_steps`` (both auto-chunk recorded
    trajectories through it) and the experiment drivers."""
    bytes_per_step = n * max(d, 128) * 4
    return max(1, min(RECORD_CHUNK_MAX, RECORD_HBM_BUDGET_BYTES // bytes_per_step))


def history_to_dataframe(
    history: np.ndarray,
    timesteps: Optional[Sequence[int]] = None,
    particle_ids: Optional[Sequence[int]] = None,
    include_particle_column: bool = True,
) -> pd.DataFrame:
    """Convert a ``(T, n, d)`` history array to the reference DataFrame schema.

    Columns: ``timestep`` (int), ``particle`` (int, optional — the reference's
    distributed driver records only timestep/value, experiments/logreg.py:81),
    ``value`` (numpy ``(d,)`` vector), matching ``dsvgd/sampler.py:66,74``.
    """
    history = np.asarray(history)
    T, n, d = history.shape
    if timesteps is None:
        timesteps = np.arange(T)
    if particle_ids is None:
        particle_ids = np.arange(n)
    rows = {
        "timestep": np.repeat(np.asarray(timesteps), n),
        "particle": np.tile(np.asarray(particle_ids), T),
        # one reshape, not a T×n Python double loop (millions of iterations
        # at 10k particles × 500 steps); row (t, i) of the reshape IS
        # history[t, i], so the schema is unchanged
        "value": list(history.reshape(T * n, d)),
    }
    if not include_particle_column:
        del rows["particle"]
    return pd.DataFrame(rows)
