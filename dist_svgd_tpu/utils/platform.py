"""Backend/platform helpers for this framework's runtime environments.

Some images pre-register an out-of-tree TPU PJRT plugin ("axon") in *every*
interpreter via sitecustomize; its factory blocks CPU-only backend init.  All
CPU-forcing code paths (tests, ``--backend=cpu``, bench fallback, the
multichip dry run) share this one helper instead of three hand-rolled copies.
"""

from __future__ import annotations


def drop_axon_factory() -> None:
    """Unregister the axon backend factory if present (no-op elsewhere).

    Uses a private jax API (``jax._src.xla_bridge._backend_factories``);
    guarded so a jax upgrade degrades to a no-op rather than a crash.
    """
    try:
        from jax._src import xla_bridge

        xla_bridge._backend_factories.pop("axon", None)
    except Exception:
        pass


def force_cpu_backend() -> None:
    """Force jax onto the CPU backend, working around the blocked init.

    Must be called before the first backend use.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    drop_axon_factory()


def select_backend(backend: str) -> None:
    """Apply an experiment driver's ``--backend {auto,tpu,cpu}`` flag.

    ``auto`` keeps jax's default device resolution; ``cpu`` uses
    :func:`force_cpu_backend`; anything else is passed to
    ``jax.config.jax_platforms`` verbatim.  Must run before first backend use.
    """
    if backend == "auto":
        return
    if backend == "cpu":
        force_cpu_backend()
        return
    import jax

    jax.config.update("jax_platforms", backend)
