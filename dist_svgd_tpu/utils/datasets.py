"""Dataset loading.

The reference trains on the Rätsch/Cawley `benchmarks.mat` suite
(experiments/data/benchmarks.mat), loaded with the convention
(experiments/logreg.py:28-33, logreg_plots.py:27-34):

    mat[name][0, 0] → dataset struct with fields
        [0] X      — (N, d) instances
        [1] t      — (N, 1) labels in {-1, +1}
        [2] train  — (n_folds, n_train) 1-based indices
        [3] test   — (n_folds, n_test)  1-based indices
    x_train = X[train - 1][fold]   (fold indexes the fold axis 0-based)

⚠️ The mounted reference ships only a Git-LFS pointer for the .mat file
(SURVEY.md §7.3.6), so this loader falls back to a deterministic synthetic
generator with the *same structural convention* — banana-shaped 2-D data for
'banana', Gaussian-blob data with dataset-specific dimensionalities for the
rest — making every experiment and test runnable offline.  Fold counts follow
the reference's sweep range (grid.sh uses folds 1..100; the reference indexes
folds 0-based, a quirk noted in SURVEY.md §7.2.2, so we generate 101 folds).
"""

from __future__ import annotations

import math
import os
import zlib
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

#: The reference CLI's dataset choices (experiments/logreg.py:106-107).
DATASET_NAMES = ("banana", "diabetis", "german", "image", "splice", "titanic", "waveform")

#: Feature dimensionalities of the real Rätsch benchmark datasets, used to
#: shape the synthetic fallbacks identically.
_DATASET_DIMS: Dict[str, int] = {
    "banana": 2,
    "diabetis": 8,
    "german": 20,
    "image": 18,
    "splice": 60,
    "titanic": 3,
    "waveform": 21,
    "covertype": 54,  # BASELINE.json config 4 (not part of benchmarks.mat)
}

_N_FOLDS = 101
_N_TRAIN = 400
_N_TEST = 1000


@dataclass
class Fold:
    """One train/test fold in reference layout."""

    x_train: np.ndarray
    t_train: np.ndarray
    x_test: np.ndarray
    t_test: np.ndarray


def _banana_points(rng: np.random.Generator, n: int) -> Tuple[np.ndarray, np.ndarray]:
    """Two interleaved crescents — the classic 'banana' binary task."""
    labels = rng.integers(0, 2, size=n)
    angle = rng.uniform(0.0, np.pi, size=n)
    radius = 2.0 + 0.35 * rng.normal(size=n)
    x = np.empty((n, 2))
    x[:, 0] = radius * np.cos(angle)
    x[:, 1] = radius * np.sin(angle)
    flip = labels == 1
    x[flip, 0] = 2.0 - x[flip, 0] * 1.0
    x[flip, 1] = 1.0 - x[flip, 1]
    x += 0.25 * rng.normal(size=(n, 2))
    t = np.where(labels > 0, 1.0, -1.0)
    return x / 2.0, t


def _blob_points(rng: np.random.Generator, n: int, dim: int) -> Tuple[np.ndarray, np.ndarray]:
    """Generic linearly-separable-ish Gaussian blobs for non-banana names."""
    labels = rng.integers(0, 2, size=n)
    direction = rng.normal(size=dim)
    direction /= np.linalg.norm(direction)
    x = rng.normal(size=(n, dim)) + np.outer(np.where(labels > 0, 1.0, -1.0), direction) * 1.2
    t = np.where(labels > 0, 1.0, -1.0)
    return x, t


def make_synthetic_mat_struct(name: str, seed: Optional[int] = None) -> tuple:
    """Build a synthetic dataset tuple in the .mat struct layout
    ``(X, t, train_idx_1based, test_idx_1based)``; deterministic per name."""
    dim = _DATASET_DIMS.get(name, 10)
    if seed is None:
        seed = zlib.crc32(f"dist_svgd_tpu:{name}".encode())  # stable across processes
    rng = np.random.default_rng(seed)
    n_total = _N_TRAIN + _N_TEST
    if name == "banana":
        x, t = _banana_points(rng, n_total)
    else:
        x, t = _blob_points(rng, n_total, dim)
    train = np.empty((_N_FOLDS, _N_TRAIN), dtype=np.int64)
    test = np.empty((_N_FOLDS, _N_TEST), dtype=np.int64)
    for f in range(_N_FOLDS):
        perm = rng.permutation(n_total)
        train[f] = perm[:_N_TRAIN] + 1  # 1-based, like the .mat files
        test[f] = perm[_N_TRAIN:] + 1
    return x.astype(np.float32), t.reshape(-1, 1).astype(np.float64), train, test


def _is_lfs_pointer(path: str) -> bool:
    try:
        with open(path, "rb") as fh:
            head = fh.read(100)
        return head.startswith(b"version https://git-lfs")
    except OSError:
        return True


def load_benchmark(
    name: str,
    fold: int,
    mat_path: Optional[str] = None,
) -> Fold:
    """Load one train/test fold, from a real ``benchmarks.mat`` when available
    (reference indexing convention) or the synthetic fallback otherwise.
    """
    struct = None
    if mat_path is not None and os.path.exists(mat_path) and not _is_lfs_pointer(mat_path):
        from scipy.io import loadmat

        mat = loadmat(mat_path)
        dataset = mat[name][0, 0]
        struct = (dataset[0], dataset[1], dataset[2], dataset[3])
    if struct is None:
        struct = make_synthetic_mat_struct(name)

    x, t, train, test = struct
    # reference indexing: X[train - 1][fold] (experiments/logreg.py:31-33)
    x_train = np.asarray(x[train - 1][fold], dtype=np.float32)
    t_train = np.asarray(t[train - 1][fold], dtype=np.float64)
    x_test = np.asarray(x[test - 1][fold], dtype=np.float32)
    t_test = np.asarray(t[test - 1][fold], dtype=np.float64)
    return Fold(x_train, t_train, x_test, t_test)


#: Feature dimensionalities of the standard UCI regression suite used by the
#: SVGD BNN experiments (BASELINE.json config 5) — shapes the synthetic
#: fallbacks identically to the real datasets.
UCI_REGRESSION_DIMS: Dict[str, int] = {
    "boston": 13,
    "concrete": 8,
    "energy": 8,
    "kin8nm": 8,
    "naval": 16,
    "power": 4,
    "protein": 9,
    "wine": 11,
    "yacht": 6,
}

_UCI_ROWS = 1000


@dataclass
class RegressionSplit:
    """One 90/10 train/test split of a regression dataset (the standard UCI
    BNN protocol), with the train-set standardization statistics the driver
    needs to report metrics on the original target scale."""

    x_train: np.ndarray
    y_train: np.ndarray
    x_test: np.ndarray
    y_test: np.ndarray
    x_mean: np.ndarray
    x_std: np.ndarray
    y_mean: float
    y_std: float


def load_uci_regression(
    name: str,
    split: int = 0,
    standardize: bool = True,
    data_path: Optional[str] = None,
) -> RegressionSplit:
    """Load one train/test split of a UCI regression dataset.

    Reads ``<data_path>/<name>.npz`` (arrays ``x``, ``y``) when present; the
    real UCI files require network access (unavailable here), so the default
    is a deterministic synthetic nonlinear-regression stand-in with the real
    dataset's dimensionality: ``y = sin(x·a) + (x·b)²/2 + x·c + noise``,
    which a 2-layer ReLU net fits well but a linear model cannot.

    ``standardize=True`` (the BNN protocol) z-scores features and targets by
    *train-split* statistics; predictions are mapped back via
    ``y_mean``/``y_std``.
    """
    dim = UCI_REGRESSION_DIMS.get(name)
    if dim is None:
        raise ValueError(
            f"unknown UCI regression dataset {name!r}; choose from "
            f"{sorted(UCI_REGRESSION_DIMS)}"
        )
    x = y = None
    if data_path is not None:
        path = os.path.join(data_path, f"{name}.npz")
        if os.path.exists(path):
            arr = np.load(path)
            x, y = np.asarray(arr["x"], dtype=np.float64), np.asarray(
                arr["y"], dtype=np.float64
            ).reshape(-1)
    if x is None:
        seed = zlib.crc32(f"dist_svgd_tpu:uci:{name}".encode())
        rng = np.random.default_rng(seed)
        x = rng.normal(size=(_UCI_ROWS, dim))
        a, b, c = rng.normal(size=(3, dim)) / math.sqrt(dim)
        y = (
            np.sin(x @ a * 2.0)
            + 0.5 * (x @ b) ** 2
            + x @ c
            + 0.1 * rng.normal(size=_UCI_ROWS)
        )

    n = x.shape[0]
    rng_split = np.random.default_rng(zlib.crc32(f"{name}:split:{split}".encode()))
    perm = rng_split.permutation(n)
    n_train = int(round(0.9 * n))
    tr, te = perm[:n_train], perm[n_train:]
    x_train, y_train = x[tr], y[tr]
    x_test, y_test = x[te], y[te]

    if standardize:
        x_mean, x_std = x_train.mean(axis=0), x_train.std(axis=0) + 1e-8
        y_mean, y_std = float(y_train.mean()), float(y_train.std() + 1e-8)
        x_train = (x_train - x_mean) / x_std
        x_test = (x_test - x_mean) / x_std
        y_train = (y_train - y_mean) / y_std
        # y_test stays on the original scale; metrics un-standardize predictions
    else:
        x_mean, x_std = np.zeros(x.shape[1]), np.ones(x.shape[1])
        y_mean, y_std = 0.0, 1.0

    return RegressionSplit(
        x_train.astype(np.float32),
        y_train.astype(np.float32),
        x_test.astype(np.float32),
        y_test.astype(np.float64),
        x_mean,
        x_std,
        y_mean,
        y_std,
    )


def load_covertype(
    n_rows: int = 50_000, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Covertype-style binary task (BASELINE.json config 4).

    The real UCI Covertype requires network access (unavailable here), so this
    produces a deterministic synthetic stand-in with the same shape: 54
    features, binary labels in {-1, +1}, ``n_rows`` rows.
    """
    rng = np.random.default_rng(seed)
    x, t = _blob_points(rng, n_rows, _DATASET_DIMS["covertype"])
    return x.astype(np.float32), t.astype(np.float64)
