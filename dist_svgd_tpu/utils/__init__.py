"""Utilities: dataset loading, history recording, and RNG helpers."""

from dist_svgd_tpu.utils.datasets import (
    DATASET_NAMES,
    Fold,
    load_benchmark,
    load_covertype,
)
from dist_svgd_tpu.utils.history import history_to_dataframe
from dist_svgd_tpu.utils.rng import as_key, init_particles, init_particles_per_shard

__all__ = [
    "DATASET_NAMES",
    "Fold",
    "load_benchmark",
    "load_covertype",
    "history_to_dataframe",
    "as_key",
    "init_particles",
    "init_particles_per_shard",
]
