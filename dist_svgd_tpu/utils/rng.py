"""RNG helpers.

The reference seeds torch's global RNG per rank (``torch.manual_seed(rank)``,
experiments/logreg.py:24) so each rank draws an entirely different initial
particle array yet only uses its own block (SURVEY.md §7.3.5).  JAX's explicit
keys make the equivalent well-defined globally: one root key, ``fold_in`` per
shard, each shard's block drawn from its own independent stream.
"""

from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp


def as_key(seed_or_key: Union[int, jax.Array]) -> jax.Array:
    """Accept either an integer seed or a PRNG key."""
    if isinstance(seed_or_key, int):
        return jax.random.PRNGKey(seed_or_key)
    return seed_or_key


def init_particles(key, n: int, d: int, dtype=jnp.float32) -> jax.Array:
    """Standard-normal initial particles, matching the reference's
    ``Normal(0, 1).sample((d, 1))`` per particle (dsvgd/sampler.py:58-60)."""
    return jax.random.normal(as_key(key), (n, d), dtype=dtype)


def init_particles_per_shard(key, n: int, d: int, num_shards: int, dtype=jnp.float32) -> jax.Array:
    """Global ``(n, d)`` initial particles where shard ``r``'s block comes from
    an independent stream ``fold_in(key, r)`` — the distributional equivalent
    of the reference's per-rank seeding (experiments/logreg.py:24,63-66).

    ``n`` must be divisible by ``num_shards`` (the caller applies the
    reference's drop-remainder policy first).
    """
    key = as_key(key)
    assert n % num_shards == 0
    block = n // num_shards
    blocks = [
        jax.random.normal(jax.random.fold_in(key, r), (block, d), dtype=dtype)
        for r in range(num_shards)
    ]
    return jnp.concatenate(blocks, axis=0)
