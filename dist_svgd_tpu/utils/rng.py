"""RNG helpers — the ONE place this repo turns seeds into PRNG keys.

The reference seeds torch's global RNG per rank (``torch.manual_seed(rank)``,
experiments/logreg.py:24) so each rank draws an entirely different initial
particle array yet only uses its own block (SURVEY.md §7.3.5).  JAX's explicit
keys make the equivalent well-defined globally: one root key, ``fold_in`` per
shard, each shard's block drawn from its own independent stream.

Construction discipline (enforced by ``tools/jaxlint`` rule **JL002**):
``jax.random.PRNGKey`` is called nowhere outside this module.  Call sites
use :func:`as_key` (seed → key), :func:`minibatch_key` (the minibatch
stream's root, a fixed fold so it never collides with the particle-init
stream), or the ``init_particles*`` helpers.  Centralising construction is
what makes key-reuse statically checkable: every key in the codebase is
either derived here or split/folded from one that was, so two draws from
the same name without an intervening ``split``/``fold_in`` are provably
correlated — exactly what JL002 flags.
"""

from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp


def as_key(seed_or_key: Union[int, jax.Array]) -> jax.Array:
    """Accept either an integer seed or a PRNG key."""
    if isinstance(seed_or_key, int):
        return jax.random.PRNGKey(seed_or_key)
    return seed_or_key


def minibatch_key(seed_or_key) -> jax.Array:
    """Root key of the minibatch stream, derived from the run seed by a fixed
    fold so it never collides with the particle-init stream."""
    return jax.random.fold_in(as_key(seed_or_key), 7919)


def approx_bank_key(seed_or_key) -> jax.Array:
    """Root key of the random-feature bank stream (``ops/approx.py``'s RFF
    frequency draw), derived from the run seed by its own fixed fold so it
    collides with neither the particle-init nor the minibatch stream.  The
    bank is drawn ONCE per run from this key and shared by every shard —
    and the key (not the bank) rides ``state_dict``, so a resumed or
    resharded run re-derives the identical bank deterministically."""
    return jax.random.fold_in(as_key(seed_or_key), 104729)


def draw_minibatch(key, data, n_rows: int, batch_size: int):
    """One without-replacement minibatch and its importance scale.

    The single sampling convention shared by the single-device and
    distributed samplers (writeup.tex:214-231 minibatch approximation).

    Returns ``(batch, scale)`` with ``scale = n_rows / batch_size``, the
    factor that makes ``scale · ∇logp(θ, batch)`` an unbiased estimate of the
    full-data score for row-additive likelihoods.
    """
    idx = jax.random.choice(key, n_rows, (batch_size,), replace=False)
    return jax.tree_util.tree_map(lambda a: a[idx], data), n_rows / batch_size


def init_particles(key, n: int, d: int, dtype=jnp.float32) -> jax.Array:
    """Standard-normal initial particles, matching the reference's
    ``Normal(0, 1).sample((d, 1))`` per particle (dsvgd/sampler.py:58-60)."""
    return jax.random.normal(as_key(key), (n, d), dtype=dtype)


def init_particles_per_shard(key, n: int, d: int, num_shards: int, dtype=jnp.float32) -> jax.Array:
    """Global ``(n, d)`` initial particles where shard ``r``'s block comes from
    an independent stream ``fold_in(key, r)`` — the distributional equivalent
    of the reference's per-rank seeding (experiments/logreg.py:24,63-66).

    ``n`` must be divisible by ``num_shards`` (the caller applies the
    reference's drop-remainder policy first).
    """
    key = as_key(key)
    assert n % num_shards == 0
    block = n // num_shards
    blocks = [
        jax.random.normal(jax.random.fold_in(key, r), (block, d), dtype=dtype)
        for r in range(num_shards)
    ]
    return jnp.concatenate(blocks, axis=0)
