"""Checkpoint / resume for long SVGD runs.

The reference has no checkpointing: results are written once, at run end
(experiments/logreg.py:89-92), and a crash loses the run (SURVEY.md §5).  The
TPU-native plan from SURVEY.md §5 is an Orbax-style checkpoint of the sampler
state every K steps plus resume; this module provides exactly that.

Design:

- :func:`save_state` / :func:`load_state` persist an arbitrary pytree of
  arrays via Orbax (``PyTreeCheckpointer``) on provably single-process runs,
  and via a plain ``.npz`` otherwise — multi-process runs save per-process
  state to per-process paths, where Orbax's path-keyed cross-process
  barriers would deadlock (:func:`_use_orbax`).  Both layouts are
  self-describing and the loader auto-detects which one is on disk.
- :class:`CheckpointManager` wraps the every-K-steps cadence with retention
  (keep the newest ``max_to_keep`` step dirs) and latest-step discovery.
- ``DistSampler.state_dict()`` / ``.load_state_dict()`` (distsampler.py)
  expose the sampler's resume state: particle array, Wasserstein
  ``previous_particles`` snapshot, and the step counter ``t`` that drives both
  the ``partitions`` rotation and the per-step minibatch key fold — restoring
  them reproduces the uninterrupted trajectory bit-for-bit.
"""

from __future__ import annotations

import os
import re
import shutil
from typing import Any, Dict, List, Optional

import numpy as np

_STEP_DIR_RE = re.compile(r"^step_(\d+)$")
_NPZ_NAME = "state.npz"


def _to_numpy_tree(tree: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in tree.items():
        if v is None:
            continue
        out[k] = np.asarray(v)
    return out


def _use_orbax() -> bool:
    """Orbax for single-process saves only.  Its PyTreeCheckpointer runs
    cross-process barriers keyed by the checkpoint path; the framework's
    multi-host contract is per-process state (each process saves its own
    addressable block to its own path — ``DistSampler.state_dict``), where
    those barriers deadlock until the coordination-service timeout.  The
    plain ``.npz`` layout is the correct per-process backend."""
    try:
        import orbax.checkpoint  # noqa: F401
    except ImportError:
        return False
    try:
        import jax

        return jax.process_count() == 1
    except Exception:
        # process count unknowable (partially-initialized/torn-down runtime):
        # .npz works everywhere; orbax is only safe when provably single-process
        return False


def save_state(path: str, state: Dict[str, Any], backend: str = "auto") -> str:
    """Persist a flat dict of arrays/scalars (``None`` values are elided).

    ``backend='auto'`` picks per :func:`_use_orbax`: Orbax on provably
    single-process runs, ``.npz`` otherwise (multi-process per-path saves
    deadlock Orbax's barriers).  ``backend='npz'`` forces the plain layout —
    the right choice for **high-frequency periodic** saves (the resilience
    supervisor's cadence): an orbax save costs a fixed ~quarter second of
    directory/manifest machinery regardless of array size, while an npz of
    sampler-sized state is ~a millisecond; both layouts are self-describing
    and :func:`load_state` auto-detects them, so readers never care.
    ``path`` is a directory; an existing checkpoint there is replaced
    atomically enough for single-writer use (removed then rewritten).
    """
    if backend not in ("auto", "npz"):
        raise ValueError(f"unknown checkpoint backend {backend!r}")
    state = _to_numpy_tree(state)
    path = os.path.abspath(path)
    # write-tmp-then-rename: a crash mid-write leaves only a stale .tmp dir,
    # never a truncated checkpoint at the final path
    tmp = path + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    if backend == "auto" and _use_orbax():
        import orbax.checkpoint as ocp

        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(tmp, state)
    else:
        os.makedirs(tmp)
        np.savez(os.path.join(tmp, _NPZ_NAME), **state)
    if os.path.exists(path):
        shutil.rmtree(path)
    os.rename(tmp, path)
    return path


#: Files whose presence marks an orbax-layout checkpoint (PyTreeCheckpointer
#: writes `_METADATA`/`_CHECKPOINT_METADATA` plus ocdbt manifests).
_ORBAX_MARKERS = ("_METADATA", "_CHECKPOINT_METADATA", "manifest.ocdbt")


def _looks_like_orbax(path: str, entries) -> bool:
    return any(m in entries for m in _ORBAX_MARKERS) or any(
        e.startswith("ocdbt.process_") for e in entries
    )


def load_state(path: str) -> Dict[str, Any]:
    """Load a checkpoint written by :func:`save_state` (auto-detects layout).

    A directory holding neither layout — empty, or stray files without the
    npz or any orbax marker (partial writes from a killed pre-rename-era
    writer) — raises ``ValueError`` *before* the orbax import, so
    ``CheckpointManager.restore_latest`` can classify it as corruption and
    fall back to an older step even when orbax is not installed
    (``ImportError`` is reserved for a checkpoint that IS orbax-layout in an
    orbax-less environment, which must propagate)."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise FileNotFoundError(f"no checkpoint directory at {path}")
    npz = os.path.join(path, _NPZ_NAME)
    if os.path.exists(npz):
        with np.load(npz) as data:
            return {k: data[k] for k in data.files}
    entries = os.listdir(path)
    if not _looks_like_orbax(path, entries):
        raise ValueError(
            f"checkpoint directory {path} holds neither layout "
            f"(entries: {sorted(entries)[:5]}) — partial write from a "
            "killed save?"
        )
    import orbax.checkpoint as ocp

    with ocp.PyTreeCheckpointer() as ckptr:
        restored = ckptr.restore(path)
    return dict(restored)


def assemble_full_state(paths) -> Dict[str, Any]:
    """Assemble the per-process block checkpoints of ONE multi-host save
    into a full-global state dict, enabling **cross-process-count restore**
    (round-5, VERDICT r04 item 7).

    A multi-host ``DistSampler.state_dict`` holds only each process's
    contiguous axis-0 block plus its ``<key>_start`` offset
    (``parallel/multihost.py:host_addressable_block``).  A federation with a
    *different* process partitioning cannot restore any single file — its
    row ranges don't match — but the mesh *size* (and therefore every
    global array's shape) is process-layout-independent, so concatenating
    every saved block along axis 0 reconstructs the exact global state,
    which ``load_state_dict`` then re-slices for the new layout (its
    full-save branch).  Every process of the new federation calls this on
    the complete list of old per-process paths.

    Raises ``ValueError`` when the blocks are not contiguous from row 0
    (paths from different saves, or an incomplete list)."""
    states = [load_state(p) for p in paths]
    if not states:
        raise ValueError("assemble_full_state needs at least one checkpoint")
    out: Dict[str, Any] = {}
    keys = {k for s in states for k in s if not k.endswith("_start")}
    for key in keys:
        # classify replicated-vs-block from the first state that actually
        # CONTAINS the key — classifying from states[0] alone turned a
        # mixed-version/corrupt save (key present only in later files) into
        # a bare KeyError instead of the diagnosis below (ADVICE round 5)
        holders = [s for s in states if s.get(key) is not None]
        if not holders:
            out[key] = None
            continue
        has_start = any(key + "_start" in s for s in holders)
        if not has_start:
            # a scalar/replicated entry (t): must be present and identical
            # in every file — a mismatch (or partial presence) means the
            # paths mix two different saves (the contiguity check below
            # cannot catch that when the row layouts happen to line up)
            if len(holders) != len(states):
                raise ValueError(
                    f"checkpoint files disagree on the presence of {key!r} "
                    f"({len(holders)} of {len(states)} files carry it) — "
                    "are these paths from one complete multi-host save?"
                )
            for s in holders[1:]:
                if not np.array_equal(np.asarray(s[key]),
                                      np.asarray(holders[0][key])):
                    raise ValueError(
                        f"checkpoint files disagree on {key!r} "
                        f"({np.asarray(holders[0][key])} vs "
                        f"{np.asarray(s[key])}) — are these paths from one "
                        "complete multi-host save?"
                    )
            out[key] = holders[0][key]
            continue
        parts = [
            (int(np.asarray(s.get(key + "_start", 0))), s[key])
            for s in holders
        ]
        parts.sort(key=lambda p: p[0])
        cursor = 0
        for start, rows in parts:
            if start != cursor:
                raise ValueError(
                    f"checkpoint blocks for {key!r} are not contiguous: "
                    f"expected a block starting at row {cursor}, got {start} "
                    "— are these paths from one complete multi-host save?"
                )
            cursor += rows.shape[0]
        out[key] = np.concatenate([rows for _, rows in parts])
    return out


class CheckpointManager:
    """Every-K-steps checkpointing with retention.

    Layout: ``<root>/step_<t>/`` per checkpoint, newest ``max_to_keep`` kept.
    ``backend`` forwards to :func:`save_state` (``'npz'`` for high-frequency
    periodic cadences — see its docstring; reads auto-detect either way).
    """

    def __init__(self, root: str, every: int = 100, max_to_keep: int = 3,
                 backend: str = "auto"):
        if every <= 0:
            raise ValueError("every must be positive")
        if backend not in ("auto", "npz"):
            raise ValueError(f"unknown checkpoint backend {backend!r}")
        self.root = os.path.abspath(root)
        self.every = every
        self.max_to_keep = max_to_keep
        self.backend = backend
        os.makedirs(self.root, exist_ok=True)

    def _step_dirs(self) -> List[int]:
        steps = []
        for name in os.listdir(self.root):
            m = _STEP_DIR_RE.match(name)
            if m and os.path.isdir(os.path.join(self.root, name)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def should_save(self, step: int) -> bool:
        return step > 0 and step % self.every == 0

    def save(self, step: int, state: Dict[str, Any]) -> str:
        path = save_state(os.path.join(self.root, f"step_{step}"), state,
                          backend=self.backend)
        for old in self._step_dirs()[: -self.max_to_keep or None]:
            if old != step:
                shutil.rmtree(os.path.join(self.root, f"step_{old}"), ignore_errors=True)
        return path

    def latest_step(self) -> Optional[int]:
        steps = self._step_dirs()
        return steps[-1] if steps else None

    def restore_latest(self, with_step: bool = False):
        """Restore the newest *loadable* checkpoint, falling back past any
        that fail to load (e.g. a partial write from a pre-rename crash of an
        older writer) and warning about the skip.  ``with_step=True``
        returns ``(step, state)`` instead of ``state`` alone (``(None,
        None)`` when nothing is restorable) — the hot-reload watcher needs
        the step to tell a *new* checkpoint from the one already served."""
        for step in reversed(self._step_dirs()):
            path = os.path.join(self.root, f"step_{step}")
            try:
                state = load_state(path)
                return (step, state) if with_step else state
            except ImportError:
                # environment problem (orbax-format checkpoint, no orbax
                # installed) — not corruption; skipping would silently restart
                # from scratch and eventually retention-delete the real state
                raise
            except Exception as e:  # corrupt/partial — try the next-oldest
                import warnings

                warnings.warn(
                    f"skipping unloadable checkpoint {path}: {type(e).__name__}: {e}"
                )
        return (None, None) if with_step else None

    def clear(self) -> None:
        """Delete every checkpoint under the root (fresh-run hygiene: a new
        run writing into a dir holding an older run's step dirs would let
        retention keep the *stale* high-step checkpoints and delete its own)."""
        for step in self._step_dirs():
            shutil.rmtree(os.path.join(self.root, f"step_{step}"), ignore_errors=True)
