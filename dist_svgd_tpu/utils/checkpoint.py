"""Checkpoint / resume for long SVGD runs.

The reference has no checkpointing: results are written once, at run end
(experiments/logreg.py:89-92), and a crash loses the run (SURVEY.md §5).  The
TPU-native plan from SURVEY.md §5 is an Orbax-style checkpoint of the sampler
state every K steps plus resume; this module provides exactly that.

Design:

- :func:`save_state` / :func:`load_state` persist an arbitrary pytree of
  arrays via Orbax (``PyTreeCheckpointer``) on provably single-process runs,
  and via a plain ``.npz`` otherwise — multi-process runs save per-process
  state to per-process paths, where Orbax's path-keyed cross-process
  barriers would deadlock (:func:`_use_orbax`).  Both layouts are
  self-describing and the loader auto-detects which one is on disk.
- :class:`CheckpointManager` wraps the every-K-steps cadence with retention
  (keep the newest ``max_to_keep`` step dirs) and latest-step discovery.
- ``DistSampler.state_dict()`` / ``.load_state_dict()`` (distsampler.py)
  expose the sampler's resume state: particle array, Wasserstein
  ``previous_particles`` snapshot, and the step counter ``t`` that drives both
  the ``partitions`` rotation and the per-step minibatch key fold — restoring
  them reproduces the uninterrupted trajectory bit-for-bit.
- **Topology manifest + reshard (elastic capacity, ROADMAP item 5):** every
  sampler ``state_dict`` stamps its shard topology
  (:func:`topology_manifest` — ``n_shards``, per-shard particle counts, the
  data partition) into the saved dict, so a loader can compare the saved
  layout against the requested one and raise :class:`TopologyMismatch`
  *before* any array op (:func:`check_topology`), and
  :func:`reshard_state` can reshape a run saved at N shards into one
  loadable at M — the prerequisite for resuming a checkpointed run on a
  shrunk/grown mesh instead of dying with the lost device.
"""

from __future__ import annotations

import os
import re
import shutil
import warnings
from typing import Any, Dict, List, Optional

import numpy as np

_STEP_DIR_RE = re.compile(r"^step_(\d+)$")
_NPZ_NAME = "state.npz"

#: Keys of the topology manifest stamped into every sampler checkpoint.
MANIFEST_KEYS = (
    "topo_n_shards",
    "topo_n_particles",
    "topo_d",
    "topo_particles_per_shard",
    "topo_data_rows_per_shard",
    "topo_process_count",
    "topo_granule_shards",
)


class TopologyMismatch(ValueError):
    """A checkpoint's saved topology manifest does not match the topology it
    is being loaded into.  Raised *before* any array reshape/broadcast runs,
    with both shapes in one line — the raw jax/numpy error it replaces named
    neither.  Shard-count-only mismatches are reshardable: convert the state
    with :func:`reshard_state` first."""


def topology_manifest(n_shards: int, n_particles: int, d: int,
                      data_rows_per_shard: int = 0,
                      process_count: int = 1,
                      granule_shards=None) -> Dict[str, np.ndarray]:
    """The manifest entries a sampler ``state_dict`` stamps into every save:
    shard count, global particle count and dimension, per-shard particle
    counts (equal blocks — the drop-remainder policy runs at construction),
    the per-shard data partition (0 = no data), and the **process layout**
    — how many processes held the mesh and how many shards each granule
    owned (``granule_shards`` defaults to an equal split; the granule-major
    ``make_particle_mesh`` guarantees one exists).

    The process-layout entries are *global* values, identical in every
    process's save — never per-process (``assemble_full_state`` requires
    replicated entries to be bitwise equal across the per-process files)."""
    s = int(n_shards)
    if s < 1:
        raise ValueError(f"n_shards must be >= 1, got {s}")
    w = int(process_count)
    if w < 1:
        raise ValueError(f"process_count must be >= 1, got {w}")
    if granule_shards is None:
        if s % w:
            raise ValueError(
                f"process_count {w} does not divide n_shards {s}: pass the "
                "explicit granule_shards layout"
            )
        granule_shards = (s // w,) * w
    g = np.asarray(granule_shards, dtype=np.int64).reshape(-1)
    if g.shape[0] != w or int(g.sum()) != s or int(g.min()) < 1:
        raise ValueError(
            f"granule_shards {tuple(int(x) for x in g)} does not lay out "
            f"{s} shards over {w} processes"
        )
    return {
        "topo_n_shards": np.asarray(s, dtype=np.int64),
        "topo_n_particles": np.asarray(int(n_particles), dtype=np.int64),
        "topo_d": np.asarray(int(d), dtype=np.int64),
        "topo_particles_per_shard": np.full(s, int(n_particles) // s,
                                            dtype=np.int64),
        "topo_data_rows_per_shard": np.asarray(int(data_rows_per_shard),
                                               dtype=np.int64),
        "topo_process_count": np.asarray(w, dtype=np.int64),
        "topo_granule_shards": g,
    }


def read_manifest(state: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Parse the topology manifest out of a loaded state dict.

    Returns ``{'n_shards', 'n_particles', 'd', 'particles_per_shard',
    'data_rows_per_shard', 'process_count', 'granule_shards'}`` or ``None``
    when the save predates the manifest **or** the manifest entries are
    unreadable/internally inconsistent (a corrupt manifest must degrade to
    the manifest-less path, not crash the restore — the caller warns and
    falls back to shape inference).  The process-layout entries default to a
    single-process layout for saves that predate them."""
    if state.get("topo_n_shards") is None:
        return None
    try:
        man = {
            "n_shards": int(np.asarray(state["topo_n_shards"])),
            "n_particles": int(np.asarray(state["topo_n_particles"])),
            "d": int(np.asarray(state["topo_d"])),
            "particles_per_shard": np.asarray(
                state["topo_particles_per_shard"], dtype=np.int64
            ).reshape(-1),
            "data_rows_per_shard": int(
                np.asarray(state.get("topo_data_rows_per_shard", 0))
            ),
            "process_count": int(
                np.asarray(state.get("topo_process_count", 1))
            ),
        }
        gs = state.get("topo_granule_shards")
        man["granule_shards"] = (
            np.full(1, man["n_shards"], dtype=np.int64) if gs is None
            else np.asarray(gs, dtype=np.int64).reshape(-1)
        )
    except (KeyError, TypeError, ValueError, OverflowError):
        return None
    if (man["n_shards"] < 1
            or man["particles_per_shard"].shape[0] != man["n_shards"]
            or int(man["particles_per_shard"].sum()) != man["n_particles"]):
        return None
    if (man["process_count"] < 1
            or man["granule_shards"].shape[0] != man["process_count"]
            or int(man["granule_shards"].sum()) != man["n_shards"]):
        return None
    return man


def check_topology(state: Dict[str, Any], expect: Dict[str, int],
                   context: str = "checkpoint") -> Optional[Dict[str, Any]]:
    """Compare a state's saved manifest against a requested topology.

    ``expect`` names any subset of ``n_shards`` / ``n_particles`` / ``d``;
    a mismatch raises :class:`TopologyMismatch` naming both sides and
    pointing at :func:`reshard_state` — before any array op.  Manifest-less
    (pre-elastic) saves pass silently; returns the parsed manifest (or
    ``None``)."""
    man = read_manifest(state)
    if man is None:
        return None
    bad = {k: (man[k], v) for k, v in expect.items()
           if v is not None and man.get(k) != int(v)}
    if bad:
        saved = ", ".join(f"{k}={man[k]}" for k in sorted(bad))
        want = ", ".join(f"{k}={int(v)}" for k, (_, v) in sorted(bad.items()))
        raise TopologyMismatch(
            f"{context} was saved at topology ({saved}) but ({want}) was "
            "requested — reshard the state with "
            "dist_svgd_tpu.utils.checkpoint.reshard_state(state, n_shards) "
            "(shard counts convert exactly; particle count / dimension "
            "cannot change)"
        )
    return man


def reshard_previous_stack(prev_arr: np.ndarray, n: int, d: int,
                           want: tuple) -> np.ndarray:
    """Convert a Wasserstein ``previous`` snapshot stack saved under one
    shard layout to the layout ``want`` — exactly, by reconstructing the
    shard-independent pre/post-update global states the stacks encode:

    - the post-update global is the concatenation of each shard's own
      block (exchanged stacks carry it inside the mixed snapshots;
      ``partitions``/block stacks ARE it);
    - exchanged stacks at ``S_old ≥ 2`` additionally carry every
      pre-update row (each block's pre value sits in any *other* shard's
      snapshot), so a mixed stack at any new S can be rebuilt verbatim.

    A target layout needing pre-update rows the save does not contain
    (block-only save → mixed S>1 target) raises ``ValueError``.  Shared by
    ``DistSampler.load_state_dict``'s reshard-on-restore and
    :func:`reshard_state`."""
    if prev_arr.shape == want:
        return prev_arr
    if prev_arr.ndim != 3 or prev_arr.shape[2] != d:
        raise ValueError(
            f"checkpoint 'previous' snapshot {prev_arr.shape} is not a "
            f"snapshot stack for {n} particles of dim {d}"
        )
    S_old, rows = prev_arr.shape[0], prev_arr.shape[1]
    exch_save = rows == n              # mixed per-shard snapshots
    part_save = rows * S_old == n      # owned-block stacks (S_old == 1:
    if not (exch_save or part_save):   # both — the post-update global)
        raise ValueError(
            f"checkpoint 'previous' snapshot {prev_arr.shape} matches "
            f"neither a mixed (S, {n}, {d}) nor an owned-block "
            f"(S, {n}//S, {d}) stack for {n} particles"
        )
    if exch_save:
        s_old = n // S_old
        post = np.concatenate(
            [prev_arr[b, b * s_old:(b + 1) * s_old] for b in range(S_old)]
        )
    else:
        post = prev_arr.reshape(n, d)
    S_new = want[0]
    if want[1] != n:
        # block-sized target (partitions, or exchanged w2_pairing='block'):
        # owned-block (post-update) stacks
        return post.reshape(want)
    if S_new == 1:
        # the (1, n, d) stack is just the post-update global, whichever
        # mode family wrote the save
        return post.reshape(1, n, d)
    # exchanged target at S_new > 1: needs the pre-update rows
    if not exch_save or S_old < 2:
        raise ValueError(
            f"cannot reshard 'previous' {prev_arr.shape} to {want}: the "
            "save holds only post-update blocks (partitions-mode, "
            "w2_pairing='block', or single-shard save), but a global-"
            f"pairing exchanged stack at num_shards={S_new} needs the "
            "pre-update rows it never recorded"
        )
    s_old = n // S_old
    pre = np.empty_like(post)
    for b in range(S_old):
        # block b's pre-update rows live in any OTHER shard's snapshot
        pre[b * s_old:(b + 1) * s_old] = (
            prev_arr[(b + 1) % S_old, b * s_old:(b + 1) * s_old]
        )
    out = np.broadcast_to(pre, (S_new, n, d)).copy()
    s_new = n // S_new
    for r in range(S_new):
        out[r, r * s_new:(r + 1) * s_new] = post[r * s_new:(r + 1) * s_new]
    return out


def reshard_state(state: Dict[str, Any], n_shards_to: int) -> Dict[str, Any]:
    """Reshape a full-global checkpoint saved at N shards into one loadable
    at ``n_shards_to`` — the elastic-capacity primitive (a run checkpointed
    at 8 shards resumes at 4 after a device loss, or at 8 again after the
    capacity comes back).

    What converts, and how:

    - **particles**: unchanged.  The global array is stored in logical block
      order, which is shard-layout-free — regrouping N blocks into M is a
      pure reinterpretation of the same rows, no permutation;
    - **Wasserstein ``previous`` stack**: rebuilt exactly for the new shard
      count in the family the save used (:func:`reshard_previous_stack`);
      a stack only the loader can finish adapting (mode-dependent target)
      is passed through for ``load_state_dict``'s reshard-on-restore;
    - **Sinkhorn duals** (``w2_g``): *invalidated explicitly* whenever the
      shard count actually changes — their per-block pairing does not
      survive a layout change, so the first resumed solve cold-starts from
      zeroed duals (the safe soft-transform start; trajectory within the
      solver's tol band).  A same-count reshard keeps them.  Ring-hop
      chunk carries never enter a checkpoint (they live only inside one
      ``run_steps`` dispatch chain), so there is nothing to invalidate;
    - **RNG**: the stamped minibatch root key (``rng_batch_key``) is kept
      verbatim — the per-step streams fold ``(root, t)`` and are therefore
      shard-layout-free, so every later key re-derives deterministically
      from the saved root on any mesh;
    - **kernel-approximation identity** (``approx_method`` /
      ``approx_dial`` / ``approx_bank_key`` / ``approx_landmark_idx``,
      stamped by approximate-φ runs — ``ops/approx.py``): passed through
      verbatim.  The RFF bank derives from the key alone and Nyström
      landmarks re-derive from the (layout-free) global particle order, so
      a resharded resume reconstructs the identical approximation;
    - **manifest**: restamped for the new topology, with
      ``topo_resharded_from`` recording the source shard count.

    A target that does not divide the particle count takes the SAME
    replicate-and-warn fallback as ``Plan.shard_ensemble`` (the state lands
    at 1 shard — correct, no longer distributed).  Per-process block saves
    must be assembled first (:func:`assemble_full_state`); resharding a
    lone block raises."""
    M = int(n_shards_to)
    if M < 1:
        raise ValueError(f"n_shards_to must be >= 1, got {M}")
    parts = state.get("particles")
    if parts is None:
        raise ValueError("reshard_state needs a 'particles' entry — is this "
                         "a sampler checkpoint?")
    if int(np.asarray(state.get("particles_start", 0))) != 0:
        raise ValueError(
            "reshard_state needs the FULL global state, but this dict is a "
            "per-process block (particles_start != 0) — assemble every "
            "process's save with assemble_full_state first"
        )
    parts = np.asarray(parts)
    n = parts.shape[0]
    d = parts.shape[1] if parts.ndim > 1 else 1
    man = read_manifest(state)
    if man is None:
        warnings.warn(
            "checkpoint carries no readable topology manifest (pre-elastic "
            f"save, or corrupt entries): inferring n={n}, d={d} from the "
            "particle array and resharding anyway",
            stacklevel=2,
        )
        S_old = None
    else:
        if man["n_particles"] != n:
            raise TopologyMismatch(
                f"manifest says {man['n_particles']} particles but the "
                f"'particles' array holds {n} rows — corrupt or mixed-up "
                "checkpoint"
            )
        S_old = man["n_shards"]
    if n % M:
        from dist_svgd_tpu.parallel.plan import nondividing_replicate_warning

        warnings.warn(nondividing_replicate_warning(n, M), UserWarning,
                      stacklevel=2)
        M = 1
    out = dict(state)
    prev = out.get("previous")
    if prev is not None:
        prev_arr = np.asarray(prev)
        if prev_arr.ndim == 3 and prev_arr.shape[2] == d:
            mixed = prev_arr.shape[1] == n and prev_arr.shape[0] >= 2
            want = (M, n, d) if (mixed and M > 1) else (
                (1, n, d) if M == 1 else (M, n // M, d))
            try:
                out["previous"] = reshard_previous_stack(prev_arr, n, d, want)
            except ValueError:
                # mode-dependent target the loader knows better — leave the
                # stack for load_state_dict's reshard-on-restore
                pass
    # duals: per-block pairing does not survive a layout CHANGE — drop
    # them explicitly so the first resumed solve cold-starts (documented).
    # A same-count reshard (or an unknown source count) with no change to
    # make keeps them: the pairing is still valid and cold-starting would
    # needlessly re-pay the warm-start win.
    if S_old != M:
        out.pop("w2_g", None)
        out.pop("w2_g_start", None)
    rows_ps = man["data_rows_per_shard"] if man is not None else 0
    total_rows = rows_ps * (S_old or 1)
    out.update(topology_manifest(M, n, d, total_rows // M))
    if S_old is not None:
        out["topo_resharded_from"] = np.asarray(S_old, dtype=np.int64)
    return out


def _to_numpy_tree(tree: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in tree.items():
        if v is None:
            continue
        out[k] = np.asarray(v)
    return out


def _use_orbax() -> bool:
    """Orbax for single-process saves only.  Its PyTreeCheckpointer runs
    cross-process barriers keyed by the checkpoint path; the framework's
    multi-host contract is per-process state (each process saves its own
    addressable block to its own path — ``DistSampler.state_dict``), where
    those barriers deadlock until the coordination-service timeout.  The
    plain ``.npz`` layout is the correct per-process backend."""
    try:
        import orbax.checkpoint  # noqa: F401
    except ImportError:
        return False
    try:
        import jax

        return jax.process_count() == 1
    except Exception:
        # process count unknowable (partially-initialized/torn-down runtime):
        # .npz works everywhere; orbax is only safe when provably single-process
        return False


def save_state(path: str, state: Dict[str, Any], backend: str = "auto") -> str:
    """Persist a flat dict of arrays/scalars (``None`` values are elided).

    ``backend='auto'`` picks per :func:`_use_orbax`: Orbax on provably
    single-process runs, ``.npz`` otherwise (multi-process per-path saves
    deadlock Orbax's barriers).  ``backend='npz'`` forces the plain layout —
    the right choice for **high-frequency periodic** saves (the resilience
    supervisor's cadence): an orbax save costs a fixed ~quarter second of
    directory/manifest machinery regardless of array size, while an npz of
    sampler-sized state is ~a millisecond; both layouts are self-describing
    and :func:`load_state` auto-detects them, so readers never care.
    ``path`` is a directory; an existing checkpoint there is replaced
    atomically enough for single-writer use (removed then rewritten).
    """
    if backend not in ("auto", "npz"):
        raise ValueError(f"unknown checkpoint backend {backend!r}")
    state = _to_numpy_tree(state)
    path = os.path.abspath(path)
    # write-tmp-then-rename: a crash mid-write leaves only a stale .tmp dir,
    # never a truncated checkpoint at the final path
    tmp = path + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    if backend == "auto" and _use_orbax():
        import orbax.checkpoint as ocp

        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(tmp, state)
    else:
        os.makedirs(tmp)
        np.savez(os.path.join(tmp, _NPZ_NAME), **state)
    if os.path.exists(path):
        shutil.rmtree(path)
    os.rename(tmp, path)
    return path


#: Files whose presence marks an orbax-layout checkpoint (PyTreeCheckpointer
#: writes `_METADATA`/`_CHECKPOINT_METADATA` plus ocdbt manifests).
_ORBAX_MARKERS = ("_METADATA", "_CHECKPOINT_METADATA", "manifest.ocdbt")


def _looks_like_orbax(path: str, entries) -> bool:
    return any(m in entries for m in _ORBAX_MARKERS) or any(
        e.startswith("ocdbt.process_") for e in entries
    )


def load_state(path: str,
               expect_topology: Optional[Dict[str, int]] = None
               ) -> Dict[str, Any]:
    """Load a checkpoint written by :func:`save_state` (auto-detects layout).

    A directory holding neither layout — empty, or stray files without the
    npz or any orbax marker (partial writes from a killed pre-rename-era
    writer) — raises ``ValueError`` *before* the orbax import, so
    ``CheckpointManager.restore_latest`` can classify it as corruption and
    fall back to an older step even when orbax is not installed
    (``ImportError`` is reserved for a checkpoint that IS orbax-layout in an
    orbax-less environment, which must propagate).

    ``expect_topology`` (any subset of ``n_shards`` / ``n_particles`` /
    ``d``) is compared against the saved topology manifest the moment the
    dict is read: a mismatch raises :class:`TopologyMismatch` naming both
    shapes before any array op — instead of the raw reshape/broadcast error
    a mismatched load used to die with deep inside jax."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise FileNotFoundError(f"no checkpoint directory at {path}")
    npz = os.path.join(path, _NPZ_NAME)
    state = None
    if os.path.exists(npz):
        with np.load(npz) as data:
            state = {k: data[k] for k in data.files}
    if state is None:
        entries = os.listdir(path)
        if not _looks_like_orbax(path, entries):
            raise ValueError(
                f"checkpoint directory {path} holds neither layout "
                f"(entries: {sorted(entries)[:5]}) — partial write from a "
                "killed save?"
            )
        import orbax.checkpoint as ocp

        with ocp.PyTreeCheckpointer() as ckptr:
            restored = ckptr.restore(path)
        state = dict(restored)
    if expect_topology:
        check_topology(state, expect_topology, context=f"checkpoint {path}")
    return state


def assemble_full_state(paths,
                        expect_topology: Optional[Dict[str, int]] = None
                        ) -> Dict[str, Any]:
    """Assemble the per-process block checkpoints of ONE multi-host save
    into a full-global state dict, enabling **cross-process-count restore**
    (round-5, VERDICT r04 item 7).

    A multi-host ``DistSampler.state_dict`` holds only each process's
    contiguous axis-0 block plus its ``<key>_start`` offset
    (``parallel/multihost.py:host_addressable_block``).  A federation with a
    *different* process partitioning cannot restore any single file — its
    row ranges don't match — but the mesh *size* (and therefore every
    global array's shape) is process-layout-independent, so concatenating
    every saved block along axis 0 reconstructs the exact global state,
    which ``load_state_dict`` then re-slices for the new layout (its
    full-save branch).  Every process of the new federation calls this on
    the complete list of old per-process paths.

    Raises ``ValueError`` when the blocks are not contiguous from row 0
    (paths from different saves, or an incomplete list).
    ``expect_topology`` is checked against each file's saved manifest
    **before** any block is concatenated (:class:`TopologyMismatch` instead
    of a shape error mid-assembly)."""
    states = [load_state(p) for p in paths]
    if not states:
        raise ValueError("assemble_full_state needs at least one checkpoint")
    if expect_topology:
        for p, s in zip(paths, states):
            check_topology(s, expect_topology, context=f"checkpoint {p}")
    out: Dict[str, Any] = {}
    keys = {k for s in states for k in s if not k.endswith("_start")}
    for key in keys:
        # classify replicated-vs-block from the first state that actually
        # CONTAINS the key — classifying from states[0] alone turned a
        # mixed-version/corrupt save (key present only in later files) into
        # a bare KeyError instead of the diagnosis below (ADVICE round 5)
        holders = [s for s in states if s.get(key) is not None]
        if not holders:
            out[key] = None
            continue
        has_start = any(key + "_start" in s for s in holders)
        if not has_start:
            # a scalar/replicated entry (t): must be present and identical
            # in every file — a mismatch (or partial presence) means the
            # paths mix two different saves (the contiguity check below
            # cannot catch that when the row layouts happen to line up)
            if len(holders) != len(states):
                raise ValueError(
                    f"checkpoint files disagree on the presence of {key!r} "
                    f"({len(holders)} of {len(states)} files carry it) — "
                    "are these paths from one complete multi-host save?"
                )
            for s in holders[1:]:
                if not np.array_equal(np.asarray(s[key]),
                                      np.asarray(holders[0][key])):
                    raise ValueError(
                        f"checkpoint files disagree on {key!r} "
                        f"({np.asarray(holders[0][key])} vs "
                        f"{np.asarray(s[key])}) — are these paths from one "
                        "complete multi-host save?"
                    )
            out[key] = holders[0][key]
            continue
        parts = [
            (int(np.asarray(s.get(key + "_start", 0))), s[key])
            for s in holders
        ]
        parts.sort(key=lambda p: p[0])
        cursor = 0
        for start, rows in parts:
            if start != cursor:
                raise ValueError(
                    f"checkpoint blocks for {key!r} are not contiguous: "
                    f"expected a block starting at row {cursor}, got {start} "
                    "— are these paths from one complete multi-host save?"
                )
            cursor += rows.shape[0]
        out[key] = np.concatenate([rows for _, rows in parts])
    # the assembled dict IS the full-global state: restamp the process
    # layout as single-process so the manifest describes what the dict now
    # holds, not the federation that wrote the blocks
    man = read_manifest(out)
    if man is not None:
        out["topo_process_count"] = np.asarray(1, dtype=np.int64)
        out["topo_granule_shards"] = np.full(1, man["n_shards"],
                                             dtype=np.int64)
    return out


#: State keys a multi-process ``DistSampler.state_dict`` saves as
#: per-process blocks (``host_addressable_block``); everything else is
#: replicated verbatim in every process's file.
BLOCK_KEYS = ("particles", "previous", "w2_g")


def split_state_for_processes(state: Dict[str, Any],
                              process_count: int) -> List[Dict[str, Any]]:
    """Split a FULL single-process state dict into the ``process_count``
    per-process block dicts the same run would have saved from a
    multi-process federation — the emulation seam for exercising the
    host-sharded checkpoint path (save blocks → ``assemble_full_state`` →
    restore) without a real multi-process runtime.

    Mirrors ``DistSampler.state_dict``: :data:`BLOCK_KEYS` arrays are cut
    along axis 0 at this layout's shard boundaries (each process owns an
    equal contiguous run of shards, the granule-major mesh contract) with
    ``<key>_start`` offsets; every other entry — including the topology
    manifest, restamped with the process layout — is replicated bitwise in
    every block, exactly what ``assemble_full_state`` requires."""
    W = int(process_count)
    if W < 1:
        raise ValueError(f"process_count must be >= 1, got {W}")
    if int(np.asarray(state.get("particles_start", 0))) != 0:
        raise ValueError(
            "split_state_for_processes needs the FULL global state, but "
            "this dict is already a per-process block (particles_start != 0)"
        )
    man = read_manifest(state)
    if man is None:
        raise ValueError(
            "split_state_for_processes needs a manifest-stamped state "
            "(topo_* entries) to know the shard layout"
        )
    S, n, d = man["n_shards"], man["n_particles"], man["d"]
    if S % W:
        raise ValueError(f"process_count {W} must divide n_shards {S}")
    shards_per = S // W
    stamp = topology_manifest(
        S, n, d, man["data_rows_per_shard"],
        process_count=W, granule_shards=(shards_per,) * W,
    )
    blocks: List[Dict[str, Any]] = []
    for p in range(W):
        blk: Dict[str, Any] = {}
        for key, value in state.items():
            if key in stamp or key.endswith("_start"):
                continue
            arr = None if value is None else np.asarray(value)
            if key in BLOCK_KEYS and arr is not None and arr.ndim >= 1:
                L = arr.shape[0]
                if L % S:
                    raise ValueError(
                        f"state entry {key!r} has leading dim {L} not "
                        f"divisible by n_shards {S} — not a sharded array?"
                    )
                per_shard = L // S
                lo = p * shards_per * per_shard
                hi = (p + 1) * shards_per * per_shard
                blk[key] = arr[lo:hi]
                blk[key + "_start"] = np.asarray(lo, dtype=np.int64)
            else:
                blk[key] = value
        blk.update(stamp)
        blocks.append(blk)
    return blocks


class CheckpointManager:
    """Every-K-steps checkpointing with retention.

    Layout: ``<root>/step_<t>/`` per checkpoint, newest ``max_to_keep`` kept.
    ``backend`` forwards to :func:`save_state` (``'npz'`` for high-frequency
    periodic cadences — see its docstring; reads auto-detect either way).
    """

    def __init__(self, root: str, every: int = 100, max_to_keep: int = 3,
                 backend: str = "auto"):
        if every <= 0:
            raise ValueError("every must be positive")
        if backend not in ("auto", "npz"):
            raise ValueError(f"unknown checkpoint backend {backend!r}")
        self.root = os.path.abspath(root)
        self.every = every
        self.max_to_keep = max_to_keep
        self.backend = backend
        os.makedirs(self.root, exist_ok=True)

    def _step_dirs(self) -> List[int]:
        steps = []
        for name in os.listdir(self.root):
            m = _STEP_DIR_RE.match(name)
            if m and os.path.isdir(os.path.join(self.root, name)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def should_save(self, step: int) -> bool:
        return step > 0 and step % self.every == 0

    def save(self, step: int, state: Dict[str, Any]) -> str:
        path = save_state(os.path.join(self.root, f"step_{step}"), state,
                          backend=self.backend)
        for old in self._step_dirs()[: -self.max_to_keep or None]:
            if old != step:
                shutil.rmtree(os.path.join(self.root, f"step_{old}"), ignore_errors=True)
        return path

    def latest_step(self) -> Optional[int]:
        steps = self._step_dirs()
        return steps[-1] if steps else None

    def restore_latest(self, with_step: bool = False):
        """Restore the newest *loadable* checkpoint, falling back past any
        that fail to load (e.g. a partial write from a pre-rename crash of an
        older writer) and warning about the skip.  ``with_step=True``
        returns ``(step, state)`` instead of ``state`` alone (``(None,
        None)`` when nothing is restorable) — the hot-reload watcher needs
        the step to tell a *new* checkpoint from the one already served."""
        for step in reversed(self._step_dirs()):
            path = os.path.join(self.root, f"step_{step}")
            try:
                state = load_state(path)
                return (step, state) if with_step else state
            except ImportError:
                # environment problem (orbax-format checkpoint, no orbax
                # installed) — not corruption; skipping would silently restart
                # from scratch and eventually retention-delete the real state
                raise
            except Exception as e:  # corrupt/partial — try the next-oldest
                import warnings

                warnings.warn(
                    f"skipping unloadable checkpoint {path}: {type(e).__name__}: {e}"
                )
        return (None, None) if with_step else None

    def clear(self) -> None:
        """Delete every checkpoint under the root (fresh-run hygiene: a new
        run writing into a dir holding an older run's step dirs would let
        retention keep the *stale* high-step checkpoints and delete its own)."""
        for step in self._step_dirs():
            shutil.rmtree(os.path.join(self.root, f"step_{step}"), ignore_errors=True)
