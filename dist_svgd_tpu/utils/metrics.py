"""Structured metrics, timing, and profiling hooks.

The reference's observability is ``print('Iteration {}')`` progress lines and
shell ``time`` (dsvgd/sampler.py:63, grid.sh:6-8; SURVEY.md §5).  The
TPU-native replacement here:

- :class:`JsonlLogger` — structured per-step scalars as JSON lines to a file
  and/or a stream (machine-readable sweeps instead of visdom's live server);
- :func:`particle_stats` — one small jitted program computing the per-step
  scalars worth logging (mean particle norm, dispersion, update magnitude) so
  logging costs one tiny device→host transfer, not a full-array sync;
- :class:`StepTimer` — wall-clock timing with ``block_until_ready`` fencing
  for honest updates/sec (async dispatch otherwise under-counts);
- :func:`profiler_trace` — ``jax.profiler.trace`` context for TensorBoard-
  readable device traces (``tools/profile_step_floor.py --jax-trace DIR``
  wires it into the floor decomposition).

These are the per-record primitives; the *aggregating* layer — counters,
gauges, latency histograms with Prometheus exposition, and causal span
traces — lives in :mod:`dist_svgd_tpu.telemetry` (round 10).  ``JsonlLogger``
doubles as the tracer's JSONL exporter sink, and ``StepTimer`` can mirror
its laps as tracer spans (``span_name=``).
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import IO, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dist_svgd_tpu.telemetry import profile as _profile


class JsonlLogger:
    """Append-only JSON-lines metric log.

    Each :meth:`log` call writes one line ``{"ts": <unix>, **record}``.
    ``path`` and ``stream`` may both be given (e.g. file + stderr echo).

    Lifecycle contract (round 8 — crash-log integrity for supervised runs):
    every line is flushed as it is written (``fsync=True`` additionally
    forces it to the OS disk cache per line, the right setting for the
    resilience supervisor's crash logs — a SIGKILL then truncates nothing),
    writers from several threads interleave whole lines (internal lock, the
    batcher + server + supervisor share one logger), :meth:`close` is
    idempotent, logging after close raises ``ValueError`` instead of
    silently dropping records, and the context manager closes on the way
    out of a crashing ``with`` block.
    """

    def __init__(self, path: Optional[str] = None, stream: Optional[IO] = None,
                 fsync: bool = False):
        import threading

        self._fh = open(path, "a") if path is not None else None
        self._stream = stream
        self._fsync = bool(fsync)
        self._lock = threading.Lock()
        self._closed = False

    @property
    def closed(self) -> bool:
        """True once :meth:`close` ran (a sink-less ``JsonlLogger()`` is a
        valid null sink and stays open until closed)."""
        return self._closed

    def log(self, **record) -> dict:
        record = {"ts": round(time.time(), 3), **record}
        line = json.dumps(record, default=_json_default)
        with self._lock:
            if self.closed:
                raise ValueError("log() after close(): the record would be "
                                 "silently dropped")
            if self._fh is not None:
                self._fh.write(line + "\n")
                self._fh.flush()
                if self._fsync:
                    import os

                    os.fsync(self._fh.fileno())
            if self._stream is not None:
                self._stream.write(line + "\n")
        return record

    def flush(self) -> None:
        """Flush the file handle (and fsync when enabled) — for callers that
        batch several :meth:`log` lines and want a durability point."""
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                if self._fsync:
                    import os

                    os.fsync(self._fh.fileno())

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            self._stream = None  # caller-owned: dropped, not closed

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _json_default(o):
    if isinstance(o, (np.generic,)):
        return o.item()
    if isinstance(o, (np.ndarray, jax.Array)):
        return np.asarray(o).tolist()
    raise TypeError(f"not JSON serialisable: {type(o)}")


@jax.jit
def _stats(particles, prev):
    norms = jnp.linalg.norm(particles, axis=1)
    delta = jnp.linalg.norm(particles - prev, axis=1)
    return (
        jnp.mean(norms),
        jnp.std(norms),
        jnp.mean(particles, axis=0).mean(),
        jnp.mean(delta),
        jnp.max(delta),
    )


def particle_stats(particles, prev=None) -> dict:
    """Per-step scalar diagnostics as plain floats.

    ``prev`` (the pre-step array) adds update-magnitude stats — the honest
    φ-norm proxy: ``mean_update = ε·mean‖φ̂ + h·w_grad‖``.
    """
    if prev is None:
        prev = particles
    mean_norm, std_norm, mean_val, mean_delta, max_delta = _stats(particles, prev)
    out = {
        "particle_mean_norm": float(mean_norm),
        "particle_norm_std": float(std_norm),
        "particle_mean": float(mean_val),
    }
    if prev is not particles:
        out["mean_update"] = float(mean_delta)
        out["max_update"] = float(max_delta)
    return out


class StepTimer:
    """Fenced step timing: ``mark(value)`` blocks on ``value`` (device fence)
    and records the wall time since the previous mark.

    ``span_name`` bridges into the telemetry tracer: while
    ``telemetry.enable()`` is active, every lap additionally records a
    completed span of that name (explicit timestamps — the fence already
    happened, so the span covers the honest device wall).  The tracer's
    fencing discipline is this class's, inherited; disabled tracing costs
    one ``None`` check per mark.

    The fence routes through :func:`dist_svgd_tpu.telemetry.profile.
    fence`: when the dispatch profiler is enabled it has *already* fenced
    the value this mark is handed, and fencing twice would bill the
    device round-trip to both windows — ``fence`` consumes the
    profiler's note and blocks at most once per dispatch."""

    def __init__(self, span_name: Optional[str] = None):
        self._last = time.perf_counter()
        self._span_name = span_name
        self.laps: list = []

    def mark(self, value=None) -> float:
        if value is not None:
            _profile.fence(value)
        now = time.perf_counter()
        lap = now - self._last
        self._last = now
        self.laps.append(lap)
        if self._span_name is not None:
            from dist_svgd_tpu.telemetry import trace as _trace

            tracer = _trace.get_tracer()
            if tracer is not None:
                end = tracer.now()
                tracer.complete(self._span_name, max(end - lap, 0.0), end)
        return lap

    @property
    def total(self) -> float:
        return sum(self.laps)

    def updates_per_sec(self, updates_per_lap: int) -> float:
        """Throughput over all recorded laps."""
        return len(self.laps) * updates_per_lap / self.total if self.laps else 0.0


@contextlib.contextmanager
def profiler_trace(logdir: Optional[str]):
    """``jax.profiler.trace`` context; no-op when ``logdir`` is falsy."""
    if not logdir:
        yield
        return
    with jax.profiler.trace(logdir):
        yield
