"""Micro-batching request queue: coalesce concurrent predict requests into
one fused device call, scatter results back per-request.

Why: a TPU/XLA predictive kernel has a per-dispatch floor that dwarfs the
marginal cost of extra rows (docs/notes.md step-floor decomposition) — N
concurrent 1-row dispatches waste N-1 floors.  The batcher holds the first
request of a batch for at most ``max_wait_ms`` while coalescing whatever
else arrives, up to ``max_batch`` rows, then issues ONE dispatch over the
whole ensemble and slices the result back to each caller's future.

Backpressure is explicit: the queue is bounded at ``max_queue_rows`` and
``submit`` raises :class:`Overloaded` instead of growing without bound — a
shed request costs the client one clean error, an unbounded queue costs
every client unbounded latency.

Oversize requests (> ``max_batch`` rows) split into ``max_batch``-row chunks
that ride separate batches and reassemble before the future resolves — a
request can never deadlock waiting for a batch slot bigger than batches get.

Time is injectable (``clock`` + ``wait``) so tests drive ``max_wait_ms``
expiry deterministically instead of real-sleeping (tier-1 has no
multi-hundred-ms waits); production uses ``time.monotonic`` and plain
condition waits.

Telemetry (round 10): every batcher writes process-wide counters, the
queue-depth gauge, and latency histograms into the shared
``telemetry.MetricsRegistry`` (``registry=`` for an isolated one — benches
and tests), and, while the span tracer is enabled, emits one **request lane
tree** per completed request — ``serve.request`` with ``serve.queue_wait`` /
``serve.coalesce`` / ``serve.dispatch`` children, tagged with rows and batch
occupancy — the "where did this slow request spend its time" view.
:meth:`stats` keeps its per-instance bounded-window semantics (the registry
aggregates across instances and over the process lifetime).

Worker lanes (round 12): ``lanes=N`` runs N dispatch workers over the one
shared queue, so ``queue_wait`` stops serializing behind a single in-flight
device call — while lane 0's dispatch blocks on the fetch, lane 1 pops the
next coalesced batch and dispatches it (the engine's kernel lookup is
lock-snapshotted and the XLA execution itself releases the GIL, so lanes
genuinely overlap; with a mesh-sharded engine every lane's batch still uses
all devices).  Each lane is labelled in telemetry
(``svgd_serve_lane_batches_total{lane=...}``, the per-lane in-flight gauge)
and tagged on its request lane trees, so a stuck lane is visible instead of
averaged away.

Multi-tenant requests (round 14): ``submit(x, tenant=name)`` queues the
request under a tenant identity.  One bounded queue carries every tenant's
chunks; a batch only ever coalesces chunks of ONE tenant (different
tenants hit different engines with different shapes — fusing them would be
wrong, not just slow), and the dispatch callable is invoked as
``dispatch(x, tenant)`` for tenant requests (``dispatch(x)`` unchanged for
tenant-less ones).  ``quotas={tenant: max_inflight_rows}`` (a live mapping
— the :class:`~dist_svgd_tpu.serving.registry.ModelRegistry` shares its
own) arms **shed priorities**: while the queue has room, quotas are inert;
when an arriving request would overflow ``max_queue_rows``, tenants over
their quota shed FIRST — an over-quota submitter is refused outright, and
otherwise the newest queued requests of over-quota tenants are shed (whole
requests, ``Overloaded`` on their futures) to make room for the under-
quota arrival.  A hog tenant degrades itself; polite tenants keep their
admission.  Every serving metric/histogram/lane tree carries a ``tenant``
label for tenant requests (tenant-less series stay unlabelled — the
single-tenant deployment is byte-identical), plus
``svgd_serve_quota_sheds_total{tenant=...}`` and the per-tenant queued-
rows gauge.

Live capacity retune (round 18): :meth:`MicroBatcher.set_lanes` spawns or
retires dispatch workers while the batcher serves (retiring lanes finish
their in-flight batch, re-check the live target, and exit — lock-safe
against concurrent submits), and :meth:`MicroBatcher.set_max_wait_ms`
changes the coalescing window for batches already waiting (collectors
re-derive the flush deadline from the live window every wakeup).  These
are the :mod:`~dist_svgd_tpu.serving.autoscale` controller's actuation
seams; the current targets are scrapeable as ``svgd_serve_lanes`` /
``svgd_serve_max_wait_ms`` gauges, and every :class:`Overloaded` drain
estimate reads the live knobs (window, queue depth, lane count) at shed
time so Retry-After stays honest across retunes.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from concurrent.futures import CancelledError, Future, InvalidStateError
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from dist_svgd_tpu.telemetry import metrics as _metrics
from dist_svgd_tpu.telemetry import trace as _trace
from dist_svgd_tpu.telemetry import usage as _usage

#: Batch-occupancy buckets (rows per dispatched batch): powers of two up to
#: the queue bound's usual order of magnitude.
_BATCH_ROW_BUCKETS = tuple(float(1 << i) for i in range(14))

#: Per-process batcher ids for the instance-labelled gauge series.
_INSTANCE_IDS = itertools.count()


class Overloaded(RuntimeError):
    """Raised by :meth:`MicroBatcher.submit` when the bounded queue is full.

    ``retry_after_s`` (round 15) is the batcher's own estimate of when the
    backlog will have drained enough to admit a retry — derived from the
    coalescing window and the queue depth at shed time (one ``max_batch``
    batch drains per ``max_wait_ms`` window at worst, plus one window for
    the retry itself).  The HTTP layer surfaces it as a 429
    ``Retry-After`` and the fleet router honors it instead of its generic
    backoff — the replica knows its queue better than the caller does."""

    def __init__(self, msg: str, retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


def _default_wait(cond: threading.Condition, timeout: Optional[float]) -> bool:
    return cond.wait(timeout)


class _Request:
    """One client submit(): a future plus chunk-reassembly state.

    ``trace_enq`` is the tracer-clock enqueue timestamp and ``trace_src``
    the tracer it was read from (both None while tracing is disabled) — the
    batcher clock is injectable and test-faked, so the span timeline keeps
    its own honest clock, and a disable()/enable() cycle mid-flight resets
    the epoch, so a timestamp is only meaningful against the same tracer."""

    __slots__ = ("future", "n_chunks", "parts", "enqueued", "trace_enq",
                 "trace_src", "tenant", "trace", "generation", "mirror")

    def __init__(self, n_chunks: int, enqueued: float,
                 trace_enq: Optional[float] = None, trace_src=None,
                 tenant: Optional[str] = None,
                 trace: Optional[str] = None,
                 generation: Optional[str] = None,
                 mirror: bool = False):
        self.future: Future = Future()
        self.n_chunks = n_chunks
        self.parts: List[Optional[Dict[str, np.ndarray]]] = [None] * n_chunks
        self.enqueued = enqueued
        self.trace_enq = trace_enq
        self.trace_src = trace_src
        self.tenant = tenant
        self.trace = trace
        # progressive delivery (round 21): which generation serves this
        # request (None = incumbent, "candidate" = the rollout's hash
        # split routed it to the staged candidate), and whether the
        # incumbent answer should be shadow-mirrored to the candidate
        self.generation = generation
        self.mirror = mirror


class _Chunk:
    """A ≤ max_batch slice of one request, as queued."""

    __slots__ = ("x", "req", "index")

    def __init__(self, x: np.ndarray, req: _Request, index: int):
        self.x = x
        self.req = req
        self.index = index


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class MicroBatcher:
    """Coalescing dispatch queue in front of a ``dispatch(x) -> dict`` callable
    (typically :meth:`PredictiveEngine.predict`).

    Args:
        dispatch: called with one ``(rows, feature_dim)`` array per batch;
            must return a dict of arrays with leading dimension ``rows``.
        max_batch: coalescing ceiling in rows; larger requests split.
        lanes: dispatch worker threads over the shared queue (default 1 —
            the old serialized behavior).  More lanes overlap device
            dispatch with coalescing and with other dispatches; pair with
            a mesh-sharded engine to keep every device busy.
        quotas: live ``{tenant: max_inflight_rows}`` mapping (``None``
            values exempt a tenant) read under the batcher lock on every
            overflow — mutate it to retune quotas without rebuilding the
            batcher.  Quotas only bite when the bounded queue fills: see
            the module docstring's shed-priority contract.
        max_wait_ms: how long the oldest queued request may wait for
            co-travellers before a partial batch is flushed.
        max_queue_rows: bound on queued (not-yet-dispatched) rows; beyond it
            ``submit`` sheds with :class:`Overloaded`.
        clock / wait: injectable time source and condition-wait, for
            deterministic tests.  ``wait(cond, timeout)`` must behave like
            ``cond.wait`` (held lock, returns after notify or timeout).
        logger: optional ``JsonlLogger``; one record per dispatched batch
            (rows, request count, queue-wait vs device-time split).
        registry: ``telemetry.MetricsRegistry`` to write counters / the
            queue-depth gauge / latency histograms into (default: the
            process-wide :func:`~dist_svgd_tpu.telemetry.default_registry`).
        autostart: start the worker thread immediately.  Tests that need a
            deterministic pre-filled queue pass False, submit, then
            :meth:`start`.
    """

    def __init__(
        self,
        dispatch: Callable[[np.ndarray], Dict[str, np.ndarray]],
        *,
        max_batch: int = 256,
        lanes: int = 1,
        max_wait_ms: float = 2.0,
        max_queue_rows: int = 8192,
        quotas: Optional[Dict[str, Optional[int]]] = None,
        clock: Callable[[], float] = time.monotonic,
        wait: Callable[[threading.Condition, Optional[float]], bool] = _default_wait,
        logger=None,
        registry: Optional[_metrics.MetricsRegistry] = None,
        autostart: bool = True,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if lanes < 1:
            raise ValueError("lanes must be >= 1")
        if max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")
        if max_queue_rows < max_batch:
            raise ValueError("max_queue_rows must be >= max_batch")
        self._dispatch = dispatch
        self.max_batch = int(max_batch)
        #: Live lane target (round 18): :meth:`set_lanes` retunes it while
        #: the batcher runs — lanes at index >= the target retire after
        #: their in-flight batch; missing lanes spawn.  Read-only outside.
        self.lanes = int(lanes)
        self._max_wait_s = float(max_wait_ms) / 1e3
        self.max_queue_rows = int(max_queue_rows)
        self._clock = clock
        self._wait = wait
        self._logger = logger

        self._cond = threading.Condition()
        self._queue: deque = deque()  # of _Chunk
        self._queued_rows = 0
        self._open = True
        # multi-tenant state (round 14): live quota mapping (shared with
        # the ModelRegistry that mutates it), queued rows and quota-shed
        # counts per tenant — all guarded by _cond's lock
        self._quotas = quotas if quotas is not None else {}
        # 'overflow' (round 14, default): quotas bite only when the
        # bounded queue fills.  'admission' (round 18): an over-quota
        # tenant is refused at submit time even with queue room — the
        # autoscale controller flips this on WHILE quotas are tightened
        # under overload, so a flooding tenant's queue occupancy (and
        # therefore everyone's queue delay) stays bounded between
        # overflow events, and flips it back when calm restores quotas.
        self._quota_mode = "overflow"
        # progressive delivery (round 21): an armed RolloutController
        # assigns each arriving request a generation (deterministic hash
        # split) and flags incumbent requests for shadow mirroring; the
        # submit ordinal is the hash key (guarded by _cond's lock)
        self._rollout = None
        self._submit_seq = 0
        self._tenant_queued: Dict[str, int] = {}
        # rows collected into a batch but not yet resolved: the drain
        # condition on tenant removal is queued AND inflight == 0 (a
        # tenant popped while its last batch is between _collect and
        # dispatch would KeyError in the router)
        self._tenant_inflight: Dict[str, int] = {}
        self._quota_sheds: Dict[str, int] = {}

        # metrics (guarded by _cond's lock)
        self._n_requests = 0
        self._n_rows = 0
        self._n_batches = 0
        self._n_shed = 0
        self._n_errors = 0
        self._occupancy: deque = deque(maxlen=4096)  # rows per batch
        self._requests_per_batch: deque = deque(maxlen=4096)
        self._queue_wait_ms: deque = deque(maxlen=4096)  # per batch
        self._device_ms: deque = deque(maxlen=4096)  # per batch
        self._latency_ms: deque = deque(maxlen=8192)  # per request, end to end
        # per-lane fairness counters (round 12): a stuck/starved lane is
        # visible here and in the lane-labelled registry series instead of
        # being averaged into the aggregate
        self._lane_batches = [0] * self.lanes
        self._lane_requests = [0] * self.lanes
        self._lane_rows = [0] * self.lanes

        # process-wide telemetry (shared registry; get-or-create, so several
        # batchers aggregate into the same counter/histogram series — the
        # Prometheus convention.  The queue-depth GAUGE is last-write-wins
        # and so carries a per-instance label: two batchers on one registry
        # must not overwrite each other's depth)
        reg = registry if registry is not None else _metrics.default_registry()
        self.registry = reg
        #: This batcher's ``batcher=`` label value on per-instance series
        #: (the queue-depth gauge).
        self.metrics_instance = f"b{next(_INSTANCE_IDS)}"
        self._m_requests = reg.counter(
            "svgd_serve_requests_total", "requests fully resolved")
        self._m_rows = reg.counter(
            "svgd_serve_rows_total", "rows dispatched in resolved requests")
        self._m_batches = reg.counter(
            "svgd_serve_batches_total", "coalesced batches dispatched")
        self._m_shed = reg.counter(
            "svgd_serve_shed_total",
            "requests shed with Overloaded (bounded queue full)")
        self._m_errors = reg.counter(
            "svgd_serve_dispatch_errors_total", "batch dispatch exceptions")
        self._m_queue_depth = reg.gauge(
            "svgd_serve_queue_depth_rows", "rows queued, not yet dispatched")
        self._m_latency = reg.histogram(
            "svgd_serve_request_latency_seconds",
            "request end-to-end latency (enqueue to resolve)")
        self._m_queue_wait = reg.histogram(
            "svgd_serve_queue_wait_seconds",
            "oldest-request coalescing wait per batch")
        self._m_device = reg.histogram(
            "svgd_serve_device_time_seconds",
            "dispatch wall (device + fetch) per batch")
        self._m_batch_rows = reg.histogram(
            "svgd_serve_batch_rows", "rows per dispatched batch",
            buckets=_BATCH_ROW_BUCKETS)
        # lane-labelled series (per-instance + per-lane labels): counters
        # for fairness, and an in-flight gauge a stuck lane pins nonzero
        self._m_lane_batches = reg.counter(
            "svgd_serve_lane_batches_total", "batches dispatched per lane")
        self._m_lane_requests = reg.counter(
            "svgd_serve_lane_requests_total", "requests resolved per lane")
        self._m_lane_rows = reg.counter(
            "svgd_serve_lane_rows_total", "rows dispatched per lane")
        self._m_lane_inflight = reg.gauge(
            "svgd_serve_lane_inflight_rows",
            "rows currently inside a lane's dispatch (0 when idle; a lane "
            "stuck in a hung device call stays nonzero)")
        # multi-tenant series (round 14)
        self._m_quota_shed = reg.counter(
            "svgd_serve_quota_sheds_total",
            "requests shed by quota priority (tenant over its "
            "inflight-rows quota when the bounded queue filled)")
        self._m_tenant_queued = reg.gauge(
            "svgd_serve_tenant_queued_rows",
            "rows queued per tenant, not yet dispatched")
        # live capacity knobs (round 18): last-write-wins gauges so the
        # autoscale controller's retunes are scrapeable next to the load
        # they reacted to
        self._m_lanes = reg.gauge(
            "svgd_serve_lanes", "live dispatch-lane target per batcher")
        self._m_max_wait = reg.gauge(
            "svgd_serve_max_wait_ms", "live coalescing window per batcher")
        self._m_lanes.set(self.lanes, batcher=self.metrics_instance)
        self._m_max_wait.set(self._max_wait_s * 1e3,
                             batcher=self.metrics_instance)

        self._threads: List[threading.Thread] = []
        # lane id -> its current worker thread (a retired-then-regrown lane
        # id gets a fresh thread; every thread ever spawned stays in
        # _threads so close() can join them all)
        self._lane_threads: Dict[int, threading.Thread] = {}
        self._started = False
        if autostart:
            self.start()

    # ------------------------------------------------------------------ #
    # client side

    def submit(self, x, tenant: Optional[str] = None,
               trace: Optional[str] = None) -> Future:
        """Enqueue one request; returns a ``Future`` resolving to the dispatch
        output dict sliced back to this request's rows.

        ``tenant`` tags the request with a tenant identity: it rides the
        same bounded queue but only coalesces with its own tenant's chunks,
        dispatches as ``dispatch(x, tenant)``, and participates in the
        quota shed priorities (module docstring).

        ``trace`` (round 16) is the cross-process trace id this request
        belongs to (the HTTP layer extracts it from ``X-Fleet-Trace``);
        it tags the request's lane tree so ``trace_report --stitch`` can
        join this hop to the router's.  While tracing is enabled, a
        trace-less request **mints its own id** — propagation cost is then
        always inside the telemetry-overhead A/B ceiling, and standalone
        serving traces stay self-joinable.

        Raises :class:`Overloaded` when accepting the request would push the
        queue past ``max_queue_rows`` (all-or-nothing: a request is never
        partially enqueued), and ``RuntimeError`` after :meth:`close`.
        """
        x = np.asarray(x)
        if x.ndim != 2 or x.shape[0] == 0:
            raise ValueError(f"expected a non-empty (rows, features) array, got {x.shape}")
        rows = x.shape[0]
        tracer = _trace.get_tracer()
        if trace is None and tracer is not None:
            trace = _trace.mint_trace_id()
        tl = {} if tenant is None else {"tenant": tenant}
        shed_futures: List[Future] = []
        shed_err: Optional[Overloaded] = None
        try:
            with self._cond:
                if not self._open:
                    raise RuntimeError("batcher is closed")
                if self._quota_mode == "admission" and tenant is not None:
                    quota = self._quota_for(tenant)
                    if (quota is not None
                            and self._tenant_queued.get(tenant, 0) + rows
                            > quota):
                        # admission-time quota (round 18): while the
                        # controller holds quotas tightened, an over-quota
                        # tenant is refused BEFORE it occupies queue rows
                        # other tenants will wait behind
                        self._n_shed += 1
                        self._quota_sheds[tenant] = (
                            self._quota_sheds.get(tenant, 0) + 1)
                        self._m_shed.inc(**tl)
                        self._m_quota_shed.inc(tenant=tenant)
                        raise Overloaded(
                            f"tenant {tenant!r} is over its inflight-rows "
                            f"quota ({quota}, admission-enforced); retry "
                            "with backoff",
                            retry_after_s=self._retry_after_s_locked(),
                        )
                if self._queued_rows + rows > self.max_queue_rows:
                    quota = self._quota_for(tenant)
                    if (quota is not None
                            and self._tenant_queued.get(tenant, 0) + rows
                            > quota):
                        # the submitter is itself over quota while the
                        # queue is full: IT is the first shed victim
                        self._n_shed += 1
                        self._quota_sheds[tenant] = (
                            self._quota_sheds.get(tenant, 0) + 1)
                        self._m_shed.inc(**tl)
                        self._m_quota_shed.inc(tenant=tenant)
                        raise Overloaded(
                            f"queue full and tenant {tenant!r} is over its "
                            f"inflight-rows quota ({quota}); retry with "
                            "backoff",
                            retry_after_s=self._retry_after_s_locked(),
                        )
                    shed_futures, shed_err = self._shed_over_quota_locked(
                        self._queued_rows + rows - self.max_queue_rows)
                    if self._queued_rows + rows > self.max_queue_rows:
                        self._n_shed += 1
                        self._m_shed.inc(**tl)
                        raise Overloaded(
                            f"queue full ({self._queued_rows} rows queued, "
                            f"request of {rows} would exceed max_queue_rows="
                            f"{self.max_queue_rows}); retry with backoff",
                            retry_after_s=self._retry_after_s_locked(),
                        )
                # progressive delivery: assign the request a generation via
                # the rollout's deterministic hash split (nested threshold
                # — an assignment never flaps backwards as stages widen),
                # and flag incumbent requests for shadow mirroring.  The
                # submit ordinal is the hash key: pure, replayable, and
                # uniform across tenants' interleaving
                generation = None
                mirror = False
                ro = self._rollout
                if ro is not None and ro.active and tenant == ro.tenant:
                    seq = self._submit_seq
                    self._submit_seq += 1
                    if ro.assign(seq) == "candidate":
                        generation = "candidate"
                    else:
                        mirror = ro.should_mirror(seq)
                n_chunks = -(-rows // self.max_batch)
                req = _Request(n_chunks, self._clock(),
                               tracer.now() if tracer is not None else None,
                               tracer, tenant, trace, generation, mirror)
                for i in range(n_chunks):
                    chunk = x[i * self.max_batch : (i + 1) * self.max_batch]
                    self._queue.append(_Chunk(chunk, req, i))
                self._queued_rows += rows
                if tenant is not None:
                    self._tenant_queued[tenant] = (
                        self._tenant_queued.get(tenant, 0) + rows)
                    self._m_tenant_queued.set(
                        self._tenant_queued[tenant],
                        batcher=self.metrics_instance, tenant=tenant)
                self._m_queue_depth.set(self._queued_rows,
                                        batcher=self.metrics_instance)
                self._cond.notify_all()
                return req.future
        finally:
            # resolve priority-shed victims OUTSIDE the condition lock:
            # their done-callbacks (client retry logic) may re-enter
            # submit(), which would deadlock on the non-reentrant lock
            for fut in shed_futures:
                try:
                    fut.set_exception(shed_err)
                except InvalidStateError:
                    pass

    def _retry_after_s_locked(self) -> float:
        """Estimated seconds until the current backlog admits a retry:
        ``(1 + ceil(ceil(queued_rows / max_batch) / lanes)) · max_wait_s``
        — the queue drains at worst one ``max_batch`` batch *per lane* per
        coalescing window, and the retry itself waits one more window.
        Every term is read LIVE at shed time (round 18): after the
        autoscale controller retunes ``max_wait_ms`` or the lane count,
        the next shed's Retry-After describes the batcher as it now runs,
        not as it was built.  Floored at 1 ms so a zero-wait batcher
        still emits a positive hint."""
        batches = -(-self._queued_rows // self.max_batch)
        windows = -(-batches // max(self.lanes, 1))
        return (1 + windows) * max(self._max_wait_s, 1e-3)

    def _quota_for(self, tenant: Optional[str]) -> Optional[int]:
        if tenant is None or not self._quotas:
            return None
        return self._quotas.get(tenant)

    def _shed_over_quota_locked(self, needed: int):
        """Free ≥ ``needed`` queued rows by shedding whole queued requests
        of over-quota tenants, newest first (they waited least), each
        tenant only down to its quota.  Call under the condition lock;
        returns ``(victim futures, the Overloaded to fail them with)`` —
        the caller resolves them after releasing the lock."""
        if needed <= 0 or not self._quotas:
            return [], None
        victims: List[_Request] = []
        victim_ids = set()
        freed = 0
        for chunk in reversed(self._queue):
            if freed >= needed:
                break
            req = chunk.req
            t = req.tenant
            if t is None or id(req) in victim_ids:
                continue
            quota = self._quotas.get(t)
            if quota is None or self._tenant_queued.get(t, 0) <= quota:
                continue
            req_rows = sum(c.x.shape[0] for c in self._queue if c.req is req)
            victim_ids.add(id(req))
            victims.append(req)
            self._tenant_queued[t] = max(
                0, self._tenant_queued.get(t, 0) - req_rows)
            freed += req_rows
        if not victims:
            return [], None
        # _locked contract: submit() holds self._cond for this whole
        # helper (the Condition lock is non-reentrant, so re-taking it
        # here would deadlock) — the bare writes are lock-guarded by the
        # caller, which the lexical analyzer cannot see
        self._queue = deque(  # jaxlint: disable=JL004
            c for c in self._queue if id(c.req) not in victim_ids)
        self._queued_rows -= freed  # jaxlint: disable=JL004
        for req in victims:
            self._n_shed += 1  # jaxlint: disable=JL004
            self._quota_sheds[req.tenant] = (
                self._quota_sheds.get(req.tenant, 0) + 1)
            self._m_shed.inc(tenant=req.tenant)
            self._m_quota_shed.inc(tenant=req.tenant)
            self._m_tenant_queued.set(
                self._tenant_queued.get(req.tenant, 0),
                batcher=self.metrics_instance, tenant=req.tenant)
        self._m_queue_depth.set(self._queued_rows,
                                batcher=self.metrics_instance)
        err = Overloaded(
            "shed by quota priority: tenant over its inflight-rows quota "
            "when the bounded queue filled; retry with backoff",
            retry_after_s=self._retry_after_s_locked(),
        )
        return [r.future for r in victims], err

    # ------------------------------------------------------------------ #
    # worker side

    def start(self) -> None:
        with self._cond:
            self._started = True
            target = self.lanes
        self._spawn_lanes(target)

    def _spawn_lanes(self, target: int) -> None:
        """Ensure a live worker thread exists for every lane id below
        ``target`` (idempotent; called outside the condition lock — thread
        starts must not run under it)."""
        for lane in range(target):
            t = self._lane_threads.get(lane)
            if t is None or not t.is_alive():
                t = threading.Thread(
                    target=self._loop, args=(lane,),
                    name=f"microbatcher-l{lane}", daemon=True,
                )
                self._lane_threads[lane] = t
                self._threads.append(t)
                t.start()

    def set_lanes(self, lanes: int) -> int:
        """Retune the dispatch-lane count LIVE (round 18, the autoscale
        controller's seam).  Growing spawns workers for the missing lane
        ids; shrinking retires the highest lanes — each retiring worker
        finishes its in-flight batch, re-checks the target, and exits
        (never mid-dispatch, never holding queued work: the surviving
        lanes drain the shared queue).  Lock-safe against concurrent
        submits and collects; per-lane metric lists grow monotonically so
        a retired lane's counters stay visible.  Returns the previous
        target."""
        lanes = int(lanes)
        if lanes < 1:
            raise ValueError(f"lanes must be >= 1, got {lanes}")
        with self._cond:
            old = self.lanes
            self.lanes = lanes
            while len(self._lane_batches) < lanes:
                self._lane_batches.append(0)
                self._lane_requests.append(0)
                self._lane_rows.append(0)
            started = self._started
            # wake every parked worker: retiring lanes must notice the
            # shrunken target instead of sleeping in _collect forever
            self._cond.notify_all()
        self._m_lanes.set(lanes, batcher=self.metrics_instance)
        if started:
            self._spawn_lanes(lanes)
        return old

    @property
    def max_wait_ms(self) -> float:
        """The live coalescing window (milliseconds)."""
        return self._max_wait_s * 1e3

    def set_max_wait_ms(self, max_wait_ms: float) -> float:
        """Retune the coalescing window LIVE.  Collectors re-derive their
        flush deadline from the live window on every wakeup, so a retune
        takes effect for batches already coalescing, and
        :class:`Overloaded` drain estimates computed after it are honest
        about the new window.  Returns the previous window (ms)."""
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        with self._cond:
            old = self._max_wait_s * 1e3
            self._max_wait_s = float(max_wait_ms) / 1e3
            self._cond.notify_all()
        self._m_max_wait.set(float(max_wait_ms),
                             batcher=self.metrics_instance)
        return old

    def queued_rows(self) -> int:
        """Rows queued and not yet collected into a batch (the controller's
        cheap backlog probe — no full :meth:`stats` snapshot)."""
        with self._cond:
            return self._queued_rows

    @property
    def quota_mode(self) -> str:
        """``'overflow'`` (quotas bite only when the queue fills — the
        round-14 default) or ``'admission'`` (over-quota tenants refused
        at submit time)."""
        return self._quota_mode

    def set_quota_mode(self, mode: str) -> str:
        """Switch quota enforcement LIVE (round 18).  The autoscale
        controller runs ``'admission'`` exactly while quotas are
        tightened under overload — a flooding tenant then cannot occupy
        queue rows that bound every other tenant's delay — and restores
        ``'overflow'`` with the base quotas.  Returns the previous mode."""
        if mode not in ("overflow", "admission"):
            raise ValueError(
                f"quota mode must be 'overflow' or 'admission', got {mode!r}")
        with self._cond:
            old = self._quota_mode
            self._quota_mode = mode
        return old

    @property
    def rollout(self):
        """The armed :class:`~dist_svgd_tpu.rollout.RolloutController`
        (None outside a rollout)."""
        return self._rollout

    def set_rollout(self, controller) -> None:
        """Arm (or with ``None`` disarm) the progressive-delivery hook
        LIVE (round 21).  While armed, every arriving request of the
        controller's tenant is hash-assigned a generation (candidate
        requests dispatch against the staged candidate and carry
        ``generation="candidate"`` serve labels) and incumbent requests
        may be shadow-mirrored.  Requests already queued keep the
        assignment they got at submit time — disarming mid-flight is
        safe (candidate batches fall back to the incumbent dispatch)."""
        with self._cond:
            self._rollout = controller

    def _collect(self, lane: int = 0) -> Optional[List[_Chunk]]:
        """Block until a batch is ready (max_batch reached, max_wait expired,
        or draining); None once closed and drained — or once this lane's id
        is at or past the live lane target (retirement, ``set_lanes``)."""
        with self._cond:
            while True:
                while (not self._queue and self._open
                       and lane < self.lanes):
                    self._wait(self._cond, None)
                if lane >= self.lanes:
                    # retired by set_lanes (the queue, if any, belongs to
                    # the surviving lanes).  Deregister NOW, under the
                    # lock: a shrink-then-regrow racing this thread's
                    # actual exit would otherwise see it still alive and
                    # skip respawning the lane — a silently dead lane id
                    # below the live target
                    if self._lane_threads.get(lane) is threading.current_thread():
                        del self._lane_threads[lane]
                    return None
                if not self._queue:
                    return None  # closed and drained
                # the deadline reads the LIVE window each pass so a
                # set_max_wait_ms retune applies to batches mid-coalesce
                while self._open and self._queue and self._queued_rows < self.max_batch:
                    remaining = (self._queue[0].req.enqueued
                                 + self._max_wait_s) - self._clock()
                    if remaining <= 0:
                        break
                    self._wait(self._cond, remaining)
                if not self._queue:
                    continue  # drained under us (close(drain=False))
                batch: List[_Chunk] = []
                rows = 0
                # one batch = one (tenant, generation): different tenants
                # hit different engines/shapes, and a candidate-split chunk
                # dispatches against a different resident ensemble than an
                # incumbent one — fusing across either would be wrong, not
                # just slow (a foreign chunk ends the batch; the next
                # _collect — or another lane — picks it up)
                head_tenant = self._queue[0].req.tenant
                head_gen = self._queue[0].req.generation
                while (self._queue
                       and rows + self._queue[0].x.shape[0] <= self.max_batch
                       and self._queue[0].req.tenant == head_tenant
                       and self._queue[0].req.generation == head_gen):
                    chunk = self._queue.popleft()
                    batch.append(chunk)
                    rows += chunk.x.shape[0]
                self._queued_rows -= rows
                if head_tenant is not None:
                    self._tenant_queued[head_tenant] = max(
                        0, self._tenant_queued.get(head_tenant, 0) - rows)
                    self._tenant_inflight[head_tenant] = (
                        self._tenant_inflight.get(head_tenant, 0) + rows)
                    self._m_tenant_queued.set(
                        self._tenant_queued[head_tenant],
                        batcher=self.metrics_instance, tenant=head_tenant)
                self._m_queue_depth.set(self._queued_rows,
                                        batcher=self.metrics_instance)
                return batch

    def _run_batch(self, batch: List[_Chunk], lane: int = 0) -> None:
        rows = sum(c.x.shape[0] for c in batch)
        lane_label = f"l{lane}"
        # _collect guarantees a single-(tenant, generation) batch;
        # tenant-less batches keep the unlabelled metric series
        # (single-tenant deployments are byte-identical).  Candidate-split
        # batches add generation="candidate" to every dispatch-side serve
        # series — the rollout's SLO engine judges that label set alone,
        # so candidate and incumbent never dilute each other's windows
        tenant = batch[0].req.tenant
        generation = batch[0].req.generation
        ro = self._rollout
        tl = {} if tenant is None else {"tenant": tenant}
        gl = tl if generation is None else {**tl, "generation": generation}
        tracer = _trace.get_tracer()
        t0 = self._clock()
        t_pop = tracer.now() if tracer is not None else 0.0
        queue_wait_ms = (t0 - min(c.req.enqueued for c in batch)) * 1e3
        x = np.concatenate([c.x for c in batch], axis=0)
        self._m_lane_inflight.set(rows, batcher=self.metrics_instance,
                                  lane=lane_label, **gl)
        # thread the trace id through the dispatch via the trace context
        # (the engine's spans tag themselves from it — same mechanics as
        # the tenant label, but per-request): only when the whole batch
        # belongs to ONE trace is the context unambiguous
        batch_traces = {c.req.trace for c in batch}
        ctx_trace = (batch_traces.pop() if len(batch_traces) == 1 else None)
        prev_ctx = (_trace.set_trace_context(ctx_trace)
                    if ctx_trace is not None else None)
        t_disp0 = tracer.now() if tracer is not None else 0.0
        try:
            if generation == "candidate" and ro is not None:
                # candidate-split batch: dispatch against the staged
                # candidate generation (the controller falls back to the
                # incumbent if a rollback raced this batch — the client
                # gets an answer either way)
                out = ro.dispatch_candidate(x, tenant)
            else:
                out = (self._dispatch(x) if tenant is None
                       else self._dispatch(x, tenant))
        except Exception as e:
            with self._cond:
                self._n_errors += 1
                if tenant is not None:
                    self._tenant_inflight[tenant] = max(
                        0, self._tenant_inflight.get(tenant, 0) - rows)
            self._m_errors.inc(**gl)
            self._m_lane_inflight.set(0, batcher=self.metrics_instance,
                                      lane=lane_label, **gl)
            for c in batch:
                try:
                    c.req.future.set_exception(e)
                except InvalidStateError:
                    # another lane resolved a sibling chunk's request (a
                    # split request erroring in two batches at once) —
                    # first resolution wins, and losing must not kill
                    # this lane thread
                    pass
            return
        finally:
            if ctx_trace is not None:
                _trace.set_trace_context(prev_ctx)
        t_disp1 = tracer.now() if tracer is not None else 0.0
        self._m_lane_inflight.set(0, batcher=self.metrics_instance,
                                  lane=lane_label, **gl)
        device_ms = (self._clock() - t0) * 1e3
        now = self._clock()
        with self._cond:
            # chunk reassembly UNDER the lock: with lanes > 1, the chunks
            # of one split request can finish in different lanes at the
            # same moment — the write-then-completeness-check must be
            # atomic so exactly ONE lane observes the final fill (else
            # both count the request and race future.set_result)
            done_requests = []
            mirrors = []
            offset = 0
            for c in batch:
                n = c.x.shape[0]
                c.req.parts[c.index] = {
                    k: v[offset : offset + n] for k, v in out.items()
                }
                if c.req.mirror and ro is not None:
                    # shadow mirror: hand this chunk's input + incumbent
                    # answer to the rollout's background worker AFTER the
                    # lock drops — the controller copies and never blocks,
                    # so the client's critical path is untouched
                    mirrors.append((c.x, c.req.parts[c.index]))
                offset += n
                if all(p is not None for p in c.req.parts):
                    done_requests.append(c.req)
            if tenant is not None:
                self._tenant_inflight[tenant] = max(
                    0, self._tenant_inflight.get(tenant, 0) - rows)
            self._n_batches += 1
            self._occupancy.append(rows)
            self._requests_per_batch.append(len(batch))
            self._queue_wait_ms.append(queue_wait_ms)
            self._device_ms.append(device_ms)
            self._lane_batches[lane] += 1
            self._lane_rows[lane] += rows
            latencies = []
            for req in done_requests:
                self._n_requests += 1
                n_rows = sum(p[next(iter(p))].shape[0] for p in req.parts)
                self._n_rows += n_rows
                lat_ms = (now - req.enqueued) * 1e3
                self._latency_ms.append(lat_ms)
                latencies.append((req, n_rows, lat_ms))
            self._lane_requests[lane] += len(latencies)
        for mx, mout in mirrors:
            ro.mirror(mx, mout)
        self._m_batches.inc(**gl)
        self._m_batch_rows.observe(rows, **gl)
        self._m_queue_wait.observe(queue_wait_ms / 1e3, **gl)
        self._m_device.observe(device_ms / 1e3, **gl)
        self._m_lane_batches.inc(batcher=self.metrics_instance,
                                 lane=lane_label, **gl)
        self._m_lane_rows.inc(rows, batcher=self.metrics_instance,
                              lane=lane_label, **gl)
        if latencies:
            self._m_lane_requests.inc(len(latencies),
                                      batcher=self.metrics_instance,
                                      lane=lane_label, **gl)
        for req, n_rows, lat_ms in latencies:
            self._m_requests.inc(**gl)
            self._m_rows.inc(n_rows, **gl)
            self._m_latency.observe(lat_ms / 1e3, **gl)
        meter = _usage.get_meter()
        if meter is not None:
            # the cost ledger: same measured device window the histogram
            # above observed, so usage and latency accounting agree by
            # construction; queue-seconds are summed over the requests
            # COMPLETED by this batch (their wait ended at this t0)
            meter.record_batch(
                tenant=tenant, generation=generation, rows=rows,
                device_s=device_ms / 1e3,
                queue_s=sum(max(t0 - req.enqueued, 0.0)
                            for req, _, _ in latencies),
                requests=len(latencies))
        if tracer is not None:
            # one lane tree per completed request: the cross-thread
            # enqueue→reply lifetime with the queue-wait / coalesce /
            # dispatch split of its final batch (a split oversize request
            # reports the batch that completed it; n_chunks tags that)
            t_reply = tracer.now()
            for req, n_rows, _lat in latencies:
                # only trust an enqueue stamp from THIS tracer: a request
                # submitted under an earlier (since-disabled) tracer carries
                # another epoch's timestamp
                enq = (req.trace_enq
                       if req.trace_src is tracer and req.trace_enq is not None
                       else t_pop)
                attrs = {"rows": n_rows, "n_chunks": req.n_chunks,
                         "batch_rows": rows, "batch_requests": len(batch),
                         "lane": lane_label}
                if tenant is not None:
                    attrs["tenant"] = tenant
                if generation is not None:
                    attrs["generation"] = generation
                if req.trace is not None:
                    # the cross-process join key: trace_report --stitch
                    # matches this tree to the router's fleet.route on it
                    attrs["trace"] = req.trace
                tracer.lane_tree(
                    "serve.request", enq, t_reply, attrs,
                    children=[
                        ("serve.queue_wait", enq, t_pop, None),
                        ("serve.coalesce", t_pop, t_disp0,
                         {"requests": len(batch), "rows": rows}),
                        ("serve.dispatch", t_disp0, t_disp1,
                         {"rows": rows, "lane": lane_label}),
                    ],
                )
        if self._logger is not None:
            self._logger.log(
                event="batch",
                lane=lane_label,
                rows=rows,
                requests=len(batch),
                queue_wait_ms=round(queue_wait_ms, 3),
                device_ms=round(device_ms, 3),
                **({"tenant": tenant} if tenant is not None else {}),
            )
        for req, _rows, _lat in latencies:
            keys = req.parts[0].keys()
            result = {
                k: np.concatenate([p[k] for p in req.parts], axis=0) for k in keys
            }
            try:
                req.future.set_result(result)
            except InvalidStateError:
                # already failed by a sibling chunk's dispatch error (the
                # completion check above makes this lane the only
                # *resolver*, but an error lane may have beaten it)
                pass

    def _loop(self, lane: int = 0) -> None:
        while True:
            batch = self._collect(lane)
            if batch is None:
                return
            self._run_batch(batch, lane)

    # ------------------------------------------------------------------ #
    # lifecycle / metrics

    def close(self, drain: bool = True, timeout: Optional[float] = 30.0) -> None:
        """Stop accepting requests.  ``drain=True`` (graceful) dispatches
        everything already queued before the worker exits; ``drain=False``
        cancels queued requests with ``CancelledError``."""
        with self._cond:
            self._open = False
            if not drain:
                cancelled = {c.req for c in self._queue}
                self._queue.clear()
                self._queued_rows = 0
                # zero the per-tenant gauges BEFORE dropping the state:
                # a stale nonzero queued-rows series on the shared
                # registry would outlive the batcher
                for t in self._tenant_queued:
                    self._m_tenant_queued.set(
                        0, batcher=self.metrics_instance, tenant=t)
                self._m_queue_depth.set(0, batcher=self.metrics_instance)
                self._tenant_queued.clear()
                for req in cancelled:
                    if not req.future.done():
                        req.future.set_exception(CancelledError("batcher closed"))
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=timeout)

    def tenant_queued_rows(self, tenant: str) -> int:
        """Rows of ``tenant`` queued and not yet collected into a batch."""
        with self._cond:
            return self._tenant_queued.get(tenant, 0)

    def tenant_pending_rows(self, tenant: str) -> int:
        """Rows of ``tenant`` still owed a result: queued PLUS collected-
        but-unresolved (the registry's drain condition on tenant removal —
        queued alone goes to zero while the last batch is between
        ``_collect`` and its dispatch, and removing the tenant in that
        window would fail the batch in the router)."""
        with self._cond:
            return (self._tenant_queued.get(tenant, 0)
                    + self._tenant_inflight.get(tenant, 0))

    def cancel_tenant(self, tenant: str) -> int:
        """Drop every queued chunk of ``tenant``; their futures fail with
        ``CancelledError``.  In-flight dispatches finish normally (their
        engine closure stays alive).  Returns the number of requests
        cancelled — the registry's ``remove_tenant(drain=False)`` path."""
        victims: List[_Request] = []
        with self._cond:
            victim_ids = set()
            dropped_rows = 0
            for c in self._queue:
                if c.req.tenant == tenant:
                    if id(c.req) not in victim_ids:
                        victim_ids.add(id(c.req))
                        victims.append(c.req)
                    dropped_rows += c.x.shape[0]
            if victim_ids:
                self._queue = deque(
                    c for c in self._queue if id(c.req) not in victim_ids)
                self._queued_rows -= dropped_rows
            self._tenant_queued.pop(tenant, None)
            self._m_tenant_queued.set(0, batcher=self.metrics_instance,
                                      tenant=tenant)
            self._m_queue_depth.set(self._queued_rows,
                                    batcher=self.metrics_instance)
        for req in victims:
            try:
                req.future.set_exception(
                    CancelledError(f"tenant {tenant!r} removed"))
            except InvalidStateError:
                pass
        return len(victims)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close(drain=True)

    def stats(self) -> Dict[str, Any]:
        """Aggregate serving metrics (bounded windows for the percentiles).

        Only the snapshot happens under the batcher's lock; the sorts run
        after release, so a /metrics poll never stalls submit() or the
        dispatch worker behind an O(window log window) sort."""
        with self._cond:
            lat = list(self._latency_ms)
            qw = list(self._queue_wait_ms)
            dv = list(self._device_ms)
            occ = list(self._occupancy)
            rpb = list(self._requests_per_batch)
            counters = {
                "requests": self._n_requests,
                "rows": self._n_rows,
                "batches": self._n_batches,
                "shed": self._n_shed,
                "dispatch_errors": self._n_errors,
                "queued_rows": self._queued_rows,
                "lanes": self.lanes,
                "lane_batches": {f"l{i}": v
                                 for i, v in enumerate(self._lane_batches)},
                "lane_requests": {f"l{i}": v
                                  for i, v in enumerate(self._lane_requests)},
                "lane_rows": {f"l{i}": v
                              for i, v in enumerate(self._lane_rows)},
                "quota_sheds": dict(self._quota_sheds),
                "tenant_queued": dict(self._tenant_queued),
            }
        lat.sort()
        qw.sort()
        dv.sort()
        return {
            **counters,
            "batch_occupancy_mean": float(np.mean(occ)) if occ else 0.0,
            "batch_occupancy_max": int(max(occ)) if occ else 0,
            "requests_per_batch_mean": float(np.mean(rpb)) if rpb else 0.0,
            "latency_p50_ms": _percentile(lat, 0.50),
            "latency_p99_ms": _percentile(lat, 0.99),
            "queue_wait_p50_ms": _percentile(qw, 0.50),
            "queue_wait_p99_ms": _percentile(qw, 0.99),
            "device_p50_ms": _percentile(dv, 0.50),
            "device_p99_ms": _percentile(dv, 0.99),
        }
