"""Posterior-predictive serving over checkpointed SVGD ensembles.

Training produces a converged particle set — which, per SVGD's construction
(Liu & Wang 2016, PAPER.md Algorithm 1), *is* the posterior.  This package
turns a checkpointed ensemble into a low-latency prediction service:

- :mod:`engine`  — :class:`PredictiveEngine`: loads an ensemble from any
  checkpoint layout (single save, ``CheckpointManager`` root, or a
  multi-process save's per-process block files), registers per-model jitted
  predictive kernels, and serves them through a shape-bucketed compile cache
  (request batches pad up to power-of-two buckets, so steady-state traffic
  never recompiles), with **checkpoint hot reload**
  (:class:`CheckpointHotReloader` watches a manager root and atomically
  swaps the served ensemble between micro-batches — train-while-serving
  with ``resilience.RunSupervisor``).  Pass ``plan=``/``mesh=`` and the
  ensemble is **particle-sharded across the device mesh** — every bucket
  kernel compiles through ``parallel/plan.py`` with explicit in/out
  shardings, and hot reload re-places each new generation on the mesh;
- :mod:`batcher` — :class:`MicroBatcher`: coalesces concurrent requests into
  one fused device call over the whole ensemble, scatters results back
  per-request, sheds on overflow instead of queueing unboundedly, and runs
  ``lanes=N`` dispatch workers over the shared queue so queue-wait stops
  serializing behind one in-flight device call;
- :mod:`server`  — a thin stdlib HTTP front end (``/predict``, ``/healthz``,
  ``/metrics``, ``/slo``) with graceful drain and structured per-request
  records;
- :mod:`fleet`   — the **shared-nothing serving fleet**: a pure-stdlib
  :class:`FleetRouter` (no jax in the router process) consistent-hashes
  tenants over N replica servers with bounded-load overflow, health-gates
  each replica behind a circuit breaker (active ``/healthz``+``/slo``
  probes, passive request scoring, half-open readmission), and forwards
  with deadline propagation, idempotency-aware jittered retries,
  429-backpressure honoring, optional tail hedging, and graceful 503
  degradation — the unit of failure becomes a whole process and the
  system keeps serving (``tools/fleet_drill.py`` measures it);
- :mod:`autoscale` — :class:`AutoscaleController`: the **control plane**
  — watches SLO burn rates and queue/latency windows from the metrics
  registry and retunes the batcher's lanes, its coalescing window, and
  per-tenant quotas live (bounded hysteresis, injectable clock), so the
  system sheds and widens *before* p99 breaches instead of recovering
  after; served at ``/autoscale`` (``tools/workload_replay.py`` measures
  it under production-shaped traffic);
- :mod:`registry` — :class:`ModelRegistry`: **multi-tenant serving** —
  many heterogeneous posteriors (logreg/BNN/GMM, different shapes, steps,
  dtypes, plans) hosted as named tenants behind ONE process: one shared
  micro-batcher with per-tenant quotas and shed priorities, one scanner
  thread over every tenant's checkpoint root, one process-wide
  :class:`KernelBucketLRU` bounding compiled kernel buckets across
  tenants, and a ``tenant=`` label on every serving metric.  The server
  routes ``/predict`` on a ``tenant`` field and lists ``/tenants``.

Reload admission: an engine built with a ``telemetry.diagnostics.
ReloadPolicy`` health-checks every hot-reload candidate (kernel ESS,
collapse indicators) and raises :class:`EnsembleRejected` instead of
swapping in a regressed ensemble — the reloader then keeps serving the
previous generation.

The load generator lives in ``tools/serve_bench.py``; the covertype
train → checkpoint → serve demo in ``experiments/serve_covertype.py``.
"""

from dist_svgd_tpu.serving.autoscale import (
    AutoscaleController,
    AutoscalePolicy,
)
from dist_svgd_tpu.serving.batcher import MicroBatcher, Overloaded
from dist_svgd_tpu.serving.engine import (
    CheckpointHotReloader,
    EnsembleRejected,
    PredictiveEngine,
)
from dist_svgd_tpu.serving.fleet import (
    FakeTransport,
    FleetRouter,
    HttpTransport,
    LoopbackReplica,
    MetricsFederation,
    ReplicaSet,
)
from dist_svgd_tpu.serving.registry import (
    KernelBucketLRU,
    ModelRegistry,
    Tenant,
)
from dist_svgd_tpu.serving.server import PredictionServer

__all__ = [
    "AutoscaleController",
    "AutoscalePolicy",
    "PredictiveEngine",
    "CheckpointHotReloader",
    "EnsembleRejected",
    "KernelBucketLRU",
    "MicroBatcher",
    "ModelRegistry",
    "Overloaded",
    "PredictionServer",
    "Tenant",
    "FleetRouter",
    "MetricsFederation",
    "ReplicaSet",
    "HttpTransport",
    "FakeTransport",
    "LoopbackReplica",
]
