"""Serving fleet: replica router with health-gated failover.

Everything the serving stack pushed so far flows through ONE process — a
single crash takes every tenant down.  This module is the shared-nothing
fix (ROADMAP item 4b): N independent :class:`~dist_svgd_tpu.serving.server.
PredictionServer` replicas behind a :class:`FleetRouter` whose **unit of
failure is a whole process** and whose job is to keep serving anyway.

Deliberately **pure stdlib + telemetry** — no jax, no numpy.  The router
runs fine in a process that never touches an accelerator; replicas carry
the models.

- :class:`ReplicaSet` — membership + health.  Each replica owns a
  circuit breaker (``closed``/``open``/``half_open``, all transitions on an
  injectable clock): **active** probes hit ``/healthz`` (and
  ``/healthz/<tenant>`` for the tenants it should carry) plus ``/slo``;
  **passive** scoring feeds per-request outcomes back in.  A replica is
  ejected (circuit opened) when probes fail ``fail_threshold`` times in a
  row, when consecutive forwards fail ``passive_fail_threshold`` times,
  when a probe reports ``"draining"`` (a deliberate signal — one strike),
  or when its own SLO engine reports **burning** (``/slo`` status
  ``breach``); after ``open_cooldown_s`` the circuit half-opens and ONE
  trial (probe or forward) decides: success re-admits, failure re-opens.
  A stale or absent ``/slo`` verdict reads **unknown, never healthy**
  (:func:`classify_slo`).
- :class:`FleetRouter` — the HTTP front door.  Tenants spread over
  replicas by **consistent hashing** (virtual nodes) with **bounded-load
  overflow**: a replica already carrying more than ``load_factor×`` its
  fair share of in-flight requests overflows the request to the next ring
  candidate.  The forwarding path carries the full robustness kit:

  * **deadline propagation** — every attempt forwards the remaining
    budget downstream as ``X-Fleet-Deadline-S`` (replicas cap their own
    future-wait with it) and the router answers 504 the moment the budget
    is gone;
  * **idempotency-aware retries** — connect errors, timeouts and 5xx
    retry against the next ring candidate under the shared
    :class:`~dist_svgd_tpu.resilience.backoff.Backoff` (jittered, capped,
    clamped to the deadline).  A **429 shed is never retried** — that's
    load, not failure; the router passes the replica's computed
    ``Retry-After`` through to the client and remembers the backpressure
    window so the next requests prefer other candidates;
  * **tail hedging** (opt-in) — after a p99-derived delay without a
    response, the same request is hedged to a second admitted replica and
    the first reply wins (the degraded-replica shape
    :class:`~dist_svgd_tpu.resilience.faults.SlowReplicaAt` injects);
  * **graceful degradation** — when every candidate for a tenant is out,
    the router answers 503 immediately with a ``Retry-After`` derived
    from the soonest half-open eligibility plus a last-known-healthy
    hint, instead of hanging the client.

Transports are injectable: :class:`HttpTransport` (stdlib
``http.client``, with a router-side ``partition``/``heal`` deny-list so
real-subprocess drills can cut a link without iptables) for production,
:class:`FakeTransport` + :class:`LoopbackReplica` for tier-1 — every
failover path runs on CPU without real sockets, driven by the
process-level faults in ``resilience/faults.py`` (``ReplicaKillAt``,
``ReplicaHangAt``, ``SlowReplicaAt``, ``PartitionAt``).

Telemetry rides the shared registry: ``svgd_fleet_replica_state{replica}``
(0 closed / 1 half-open / 2 open), ``svgd_fleet_retries_total{reason}``,
``svgd_fleet_hedges_total``, ``svgd_fleet_failovers_total{tenant}``,
ejection/readmission counters, and one ``fleet.route ⊃ fleet.attempt ⊃
fleet.forward`` lane tree per routed request while tracing is enabled —
``tools/trace_report.py`` then ranks where failover latency hides.
``tools/fleet_drill.py`` measures the whole story as the
``fleet_failover`` bench row.

**Cross-process observability (round 16)** — the layers above used to
stop at the process boundary; three additions carry them across it:

- **trace propagation** — every routed request carries a trace id
  (client-supplied ``X-Fleet-Trace`` or minted here) downstream on each
  attempt; the router's ``fleet.route``/``fleet.attempt`` lane trees and
  the replica's ``serve.request`` trees tag it, and every trace export
  carries a process-identity header + clock anchor, so
  ``tools/trace_report.py --stitch`` joins the disjoint per-process
  exports into one tree per request (retries as sibling attempts, the
  network/queue gap as a synthetic span);
- **metrics federation** (:class:`MetricsFederation`) — the router's
  ``/metrics`` scrapes every replica's full-fidelity ``/metrics.dump``
  and merges clamped per-replica deltas into one fleet registry
  (replica-labelled series + exact rollups; a restarted replica's
  counter reset clamps to a zero delta, never a negative rate; scrape
  failures are themselves counted per replica);
- **fleet SLO + status plane** — the router's ``/slo`` evaluates
  ``default_serving_slos`` over the *federated* window (fleet-wide p99,
  not any one replica's), and ``/fleet`` serves the one-stop status
  document ``tools/fleet_status.py`` renders (breaker states, per-tenant
  fleet rps/p99 from merged histograms, fleet-wide cost columns from the
  federated ``svgd_usage_*`` series, SLO verdicts); ``/usage`` answers
  cost-per-tenant across the fleet (``telemetry/usage.py:usage_summary``
  over the merged registry, per-replica breakdown included).
"""

from __future__ import annotations

import bisect
import hashlib
import json
import math
import threading
import time
from collections import deque
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout
from concurrent.futures import wait as futures_wait
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from dist_svgd_tpu.resilience.backoff import Backoff
from dist_svgd_tpu.telemetry import metrics as _metrics
from dist_svgd_tpu.telemetry import trace as _trace
from dist_svgd_tpu.telemetry import usage as _usage
from dist_svgd_tpu.telemetry.slo import default_serving_slos

__all__ = [
    "TransportError",
    "ConnectError",
    "RequestTimeout",
    "Reply",
    "HttpTransport",
    "FakeTransport",
    "LoopbackReplica",
    "Shed",
    "classify_slo",
    "format_retry_after",
    "MetricsFederation",
    "ReplicaSet",
    "FleetRouter",
    "CLOSED",
    "OPEN",
    "HALF_OPEN",
]

#: Circuit-breaker states (and the ``svgd_fleet_replica_state`` gauge
#: encoding: closed=0, half_open=1, open=2 — "bigger is sicker").
CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

#: Downstream headers: the remaining per-request budget, the attempt
#: ordinal, and (round 16) the per-request trace id — so replicas can
#: bound their own waits, logs can join retries to one logical request,
#: and every hop's spans stitch into one cross-process tree
#: (``tools/trace_report.py --stitch``).
DEADLINE_HEADER = "X-Fleet-Deadline-S"
ATTEMPT_HEADER = "X-Fleet-Attempt"
TRACE_HEADER = _trace.TRACE_HEADER  # one spelling, shared with server.py


class TransportError(RuntimeError):
    """Transport-level failure (the retryable kind — the request may never
    have reached the replica, and predict is idempotent by construction)."""


class ConnectError(TransportError):
    """Connection refused / replica unreachable (dead process, partition)."""


class RequestTimeout(TransportError):
    """No response within the per-try budget (hung process, slow network)."""


class Reply:
    """One transport response: ``status``, lower-cased ``headers``, raw
    ``body`` bytes (the router is payload-agnostic passthrough)."""

    __slots__ = ("status", "headers", "body")

    def __init__(self, status: int, headers: Optional[Dict[str, str]] = None,
                 body: bytes = b""):
        self.status = int(status)
        self.headers = {k.lower(): str(v)
                        for k, v in (headers or {}).items()}
        self.body = body if isinstance(body, bytes) else str(body).encode()

    def json(self) -> Any:
        try:
            return json.loads(self.body or b"null")
        except (ValueError, UnicodeDecodeError):
            return None

    def retry_after_s(self) -> Optional[float]:
        """The ``Retry-After`` header as seconds (delta-seconds form only —
        the only form this codebase emits), None when absent/garbled."""
        raw = self.headers.get("retry-after")
        if raw is None:
            return None
        try:
            return max(float(raw), 0.0)
        except ValueError:
            return None

    def __repr__(self):
        return f"Reply(status={self.status}, bytes={len(self.body)})"


# --------------------------------------------------------------------- #
# transports


class HttpTransport:
    """Real-socket transport over stdlib ``http.client``.

    ``addresses`` maps replica id → ``(host, port)``; :meth:`set_address`
    re-points a replica after a restart on a new port.  The
    :meth:`partition`/:meth:`heal` deny-list simulates a network partition
    from the router's side — the replica process stays untouched, exactly
    the :class:`~dist_svgd_tpu.resilience.faults.PartitionAt` semantics,
    usable against real subprocesses (``tools/fleet_drill.py``)."""

    def __init__(self, addresses: Dict[str, Tuple[str, int]]):
        self._lock = threading.Lock()
        self._addresses = {str(k): (str(h), int(p))
                           for k, (h, p) in addresses.items()}
        self._partitioned: set = set()

    def set_address(self, replica: str, host: str, port: int) -> None:
        with self._lock:
            self._addresses[replica] = (host, int(port))

    def partition(self, replica: str) -> None:
        with self._lock:
            self._partitioned.add(replica)

    def heal(self, replica: str) -> None:
        with self._lock:
            self._partitioned.discard(replica)

    def request(self, replica: str, method: str, path: str,
                body: Optional[bytes] = None,
                headers: Optional[Dict[str, str]] = None,
                timeout_s: float = 5.0) -> Reply:
        import http.client
        import socket

        with self._lock:
            if replica in self._partitioned:
                raise ConnectError(
                    f"replica {replica!r} unreachable (partitioned)")
            try:
                host, port = self._addresses[replica]
            except KeyError:
                raise ConnectError(f"unknown replica {replica!r}") from None
        conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
        try:
            conn.request(method, path, body=body, headers=headers or {})
            resp = conn.getresponse()
            data = resp.read()
            return Reply(resp.status, dict(resp.getheaders()), data)
        except socket.timeout as e:
            raise RequestTimeout(
                f"replica {replica!r} timed out after {timeout_s}s") from e
        except (ConnectionError, OSError) as e:
            raise ConnectError(f"replica {replica!r}: {e}") from e
        finally:
            conn.close()


class Shed(RuntimeError):
    """Raised by a :class:`LoopbackReplica` predict fn to model the
    micro-batcher's Overloaded shed: surfaces as a 429 with the computed
    ``Retry-After`` — load, not failure."""

    def __init__(self, msg: str = "overloaded", retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class LoopbackReplica:
    """In-process stand-in for one ``PredictionServer`` replica: the same
    route surface (``POST /predict``, ``GET /healthz``,
    ``GET /healthz/<tenant>``, ``GET /slo``, ``GET /metrics``,
    ``GET /metrics.dump``) with no jax, no sockets and no threads —
    tier-1 failover tests drive it through :class:`FakeTransport`.

    ``predict_fn(inputs, tenant, headers)`` returns the outputs dict (or
    raises :class:`Shed` to model a 429).  ``slo_status`` and ``draining``
    are plain mutable attributes for tests/drills.  ``flight_trips``
    counts internal crashes (a handler exception → 500) — the partition
    acceptance test asserts it stays 0 while the router ejects the
    replica, pinning *partition ≠ crash*.

    Observability (round 16): each loopback owns its OWN metrics registry
    (default: a fresh one — it stands in for a separate process) and
    writes the real server's series names (``svgd_serve_requests_total``,
    ``svgd_serve_request_latency_seconds``, ``svgd_serve_shed_total``,
    ``svgd_http_requests_total``), so the router's federation merges fake
    and real replicas identically.  Pass ``tracer=`` (a per-replica
    :class:`~dist_svgd_tpu.telemetry.trace.Tracer`, again standing in for
    the other process's tracer) and every served predict emits a
    ``serve.request`` lane tree tagged with the incoming
    ``X-Fleet-Trace`` id — the replica half of a stitch."""

    def __init__(self, name: str,
                 predict_fn: Optional[Callable] = None,
                 tenants: Sequence[str] = (),
                 clock: Callable[[], float] = time.time,
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 tracer: Optional[_trace.Tracer] = None):
        self.name = name
        self.tenants = list(tenants)
        self.slo_status = "ok"
        self.draining = False
        self.flight_trips = 0
        self.requests = 0
        self.last_headers: Dict[str, str] = {}
        self._clock = clock
        self._predict = predict_fn or (
            lambda inputs, tenant, headers: {
                "mean": [0.0] * len(inputs)})
        self.registry = (registry if registry is not None
                         else _metrics.MetricsRegistry())
        self.tracer = tracer
        if tracer is not None:
            tracer.set_process("replica", name, only_if_default=True)
        self._m_requests = self.registry.counter(
            "svgd_serve_requests_total", "requests fully resolved")
        self._m_latency = self.registry.histogram(
            "svgd_serve_request_latency_seconds",
            "request end-to-end latency (enqueue to resolve)")
        self._m_shed = self.registry.counter(
            "svgd_serve_shed_total",
            "requests shed with Overloaded (bounded queue full)")
        self._m_http = self.registry.counter(
            "svgd_http_requests_total", "HTTP requests by route and status")

    def handle(self, method: str, path: str, body: Optional[bytes],
               headers: Optional[Dict[str, str]]) -> Reply:
        try:
            return self._handle(method, path, body, headers or {})
        except Shed as e:
            self._m_shed.inc()
            self._m_http.inc(route="/predict", status=429)
            return _json_reply(429, {"error": str(e),
                                     "retry_after_s": e.retry_after_s},
                               {"Retry-After": _format_retry_after(
                                   e.retry_after_s)})
        except Exception as e:  # a crashed handler — the flight-recorder
            self.flight_trips += 1  # shape a partition must NOT produce
            return _json_reply(500, {"error": f"{type(e).__name__}: {e}"})

    def _handle(self, method, path, body, headers) -> Reply:
        path = path.split("?", 1)[0]
        if method == "POST" and path == "/predict":
            self.requests += 1
            # per-request headers stay LOCAL through the handler: the
            # loopback serves concurrent requests on many router threads,
            # and reading the instance attribute after the predict would
            # tag this request with whichever trace id arrived last
            hdrs = {k.lower(): v for k, v in headers.items()}
            self.last_headers = hdrs  # test introspection only
            if self.draining:
                return _json_reply(503, {"error": "draining"})
            doc = json.loads(body or b"null")
            inputs = doc.get("inputs") if isinstance(doc, dict) else None
            if inputs is None:
                return _json_reply(400, {"error": "body needs inputs"})
            tenant = doc.get("tenant") if isinstance(doc, dict) else None
            tr = self.tracer
            t0 = tr.now() if tr is not None else 0.0
            wall0 = time.perf_counter()
            out = self._predict(inputs, tenant, hdrs)
            wall = time.perf_counter() - wall0
            tl = {} if tenant is None else {"tenant": tenant}
            self._m_requests.inc(**tl)
            self._m_latency.observe(wall, **tl)
            self._m_http.inc(route="/predict", status=200, **tl)
            if tr is not None:
                t1 = tr.now()
                attrs = {"rows": len(inputs), "replica": self.name, **tl}
                trace_id = hdrs.get(TRACE_HEADER.lower())
                if trace_id:
                    attrs["trace"] = trace_id
                attempt = hdrs.get(ATTEMPT_HEADER.lower())
                if attempt is not None:
                    attrs["attempt"] = attempt
                tr.lane_tree(
                    "serve.request", t0, t1, attrs,
                    children=[("serve.dispatch", t0, t1,
                               {"rows": len(inputs)})])
            payload = {"outputs": out, "replica": self.name}
            if tenant is not None:
                payload["tenant"] = tenant
            return _json_reply(200, payload)
        if method == "GET" and path == "/metrics":
            return Reply(200, {"Content-Type":
                               "text/plain; version=0.0.4; charset=utf-8"},
                         self.registry.exposition().encode())
        if method == "GET" and path == "/metrics.dump":
            return _json_reply(200, self.registry.dump())
        if method == "GET" and path == "/healthz":
            if self.draining:
                return _json_reply(503, {"status": "draining"})
            return _json_reply(200, {"status": "ok", "replica": self.name})
        if method == "GET" and path.startswith("/healthz/"):
            tenant = path[len("/healthz/"):]
            if self.draining:
                return _json_reply(503, {"status": "draining"})
            if self.tenants and tenant not in self.tenants:
                return _json_reply(404, {"error": f"no tenant {tenant!r}"})
            return _json_reply(200, {"status": "ok", "tenant": tenant})
        if method == "GET" and path == "/slo":
            return _json_reply(200, {"status": self.slo_status,
                                     "ts": self._clock()})
        return _json_reply(404, {"error": f"no route {path}"})


def _json_reply(status: int, payload: dict,
                headers: Optional[Dict[str, str]] = None) -> Reply:
    return Reply(status, {"Content-Type": "application/json",
                          **(headers or {})},
                 json.dumps(payload).encode())


def format_retry_after(seconds: float) -> str:
    """HTTP ``Retry-After`` delta-seconds (integer per RFC 9110, rounded
    up and floored at 1 so the client never comes back early).  The ONE
    formatter — the replica server and the router must emit the same
    header for the same hint."""
    return str(max(int(math.ceil(seconds)), 1))


_format_retry_after = format_retry_after  # internal alias


class FakeTransport:
    """Injectable in-process transport: replica id → handler (anything
    with ``handle(method, path, body, headers) -> Reply``, i.e. a
    :class:`LoopbackReplica`).

    Process-level faults come in two flavors:

    - **scheduled** — ``faults=[ReplicaKillAt(at=40, replica="r1"), ...]``
      keyed by the transport's request ordinal (every :meth:`request`
      increments it, probes included), for deterministic tier-1 schedules;
    - **runtime** — :meth:`kill` / :meth:`hang` / :meth:`partition` /
      :meth:`slow` / :meth:`restore`, for drills that flip state on wall
      clock.

    ``advance(seconds)`` models elapsed time (``time.sleep`` by default;
    tests pass the fake clock's advance) — a hang charges the full per-try
    timeout, a slow replica charges its delay, so drills measure fault
    cost instead of waiting for it."""

    def __init__(self, replicas: Dict[str, Any], faults: Sequence = (),
                 advance: Callable[[float], None] = time.sleep):
        self._replicas = dict(replicas)
        self._faults = list(faults)
        self._advance = advance
        self._lock = threading.Lock()
        self._ordinal = 0
        self._forced: Dict[str, str] = {}  # replica -> kind
        self._forced_slow: Dict[str, float] = {}

    # runtime fault switches (drills) ---------------------------------- #

    def kill(self, replica: str) -> None:
        with self._lock:
            self._forced[replica] = "kill"

    def hang(self, replica: str) -> None:
        with self._lock:
            self._forced[replica] = "hang"

    def partition(self, replica: str) -> None:
        with self._lock:
            self._forced[replica] = "partition"

    def slow(self, replica: str, seconds: float) -> None:
        with self._lock:
            self._forced_slow[replica] = float(seconds)

    def restore(self, replica: str) -> None:
        """Lift every runtime fault on ``replica`` (process restarted /
        partition healed / slowdown over)."""
        with self._lock:
            self._forced.pop(replica, None)
            self._forced_slow.pop(replica, None)

    def set_replica(self, replica: str, handler: Any) -> None:
        """Swap the handler behind ``replica`` — a drill models a process
        *restart* by installing a FRESH :class:`LoopbackReplica` (new
        registry, counters back at zero, new tracer), which is exactly
        what exercises the federation's counter-reset clamping."""
        with self._lock:
            self._replicas[replica] = handler

    @property
    def ordinal(self) -> int:
        with self._lock:
            return self._ordinal

    # transport -------------------------------------------------------- #

    def _state_for(self, replica: str) -> Tuple[Optional[str], float]:
        """(fault kind or None, slow seconds) for this request — advances
        the ordinal."""
        with self._lock:
            self._ordinal += 1
            n = self._ordinal
            kind = self._forced.get(replica)
            slow = self._forced_slow.get(replica, 0.0)
            for f in self._faults:
                if f.replica == replica and f.active(n):
                    if f.kind == "slow":
                        slow = max(slow, f.seconds)
                    elif kind is None:
                        kind = f.kind
            return kind, slow

    def request(self, replica: str, method: str, path: str,
                body: Optional[bytes] = None,
                headers: Optional[Dict[str, str]] = None,
                timeout_s: float = 5.0) -> Reply:
        handler = self._replicas.get(replica)
        if handler is None:
            raise ConnectError(f"unknown replica {replica!r}")
        kind, slow = self._state_for(replica)
        if kind == "kill":
            raise ConnectError(
                f"replica {replica!r} connection refused (process dead)")
        if kind == "partition":
            # the replica object is NOT touched: it stays alive and
            # reachable by anyone on the healthy side of the cut
            raise ConnectError(
                f"replica {replica!r} unreachable (partitioned)")
        if kind == "hang":
            self._advance(timeout_s)
            raise RequestTimeout(
                f"replica {replica!r} hung past {timeout_s}s")
        if slow:
            self._advance(slow)
        return handler.handle(method, path, body, headers)


# --------------------------------------------------------------------- #
# health classification


def classify_slo(doc: Any, now_s: Optional[float] = None,
                 max_age_s: Optional[float] = None) -> str:
    """Map a replica's ``/slo`` document to a routing verdict:
    ``"burning"`` (status ``breach`` — eject), ``"healthy"`` (status
    ``ok``), else ``"unknown"``.

    Unknown is sticky-conservative: a missing/garbled document, a
    ``no_data`` engine, or a verdict older than ``max_age_s`` (judged by
    the document's own ``ts`` stamp) must read **unknown, never
    healthy** — stale good news is no news.  Unknown neither ejects nor
    re-admits; only a fresh verdict moves the circuit."""
    if not isinstance(doc, dict):
        return "unknown"
    status = doc.get("status")
    if (max_age_s is not None and now_s is not None):
        ts = doc.get("ts")
        if not isinstance(ts, (int, float)) or now_s - ts > max_age_s:
            return "unknown"
    if status == "breach":
        return "burning"
    if status == "ok":
        return "healthy"
    return "unknown"


# --------------------------------------------------------------------- #
# membership / circuit breaker


class _ReplicaState:
    __slots__ = ("state", "probe_failures", "request_failures", "opened_at",
                 "last_healthy", "inflight", "ejections", "reason",
                 "backpressure_until", "generation")

    def __init__(self):
        self.state = CLOSED
        self.probe_failures = 0
        self.request_failures = 0
        self.opened_at = 0.0
        self.last_healthy: Optional[float] = None
        self.inflight = 0
        self.ejections = 0
        self.reason = ""
        self.backpressure_until = 0.0
        # serving generation the last healthy probe reported (round 21):
        # a mid-rollout fleet shows which replicas flipped generations
        self.generation: Optional[int] = None


class ReplicaSet:
    """Fleet membership with per-replica circuit breakers.

    Active probing (:meth:`probe_once`, or the background thread
    :meth:`start`/:meth:`close` drive with ``probe_interval_s``) and
    passive per-request scoring (:meth:`record_success` /
    :meth:`record_failure` / :meth:`record_shed`) feed one state machine
    per replica — see the module docstring for the transition rules.  All
    clocks are injectable; probes do network I/O outside the lock.
    """

    def __init__(self, replicas: Sequence[str], transport, *,
                 probe_interval_s: float = 1.0,
                 probe_timeout_s: float = 1.0,
                 fail_threshold: int = 2,
                 passive_fail_threshold: int = 3,
                 open_cooldown_s: float = 2.0,
                 probe_tenants: Sequence[str] = (),
                 health_path: str = "/healthz",
                 slo_path: str = "/slo",
                 slo_max_age_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 registry: Optional[_metrics.MetricsRegistry] = None):
        if fail_threshold < 1 or passive_fail_threshold < 1:
            raise ValueError("thresholds must be >= 1")
        if not replicas:
            raise ValueError("need at least one replica")
        self.transport = transport
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.fail_threshold = int(fail_threshold)
        self.passive_fail_threshold = int(passive_fail_threshold)
        self.open_cooldown_s = float(open_cooldown_s)
        self.probe_tenants = list(probe_tenants)
        self.health_path = health_path
        self.slo_path = slo_path
        self.slo_max_age_s = slo_max_age_s
        self._clock = clock
        self._lock = threading.Lock()
        self._replicas: Dict[str, _ReplicaState] = {
            str(r): _ReplicaState() for r in replicas}
        #: bounded log of ``(ts, replica, from_state, to_state, reason)``
        #: transitions — drills read detection/readmit latency off it
        self.state_changes: deque = deque(maxlen=1024)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        reg = registry if registry is not None else _metrics.default_registry()
        self.registry = reg
        self._m_state = reg.gauge(
            "svgd_fleet_replica_state",
            "replica circuit state: 0 closed, 1 half-open, 2 open")
        self._m_ejections = reg.counter(
            "svgd_fleet_ejections_total",
            "circuit-open transitions by reason")
        self._m_readmissions = reg.counter(
            "svgd_fleet_readmissions_total",
            "half-open trials that re-admitted a replica")
        # judged INSIDE begin_request's lock so an admit-then-eject race
        # can never count: only an admission granted while the circuit was
        # already open is a misroute (a selection bug), and that decision
        # and the state read happen under one lock acquisition
        self._m_misroutes = reg.counter(
            "svgd_fleet_misroutes_total",
            "admissions granted to a replica whose circuit was open "
            "(must stay 0 — perf_regress FAILs on any)")
        for rid in self._replicas:
            self._m_state.set(0, replica=rid)

    # ---- state machine core (all under the lock) --------------------- #

    def _transition_locked(self, rid: str, to_state: str,
                           reason: str) -> None:
        st = self._replicas[rid]
        if st.state == to_state:
            return
        now = self._clock()
        self.state_changes.append((now, rid, st.state, to_state, reason))
        if to_state == OPEN:
            st.opened_at = now
            st.ejections += 1
            self._m_ejections.inc(reason=reason)
        if to_state == CLOSED and st.state == HALF_OPEN:
            self._m_readmissions.inc()
        st.state = to_state
        st.reason = reason
        if to_state == CLOSED:
            st.probe_failures = 0
            st.request_failures = 0
        self._m_state.set(_STATE_GAUGE[to_state], replica=rid)

    def _maybe_half_open_locked(self, rid: str) -> None:
        st = self._replicas[rid]
        if (st.state == OPEN
                and self._clock() - st.opened_at >= self.open_cooldown_s):
            self._transition_locked(rid, HALF_OPEN, "cooldown_elapsed")

    # ---- passive scoring (router-reported outcomes) ------------------ #

    def begin_request(self, rid: str,
                      load_factor: Optional[float] = None) -> bool:
        """Admission check + in-flight accounting for one forward attempt.
        False when the circuit is open, a half-open trial is already in
        flight, or (with ``load_factor``) the replica is past its bounded
        fair share of the fleet's in-flight load.  A True return MUST be
        paired with exactly one ``record_*`` call."""
        with self._lock:
            st = self._replicas.get(rid)
            if st is None:
                return False
            self._maybe_half_open_locked(rid)
            if st.state == OPEN:
                return False
            if st.state == HALF_OPEN and st.inflight > 0:
                return False  # one trial at a time — that's the point
            if load_factor is not None and st.state == CLOSED:
                admitted = [s for s in self._replicas.values()
                            if s.state != OPEN]
                total = sum(s.inflight for s in admitted)
                cap = max(1.0, math.ceil(
                    load_factor * (total + 1) / max(len(admitted), 1)))
                if st.inflight + 1 > cap:
                    return False  # bounded-load overflow to the next node
            if st.state == OPEN:  # pragma: no cover
                # assert-style invariant detector: unreachable while the
                # OPEN gate above stands, but if a future selection change
                # ever admits an ejected replica, this counts it at the
                # admission decision itself — under THIS lock acquisition,
                # so an admit-then-eject race can never false-positive the
                # perf_regress unconditional-FAIL gate
                self._m_misroutes.inc()
            st.inflight += 1
            return True

    def record_success(self, rid: str) -> None:
        with self._lock:
            st = self._replicas[rid]
            st.inflight = max(0, st.inflight - 1)
            st.probe_failures = 0
            st.request_failures = 0
            st.last_healthy = self._clock()
            if st.state == HALF_OPEN:
                self._transition_locked(rid, CLOSED, "trial_request_ok")

    def record_failure(self, rid: str, reason: str = "request") -> None:
        with self._lock:
            st = self._replicas[rid]
            st.inflight = max(0, st.inflight - 1)
            st.request_failures += 1
            if st.state == HALF_OPEN:
                self._transition_locked(rid, OPEN, f"trial_failed:{reason}")
            elif (st.state == CLOSED
                  and st.request_failures >= self.passive_fail_threshold):
                self._transition_locked(rid, OPEN, f"request_failures:{reason}")

    def record_shed(self, rid: str,
                    retry_after_s: Optional[float] = None) -> None:
        """A 429: the replica is alive and telling us it's loaded — release
        the in-flight slot, remember the backpressure window, do NOT touch
        the failure counters (sheds are load, not failure)."""
        with self._lock:
            st = self._replicas[rid]
            st.inflight = max(0, st.inflight - 1)
            st.last_healthy = self._clock()
            if retry_after_s:
                st.backpressure_until = self._clock() + retry_after_s
            if st.state == HALF_OPEN:
                # an overloaded replica is a live replica
                self._transition_locked(rid, CLOSED, "trial_shed_alive")

    # ---- active probing ---------------------------------------------- #

    def _probe_replica(self, rid: str) -> Tuple[bool, bool, str,
                                                Optional[int]]:
        """(health ok, draining, slo verdict, serving generation) —
        network I/O, NO lock.  The generation comes off the root
        ``/healthz`` doc (single-tenant replicas report it directly;
        multi-tenant docs carry it per tenant instead and report None
        here)."""
        draining = False
        generation: Optional[int] = None
        try:
            paths = [self.health_path] + [
                f"{self.health_path}/{t}" for t in self.probe_tenants]
            for path in paths:
                reply = self.transport.request(
                    rid, "GET", path, timeout_s=self.probe_timeout_s)
                doc = reply.json()
                if isinstance(doc, dict) and doc.get("status") == "draining":
                    return False, True, "unknown", None
                if reply.status != 200:
                    return False, False, "unknown", None
                if (isinstance(doc, dict) and path == self.health_path
                        and doc.get("generation_id") is not None):
                    generation = int(doc["generation_id"])
        except TransportError:
            return False, False, "unknown", None
        try:
            reply = self.transport.request(
                rid, "GET", self.slo_path, timeout_s=self.probe_timeout_s)
            verdict = classify_slo(reply.json(), now_s=self._clock(),
                                   max_age_s=self.slo_max_age_s)
        except TransportError:
            verdict = "unknown"
        return True, draining, verdict, generation

    def probe_once(self) -> Dict[str, str]:
        """One active sweep: probe every non-cooling replica, apply the
        transition rules, return ``{replica: state}`` after."""
        to_probe = []
        with self._lock:
            for rid in self._replicas:
                self._maybe_half_open_locked(rid)
                if self._replicas[rid].state != OPEN:
                    to_probe.append(rid)
        results = {rid: self._probe_replica(rid) for rid in to_probe}
        with self._lock:
            for rid, (ok, draining, verdict, generation) in results.items():
                st = self._replicas[rid]
                if draining:
                    # a deliberate signal, not a flaky probe: one strike
                    self._transition_locked(rid, OPEN, "draining")
                    continue
                if not ok:
                    st.probe_failures += 1
                    if st.state == HALF_OPEN:
                        self._transition_locked(rid, OPEN, "trial_probe_failed")
                    elif st.probe_failures >= self.fail_threshold:
                        self._transition_locked(rid, OPEN, "probe_failures")
                    continue
                if verdict == "burning":
                    st.probe_failures = 0
                    self._transition_locked(rid, OPEN, "slo_burn")
                    continue
                # healthy probe (slo healthy or unknown — unknown never
                # blocks a live health endpoint from keeping its circuit)
                st.probe_failures = 0
                st.last_healthy = self._clock()
                if generation is not None:
                    st.generation = generation
                if st.state == HALF_OPEN:
                    self._transition_locked(rid, CLOSED, "trial_probe_ok")
            return {rid: s.state for rid, s in self._replicas.items()}

    # ---- queries ------------------------------------------------------ #

    def state(self, rid: str) -> str:
        with self._lock:
            self._maybe_half_open_locked(rid)
            return self._replicas[rid].state

    def replica_ids(self) -> List[str]:
        with self._lock:
            return list(self._replicas)

    def backpressured(self, rid: str) -> bool:
        with self._lock:
            st = self._replicas[rid]
            return self._clock() < st.backpressure_until

    def last_known_healthy(self, candidates: Optional[Sequence[str]] = None
                           ) -> Optional[Dict[str, Any]]:
        """Most recent healthy sighting among ``candidates`` (default all):
        the hint a 503 carries so clients know the outage is fresh."""
        with self._lock:
            best = None
            for rid in (candidates if candidates is not None
                        else self._replicas):
                st = self._replicas.get(rid)
                if st is None or st.last_healthy is None:
                    continue
                if best is None or st.last_healthy > best[1]:
                    best = (rid, st.last_healthy)
            if best is None:
                return None
            return {"replica": best[0],
                    "age_s": round(self._clock() - best[1], 3)}

    def retry_after_hint_s(self) -> float:
        """Seconds until the soonest open circuit may half-open — what a
        blanket 503's ``Retry-After`` should say."""
        with self._lock:
            now = self._clock()
            waits = [max(st.opened_at + self.open_cooldown_s - now, 0.0)
                     for st in self._replicas.values() if st.state == OPEN]
            return min(waits) if waits else 1.0

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                rid: {"state": st.state, "reason": st.reason,
                      "inflight": st.inflight, "ejections": st.ejections,
                      "probe_failures": st.probe_failures,
                      "request_failures": st.request_failures,
                      "generation": st.generation,
                      "last_healthy_age_s": (
                          None if st.last_healthy is None
                          else round(self._clock() - st.last_healthy, 3))}
                for rid, st in self._replicas.items()
            }

    # ---- probe thread ------------------------------------------------- #

    def start(self) -> "ReplicaSet":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._probe_loop, name="fleet-prober", daemon=True)
            self._thread.start()
        return self

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            try:
                self.probe_once()
            except Exception:  # a probe sweep must never kill the prober
                pass

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# --------------------------------------------------------------------- #
# metrics federation


class MetricsFederation:
    """Router-side metrics federation: scrape every replica's
    full-fidelity registry dump (``GET /metrics.dump``) and merge the
    **clamped per-replica deltas** into one fleet registry — the
    Prometheus-federation shape, built on our own registry instead of a
    scrape stack.

    - counters and histograms accumulate non-negative window deltas per
      replica (:func:`~dist_svgd_tpu.telemetry.metrics.dump_delta`):
      merging is **exact** because every registry shares the fixed
      log-spaced bucket lattice, and a restarted replica's counter reset
      clamps to a zero delta (slo.py's window-reset discipline) so
      federated rates never go negative.  Every series lands twice —
      labelled ``replica=<id>`` and unlabelled (the **fleet rollup**: the
      sum over replicas);
    - gauges are last-write-wins under their ``replica=`` label only
      (summing instantaneous state encodings across processes is not
      meaningful; rates belong to counters);
    - a scrape failure (dead/partitioned replica, malformed dump,
      mismatched buckets) increments
      ``svgd_fleet_scrape_errors_total{replica=...}`` and leaves that
      replica's prior contribution standing — federation **degrades
      visibly, not silently**.  The ``replica`` label rides the shared
      cardinality guard, so a flapping fleet aggregates into the
      ``other`` rollup instead of growing the exposition without bound.

    One :meth:`scrape_once` sweep is serialized under the federation lock
    (two concurrent ``/metrics`` collections must not double-count one
    window) and its wall is observed into
    ``svgd_fleet_scrape_seconds`` — the ``federation_scrape_ms`` number
    the fleet drill rows carry.
    """

    def __init__(self, replica_set: "ReplicaSet", transport=None, *,
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 fleet_registry: Optional[_metrics.MetricsRegistry] = None,
                 path: str = "/metrics.dump",
                 timeout_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        self.replica_set = replica_set
        self.transport = transport if transport is not None \
            else replica_set.transport
        self.path = path
        self.timeout_s = float(timeout_s)
        self._clock = clock
        #: The federated view: replica-labelled series + fleet rollups.
        #: Deliberately its OWN registry (never the process default) so
        #: scraped series cannot collide with the router's own.
        self.fleet_registry = (fleet_registry if fleet_registry is not None
                               else _metrics.MetricsRegistry())
        self._lock = threading.Lock()
        self._prev: Dict[str, dict] = {}
        self._scrapes = 0
        self._skips = 0
        self._last_wall_ms: Optional[float] = None
        self._monotone = True
        self._last_rollup: Dict[str, float] = {}
        reg = registry if registry is not None else _metrics.default_registry()
        self._m_errors = reg.counter(
            "svgd_fleet_scrape_errors_total",
            "replica /metrics.dump scrapes that failed "
            "(unreachable replica, malformed dump)")
        self._m_scrapes = reg.counter(
            "svgd_fleet_scrapes_total", "federation scrape sweeps")
        self._m_wall = reg.histogram(
            "svgd_fleet_scrape_seconds", "one federation sweep's wall")

    def _validate_delta(self, delta: dict) -> None:
        """Reject a delta the fleet registry could not ingest atomically —
        BEFORE any series is applied, so a bad dump never leaves the
        replica-labelled and rollup views half-updated."""
        for name, entry in delta.get("metrics", {}).items():
            if not _metrics._NAME_OK.match(str(name)):
                # the registry's own name gate, applied up front: ingest
                # hitting it MID-dump would leave earlier metrics applied
                raise ValueError(f"dump carries invalid metric name "
                                 f"{name!r}")
            kind = entry.get("kind")
            if kind not in ("counter", "gauge", "histogram"):
                raise ValueError(
                    f"dump entry {name!r} has unknown kind {kind!r}")
            existing = self.fleet_registry.get(name)
            if existing is not None and existing.kind != kind:
                raise ValueError(
                    f"dump entry {name!r} is a {kind}; the fleet registry "
                    f"holds a {existing.kind} under that name")
            for s in entry.get("series", []):
                labels = s.get("labels")
                if labels is not None and not isinstance(labels, dict):
                    raise ValueError(
                        f"{name!r} series labels must be an object")
            if kind in ("counter", "gauge"):
                for s in entry.get("series", []):
                    value = s.get("value", 0)
                    if not isinstance(value, (int, float)):
                        raise ValueError(
                            f"{kind} {name!r} has non-numeric value "
                            f"{value!r}")
                    if kind == "counter" and value < 0:
                        raise ValueError(
                            f"counter {name!r} delta went negative")
            elif kind == "histogram":
                dumped = entry.get("buckets")
                bounds = (tuple(dumped) if dumped is not None
                          else getattr(existing, "buckets",
                                       _metrics.LATENCY_BUCKETS_S))
                if (existing is not None
                        and tuple(bounds) != tuple(existing.buckets)):
                    raise ValueError(
                        f"histogram {name!r}: dump buckets do not match "
                        "the fleet lattice")
                for s in entry.get("series", []):
                    counts = s.get("counts", [])
                    if len(counts) != len(bounds) + 1:
                        raise ValueError(
                            f"histogram {name!r}: series has "
                            f"{len(counts)} bucket counts, "
                            f"lattice needs {len(bounds) + 1}")
                    if not all(isinstance(c, (int, float))
                               for c in counts) or not isinstance(
                                   s.get("sum", 0.0), (int, float)):
                        raise ValueError(
                            f"histogram {name!r} has non-numeric counts")

    def scrape_once(self) -> Dict[str, Any]:
        """One federation sweep; returns ``{"wall_ms", "scraped": [...],
        "skipped": [...], "errors": {replica: reason}}``.

        Replicas whose circuit is already OPEN are **skipped**, not
        scraped: their prior contribution stands either way, and paying
        ``timeout_s`` per known-dead replica on every ``/metrics``
        collection would stall a scraper ``dead × timeout`` seconds
        through a whole outage (the breaker's probes own readmission —
        scraping resumes the sweep after they re-close the circuit).
        Failures on replicas still believed healthy ARE counted — that's
        the visible-degradation window between a death and its
        detection."""
        with self._lock:
            t0 = self._clock()
            scraped: List[str] = []
            skipped: List[str] = []
            errors: Dict[str, str] = {}
            for rid in self.replica_set.replica_ids():
                if self.replica_set.state(rid) == OPEN:
                    skipped.append(rid)
                    continue
                try:
                    reply = self.transport.request(
                        rid, "GET", self.path, timeout_s=self.timeout_s)
                    if reply.status != 200:
                        raise TransportError(
                            f"{self.path} answered {reply.status}")
                    doc = reply.json()
                    if not isinstance(doc, dict) or "metrics" not in doc:
                        raise ValueError("reply is not a metrics dump")
                    delta = _metrics.dump_delta(self._prev.get(rid), doc)
                    # validate → ingest → only THEN advance the window:
                    # a rejected dump must leave the replica's prior
                    # contribution standing and its window un-consumed
                    # (advancing _prev on failure would silently drop the
                    # failed window's counts forever)
                    self._validate_delta(delta)
                    # replica-labelled series AND the unlabelled rollup;
                    # gauges only under their replica identity.  A
                    # replica's own SLO verdict mirrors (svgd_slo_*) stay
                    # replica-labelled ONLY: the router's fleet SLO
                    # engine writes the unlabelled {slo=...} series in
                    # this same registry, and rolling replica-local
                    # verdicts into it would conflate per-engine breach
                    # counts with the fleet verdict
                    self.fleet_registry.ingest(delta, labels={"replica": rid})
                    rollup = {"metrics": {
                        n: e for n, e in delta.get("metrics", {}).items()
                        if not n.startswith("svgd_slo_")}}
                    self.fleet_registry.ingest(rollup, skip_gauges=True)
                    self._prev[rid] = doc
                    scraped.append(rid)
                except Exception as e:
                    errors[rid] = f"{type(e).__name__}: {e}"
                    self._m_errors.inc(replica=rid)
            wall = self._clock() - t0
            self._scrapes += 1
            self._skips += len(skipped)
            self._last_wall_ms = wall * 1e3
            # monotonicity audit over the ROLLUP series (everything not
            # carrying the replica identity — the federated totals): an
            # assert-style invariant detector.  Add-only ingest plus
            # clamped deltas make a decrease unreachable today; if a
            # future change breaks either half, this flips the drill's
            # federation_monotone gate instead of silently shipping
            # negative rates
            with self.fleet_registry._lock:
                fed_metrics = dict(self.fleet_registry._metrics)
            for name, metric in fed_metrics.items():
                if not isinstance(metric, _metrics.Counter):
                    continue
                value = float(sum(
                    metric.value(**ls) for ls in metric.label_sets()
                    if "replica" not in ls))
                if value < self._last_rollup.get(name, 0.0):
                    self._monotone = False
                self._last_rollup[name] = value
        self._m_scrapes.inc()
        self._m_wall.observe(wall)
        return {"wall_ms": round(wall * 1e3, 3), "scraped": scraped,
                "skipped": skipped, "errors": errors}

    @property
    def scrapes(self) -> int:
        with self._lock:
            return self._scrapes

    @property
    def skips(self) -> int:
        """Cumulative open-circuit replicas skipped across sweeps."""
        with self._lock:
            return self._skips

    @property
    def monotone(self) -> bool:
        """False if any federated counter rollup ever decreased between
        sweeps (must stay True — clamping exists exactly for this)."""
        with self._lock:
            return self._monotone

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {"scrapes": self._scrapes,
                   "skipped": self._skips,
                   "last_scrape_ms": (None if self._last_wall_ms is None
                                      else round(self._last_wall_ms, 3)),
                   "monotone": self._monotone}
        out["scrape_errors"] = {
            rid: self._m_errors.value(replica=rid)
            for rid in self.replica_set.replica_ids()
            if self._m_errors.value(replica=rid) > 0}
        return out


# --------------------------------------------------------------------- #
# consistent hashing


def _hash_point(key: str) -> int:
    return int.from_bytes(hashlib.sha1(key.encode()).digest()[:8], "big")


class _HashRing:
    """Consistent-hash ring with virtual nodes; static after construction
    (membership changes go through the circuit breaker, not the ring —
    a dead replica keeps its arc so tenants return home on re-admission)."""

    def __init__(self, replicas: Sequence[str], vnodes: int = 32):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        points: List[Tuple[int, str]] = []
        for rid in replicas:
            for v in range(vnodes):
                points.append((_hash_point(f"{rid}#{v}"), rid))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [r for _, r in points]
        self._n = len(set(replicas))

    def order(self, tenant: str) -> List[str]:
        """Every replica, ring-ordered from the tenant's hash point —
        element 0 is the tenant's home; the rest are its failover chain."""
        start = bisect.bisect_left(self._points, _hash_point(tenant))
        seen: List[str] = []
        for i in range(len(self._owners)):
            rid = self._owners[(start + i) % len(self._owners)]
            if rid not in seen:
                seen.append(rid)
                if len(seen) == self._n:
                    break
        return seen


# --------------------------------------------------------------------- #
# the router


class RouteResult:
    """Outcome of one routed request (the HTTP layer writes it verbatim)."""

    __slots__ = ("status", "headers", "body", "replica", "attempts",
                 "hedged", "outcome")

    def __init__(self, status, headers, body, replica=None, attempts=0,
                 hedged=False, outcome="served"):
        self.status = status
        self.headers = headers
        self.body = body
        self.replica = replica
        self.attempts = attempts
        self.hedged = hedged
        self.outcome = outcome

    def json(self) -> Any:
        try:
            return json.loads(self.body or b"null")
        except (ValueError, UnicodeDecodeError):
            return None


class FleetRouter:
    """Consistent-hash front door over a :class:`ReplicaSet` — see the
    module docstring for the full routing contract.

    Args:
        replicas: replica ids (with ``transport=None``, a dict
            ``{id: (host, port)}`` builds an :class:`HttpTransport`).
        transport: injectable transport (:class:`FakeTransport` in tests).
        vnodes / load_factor: consistent-hash ring shape and the
            bounded-load overflow factor (fair-share multiplier; ``None``
            disables overflow).
        max_retries: extra attempts after the first (connect/timeout/5xx
            only — never a 429).
        per_try_timeout_s / default_deadline_s: one attempt's transport
            budget and the whole request's default deadline (clients
            override per request via the ``X-Fleet-Deadline-S`` header).
        backoff: shared jittered :class:`Backoff` between retries
            (clamped to the remaining deadline).
        hedge / hedge_delay_s / hedge_min_delay_s: opt-in tail hedging;
            with ``hedge_delay_s=None`` the delay is the p99 of recent
            successful forwards (bounded window), clamped to
            ``[hedge_min_delay_s, per_try_timeout_s/2]``.
        replica_set: a pre-built :class:`ReplicaSet` (tests inject clocks
            through it); else one is built from ``probe_...`` kwargs.
    """

    def __init__(self, replicas, *,
                 transport=None,
                 vnodes: int = 32,
                 load_factor: Optional[float] = 2.0,
                 max_retries: int = 2,
                 per_try_timeout_s: float = 5.0,
                 default_deadline_s: float = 10.0,
                 backoff: Optional[Backoff] = None,
                 hedge: bool = False,
                 hedge_delay_s: Optional[float] = None,
                 hedge_min_delay_s: float = 0.01,
                 replica_set: Optional[ReplicaSet] = None,
                 probe_interval_s: float = 1.0,
                 probe_tenants: Sequence[str] = (),
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 slo_p99_ms: float = 100.0,
                 slo_min_interval_s: float = 5.0,
                 federation_timeout_s: float = 1.0,
                 host: str = "127.0.0.1",
                 port: Optional[int] = None):
        if isinstance(replicas, dict) and transport is None:
            transport = HttpTransport(replicas)
        ids = list(replicas)
        if transport is None and replica_set is not None:
            transport = replica_set.transport
        if transport is None:
            raise ValueError("pass transport= (or {id: (host, port)})")
        reg = registry if registry is not None else _metrics.default_registry()
        self.registry = reg
        self.replica_set = replica_set if replica_set is not None else (
            ReplicaSet(ids, transport,
                       probe_interval_s=probe_interval_s,
                       probe_tenants=probe_tenants,
                       probe_timeout_s=min(per_try_timeout_s, 1.0),
                       clock=clock, registry=reg))
        self.transport = (transport if replica_set is None
                          else replica_set.transport)
        self._ring = _HashRing(ids, vnodes=vnodes)
        self.load_factor = load_factor
        self.max_retries = int(max_retries)
        self.per_try_timeout_s = float(per_try_timeout_s)
        self.default_deadline_s = float(default_deadline_s)
        self.backoff = backoff if backoff is not None else Backoff(
            base_s=0.02, factor=2.0, max_s=1.0, jitter_frac=0.2)
        self.hedge = bool(hedge)
        self.hedge_delay_s = hedge_delay_s
        self.hedge_min_delay_s = float(hedge_min_delay_s)
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()
        self._lat_window: deque = deque(maxlen=512)  # successful forward walls
        self._pool: Optional[ThreadPoolExecutor] = None
        if self.hedge:
            self._pool = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="fleet-hedge")

        self._m_requests = reg.counter(
            "svgd_fleet_requests_total", "routed requests by outcome")
        self._m_retries = reg.counter(
            "svgd_fleet_retries_total", "forward retries by failure reason")
        self._m_hedges = reg.counter(
            "svgd_fleet_hedges_total", "tail-hedged forwards")
        self._m_failovers = reg.counter(
            "svgd_fleet_failovers_total",
            "requests served by a non-home replica")
        self._m_latency = reg.histogram(
            "svgd_fleet_route_seconds", "end-to-end routed request wall")

        #: Metrics federation over the fleet (round 16): ``/metrics``
        #: scrapes-on-collect and serves the router's own series plus the
        #: per-replica-labelled + rollup federated view; ``/slo``
        #: evaluates the serving objectives over the *federated* window.
        self.federation = MetricsFederation(
            self.replica_set, self.transport, registry=reg,
            timeout_s=federation_timeout_s, clock=clock)
        self.slo_engine = default_serving_slos(
            self.federation.fleet_registry, p99_ms=slo_p99_ms,
            aggregate=True)
        #: The SLO objectives are stateful windows; N concurrent pollers
        #: (an alerting scraper on /slo + an operator looping /fleet)
        #: would slice one window into N slivers and make burn rates
        #: flap.  evaluate_slo() therefore caches the verdict for
        #: ``slo_min_interval_s`` — every consumer sees windows at least
        #: that wide no matter how many poll.
        self.slo_min_interval_s = float(slo_min_interval_s)
        self._slo_cache: Optional[Tuple[float, Dict[str, Any]]] = None

        self._httpd = None
        self._serve_thread = None
        if port is not None:
            self._httpd = ThreadingHTTPServer(
                (host, port), self._make_handler())
            # ThreadingMixIn reads this off the SERVER instance (a class
            # attribute on the handler is a no-op): non-daemon handler
            # threads are what makes server_close() join in-flight
            # requests — the graceful part of graceful degradation
            self._httpd.daemon_threads = False

    # ---- candidate selection ----------------------------------------- #

    def order_for(self, tenant: str) -> List[str]:
        return self._ring.order(tenant)

    def _pick(self, order: Sequence[str], tried: set) -> Optional[str]:
        """First admitted candidate in ring order, by preference passes:
        untried + unbackpressured + within the load bound, then untried
        within the bound, then untried *ignoring* the bound (the bound is
        a placement preference — a healthy-but-busy replica beats a 503;
        real admission control is the replica's own 429), then anyone
        admitted (retry the same replica when it's all that's left)."""
        rs = self.replica_set
        passes = ((True, True, True), (True, False, True),
                  (True, False, False), (False, False, False))
        for skip_tried, skip_bp, bounded in passes:
            for rid in order:
                if (rid in tried) == skip_tried:
                    continue
                if skip_bp and rs.backpressured(rid):
                    continue
                if rs.begin_request(rid,
                                    self.load_factor if bounded else None):
                    return rid
        return None

    # ---- forwarding --------------------------------------------------- #

    def _forward(self, rid: str, method: str, path: str, body, headers,
                 timeout_s: float) -> Reply:
        """One transport attempt with outcome recording — every exit
        records exactly one outcome against the ``begin_request`` the
        caller acquired.  (Misroute detection lives in ``begin_request``,
        under the replica-set lock — re-checking the state here would race
        with a concurrent ejection of a legitimately admitted request.)"""
        rs = self.replica_set
        t0 = self._clock()
        try:
            reply = self.transport.request(rid, method, path, body=body,
                                           headers=headers,
                                           timeout_s=timeout_s)
        except ConnectError:
            rs.record_failure(rid, "connect")
            raise
        except RequestTimeout:
            rs.record_failure(rid, "timeout")
            raise
        except TransportError:
            rs.record_failure(rid, "transport")
            raise
        if reply.status == 429:
            rs.record_shed(rid, reply.retry_after_s())
        elif reply.status == 504:
            # the replica answered that the CALLER's deadline ran out: it
            # is alive, and the tight budget was ours — release the slot
            # without scoring a failure (ejecting healthy replicas on
            # short-deadline traffic would be self-inflicted)
            rs.record_success(rid)
        elif reply.status >= 500:
            rs.record_failure(rid, "5xx")
        else:
            rs.record_success(rid)
            with self._lock:
                self._lat_window.append(self._clock() - t0)
        return reply

    def _hedge_delay(self) -> float:
        if self.hedge_delay_s is not None:
            return self.hedge_delay_s
        with self._lock:
            lat = sorted(self._lat_window)
        if not lat:
            d = self.hedge_min_delay_s
        else:
            d = lat[min(len(lat) - 1, int(0.99 * (len(lat) - 1)))]
        return min(max(d, self.hedge_min_delay_s),
                   self.per_try_timeout_s / 2)

    def _attempt(self, rid: str, order, tried: set, method, path, body,
                 headers, timeout_s: float,
                 hedge_allowed: bool) -> Tuple[Reply, str, bool]:
        """One attempt, optionally tail-hedged.  Returns
        ``(reply, serving replica, hedged?)`` or raises TransportError
        (both legs failed / the only leg failed)."""
        if not (hedge_allowed and self.hedge and self._pool is not None):
            return self._forward(rid, method, path, body, headers,
                                 timeout_s), rid, False
        f1 = self._pool.submit(self._forward, rid, method, path, body,
                               headers, timeout_s)
        try:
            return f1.result(timeout=self._hedge_delay()), rid, False
        except FutureTimeout:
            pass  # primary is slow — hedge
        backup = self._pick(order, tried | {rid})
        if backup is None:
            try:
                return f1.result(timeout=timeout_s), rid, False
            except FutureTimeout:
                raise RequestTimeout(
                    f"attempt to {rid} outlived its {timeout_s:.3f}s budget"
                ) from None
        self._m_hedges.inc()
        f2 = self._pool.submit(self._forward, backup, method, path, body,
                               headers, timeout_s)
        futures = {f1: rid, f2: backup}
        pending = set(futures)
        last_exc: Optional[BaseException] = None
        deadline = self._clock() + timeout_s
        while pending:
            done, pending = futures_wait(
                pending, timeout=max(deadline - self._clock(), 0.01),
                return_when=FIRST_COMPLETED)
            if not done:
                break
            for f in done:
                exc = f.exception()
                if exc is None:
                    return f.result(), futures[f], True
                last_exc = exc
        if last_exc is not None:
            raise last_exc  # both legs failed — let the retry loop judge
        raise RequestTimeout(f"hedged attempt to {rid} timed out")

    # ---- the routed request ------------------------------------------ #

    def route(self, tenant: str, body: bytes,
              deadline_s: Optional[float] = None,
              method: str = "POST", path: str = "/predict",
              trace: Optional[str] = None) -> RouteResult:
        """Forward one request for ``tenant`` through the robustness kit.
        Never raises — every failure mode maps to a status code.

        ``trace`` is the request's cross-process trace id: taken from the
        client's ``X-Fleet-Trace`` header when present, minted here
        otherwise, and sent downstream on every attempt — the join key
        ``tools/trace_report.py --stitch`` reassembles router→replica
        trees on."""
        trace_id = trace or _trace.mint_trace_id()
        t_start = self._clock()
        deadline = t_start + (deadline_s if deadline_s is not None
                              else self.default_deadline_s)
        order = self.order_for(tenant)
        tracer = _trace.get_tracer()
        tr0 = tracer.now() if tracer is not None else 0.0
        children: List[Tuple] = []
        tried: set = set()
        attempts = 0
        hedged_any = False
        result: Optional[RouteResult] = None
        last_failure = "unroutable"
        while attempts <= self.max_retries:
            remaining = deadline - self._clock()
            if remaining <= 0:
                result = self._error_result(
                    504, {"error": f"deadline exceeded after {attempts} "
                          "attempt(s)", "tenant": tenant},
                    outcome="deadline", attempts=attempts)
                break
            rid = self._pick(order, tried)
            if rid is None:
                break  # nobody admitted — graceful 503 below
            tried.add(rid)
            attempts += 1
            timeout_s = min(self.per_try_timeout_s, remaining)
            headers = {"Content-Type": "application/json",
                       DEADLINE_HEADER: f"{remaining:.3f}",
                       ATTEMPT_HEADER: str(attempts - 1),
                       TRACE_HEADER: trace_id}
            a0 = tracer.now() if tracer is not None else 0.0
            try:
                reply, served_by, was_hedged = self._attempt(
                    rid, order, tried, method, path, body, headers,
                    timeout_s, hedge_allowed=attempts == 1)
            except TransportError as e:
                a1 = tracer.now() if tracer is not None else 0.0
                if tracer is not None:
                    children.append(("fleet.attempt", a0, a1,
                                     {"n": attempts - 1, "replica": rid,
                                      "error": type(e).__name__,
                                      "trace": trace_id}))
                reason = ("connect" if isinstance(e, ConnectError)
                          else "timeout" if isinstance(e, RequestTimeout)
                          else "transport")
                last_failure = reason
                self._m_retries.inc(reason=reason)
                if attempts <= self.max_retries:  # another attempt follows
                    delay = min(self.backoff.delay_s(attempts),
                                max(deadline - self._clock(), 0.0))
                    if delay > 0:
                        self._sleep(delay)
                continue
            a1 = tracer.now() if tracer is not None else 0.0
            hedged_any = hedged_any or was_hedged
            if tracer is not None:
                children.append(("fleet.attempt", a0, a1,
                                 {"n": attempts - 1, "replica": served_by,
                                  "status": reply.status,
                                  "hedged": was_hedged,
                                  "trace": trace_id}))
                children.append(("fleet.forward", a0, a1,
                                 {"replica": served_by}))
            if reply.status == 429:
                # a shed is the replica protecting itself: pass the computed
                # Retry-After through and do NOT spend retries on it —
                # honoring the replica's number instead of generic backoff
                hdrs = {"Content-Type": "application/json"}
                ra = reply.retry_after_s()
                if ra is not None:
                    hdrs["Retry-After"] = _format_retry_after(ra)
                result = RouteResult(429, hdrs, reply.body,
                                     replica=served_by, attempts=attempts,
                                     hedged=hedged_any, outcome="shed")
                break
            if reply.status == 504:
                # downstream echo of OUR propagated deadline: retrying
                # with even less budget is futile — answer honestly now
                result = RouteResult(
                    504, {"Content-Type": "application/json"}, reply.body,
                    replica=served_by, attempts=attempts,
                    hedged=hedged_any, outcome="deadline")
                break
            if reply.status >= 500:
                last_failure = "5xx"
                self._m_retries.inc(reason="5xx")
                if attempts <= self.max_retries:  # another attempt follows
                    ra = reply.retry_after_s()
                    delay = (ra if ra is not None
                             else self.backoff.delay_s(attempts))
                    delay = min(delay, max(deadline - self._clock(), 0.0))
                    if delay > 0:
                        self._sleep(delay)
                continue
            # 2xx / 4xx: a definitive answer — return it
            if served_by != order[0]:
                self._m_failovers.inc(tenant=tenant)
            result = RouteResult(
                reply.status,
                {"Content-Type": reply.headers.get(
                    "content-type", "application/json")},
                reply.body, replica=served_by, attempts=attempts,
                hedged=hedged_any,
                outcome="served" if reply.status < 400 else "client_error")
            break
        if result is None:
            ra = self.replica_set.retry_after_hint_s()
            hint = self.replica_set.last_known_healthy(order)
            result = self._error_result(
                503,
                {"error": f"no replica available for tenant {tenant!r} "
                 f"(last failure: {last_failure})",
                 "tenant": tenant,
                 "retry_after_s": round(ra, 3),
                 "last_known_healthy": hint},
                outcome="unroutable", attempts=attempts,
                extra_headers={"Retry-After": _format_retry_after(ra)})
        self._m_requests.inc(outcome=result.outcome)
        wall = self._clock() - t_start
        self._m_latency.observe(wall)
        if tracer is not None:
            tr1 = tracer.now()
            tracer.lane_tree(
                "fleet.route", tr0, tr1,
                {"tenant": tenant, "status": result.status,
                 "attempts": attempts, "outcome": result.outcome,
                 "replica": result.replica, "trace": trace_id},
                children=children)
        return result

    def _error_result(self, status, payload, outcome, attempts,
                      extra_headers=None) -> RouteResult:
        return RouteResult(
            status,
            {"Content-Type": "application/json", **(extra_headers or {})},
            json.dumps(payload).encode(),
            attempts=attempts, outcome=outcome)

    # ---- fleet view ---------------------------------------------------- #

    def health(self) -> Dict[str, Any]:
        states = self.replica_set.stats()
        n_up = sum(1 for s in states.values() if s["state"] == CLOSED)
        return {
            "status": ("ok" if n_up else "degraded"),
            "role": "fleet-router",
            "replicas": states,
            "replicas_closed": n_up,
            "replicas_total": len(states),
        }

    def evaluate_slo(self, scrape: bool = True) -> Dict[str, Any]:
        """The fleet SLO verdict over the federated window, cached for
        ``slo_min_interval_s`` (see the constructor note: concurrent
        pollers must not slice the objectives' windows into slivers).
        ``scrape=False`` skips the federation sweep when the caller just
        ran one."""
        now = self._clock()
        with self._lock:
            cached = self._slo_cache
        if (cached is not None
                and now - cached[0] < self.slo_min_interval_s):
            return cached[1]
        if scrape:
            self.federation.scrape_once()
        doc = self.slo_engine.evaluate()
        with self._lock:
            self._slo_cache = (now, doc)
        return doc

    def fleet_status(self, scrape: bool = True) -> Dict[str, Any]:
        """One structured fleet-status document (served at ``/fleet``;
        ``tools/fleet_status.py`` renders it): breaker states, federation
        health, per-tenant fleet-wide request counts and latency
        percentiles from the **merged** histograms (the rollup series —
        no single replica could answer these), and the SLO verdicts over
        the federated window.  ``scrape=True`` runs one federation sweep
        first so the numbers are current."""
        scrape_info = self.federation.scrape_once() if scrape else None
        slo_doc = self.evaluate_slo(scrape=False)
        fed = self.federation.fleet_registry
        tenants: Dict[str, Any] = {}
        lat = fed.get("svgd_serve_request_latency_seconds")
        req = fed.get("svgd_serve_requests_total")
        if isinstance(lat, _metrics.Histogram):
            for labels in lat.label_sets():
                if "replica" in labels:
                    continue  # per-replica detail stays in /metrics
                name = labels.get("tenant", "") or "(default)"
                s = lat.summary(scale=1e3, **labels)
                tenants[name] = {
                    "requests": s["count"],
                    "p50_ms": s["p50"], "p99_ms": s["p99"],
                }
        if isinstance(req, _metrics.Counter):
            for labels in req.label_sets():
                if "replica" in labels:
                    continue
                name = labels.get("tenant", "") or "(default)"
                tenants.setdefault(name, {})["requests_total"] = (
                    req.value(**labels))
        # fleet-wide cost columns from the federated usage counters
        # (telemetry/usage.py; zero-filled absent — replicas without
        # metering simply contribute nothing)
        usage = _usage.usage_summary(fed)
        for name, row in usage["tenants"].items():
            tenants.setdefault(name, {}).update(
                device_seconds_total=row["device_seconds"],
                usage_rows_total=row["rows"],
            )
        doc = self.health()
        doc.update(
            ts=time.time(),
            federation=self.federation.stats(),
            tenants=tenants,
            slo=slo_doc,
        )
        if scrape_info is not None:
            doc["federation"]["last_sweep"] = scrape_info
        return doc

    # ---- HTTP front door ---------------------------------------------- #

    def _make_handler(self):
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _write(self, status, headers, body):
                self.send_response(status)
                for k, v in headers.items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _write_json(self, status, payload):
                self._write(status, {"Content-Type": "application/json"},
                            json.dumps(payload).encode())

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/healthz":
                    doc = router.health()
                    self._write_json(200 if doc["replicas_closed"] else 503,
                                     doc)
                elif path == "/replicas":
                    self._write_json(200, router.replica_set.stats())
                elif path == "/metrics":
                    # scrape-on-collect federation (the Prometheus
                    # federation convention): one sweep over the live
                    # replicas, then the router's own series plus the
                    # replica-labelled + rollup federated view in ONE
                    # document (names dedup toward the router's)
                    router.federation.scrape_once()
                    self._write(
                        200,
                        {"Content-Type":
                         "text/plain; version=0.0.4; charset=utf-8"},
                        _metrics.combined_exposition(
                            router.registry,
                            router.federation.fleet_registry).encode())
                elif path == "/slo":
                    # the fleet SLO: the same declarative objectives the
                    # replicas evaluate locally, judged over the
                    # FEDERATED window — fleet-wide p99 for the fleet
                    # (verdict cached slo_min_interval_s against
                    # window-slicing by concurrent pollers)
                    self._write_json(200, router.evaluate_slo())
                elif path == "/fleet":
                    self._write_json(200, router.fleet_status())
                elif path == "/usage":
                    # fleet-wide cost-per-tenant: one federation sweep,
                    # then the usage summary over the MERGED registry —
                    # tenants/totals from the rollup series, per-replica
                    # breakdown from the replica-labelled ones
                    router.federation.scrape_once()
                    self._write_json(200, {
                        "metering": True,
                        **_usage.usage_summary(
                            router.federation.fleet_registry)})
                else:
                    self._write_json(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path.split("?", 1)[0] != "/predict":
                    self._write_json(404, {"error": f"no route {self.path}"})
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                try:
                    doc = json.loads(body or b"null")
                    tenant = (doc.get("tenant") or ""
                              if isinstance(doc, dict) else "")
                except ValueError:
                    tenant = ""
                deadline_s = None
                raw = self.headers.get(DEADLINE_HEADER)
                if raw:
                    try:
                        deadline_s = max(float(raw), 0.001)
                    except ValueError:
                        pass
                res = router.route(tenant, body, deadline_s=deadline_s,
                                   trace=self.headers.get(TRACE_HEADER))
                self._write(res.status, res.headers, res.body)

        return Handler

    @property
    def url(self) -> Optional[str]:
        if self._httpd is None:
            return None
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "FleetRouter":
        """Start the probe thread and (when built with ``port=``) the HTTP
        front door."""
        tracer = _trace.get_tracer()
        if tracer is not None:
            # stitchers label this process's export off the tracer's
            # process header; an identity a drill already set wins
            tracer.set_process("router", "router", only_if_default=True)
        self.replica_set.start()
        if self._httpd is not None and self._serve_thread is None:
            self._serve_thread = threading.Thread(
                target=self._httpd.serve_forever, name="fleet-http",
                daemon=True)
            self._serve_thread.start()
        return self

    def shutdown(self) -> None:
        self.replica_set.close()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            if self._serve_thread is not None:
                self._serve_thread.join(timeout=10)
                self._serve_thread = None
        if self._pool is not None:
            self._pool.shutdown(wait=False)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()
