"""SLO-burn-driven adaptive capacity: the serving control plane.

PR 5/6/11 made the serving stack observable — burn rates, queue depths,
latency histograms — but every capacity knob stayed frozen at construction
time, so a burst was *recovered from* (sheds, fat p99, slow drain) instead
of *absorbed*.  This module closes the loop (ROADMAP open item 5): an
:class:`AutoscaleController` watches the SLO burn rate and the queue /
latency windows from the shared :class:`~dist_svgd_tpu.telemetry.metrics.
MetricsRegistry` and retunes the :class:`~dist_svgd_tpu.serving.batcher.
MicroBatcher` live, within bounded hysteresis:

- **lanes** (``MicroBatcher.set_lanes``): more dispatch workers under
  overload — the throughput knob;
- **max_wait_ms** (``MicroBatcher.set_max_wait_ms``): a wider coalescing
  window under overload amortises the per-dispatch floor over bigger
  batches (goodput first when demand exceeds capacity); a tight window in
  steady state keeps the latency floor low (p99 first when capacity is
  spare).  No single static window is right for both regimes — that
  asymmetry is the controller's whole reason to exist;
- **per-tenant quotas** (``ModelRegistry.set_quota``): tightened under
  overload so hog tenants shed *early* — at admission, before their
  queued work turns into everyone's p99 breach — and restored when calm.

Control discipline (the hysteresis the unit tests pin):

- signals come from the controller's OWN windowed accessors
  (``telemetry/slo.py``: a second :class:`~dist_svgd_tpu.telemetry.slo.
  SloEngine` with ``mirror_metrics=False`` plus ``HistogramWindow`` /
  ``CounterWindow``) so its cadence never advances — or double-counts —
  the ``/slo`` endpoint's objective windows;
- **overload** = burn at/over ``burn_up``, any shed in the window, or
  queue depth over ``queue_high_frac`` of the bound (the *before the
  breach* signal: a growing queue predicts the p99 breach the burn rate
  only confirms afterwards);
- **calm** = burn at/under ``burn_down`` AND no sheds AND a near-empty
  queue, sustained for ``down_consecutive`` control steps — scale-down
  is deliberately slower than scale-up (flapping costs more than a few
  seconds of spare capacity);
- every action respects a per-direction ``cooldown_s`` and the bounded
  ranges; knobs never leave ``[min, max]``, and scale-down stops at the
  construction-time baseline by default.

Time is injectable (``clock=``) so every decision path runs tier-1
deterministically; :meth:`AutoscaleController.step` is the whole control
iteration, and :meth:`start` just runs it on a background cadence.  The
HTTP layer serves :meth:`status` at ``/autoscale``.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from dist_svgd_tpu.telemetry import metrics as _metrics
from dist_svgd_tpu.telemetry.slo import (
    CounterWindow,
    HistogramWindow,
    default_serving_slos,
)

__all__ = ["AutoscalePolicy", "AutoscaleController"]


class AutoscalePolicy:
    """Bounds + hysteresis configuration (static; the controller never
    mutates it).

    Args:
        lanes_max / lanes_min: bounded lane range.  ``lanes_min=None``
            (default) pins the floor at the batcher's construction-time
            lane count — scale-down returns to baseline, never below.
        max_wait_ms_max / max_wait_ms_min: bounded coalescing-window
            range; ``None`` floor = the construction-time window.
        p99_target_ms: the latency objective the controller defends (its
            own SLO engine's ``serve_p99`` threshold).
        burn_up: scale up when the p99 burn rate reaches this (1.0 = the
            error budget's edge — acting at the edge, not past it).
        burn_down: a calm window needs burn at/under this.
        queue_high_frac: queued rows over this fraction of
            ``max_queue_rows`` reads as overload even with a quiet burn
            rate — the shed-before-the-breach signal.
        queue_low_frac: a calm window needs the queue at/under this.
        up_consecutive / down_consecutive: control steps a signal must
            persist before acting (scale-down deliberately slower).
        cooldown_s: minimum seconds between actions in the same
            direction.
        quota_tighten_frac: under overload each tenant quota becomes
            ``ceil(base × frac)``; restored when calm.
        floor_slack_ms: the coalescing-window attribution margin.  A wide
            window puts a latency floor of ~``max_wait_ms`` under every
            request; a p99 within ``2·max_wait_ms + floor_slack_ms``
            (window + straggler + device/jitter envelope) is the
            controller's OWN window, not demand — it reads as calm (scale
            the window back down) and never as burn-overload (else a
            window at its bound and a tight target would read every
            second as overload and the controller could never retreat —
            the self-inflicted-burn deadlock the storm bench exposed).
        demand_release_frac: scale-down additionally requires the
            windowed request rate to have dropped to this fraction of
            the rate seen at the last overload — a wide window *serving
            a burst well* has a quiet burn rate, and without this guard
            the controller would un-provision mid-burst and oscillate.
            The tracked overload rate decays 10%/step once overload
            clears, so the guard releases within a few control steps of
            the burst actually ending (holding burst provisioning — and
            tightened admission quotas — against ordinary post-burst
            traffic would throttle the recovery it exists to protect).
    """

    def __init__(self, *, lanes_max: int = 4, lanes_min: Optional[int] = None,
                 max_wait_ms_max: float = 16.0,
                 max_wait_ms_min: Optional[float] = None,
                 p99_target_ms: float = 100.0,
                 burn_up: float = 1.0, burn_down: float = 0.25,
                 queue_high_frac: float = 0.25, queue_low_frac: float = 0.02,
                 up_consecutive: int = 1, down_consecutive: int = 4,
                 cooldown_s: float = 1.0, quota_tighten_frac: float = 0.5,
                 floor_slack_ms: float = 10.0,
                 demand_release_frac: float = 0.6):
        if lanes_max < 1:
            raise ValueError(f"lanes_max must be >= 1, got {lanes_max}")
        if lanes_min is not None and not 1 <= lanes_min <= lanes_max:
            raise ValueError(
                f"lanes_min {lanes_min} not in [1, {lanes_max}]")
        if max_wait_ms_max < 0:
            raise ValueError("max_wait_ms_max must be >= 0")
        if not 0.0 <= burn_down <= burn_up:
            raise ValueError(
                f"need 0 <= burn_down <= burn_up, got {burn_down}/{burn_up}")
        if up_consecutive < 1 or down_consecutive < 1:
            raise ValueError("consecutive thresholds must be >= 1")
        if not 0.0 < quota_tighten_frac <= 1.0:
            raise ValueError(
                f"quota_tighten_frac must be in (0, 1], got "
                f"{quota_tighten_frac}")
        self.lanes_max = int(lanes_max)
        self.lanes_min = None if lanes_min is None else int(lanes_min)
        self.max_wait_ms_max = float(max_wait_ms_max)
        self.max_wait_ms_min = (None if max_wait_ms_min is None
                                else float(max_wait_ms_min))
        self.p99_target_ms = float(p99_target_ms)
        self.burn_up = float(burn_up)
        self.burn_down = float(burn_down)
        self.queue_high_frac = float(queue_high_frac)
        self.queue_low_frac = float(queue_low_frac)
        self.up_consecutive = int(up_consecutive)
        self.down_consecutive = int(down_consecutive)
        self.cooldown_s = float(cooldown_s)
        self.quota_tighten_frac = float(quota_tighten_frac)
        if floor_slack_ms < 0:
            raise ValueError(
                f"floor_slack_ms must be >= 0, got {floor_slack_ms}")
        self.floor_slack_ms = float(floor_slack_ms)
        if not 0.0 < demand_release_frac <= 1.0:
            raise ValueError(
                f"demand_release_frac must be in (0, 1], got "
                f"{demand_release_frac}")
        self.demand_release_frac = float(demand_release_frac)


class AutoscaleController:
    """One control loop over one batcher (and optionally its registry's
    tenant quotas).

    Args:
        batcher: the :class:`~dist_svgd_tpu.serving.batcher.MicroBatcher`
            to actuate (``set_lanes`` / ``set_max_wait_ms`` seams).
        metrics: the ``MetricsRegistry`` the batcher writes into — the
            controller's signal source (default: the batcher's own).
        model_registry: optional :class:`~dist_svgd_tpu.serving.registry.
            ModelRegistry` whose per-tenant quotas are tightened under
            overload and restored when calm (tenants without a quota are
            left alone — no quota means no admission contract to tighten).
        policy: :class:`AutoscalePolicy` bounds + hysteresis.
        clock: injectable monotonic time source (tests drive cooldowns
            deterministically).
    """

    def __init__(self, batcher, *, metrics=None, model_registry=None,
                 policy: Optional[AutoscalePolicy] = None,
                 clock=time.monotonic):
        self.batcher = batcher
        self.model_registry = model_registry
        self.policy = policy if policy is not None else AutoscalePolicy()
        self.metrics = (metrics if metrics is not None
                        else getattr(batcher, "registry", None))
        if self.metrics is None:
            self.metrics = _metrics.default_registry()
        self._clock = clock
        # construction-time baseline: the scale-down floor unless the
        # policy pins explicit minimums
        self.baseline_lanes = int(batcher.lanes)
        self.baseline_max_wait_ms = float(batcher.max_wait_ms)
        self._lanes_min = (self.policy.lanes_min
                          if self.policy.lanes_min is not None
                          else min(self.baseline_lanes, self.policy.lanes_max))
        self._wait_min = (self.policy.max_wait_ms_min
                          if self.policy.max_wait_ms_min is not None
                          else min(self.baseline_max_wait_ms,
                                   self.policy.max_wait_ms_max))
        # the controller's OWN windows (never the /slo endpoint's engine —
        # two pollers on one stateful window would starve each other).
        # aggregate=True: in multi-tenant mode every serving series
        # carries a tenant= label and the unlabelled series never exists,
        # so a single-label-set window would read zero forever — the
        # aggregate mode sums across label sets (the empty set included,
        # so single-tenant batchers read identically)
        self._slo = default_serving_slos(
            self.metrics, p99_ms=self.policy.p99_target_ms,
            mirror_metrics=False, aggregate=True,
            clock=lambda: self._clock())
        self._lat_window = HistogramWindow(
            self.metrics, "svgd_serve_request_latency_seconds",
            aggregate=True)
        self._shed_window = CounterWindow(
            self.metrics, "svgd_serve_shed_total", aggregate=True)
        self._req_window = CounterWindow(
            self.metrics, "svgd_serve_requests_total", aggregate=True)
        self._m_actions = self.metrics.counter(
            "svgd_autoscale_actions_total",
            "autoscale actions by knob and direction")
        self._m_overload = self.metrics.gauge(
            "svgd_autoscale_overload",
            "1 while the controller reads the batcher as overloaded")
        self._m_quota_scale = self.metrics.gauge(
            "svgd_autoscale_quota_scale",
            "current tenant-quota scale (1.0 = base quotas)")
        self._m_quota_scale.set(1.0)
        # prime every window at construction: the first control step must
        # judge the delta since NOW — a controller attached to a
        # long-running registry would otherwise read the registry's whole
        # history as one giant "overload" window and act on stale load
        self._slo.evaluate()
        self._lat_window.poll()
        self._shed_window.poll()
        self._req_window.poll()

        self._lock = threading.Lock()
        self._up_streak = 0
        self._down_streak = 0
        self._last_up = -math.inf
        self._last_down = -math.inf
        self._steps = 0
        self._actions = 0
        # windowed request count seen at the most recent overload (decays
        # ~2%/step) — the demand-release guard's reference level
        self._overload_requests: Optional[float] = None
        self.quota_scale = 1.0
        self._base_quotas: Dict[str, int] = {}
        #: Bounded decision log (newest last) — the ``/autoscale`` body.
        self.log: deque = deque(maxlen=64)
        self._last_signals: Dict[str, Any] = {}

        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------ #
    # signals

    def _read_signals(self) -> Dict[str, Any]:
        doc = self._slo.evaluate()
        burns = self._slo.burn_rates()
        burn = burns.get("serve_p99", 0.0)
        if burn is None:  # unbounded ratio: worst case, never "fine"
            burn = math.inf
        shed = self._shed_window.poll()
        requests = self._req_window.poll()
        lat = self._lat_window.poll(self.policy.p99_target_ms / 1e3)
        depth = self.batcher.queued_rows()
        queue_frac = depth / max(self.batcher.max_queue_rows, 1)
        return {
            "burn": burn,
            "slo_status": doc["status"],
            "shed_delta": shed,
            "request_delta": requests,
            "window_count": lat["count"],
            "window_p99_ms": round(lat["p99_s"] * 1e3, 3),
            "queue_rows": depth,
            "queue_frac": round(queue_frac, 4),
            "lanes": self.batcher.lanes,
            "max_wait_ms": round(self.batcher.max_wait_ms, 3),
        }

    # ------------------------------------------------------------------ #
    # actuation

    def _scale_up(self, now: float, sig: Dict[str, Any]) -> List[str]:
        actions = []
        lanes = self.batcher.lanes
        if lanes < self.policy.lanes_max:
            self.batcher.set_lanes(lanes + 1)
            self._m_actions.inc(knob="lanes", direction="up")
            actions.append(f"lanes {lanes}->{lanes + 1}")
        wait = self.batcher.max_wait_ms
        if wait < self.policy.max_wait_ms_max:
            new = min(max(wait * 2.0, 0.5), self.policy.max_wait_ms_max)
            if new > wait:
                self.batcher.set_max_wait_ms(new)
                self._m_actions.inc(knob="max_wait_ms", direction="up")
                actions.append(f"max_wait_ms {wait:g}->{new:g}")
        if self.model_registry is not None and self.quota_scale > (
                self.policy.quota_tighten_frac):
            self._apply_quota_scale(self.policy.quota_tighten_frac)
            actions.append(f"quota_scale -> {self.quota_scale:g}")
        return actions

    def _scale_down(self, now: float, sig: Dict[str, Any]) -> List[str]:
        actions = []
        lanes = self.batcher.lanes
        if lanes > self._lanes_min:
            self.batcher.set_lanes(lanes - 1)
            self._m_actions.inc(knob="lanes", direction="down")
            actions.append(f"lanes {lanes}->{lanes - 1}")
        wait = self.batcher.max_wait_ms
        if wait > self._wait_min:
            new = max(wait / 2.0, self._wait_min)
            if new < wait:
                self.batcher.set_max_wait_ms(new)
                self._m_actions.inc(knob="max_wait_ms", direction="down")
                actions.append(f"max_wait_ms {wait:g}->{new:g}")
        if self.model_registry is not None and self.quota_scale < 1.0:
            self._apply_quota_scale(1.0)
            actions.append("quota_scale -> 1")
        return actions

    def _apply_quota_scale(self, scale: float) -> None:
        """Retune every quota'd tenant to ``ceil(base × scale)`` (base
        quotas are snapshotted the first time a tenant is tightened, and
        refreshed for tenants added since).  While tightened, the batcher
        runs **admission-enforced** quotas (``set_quota_mode``): a
        flooding tenant is refused before it occupies queue rows every
        other tenant would wait behind — the shed-*before*-the-breach
        mechanism; restoring the base quotas restores the inert-until-
        overflow default."""
        reg = self.model_registry
        for name, base in reg.quota_snapshot().items():
            if base is None:
                continue
            if name not in self._base_quotas:
                self._base_quotas[name] = base
        for name, base in list(self._base_quotas.items()):
            try:
                reg.set_quota(name, max(1, math.ceil(base * scale)))
            except KeyError:
                del self._base_quotas[name]  # tenant removed since
        if hasattr(self.batcher, "set_quota_mode"):
            self.batcher.set_quota_mode(
                "admission" if scale < 1.0 else "overflow")
        self.quota_scale = scale
        self._m_quota_scale.set(scale)
        self._m_actions.inc(knob="quota", direction=(
            "up" if scale >= 1.0 else "down"))

    # ------------------------------------------------------------------ #
    # the control iteration

    def step(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One control iteration: read the windows, update the hysteresis
        streaks, act when a streak crosses its threshold and the cooldown
        allows.  Returns the decision record (also appended to
        :attr:`log`)."""
        with self._lock:
            now = self._clock() if now is None else now
            sig = self._read_signals()
            p = self.policy
            # window-floor attribution: latency within the current
            # coalescing window (+ straggler + device/jitter slack) is the
            # controller's own doing, not demand — it must read as
            # "retreat", never as "overload" (AutoscalePolicy.floor_slack_ms)
            floor_ok = (sig["window_count"] == 0
                        or sig["window_p99_ms"]
                        <= 2.0 * self.batcher.max_wait_ms + p.floor_slack_ms)
            sig["window_floor_ok"] = floor_ok
            overload = ((sig["burn"] >= p.burn_up and not floor_ok)
                        or sig["shed_delta"] > 0
                        or sig["queue_frac"] >= p.queue_high_frac)
            if overload:
                self._overload_requests = max(
                    sig["request_delta"], self._overload_requests or 0.0)
            elif self._overload_requests is not None:
                # forget the burst's reference level within a few seconds
                # of overload ending: the guard exists to stop MID-burst
                # retreat, not to hold burst provisioning (and tightened
                # admission quotas) against post-burst traffic forever
                self._overload_requests *= 0.9
            # demand release: a wide window serving a burst WELL has a
            # quiet burn — only the offered rate falling reads as "the
            # burst is over" (AutoscalePolicy.demand_release_frac).  A
            # STRONG release (rate down to 70% of the release point)
            # reads as quiet on its own: with demand collapsed and the
            # queue empty, elevated window latency is self-inflicted
            # provisioning — retreat is safe, and a wrong retreat just
            # re-triggers scale-up one control step later.
            demand_ok = (self._overload_requests is None
                         or sig["request_delta"]
                         <= p.demand_release_frac * self._overload_requests)
            strong_release = (self._overload_requests is not None
                              and sig["request_delta"]
                              <= 0.7 * p.demand_release_frac
                              * self._overload_requests)
            sig["demand_released"] = demand_ok
            calm = (sig["shed_delta"] == 0
                    and sig["queue_frac"] <= p.queue_low_frac
                    and demand_ok
                    and (sig["burn"] <= p.burn_down or floor_ok
                         or strong_release))
            if overload:
                self._up_streak += 1
                self._down_streak = 0
            elif calm:
                self._down_streak += 1
                self._up_streak = 0
            else:
                # in-between: hold — neither streak advances (a noisy
                # boundary signal must not ratchet either direction)
                self._up_streak = 0
                self._down_streak = 0
            self._m_overload.set(1.0 if overload else 0.0)
            actions: List[str] = []
            if (overload and self._up_streak >= p.up_consecutive
                    and now - self._last_up >= p.cooldown_s):
                actions = self._scale_up(now, sig)
                if actions:
                    self._last_up = now
            elif (calm and self._down_streak >= p.down_consecutive
                    and now - self._last_down >= p.cooldown_s):
                actions = self._scale_down(now, sig)
                if actions:
                    self._last_down = now
            self._steps += 1
            self._actions += len(actions)
            record = {
                "ts": round(now, 3),
                "overload": overload,
                "calm": calm,
                "actions": actions,
                **sig,
            }
            self._last_signals = sig
            if actions or overload:
                self.log.append(record)
            return record

    # ------------------------------------------------------------------ #
    # lifecycle / introspection

    def start(self, interval_s: float = 0.25) -> "AutoscaleController":
        """Run :meth:`step` every ``interval_s`` on a daemon thread."""
        if interval_s <= 0:
            raise ValueError(f"interval_s must be positive, got {interval_s}")
        if self._thread is None:
            self._stop.clear()
            self.interval_s = float(interval_s)

            def loop():
                while not self._stop.is_set():
                    try:
                        self.step()
                    except Exception:  # a control bug must not kill serving
                        pass
                    self._stop.wait(self.interval_s)

            self._thread = threading.Thread(
                target=loop, name="autoscale", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def status(self) -> Dict[str, Any]:
        """The ``/autoscale`` document: live knobs, bounds, streaks, and
        the recent decision log."""
        with self._lock:
            p = self.policy
            return {
                "lanes": self.batcher.lanes,
                "max_wait_ms": round(self.batcher.max_wait_ms, 3),
                "quota_scale": self.quota_scale,
                "baseline": {"lanes": self.baseline_lanes,
                             "max_wait_ms": self.baseline_max_wait_ms},
                "bounds": {"lanes": [self._lanes_min, p.lanes_max],
                           "max_wait_ms": [self._wait_min,
                                           p.max_wait_ms_max]},
                "p99_target_ms": p.p99_target_ms,
                "steps": self._steps,
                "actions": self._actions,
                "up_streak": self._up_streak,
                "down_streak": self._down_streak,
                "last_signals": dict(self._last_signals),
                "recent": list(self.log)[-8:],
            }

    def __enter__(self) -> "AutoscaleController":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
