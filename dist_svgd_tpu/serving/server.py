"""Thin stdlib HTTP front end over the engine + batcher — or over a
multi-tenant :class:`~dist_svgd_tpu.serving.registry.ModelRegistry`.

JSON in/out:

- ``POST /predict``      — ``{"inputs": [[...], ...]}`` → the engine's
  output dict as lists, plus this request's latency split.  Against a
  registry, the body's ``"tenant"`` field routes to that tenant's engine
  (404 for an unknown tenant; omitted, it defaults to the registry's
  single tenant when there is exactly one, else 400);
- ``GET  /healthz``      — liveness + ensemble identity; against a
  registry, the aggregate plus one row per tenant, and
  ``GET /healthz/<tenant>`` the per-tenant detail (engine stats, cache
  counters, loaded step);
- ``GET  /tenants``      — registry mode only: the tenant listing
  (model, shapes, state, quota, watched step);
- ``GET  /metrics``      — **Prometheus text exposition** of the shared
  telemetry registry (request/row/batch/shed counters, queue-depth gauge,
  latency histograms, engine bucket-cache counters — scrape it);
- ``GET  /metrics.json`` — the legacy JSON aggregate (the batcher's
  bounded-window percentiles, the engine's ``stats()``, the server's
  request/error counts) for humans and tests;
- ``GET  /slo``          — the declarative SLO engine's evaluation
  (``telemetry/slo.py``): burn rates for the serve-p99 / shed-rate /
  dispatch-error objectives over the window since the last ``/slo`` poll,
  ``status`` ``ok``/``breach`` at the top;
- ``GET  /usage``        — per-tenant cost accounting
  (``telemetry/usage.py:usage_summary`` over this server's registry:
  device-seconds, rows, queue-seconds, requests, compiles, with
  per-generation breakdowns) — empty tenant map until usage metering is
  enabled (the serving CLI enables it by default);
- ``GET  /autoscale``    — the adaptive-capacity controller's status
  (``serving/autoscale.py``: live lanes / coalescing window / quota
  scale, bounds, streaks, recent decisions); 404 when the server runs
  without a controller.

No framework dependency by design: the container bakes only the jax_graft
toolchain, and the request path is one ``json.loads`` + a batcher future —
``ThreadingHTTPServer`` (one thread per in-flight request, parked on the
future) is exactly the concurrency the micro-batcher wants to coalesce
across.  Graceful drain on shutdown: stop accepting, finish in-flight
handlers, flush the batcher queue.

Structured per-request records go through ``utils/metrics.py:JsonlLogger``
(one line per request: route, rows, status, latency) when a logger is given.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Union

import numpy as np

from dist_svgd_tpu.serving.batcher import MicroBatcher, Overloaded
from dist_svgd_tpu.serving.engine import PredictiveEngine
from dist_svgd_tpu.serving.registry import ModelRegistry
from dist_svgd_tpu.telemetry import metrics as _metrics
from dist_svgd_tpu.telemetry import trace as _trace


class PredictionServer:
    """HTTP serving front end.  ``port=0`` binds an ephemeral port (tests).

    The first argument is either a single :class:`PredictiveEngine`
    (single-tenant, unchanged behavior) or a :class:`ModelRegistry`
    (multi-tenant: the server rides the registry's shared batcher and
    routes ``/predict`` on the body's ``tenant`` field).

    The server owns its batcher unless one is passed in (single-tenant)
    or the registry owns it (multi-tenant); :meth:`shutdown` drains it
    either way (stop accepting → finish in-flight handlers → dispatch
    everything still queued).
    """

    def __init__(
        self,
        engine: Union[PredictiveEngine, ModelRegistry],
        *,
        host: str = "127.0.0.1",
        port: int = 8000,
        max_batch: int = 256,
        lanes: int = 1,
        max_wait_ms: float = 2.0,
        max_queue_rows: int = 8192,
        request_timeout_s: float = 30.0,
        logger=None,
        batcher: Optional[MicroBatcher] = None,
        registry: Optional[_metrics.MetricsRegistry] = None,
        slo=None,
        slo_p99_ms: float = 100.0,
        autoscale=None,
    ):
        if isinstance(engine, ModelRegistry):
            self.model_registry: Optional[ModelRegistry] = engine
            self.engine = None
            if batcher is not None:
                raise ValueError(
                    "a ModelRegistry brings its own shared batcher; "
                    "don't pass batcher="
                )
            # share the registry's metrics sink so /metrics exposes the
            # tenant-labelled series the tenants actually write
            self.registry = (registry if registry is not None
                             else engine.metrics)
            self.batcher = engine.batcher
        else:
            self.model_registry = None
            self.engine = engine
            self.registry = (registry if registry is not None
                             else _metrics.default_registry())
            self.batcher = batcher or MicroBatcher(
                engine.predict,
                max_batch=max_batch,
                lanes=lanes,
                max_wait_ms=max_wait_ms,
                max_queue_rows=max_queue_rows,
                logger=None,  # batch records would interleave with request
                              # records
                registry=self.registry,
            )
        self._logger = logger
        self._request_timeout_s = request_timeout_s
        self._lock = threading.Lock()
        self._requests = 0
        self._errors = 0
        self._draining = False
        self._m_http = self.registry.counter(
            "svgd_http_requests_total", "HTTP requests by route and status")
        self._m_http_latency = self.registry.histogram(
            "svgd_http_request_seconds", "handler wall per /predict request")
        if slo is None:
            from dist_svgd_tpu.telemetry.slo import default_serving_slos

            slo = default_serving_slos(self.registry, p99_ms=slo_p99_ms)
        #: The declarative SLO engine served at ``/slo`` (pass ``slo=`` to
        #: replace the default serve-p99/shed/error objective set).
        self.slo_engine = slo
        #: Optional :class:`~dist_svgd_tpu.serving.autoscale.
        #: AutoscaleController` (round 18).  ``autoscale=True`` builds the
        #: default controller over this server's batcher (+ registry
        #: quotas in multi-tenant mode); a controller instance is used
        #: as-is.  The server starts it with :meth:`start` (unless it
        #: already runs) and stops it on :meth:`shutdown`; its status is
        #: served at ``/autoscale``.
        self.autoscale = None
        if autoscale:
            if autoscale is True:
                from dist_svgd_tpu.serving.autoscale import (
                    AutoscaleController,
                    AutoscalePolicy,
                )

                autoscale = AutoscaleController(
                    self.batcher, metrics=self.registry,
                    model_registry=self.model_registry,
                    policy=AutoscalePolicy(p99_target_ms=slo_p99_ms),
                )
            self.autoscale = autoscale
        self._started = time.time()

        server = self  # close over for the handler class

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # stderr chatter off
                pass

            def _reply(self, code: int, payload: Dict[str, Any],
                       headers: Optional[Dict[str, str]] = None) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reply_text(self, code: int, text: str,
                            content_type: str) -> None:
                body = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/healthz":
                    doc = server.health()
                    # a draining server answers 503 so a fleet router stops
                    # routing here BEFORE the socket disappears
                    self._reply(503 if doc["status"] == "draining" else 200,
                                doc)
                elif path.startswith("/healthz/"):
                    name = path[len("/healthz/"):]
                    detail = server.tenant_health(name)
                    if detail is None:
                        self._reply(404, {"error": f"no tenant {name!r}"})
                    else:
                        self._reply(503 if detail["status"] == "draining"
                                    else 200, detail)
                elif path == "/tenants":
                    if server.model_registry is None:
                        self._reply(404, {"error": "single-tenant server: "
                                          "no /tenants route"})
                    else:
                        self._reply(
                            200,
                            {"tenants":
                             server.model_registry.health()["tenants"]})
                elif path == "/metrics":
                    # Prometheus text format 0.0.4 — what scrapers expect
                    self._reply_text(
                        200, server.registry.exposition(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                elif path == "/metrics.dump":
                    # full-fidelity registry dump (raw histogram bucket
                    # counts) — the fleet federation's scrape format:
                    # exact cross-replica merging needs buckets, which
                    # the Prometheus text above quantises into exposition
                    self._reply(200, server.registry.dump())
                elif path == "/metrics.json":
                    self._reply(200, server.metrics())
                elif path == "/slo":
                    self._reply(200, server.slo_engine.evaluate())
                elif path == "/usage":
                    self._reply(200, server.usage())
                elif path == "/autoscale":
                    if server.autoscale is None:
                        self._reply(404, {"error": "no autoscale "
                                          "controller on this server"})
                    else:
                        self._reply(200, server.autoscale.status())
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path != "/predict":
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                t0 = time.perf_counter()
                # a fleet router propagates its remaining per-request
                # budget downstream — cap our own future-wait with it so a
                # doomed request releases its handler thread on time
                deadline_s = None
                raw = self.headers.get("X-Fleet-Deadline-S")
                if raw:
                    try:
                        deadline_s = max(float(raw), 1e-3)
                    except ValueError:
                        pass
                # the router's trace id: joins this replica's spans to the
                # router's fleet.route tree at stitch time
                trace_id = self.headers.get(_trace.TRACE_HEADER) or None
                with _trace.span("http.predict",
                                 {"trace": trace_id} if trace_id else None):
                    code, payload, rows, tenant, extra = server._predict(
                        self._read_body(), timeout_s=deadline_s,
                        trace=trace_id)
                wall = time.perf_counter() - t0
                payload.setdefault("latency_ms", round(wall * 1e3, 3))
                self._reply(code, payload, extra)
                tl = {} if tenant is None else {"tenant": tenant}
                server._m_http.inc(route="/predict", status=code, **tl)
                server._m_http_latency.observe(wall, **tl)
                if server._logger is not None:
                    server._logger.log(
                        route="/predict",
                        status=code,
                        rows=rows,
                        latency_ms=payload["latency_ms"],
                        **tl,
                    )

            def _read_body(self) -> bytes:
                length = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(length) if length else b""

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        # ThreadingMixIn reads daemon_threads off the SERVER instance (a
        # class attribute on the handler is a no-op): non-daemon handler
        # threads are what makes server_close() join in-flight requests —
        # the drain guarantee shutdown() documents
        self._httpd.daemon_threads = False
        self._serve_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------ #

    @property
    def address(self):
        """``(host, port)`` actually bound (resolves ``port=0``)."""
        return self._httpd.server_address

    @property
    def url(self) -> str:
        host, port = self.address[:2]
        return f"http://{host}:{port}"

    def _predict(self, body: bytes, timeout_s: Optional[float] = None,
                 trace: Optional[str] = None):
        """Returns ``(status_code, payload, rows, tenant, headers)``;
        never raises.  ``timeout_s`` (a router-propagated deadline) caps
        the future wait below the server's own ``request_timeout_s``;
        ``trace`` (the ``X-Fleet-Trace`` header) threads through to the
        batcher's request lane tree."""
        from concurrent.futures import CancelledError
        from concurrent.futures import TimeoutError as FuturesTimeout

        tenant = None
        # phase 1 — parse and validate the request (client errors → 400)
        try:
            doc = json.loads(body or b"null")
            inputs = doc["inputs"] if isinstance(doc, dict) else None
            if inputs is None:
                raise ValueError('body must be {"inputs": [[...], ...]}')
            x = np.asarray(inputs, dtype=np.float32)
            if x.ndim == 1:  # single row shorthand
                x = x[None, :]
            if self.model_registry is not None:
                tenant = doc.get("tenant")
                if tenant is None:
                    names = self.model_registry.tenant_names()
                    if len(names) != 1:
                        raise ValueError(
                            'multi-tenant server: body needs a "tenant" '
                            f"field (hosted: {names})"
                        )
                    tenant = names[0]
            elif isinstance(doc, dict) and doc.get("tenant") is not None:
                raise ValueError(
                    "single-tenant server: drop the \"tenant\" field"
                )
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            with self._lock:
                self._errors += 1
            return 400, {"error": str(e)}, 0, tenant, None
        # phase 2 — submit and resolve (server-side failures are NOT the
        # client's fault: 404 unknown tenant, 429 shed with Retry-After,
        # 503 retryable, 500 bugs)
        try:
            if self.model_registry is not None:
                try:
                    future = self.model_registry.submit(tenant, x,
                                                        trace=trace)
                except KeyError as e:
                    with self._lock:
                        self._errors += 1
                    return 404, {"error": str(e)}, 0, tenant, None
            else:
                future = self.batcher.submit(x, trace=trace)
            wait_s = self._request_timeout_s
            if timeout_s is not None:
                wait_s = min(wait_s, timeout_s)
            out = future.result(timeout=wait_s)
        except Overloaded as e:
            # a shed is load, not failure: 429 (not 503) so callers — the
            # fleet router above all — don't burn retries on it, with the
            # batcher's computed drain estimate as Retry-After
            with self._lock:
                self._errors += 1
            from dist_svgd_tpu.serving.fleet import format_retry_after

            payload = {"error": str(e)}
            headers = None
            ra = getattr(e, "retry_after_s", None)
            if ra:
                payload["retry_after_s"] = round(ra, 3)
                headers = {"Retry-After": format_retry_after(ra)}
            return 429, payload, 0, tenant, headers
        except (KeyError, CancelledError) as e:
            # the tenant was removed (or the batcher cancelled) while the
            # request was queued: retryable server-side condition, not a
            # malformed request
            with self._lock:
                self._errors += 1
            return 503, {"error": f"request dropped: {e}"}, 0, tenant, None
        except ValueError as e:
            # the engine rejected the batch (e.g. feature-width mismatch
            # discovered at dispatch) — the request itself was bad
            with self._lock:
                self._errors += 1
            return 400, {"error": str(e)}, 0, tenant, None
        except FuturesTimeout:
            # the wait budget (usually a router-propagated deadline) ran
            # out: the CALLER's condition, not a replica fault — 504, so a
            # fleet router doesn't score it into ejecting a healthy
            # replica the way a 500 would
            with self._lock:
                self._errors += 1
            return 504, {"error": f"deadline exceeded after {wait_s:.3f}s "
                         "waiting for the batch"}, 0, tenant, None
        except Exception as e:  # dispatch failure
            with self._lock:
                self._errors += 1
            return 500, {"error": f"{type(e).__name__}: {e}"}, 0, tenant, None
        with self._lock:
            self._requests += 1
        payload = {"outputs": {k: v.tolist() for k, v in out.items()}}
        if tenant is not None:
            payload["tenant"] = tenant
        return 200, payload, x.shape[0], tenant, None

    def health(self) -> Dict[str, Any]:
        with self._lock:
            draining = self._draining
        if self.model_registry is not None:
            doc = self.model_registry.health()
            doc.update(lanes=self.batcher.lanes,
                       uptime_s=round(time.time() - self._started, 1))
            if draining:
                doc["status"] = "draining"
            return doc
        st = self.engine.stats()
        return {
            "status": "draining" if draining else "ok",
            "model": st["model"],
            "n_particles": st["n_particles"],
            "feature_dim": st["feature_dim"],
            "devices": st["plan"]["num_shards"],
            "lanes": self.batcher.lanes,
            # generation identity (round 21): which posterior generation
            # answers this replica's traffic — the fleet router's /fleet
            # doc and tools/fleet_status.py surface it per replica so a
            # mid-rollout fleet is inspectable at a glance
            "generation_id": st["generation_id"],
            "previous_generation_id": st["previous_generation_id"],
            "uptime_s": round(time.time() - self._started, 1),
        }

    def tenant_health(self, name: str) -> Optional[Dict[str, Any]]:
        """Per-tenant ``/healthz/<name>`` detail (None when unknown or on
        a single-tenant server — the route 404s)."""
        if self.model_registry is None:
            return None
        try:
            stats = self.model_registry.stats()["tenants"][name]
        except KeyError:
            return None
        with self._lock:
            draining = self._draining
        return {"status": "draining" if draining else "ok",
                "tenant": name, **stats}

    def metrics(self) -> Dict[str, Any]:
        with self._lock:
            server_side = {"http_requests": self._requests, "http_errors": self._errors}
        if self.model_registry is not None:
            return {**server_side, "registry": self.model_registry.stats()}
        return {**server_side, "batcher": self.batcher.stats(),
                "engine": self.engine.stats()}

    def usage(self) -> Dict[str, Any]:
        """The ``/usage`` document: per-tenant cost accounting.  Reads
        the active meter's registry when metering is enabled (the CLI
        enables it on this server's registry, making them the same);
        otherwise this server's registry, whose empty ``svgd_usage_*``
        series yield an empty tenant map."""
        from dist_svgd_tpu.telemetry import usage as _usage

        meter = _usage.get_meter()
        reg = meter.registry if meter is not None else self.registry
        return {"metering": meter is not None,
                **_usage.usage_summary(reg)}

    # ------------------------------------------------------------------ #

    def start(self) -> "PredictionServer":
        """Serve in a background thread (returns self for chaining)."""
        tracer = _trace.get_tracer()
        if tracer is not None:
            # best-effort self-labelling for trace stitching: a drill/CLI
            # that already declared an identity wins (only_if_default)
            host, port = self.address[:2]
            tracer.set_process("replica", f"{host}:{port}",
                               only_if_default=True)
        if self._serve_thread is None:
            self._serve_thread = threading.Thread(
                target=self._httpd.serve_forever, name="http-serve", daemon=True
            )
            self._serve_thread.start()
        if self.autoscale is not None and self.autoscale._thread is None:
            self.autoscale.start()
        return self

    def serve_forever(self) -> None:
        """Blocking serve (the CLI path); KeyboardInterrupt drains."""
        try:
            self._httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.shutdown()

    def begin_drain(self) -> None:
        """Flip ``/healthz`` to 503 ``"draining"`` without closing anything
        — the drain *signal*, separable from the drain itself so a fleet
        router (probing health) stops routing here before the socket
        disappears."""
        with self._lock:
            self._draining = True

    def shutdown(self) -> None:
        """Graceful drain: advertise draining on ``/healthz`` FIRST (a
        router must see the 503 while the socket still answers — ordering
        pinned by test), then stop accepting, finish in-flight handlers,
        flush the batcher queue (and, in registry mode, stop the
        checkpoint scanner and close the registry)."""
        self.begin_drain()
        if self.autoscale is not None:
            # stop retuning first: a controller acting on a draining
            # batcher would race the close below
            self.autoscale.stop()
        self._httpd.shutdown()
        self._httpd.server_close()  # joins non-daemon handler threads
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=10)
            self._serve_thread = None
        if self.model_registry is not None:
            self.model_registry.close(drain=True)
        else:
            self.batcher.close(drain=True)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()


def main(argv=None):
    """``python -m dist_svgd_tpu.serving.server --checkpoint <dir> ...``"""
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--checkpoint", action="append", default=None,
                    help="checkpoint dir, CheckpointManager root, or repeat "
                         "the flag with every per-process path of one "
                         "multi-host save (single-tenant mode)")
    ap.add_argument("--tenants-config", default=None, metavar="PATH",
                    help="multi-tenant mode: JSON list of tenant specs "
                         '[{"name": ..., "model": ..., "checkpoint": ..., '
                         '"quota_rows": ..., "watch": true, ...}]; extra '
                         "keys go to the tenant's engine. Mutually "
                         "exclusive with --checkpoint")
    ap.add_argument("--max-total-buckets", type=int, default=64,
                    help="multi-tenant mode: process-wide LRU bound on "
                         "compiled kernel buckets across tenants")
    ap.add_argument("--scan-interval-s", type=float, default=5.0,
                    help="multi-tenant mode: shared checkpoint-scanner "
                         "cadence over the watched tenant roots")
    ap.add_argument("--model", choices=("logreg", "bnn", "gmm"), default="logreg")
    ap.add_argument("--n-features", type=int, default=None,
                    help="BNN input width (required for --model bnn)")
    ap.add_argument("--n-hidden", type=int, default=50)
    ap.add_argument("--kde-bandwidth", type=float, default=1.0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--max-batch", type=int, default=256)
    ap.add_argument("--lanes", type=int, default=1,
                    help="batcher dispatch worker lanes over the shared "
                         "queue (N frontend lanes, one engine)")
    ap.add_argument("--shards", type=int, default=1,
                    help="shard the served ensemble across this many "
                         "devices (0 = every visible device; 1 = "
                         "single-device). Falls back gracefully when the "
                         "host has fewer devices")
    ap.add_argument("--dtype", choices=("float32", "bfloat16"),
                    default=None,
                    help="opt-in low-precision serve kernels (the "
                         "ensemble is stored+computed in this dtype; "
                         "request/response stay f32)")
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--max-queue-rows", type=int, default=8192)
    ap.add_argument("--autoscale", action="store_true",
                    help="run the SLO-burn-driven capacity controller "
                         "(serving/autoscale.py): retunes batcher lanes, "
                         "the coalescing window, and tenant quotas live; "
                         "status at /autoscale")
    ap.add_argument("--autoscale-lanes-max", type=int, default=4)
    ap.add_argument("--autoscale-wait-max-ms", type=float, default=16.0)
    ap.add_argument("--autoscale-p99-ms", type=float, default=100.0,
                    help="the latency objective the controller defends")
    ap.add_argument("--autoscale-interval-s", type=float, default=0.25)
    ap.add_argument("--request-log", default=None,
                    help="JSONL per-request record path (utils/metrics.py)")
    ap.add_argument("--trace-export", default=None, metavar="PATH",
                    help="enable the span tracer for this replica's "
                         "lifetime and export a Chrome trace here on "
                         "shutdown (the replica-side half of a fleet "
                         "stitch — tools/trace_report.py --stitch)")
    ap.add_argument("--replica-name", default=None,
                    help="process-identity name stamped into trace "
                         "exports (default host:port)")
    ap.add_argument("--warmup", default=True,
                    action=argparse.BooleanOptionalAction,
                    help="pre-trace every padding bucket up to max-batch "
                         "before binding the port")
    ap.add_argument("--usage-metering", default=True,
                    action=argparse.BooleanOptionalAction,
                    help="per-tenant cost accounting (telemetry/usage.py) "
                         "on this replica's registry: /usage locally, "
                         "federated svgd_usage_* series fleet-wide")
    args = ap.parse_args(argv)

    from dist_svgd_tpu.utils.metrics import JsonlLogger

    from dist_svgd_tpu.parallel.plan import make_plan

    if (args.checkpoint is None) == (args.tenants_config is None):
        ap.error("pass exactly one of --checkpoint or --tenants-config")
    logger = JsonlLogger(path=args.request_log) if args.request_log else None
    if args.tenants_config:
        with open(args.tenants_config) as fh:
            specs = json.load(fh)
        reg = ModelRegistry(
            max_total_buckets=args.max_total_buckets,
            max_batch=args.max_batch, lanes=args.lanes,
            max_wait_ms=args.max_wait_ms,
            max_queue_rows=args.max_queue_rows,
            scan_interval_s=args.scan_interval_s,
        )
        for spec in specs:
            spec = dict(spec)
            reg.add_tenant(spec.pop("name"), spec.pop("model"), **spec)
        if args.warmup:
            warmed = reg.warm()
            print(json.dumps({"warmup_buckets": warmed}), flush=True)
        reg.start_scanner()
        srv = PredictionServer(reg, host=args.host, port=args.port,
                               logger=logger)
    else:
        source = (args.checkpoint[0] if len(args.checkpoint) == 1
                  else args.checkpoint)
        plan = make_plan(args.shards if args.shards else None)
        engine = PredictiveEngine.from_checkpoint(
            source, args.model, n_features=args.n_features,
            n_hidden=args.n_hidden, kde_bandwidth=args.kde_bandwidth,
            max_bucket=args.max_batch, plan=plan, dtype=args.dtype,
        )
        if args.warmup:
            compiled = engine.warmup()
            print(json.dumps({"warmup_buckets": compiled}), flush=True)
        srv = PredictionServer(
            engine, host=args.host, port=args.port, max_batch=args.max_batch,
            lanes=args.lanes, max_wait_ms=args.max_wait_ms,
            max_queue_rows=args.max_queue_rows, logger=logger,
        )
    if args.usage_metering:
        from dist_svgd_tpu.telemetry import usage as _usage_mod

        # meter the server's own registry so /metrics.dump carries the
        # svgd_usage_* series and the fleet federation picks them up
        _usage_mod.enable_usage(registry=srv.registry)
    if args.trace_export:
        from dist_svgd_tpu import telemetry

        tracer = telemetry.enable()
        tracer.set_process(
            "replica",
            args.replica_name or f"{args.host}:{args.port}")
    if args.autoscale:
        from dist_svgd_tpu.serving.autoscale import (
            AutoscaleController,
            AutoscalePolicy,
        )

        srv.autoscale = AutoscaleController(
            srv.batcher, metrics=srv.registry,
            model_registry=srv.model_registry,
            policy=AutoscalePolicy(
                lanes_max=args.autoscale_lanes_max,
                max_wait_ms_max=args.autoscale_wait_max_ms,
                p99_target_ms=args.autoscale_p99_ms,
            ),
        ).start(args.autoscale_interval_s)
    print(json.dumps({"serving": srv.url, **srv.health()}), flush=True)
    try:
        srv.serve_forever()
    finally:
        if args.trace_export:
            tracer = telemetry.disable()
            if tracer is not None:
                tracer.export_chrome(args.trace_export)


if __name__ == "__main__":
    main()
