"""Thin stdlib HTTP front end over the engine + batcher.

JSON in/out, five routes:

- ``POST /predict``      — ``{"inputs": [[...], ...]}`` → the engine's
  output dict as lists, plus this request's latency split;
- ``GET  /healthz``      — liveness + ensemble identity;
- ``GET  /metrics``      — **Prometheus text exposition** of the shared
  telemetry registry (request/row/batch/shed counters, queue-depth gauge,
  latency histograms, engine bucket-cache counters — scrape it);
- ``GET  /metrics.json`` — the legacy JSON aggregate (the batcher's
  bounded-window percentiles, the engine's ``stats()``, the server's
  request/error counts) for humans and tests;
- ``GET  /slo``          — the declarative SLO engine's evaluation
  (``telemetry/slo.py``): burn rates for the serve-p99 / shed-rate /
  dispatch-error objectives over the window since the last ``/slo`` poll,
  ``status`` ``ok``/``breach`` at the top.

No framework dependency by design: the container bakes only the jax_graft
toolchain, and the request path is one ``json.loads`` + a batcher future —
``ThreadingHTTPServer`` (one thread per in-flight request, parked on the
future) is exactly the concurrency the micro-batcher wants to coalesce
across.  Graceful drain on shutdown: stop accepting, finish in-flight
handlers, flush the batcher queue.

Structured per-request records go through ``utils/metrics.py:JsonlLogger``
(one line per request: route, rows, status, latency) when a logger is given.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np

from dist_svgd_tpu.serving.batcher import MicroBatcher, Overloaded
from dist_svgd_tpu.serving.engine import PredictiveEngine
from dist_svgd_tpu.telemetry import metrics as _metrics
from dist_svgd_tpu.telemetry import trace as _trace


class PredictionServer:
    """HTTP serving front end.  ``port=0`` binds an ephemeral port (tests).

    The server owns its batcher unless one is passed in; :meth:`shutdown`
    drains it either way (stop accepting → finish in-flight handlers →
    dispatch everything still queued).
    """

    def __init__(
        self,
        engine: PredictiveEngine,
        *,
        host: str = "127.0.0.1",
        port: int = 8000,
        max_batch: int = 256,
        lanes: int = 1,
        max_wait_ms: float = 2.0,
        max_queue_rows: int = 8192,
        request_timeout_s: float = 30.0,
        logger=None,
        batcher: Optional[MicroBatcher] = None,
        registry: Optional[_metrics.MetricsRegistry] = None,
        slo=None,
        slo_p99_ms: float = 100.0,
    ):
        self.engine = engine
        self.registry = (registry if registry is not None
                         else _metrics.default_registry())
        self.batcher = batcher or MicroBatcher(
            engine.predict,
            max_batch=max_batch,
            lanes=lanes,
            max_wait_ms=max_wait_ms,
            max_queue_rows=max_queue_rows,
            logger=None,  # batch records would interleave with request records
            registry=self.registry,
        )
        self._logger = logger
        self._request_timeout_s = request_timeout_s
        self._lock = threading.Lock()
        self._requests = 0
        self._errors = 0
        self._m_http = self.registry.counter(
            "svgd_http_requests_total", "HTTP requests by route and status")
        self._m_http_latency = self.registry.histogram(
            "svgd_http_request_seconds", "handler wall per /predict request")
        if slo is None:
            from dist_svgd_tpu.telemetry.slo import default_serving_slos

            slo = default_serving_slos(self.registry, p99_ms=slo_p99_ms)
        #: The declarative SLO engine served at ``/slo`` (pass ``slo=`` to
        #: replace the default serve-p99/shed/error objective set).
        self.slo_engine = slo
        self._started = time.time()

        server = self  # close over for the handler class

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # join in-flight handler threads on server_close — the drain
            # guarantee (ThreadingHTTPServer defaults them to daemons)
            daemon_threads = False

            def log_message(self, fmt, *args):  # stderr chatter off
                pass

            def _reply(self, code: int, payload: Dict[str, Any]) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reply_text(self, code: int, text: str,
                            content_type: str) -> None:
                body = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._reply(200, server.health())
                elif self.path == "/metrics":
                    # Prometheus text format 0.0.4 — what scrapers expect
                    self._reply_text(
                        200, server.registry.exposition(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                elif self.path == "/metrics.json":
                    self._reply(200, server.metrics())
                elif self.path == "/slo":
                    self._reply(200, server.slo_engine.evaluate())
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path != "/predict":
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                t0 = time.perf_counter()
                with _trace.span("http.predict"):
                    code, payload, rows = server._predict(self._read_body())
                wall = time.perf_counter() - t0
                payload.setdefault("latency_ms", round(wall * 1e3, 3))
                self._reply(code, payload)
                server._m_http.inc(route="/predict", status=code)
                server._m_http_latency.observe(wall)
                if server._logger is not None:
                    server._logger.log(
                        route="/predict",
                        status=code,
                        rows=rows,
                        latency_ms=payload["latency_ms"],
                    )

            def _read_body(self) -> bytes:
                length = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(length) if length else b""

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._serve_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------ #

    @property
    def address(self):
        """``(host, port)`` actually bound (resolves ``port=0``)."""
        return self._httpd.server_address

    @property
    def url(self) -> str:
        host, port = self.address[:2]
        return f"http://{host}:{port}"

    def _predict(self, body: bytes):
        """Returns ``(status_code, payload, rows)``; never raises."""
        try:
            doc = json.loads(body or b"null")
            inputs = doc["inputs"] if isinstance(doc, dict) else None
            if inputs is None:
                raise ValueError('body must be {"inputs": [[...], ...]}')
            x = np.asarray(inputs, dtype=np.float32)
            if x.ndim == 1:  # single row shorthand
                x = x[None, :]
            future = self.batcher.submit(x)
            out = future.result(timeout=self._request_timeout_s)
        except Overloaded as e:
            with self._lock:
                self._errors += 1
            return 503, {"error": str(e)}, 0
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            with self._lock:
                self._errors += 1
            return 400, {"error": str(e)}, 0
        except Exception as e:  # dispatch failure / timeout
            with self._lock:
                self._errors += 1
            return 500, {"error": f"{type(e).__name__}: {e}"}, 0
        with self._lock:
            self._requests += 1
        return 200, {"outputs": {k: v.tolist() for k, v in out.items()}}, x.shape[0]

    def health(self) -> Dict[str, Any]:
        st = self.engine.stats()
        return {
            "status": "ok",
            "model": st["model"],
            "n_particles": st["n_particles"],
            "feature_dim": st["feature_dim"],
            "devices": st["plan"]["num_shards"],
            "lanes": self.batcher.lanes,
            "uptime_s": round(time.time() - self._started, 1),
        }

    def metrics(self) -> Dict[str, Any]:
        with self._lock:
            server_side = {"http_requests": self._requests, "http_errors": self._errors}
        return {**server_side, "batcher": self.batcher.stats(),
                "engine": self.engine.stats()}

    # ------------------------------------------------------------------ #

    def start(self) -> "PredictionServer":
        """Serve in a background thread (returns self for chaining)."""
        if self._serve_thread is None:
            self._serve_thread = threading.Thread(
                target=self._httpd.serve_forever, name="http-serve", daemon=True
            )
            self._serve_thread.start()
        return self

    def serve_forever(self) -> None:
        """Blocking serve (the CLI path); KeyboardInterrupt drains."""
        try:
            self._httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.shutdown()

    def shutdown(self) -> None:
        """Graceful drain: stop accepting, finish in-flight handlers, flush
        the batcher queue."""
        self._httpd.shutdown()
        self._httpd.server_close()  # joins non-daemon handler threads
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=10)
            self._serve_thread = None
        self.batcher.close(drain=True)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()


def main(argv=None):
    """``python -m dist_svgd_tpu.serving.server --checkpoint <dir> ...``"""
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--checkpoint", action="append", required=True,
                    help="checkpoint dir, CheckpointManager root, or repeat "
                         "the flag with every per-process path of one "
                         "multi-host save")
    ap.add_argument("--model", choices=("logreg", "bnn", "gmm"), default="logreg")
    ap.add_argument("--n-features", type=int, default=None,
                    help="BNN input width (required for --model bnn)")
    ap.add_argument("--n-hidden", type=int, default=50)
    ap.add_argument("--kde-bandwidth", type=float, default=1.0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--max-batch", type=int, default=256)
    ap.add_argument("--lanes", type=int, default=1,
                    help="batcher dispatch worker lanes over the shared "
                         "queue (N frontend lanes, one engine)")
    ap.add_argument("--shards", type=int, default=1,
                    help="shard the served ensemble across this many "
                         "devices (0 = every visible device; 1 = "
                         "single-device). Falls back gracefully when the "
                         "host has fewer devices")
    ap.add_argument("--dtype", choices=("float32", "bfloat16"),
                    default=None,
                    help="opt-in low-precision serve kernels (the "
                         "ensemble is stored+computed in this dtype; "
                         "request/response stay f32)")
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--max-queue-rows", type=int, default=8192)
    ap.add_argument("--request-log", default=None,
                    help="JSONL per-request record path (utils/metrics.py)")
    ap.add_argument("--warmup", default=True,
                    action=argparse.BooleanOptionalAction,
                    help="pre-trace every padding bucket up to max-batch "
                         "before binding the port")
    args = ap.parse_args(argv)

    from dist_svgd_tpu.utils.metrics import JsonlLogger

    from dist_svgd_tpu.parallel.plan import make_plan

    source = args.checkpoint[0] if len(args.checkpoint) == 1 else args.checkpoint
    plan = make_plan(args.shards if args.shards else None)
    engine = PredictiveEngine.from_checkpoint(
        source, args.model, n_features=args.n_features, n_hidden=args.n_hidden,
        kde_bandwidth=args.kde_bandwidth, max_bucket=args.max_batch,
        plan=plan, dtype=args.dtype,
    )
    if args.warmup:
        compiled = engine.warmup()
        print(json.dumps({"warmup_buckets": compiled}), flush=True)
    logger = JsonlLogger(path=args.request_log) if args.request_log else None
    srv = PredictionServer(
        engine, host=args.host, port=args.port, max_batch=args.max_batch,
        lanes=args.lanes, max_wait_ms=args.max_wait_ms,
        max_queue_rows=args.max_queue_rows, logger=logger,
    )
    print(json.dumps({"serving": srv.url, **srv.health()}), flush=True)
    srv.serve_forever()


if __name__ == "__main__":
    main()
