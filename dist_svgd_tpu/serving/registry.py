"""Multi-tenant model registry: many posteriors served from one process.

"Heavy traffic from millions of users" means many models behind one
server, not one (ROADMAP open item 3): a fleet of small posteriors —
per-segment logreg heads, per-sensor BNNs, per-region GMM densities —
each trained and checkpointed independently, all needing the same serving
machinery.  Running one process per model wastes a device per tenant and
N× the compile cache; this registry hosts heterogeneous checkpoints
(logreg / BNN / GMM, different shapes, steps, dtypes, plans) as named
**tenants** behind one process:

- each tenant wraps its own :class:`~dist_svgd_tpu.serving.engine.
  PredictiveEngine` (own model kind, ensemble, bucket range, sharding
  plan, reload policy) plus an optional hot-reload watch over its own
  checkpoint root;
- ONE :class:`~dist_svgd_tpu.serving.batcher.MicroBatcher` fronts all of
  them — one bounded queue, per-tenant coalescing, per-tenant quotas with
  shed priorities (a hog tenant sheds before polite ones when the queue
  fills);
- ONE scanner thread polls every tenant's checkpoint root in turn
  (:meth:`ModelRegistry.poll_once`) instead of N polling threads — a
  corrupt newest step or a health-rejected generation in one tenant
  leaves every other tenant serving (isolation pinned in
  tests/test_registry.py);
- ONE process-wide :class:`KernelBucketLRU` bounds the compiled kernel
  buckets across all tenants: every bucket use is touched, overflow
  evicts the least-recently-used bucket anywhere in the process
  (`svgd_registry_evictions_total{tenant=...}`), so a cold tenant's
  compile cache is reclaimable while a hot tenant — touched every request
  — never loses a bucket to steady-state traffic (regression-pinned under
  the retrace sentry).

Every serving metric the tenants write carries a ``tenant=`` label (the
label-aware ``MetricsRegistry`` was built for exactly this; its
cardinality guard caps a tenant-label leak).  The HTTP front end routes
``/predict`` on a ``tenant`` field and serves ``/tenants`` +
per-tenant ``/healthz`` detail (``serving/server.py``); the load
generator is ``tools/serve_bench.py --tenants N`` (the
``serve_multitenant`` row, gated by ``tools/perf_regress.py``).
"""

from __future__ import annotations

import re
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from dist_svgd_tpu.serving.batcher import MicroBatcher
from dist_svgd_tpu.serving.engine import (
    CheckpointHotReloader,
    PredictiveEngine,
)
from dist_svgd_tpu.telemetry import metrics as _metrics

__all__ = ["KernelBucketLRU", "ModelRegistry", "Tenant"]

#: Tenant names become Prometheus label values and URL path segments —
#: keep them to a sane charset.
_TENANT_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.\-]{0,63}$")

#: Default process-wide bound on compiled kernel buckets across tenants.
#: Generous for real fleets (a tenant serving ``rows ≤ max_batch`` traffic
#: touches a handful of buckets); the bench pins it tight to observe
#: eviction deterministically.
DEFAULT_MAX_TOTAL_BUCKETS = 64


class KernelBucketLRU:
    """Process-wide LRU over compiled kernel buckets across engines.

    Engines report every bucket use via :meth:`touch`; when the total
    tracked buckets exceed ``max_buckets``, the least-recently-used
    ``(engine, bucket)`` entry anywhere in the process is evicted — the
    owning engine drops its compiled kernel
    (:meth:`~dist_svgd_tpu.serving.engine.PredictiveEngine.
    _evict_bucket`) and the next request on that bucket recompiles.  A
    hot bucket is touched on every request and is therefore never the
    LRU victim: eviction only ever costs a tenant that stopped using the
    bucket (the regression test drives a hot tenant under the retrace
    sentry while cold tenants churn evictions around it).

    Lock order is strictly ``cache lock → engine lock`` (touch is called
    by engines OUTSIDE their own lock; the eviction callback takes the
    victim engine's lock after this cache's lock is released), so two
    tenants evicting each other cannot deadlock.
    """

    def __init__(self, max_buckets: int = DEFAULT_MAX_TOTAL_BUCKETS):
        if max_buckets < 1:
            raise ValueError(f"max_buckets must be >= 1, got {max_buckets}")
        self.max_buckets = int(max_buckets)
        self._lock = threading.Lock()
        # (id(engine), bucket) -> engine, in least-recently-used-first order
        self._entries: "OrderedDict[Tuple[int, int], Any]" = OrderedDict()
        self._evictions = 0

    def touch(self, engine, bucket: int) -> None:
        """Record one use of ``(engine, bucket)``; evict LRU overflow.

        Touches are reported after the engine's own lock is released, so
        a use and its touch are not one atomic step: a concurrent
        overflow in that sub-microsecond window can evict a bucket whose
        touch is still in flight (the in-flight call keeps its compiled
        fn reference — correctness is unaffected; the next call
        recompiles once).  Irrelevant in steady state — overflow only
        happens when a NEW bucket compiles, which warmed traffic never
        does — and only entries whose engine actually dropped a kernel
        count as evictions, so a late touch re-inserting an
        already-evicted key can never inflate the counter."""
        victims = []
        with self._lock:
            key = (id(engine), bucket)
            if key in self._entries:
                self._entries.move_to_end(key)
            else:
                self._entries[key] = engine
            while len(self._entries) > self.max_buckets:
                (_, victim_bucket), victim = self._entries.popitem(last=False)
                victims.append((victim, victim_bucket))
        # the callback takes the victim engine's lock — outside ours
        evicted = 0
        for victim, victim_bucket in victims:
            if victim._evict_bucket(victim_bucket):
                evicted += 1
        if evicted:
            with self._lock:
                self._evictions += evicted

    def forget(self, engine) -> int:
        """Drop every entry of ``engine`` without counting evictions —
        tenant removal, not cache pressure.  Returns entries dropped."""
        with self._lock:
            keys = [k for k in self._entries if k[0] == id(engine)]
            for k in keys:
                del self._entries[k]
            return len(keys)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"size": len(self._entries),
                    "max_buckets": self.max_buckets,
                    "evictions": self._evictions}


class Tenant:
    """One named model behind the registry: engine + optional reload watch.

    Constructed by :meth:`ModelRegistry.add_tenant`; treat as read-only.
    ``state`` is ``'serving'`` → ``'draining'`` → removed (a draining
    tenant refuses new submits while its queued work flushes).
    """

    def __init__(self, name: str, engine: PredictiveEngine,
                 reloader: Optional[CheckpointHotReloader],
                 quota_rows: Optional[int]):
        self.name = name
        self.engine = engine
        self.reloader = reloader
        self.quota_rows = quota_rows
        self.state = "serving"
        self.added_at = time.time()
        self.reload_errors = 0

    def summary(self) -> Dict[str, Any]:
        """The ``/tenants`` listing row (cheap: no engine lock churn
        beyond one ``stats()`` snapshot)."""
        st = self.engine.stats()
        return {
            "model": st["model"],
            "n_particles": st["n_particles"],
            "feature_dim": st["feature_dim"],
            "dtype": st["dtype"],
            "state": self.state,
            "quota_rows": self.quota_rows,
            "watched": self.reloader is not None,
            "loaded_step": (self.reloader.loaded_step
                            if self.reloader is not None
                            else self.engine.checkpoint_step),
            # generation identity (round 21): which generation answers
            # this tenant's traffic, and whether a rollback target /
            # rollout candidate is resident
            "generation_id": st["generation_id"],
            "previous_generation_id": st["previous_generation_id"],
            "candidate_generation_id": st["candidate_generation_id"],
        }


class ModelRegistry:
    """Host many named posteriors behind one batcher, scanner, and LRU.

    Args:
        metrics: ``telemetry.MetricsRegistry`` every component writes to
            (default: the process-wide one).  All serving series carry a
            ``tenant=`` label.
        max_total_buckets: process-wide bound on compiled kernel buckets
            across tenants (:class:`KernelBucketLRU`), or an existing
            ``KernelBucketLRU`` to share.
        max_batch / lanes / max_wait_ms / max_queue_rows: the shared
            :class:`~dist_svgd_tpu.serving.batcher.MicroBatcher`'s knobs
            (one bounded queue for ALL tenants).
        scan_interval_s: background scanner cadence over the tenant
            checkpoint roots (:meth:`start_scanner`; :meth:`poll_once`
            drives it explicitly for tests/drivers).
        batcher_autostart: pass ``False`` to leave the batcher's lanes
            unstarted (deterministic queue-pressure tests and the bench's
            quota probe); call ``registry.batcher.start()`` when ready.
        logger: optional ``JsonlLogger`` shared by the tenant reloaders
            (one record per swap/reject).
    """

    def __init__(
        self,
        *,
        metrics: Optional[_metrics.MetricsRegistry] = None,
        max_total_buckets: Union[int, KernelBucketLRU] = (
            DEFAULT_MAX_TOTAL_BUCKETS),
        max_batch: int = 256,
        lanes: int = 1,
        max_wait_ms: float = 2.0,
        max_queue_rows: int = 8192,
        scan_interval_s: float = 5.0,
        batcher_autostart: bool = True,
        logger=None,
    ):
        self.metrics = (metrics if metrics is not None
                        else _metrics.default_registry())
        self.kernel_cache = (max_total_buckets
                             if isinstance(max_total_buckets, KernelBucketLRU)
                             else KernelBucketLRU(max_total_buckets))
        self._lock = threading.Lock()
        self._tenants: Dict[str, Tenant] = {}
        # live quota view the batcher reads under ITS lock on overflow;
        # mutated only via dict item ops (atomic under the GIL)
        self._quotas: Dict[str, Optional[int]] = {}
        self._logger = logger
        self._scan_interval_s = float(scan_interval_s)
        self._scan_stop = threading.Event()
        self._scan_thread: Optional[threading.Thread] = None
        self._closed = False
        self.batcher = MicroBatcher(
            self._route,
            max_batch=max_batch,
            lanes=lanes,
            max_wait_ms=max_wait_ms,
            max_queue_rows=max_queue_rows,
            quotas=self._quotas,
            registry=self.metrics,
            autostart=batcher_autostart,
        )
        self._m_tenants = self.metrics.gauge(
            "svgd_registry_tenants", "tenants currently hosted")
        self._m_reload_errors = self.metrics.counter(
            "svgd_registry_reload_errors_total",
            "scanner polls that raised for one tenant (others unaffected)")
        # progressive delivery (round 21): at most ONE rollout at a time
        # rides the shared batcher (its split/mirror hook is a single
        # seam); guarded by _lock
        self._rollout = None
        self._rollout_tenant: Optional[str] = None

    # ------------------------------------------------------------------ #
    # tenant lifecycle

    def add_tenant(
        self,
        name: str,
        model: str,
        *,
        particles=None,
        checkpoint: Union[str, Sequence[str], None] = None,
        quota_rows: Optional[int] = None,
        watch: bool = False,
        warm_buckets: Optional[List[int]] = None,
        **engine_kwargs,
    ) -> Tenant:
        """Register one named model.

        Exactly one of ``particles`` (an ``(n, d)`` ensemble array) or
        ``checkpoint`` (any layout ``PredictiveEngine.from_checkpoint``
        accepts) must be given.  ``quota_rows`` arms the shed-priority
        quota for this tenant; ``watch=True`` (requires a
        ``CheckpointManager``-root checkpoint) registers the tenant with
        the shared scanner so newer steps hot-swap in; ``warm_buckets``
        pre-traces the padding buckets those request sizes land in (off
        the request path — do it before taking traffic).  Remaining
        kwargs go to the engine (``plan=``, ``dtype=``,
        ``reload_policy=``, bucket bounds, model layout...).
        """
        if not _TENANT_NAME_RE.match(name or ""):
            raise ValueError(
                f"invalid tenant name {name!r} (want "
                f"{_TENANT_NAME_RE.pattern})"
            )
        if name == _metrics.OTHER_LABEL_VALUE:
            raise ValueError(
                f"tenant name {name!r} is reserved for the metrics "
                "cardinality-rollup series"
            )
        if (particles is None) == (checkpoint is None):
            raise ValueError("pass exactly one of particles= or checkpoint=")
        with self._lock:
            # cheap pre-checks before the expensive checkpoint load /
            # engine build (re-checked under the lock at insert — another
            # add may race this one)
            if self._closed:
                raise RuntimeError("registry is closed")
            if name in self._tenants:
                raise ValueError(f"tenant {name!r} already registered")
        engine_kwargs.setdefault("registry", self.metrics)
        if checkpoint is not None:
            source = (checkpoint if isinstance(checkpoint, (str, bytes))
                      or hasattr(checkpoint, "__fspath__")
                      else list(checkpoint))
            engine = PredictiveEngine.from_checkpoint(
                source, model, tenant=name,
                kernel_cache=self.kernel_cache, **engine_kwargs)
        else:
            engine = PredictiveEngine(
                model, particles, tenant=name,
                kernel_cache=self.kernel_cache, **engine_kwargs)
        reloader = None
        if watch:
            if checkpoint is None or not isinstance(
                    checkpoint, (str, bytes)) and not hasattr(
                    checkpoint, "__fspath__"):
                raise ValueError(
                    "watch=True needs a single CheckpointManager-root "
                    "checkpoint path"
                )
            reloader = CheckpointHotReloader(
                engine, checkpoint, logger=self._logger)
        tenant = Tenant(name, engine, reloader, quota_rows)
        with self._lock:
            if self._closed:
                raise RuntimeError("registry is closed")
            if name in self._tenants:
                raise ValueError(f"tenant {name!r} already registered")
            self._tenants[name] = tenant
            self._quotas[name] = quota_rows
            n_tenants = len(self._tenants)
        self._m_tenants.set(n_tenants)
        if warm_buckets:
            engine.warmup(list(warm_buckets))
        return tenant

    def remove_tenant(self, name: str, *, drain: bool = True,
                      timeout: float = 30.0) -> None:
        """Deregister ``name``.

        ``drain=True`` stops admission for the tenant, waits for its
        queued rows to flush through the batcher (in-flight dispatches
        always finish — the engine closure outlives the registry entry),
        then drops it.  ``drain=False`` cancels its queued requests with
        ``CancelledError`` immediately.  Either way the shared LRU
        forgets the tenant's buckets (without counting evictions) and
        other tenants never notice.
        """
        with self._lock:
            tenant = self._tenants.get(name)
            if tenant is None:
                raise KeyError(f"unknown tenant {name!r}")
            tenant.state = "draining"
            # quota off during the drain: its remaining queued work must
            # not be priority-shed on the way out
            self._quotas.pop(name, None)
        # a rollout targeting the removed tenant ends with it: disarm the
        # batcher hook BEFORE the drain so no still-arriving request is
        # hash-split to a candidate that is about to disappear (queued
        # candidate batches fall back to the incumbent dispatch)
        rollout = None
        with self._lock:
            if self._rollout_tenant == name:
                rollout = self._rollout
                self._rollout = None
                self._rollout_tenant = None
        if rollout is not None:
            self.batcher.set_rollout(None)
            try:
                rollout.close()
            except Exception:
                pass
        if drain:
            # pending = queued + collected-but-unresolved: the tenant must
            # stay routable until its LAST batch resolved, not just until
            # its queue emptied (a batch between _collect and dispatch
            # would otherwise KeyError in _route)
            deadline = time.monotonic() + timeout
            while self.batcher.tenant_pending_rows(name) > 0:
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"tenant {name!r} still has pending rows after "
                        f"{timeout}s drain"
                    )
                time.sleep(0.002)
        else:
            self.batcher.cancel_tenant(name)
        with self._lock:
            self._tenants.pop(name, None)
            n_tenants = len(self._tenants)
        self.kernel_cache.forget(tenant.engine)
        self._m_tenants.set(n_tenants)

    def set_quota(self, name: str, quota_rows: Optional[int]) -> None:
        """Retune one tenant's inflight-rows quota live."""
        with self._lock:
            tenant = self._tenants.get(name)
            if tenant is None:
                raise KeyError(f"unknown tenant {name!r}")
            tenant.quota_rows = quota_rows
            self._quotas[name] = quota_rows

    def quota_snapshot(self) -> Dict[str, Optional[int]]:
        """The current per-tenant quota mapping (a copy — the live view
        the batcher reads is internal).  The autoscale controller
        snapshots base quotas from here before tightening them."""
        with self._lock:
            return dict(self._quotas)

    def tenant(self, name: str) -> Tenant:
        with self._lock:
            tenant = self._tenants.get(name)
        if tenant is None:
            raise KeyError(f"unknown tenant {name!r}")
        return tenant

    def tenant_names(self) -> List[str]:
        with self._lock:
            return sorted(self._tenants)

    def __len__(self) -> int:
        with self._lock:
            return len(self._tenants)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._tenants

    # ------------------------------------------------------------------ #
    # progressive delivery (round 21)

    def begin_rollout(self, name: str, *, plan=None, clock=None,
                      controller=None):
        """Arm a progressive rollout for tenant ``name`` and return its
        :class:`~dist_svgd_tpu.rollout.RolloutController`.

        Builds a controller over the tenant's engine (or takes a
        pre-built ``controller`` — drills that inject clocks/plans), arms
        the shared batcher's split/mirror hook, and leaves offering
        candidates to the caller (``controller.offer(...)`` — typically
        the streaming supervisor's publish leg).  At most one rollout
        rides the batcher at a time; a second ``begin_rollout`` while one
        is armed raises unless it targets the same tenant (idempotent —
        returns the armed controller)."""
        from dist_svgd_tpu.rollout import RolloutController

        with self._lock:
            tenant = self._tenants.get(name)
            if tenant is None:
                raise KeyError(f"unknown tenant {name!r}")
            if tenant.state != "serving":
                raise KeyError(f"tenant {name!r} is {tenant.state}")
            if self._rollout is not None:
                if self._rollout_tenant == name:
                    return self._rollout
                raise RuntimeError(
                    f"a rollout is already armed for tenant "
                    f"{self._rollout_tenant!r}; end it first")
            if controller is None:
                kwargs = {"plan": plan, "metrics": self.metrics,
                          "logger": self._logger}
                if clock is not None:
                    kwargs["clock"] = clock
                controller = RolloutController(tenant.engine, **kwargs)
            self._rollout = controller
            self._rollout_tenant = name
        self.batcher.set_rollout(controller)
        return controller

    def end_rollout(self, name: str) -> None:
        """Disarm tenant ``name``'s rollout (idempotent).  An in-flight
        candidate is dropped (the incumbent was serving the split's
        complement all along and takes back 100%)."""
        with self._lock:
            if self._rollout_tenant != name:
                return
            rollout = self._rollout
            self._rollout = None
            self._rollout_tenant = None
        self.batcher.set_rollout(None)
        if rollout is not None:
            try:
                if rollout.active:
                    rollout.engine.drop_candidate()
            finally:
                rollout.close()

    def rollout_status(self) -> Optional[Dict[str, Any]]:
        """The armed rollout's controller document (None when idle)."""
        with self._lock:
            rollout, tenant = self._rollout, self._rollout_tenant
        if rollout is None:
            return None
        return {"tenant": tenant, **rollout.status()}

    # ------------------------------------------------------------------ #
    # request path

    def submit(self, name: str, x, trace: Optional[str] = None):
        """Enqueue one request for tenant ``name``; returns the future.
        ``trace`` is the cross-process trace id (see
        :meth:`MicroBatcher.submit`) — the HTTP layer passes the
        ``X-Fleet-Trace`` header through here."""
        with self._lock:
            tenant = self._tenants.get(name)
        if tenant is None:
            raise KeyError(f"unknown tenant {name!r}")
        if tenant.state != "serving":
            raise KeyError(f"tenant {name!r} is {tenant.state}")
        return self.batcher.submit(x, tenant=name, trace=trace)

    def predict(self, name: str, x, timeout: Optional[float] = 30.0):
        """Blocking convenience: ``submit(...).result(timeout)``."""
        return self.submit(name, x).result(timeout=timeout)

    def _route(self, x: np.ndarray, tenant: str):
        """The shared batcher's dispatch: one single-tenant coalesced
        batch → that tenant's engine.  ``remove_tenant(drain=True)``
        keeps the entry until the tenant's pending rows (queued AND
        in-flight) hit zero, so a drained removal never lands here; a
        ``drain=False`` removal racing a collected batch fails just that
        tenant's futures (KeyError → 503 at the HTTP layer)."""
        with self._lock:
            t = self._tenants.get(tenant)
        if t is None:
            raise KeyError(f"tenant {tenant!r} was removed")
        return t.engine.predict(x)

    def warm(self, batch_sizes: Optional[Sequence[int]] = None
             ) -> Dict[str, List[int]]:
        """Pre-trace every tenant's padding buckets for these request
        sizes (``None`` = each tenant's full bucket range) — the bench's
        steady-state precondition.  Returns the buckets compiled per
        tenant.  Mind the shared LRU: warming more total buckets than
        ``max_total_buckets`` evicts the earliest tenants' kernels."""
        with self._lock:
            tenants = list(self._tenants.values())
        return {t.name: t.engine.warmup(
                    list(batch_sizes) if batch_sizes is not None else None)
                for t in tenants}

    # ------------------------------------------------------------------ #
    # shared checkpoint scanner

    def poll_once(self) -> Dict[str, Optional[int]]:
        """One scan over every watched tenant root (the shared scanner's
        body; also the deterministic test/driver entrypoint).  Per-tenant
        isolation: a poll that raises (unreadable root, missing key) is
        counted and logged for THAT tenant only — every other tenant is
        still polled, and a failing tenant keeps serving its current
        generation.  Returns ``{tenant: newly served step or None}``."""
        with self._lock:
            watched = [t for t in self._tenants.values()
                       if t.reloader is not None and t.state == "serving"]
        out: Dict[str, Optional[int]] = {}
        for t in watched:
            try:
                out[t.name] = t.reloader.poll_once()
            except Exception as e:
                t.reload_errors += 1
                out[t.name] = None
                self._m_reload_errors.inc(tenant=t.name)
                if self._logger is not None:
                    try:
                        self._logger.log(event="tenant_reload_error",
                                         tenant=t.name,
                                         error=f"{type(e).__name__}: {e}")
                    except Exception:
                        pass
        return out

    def start_scanner(self) -> "ModelRegistry":
        """Start the ONE background scanner thread over all tenant roots."""
        if self._scan_thread is None:
            self._scan_stop.clear()
            self._scan_thread = threading.Thread(
                target=self._scan_loop, name="registry-scanner", daemon=True)
            self._scan_thread.start()
        return self

    def _scan_loop(self) -> None:
        while not self._scan_stop.is_set():
            self.poll_once()
            self._scan_stop.wait(self._scan_interval_s)

    def stop_scanner(self) -> None:
        self._scan_stop.set()
        if self._scan_thread is not None:
            self._scan_thread.join(timeout=10)
            self._scan_thread = None

    # ------------------------------------------------------------------ #
    # introspection / lifecycle

    def stats(self) -> Dict[str, Any]:
        """Per-tenant engine stats + shared cache/batcher view (the
        ``/metrics.json`` registry block)."""
        with self._lock:
            tenants = dict(self._tenants)
        # ONE batcher.stats() snapshot for every tenant's queued count —
        # a per-tenant lock round-trip would contend with the submit /
        # collect hot path N times per scrape
        bstats = self.batcher.stats()
        queued = bstats.get("tenant_queued", {})
        return {
            "tenants": {name: {**t.engine.stats(),
                               "state": t.state,
                               "quota_rows": t.quota_rows,
                               "queued_rows": queued.get(name, 0),
                               "reload_errors": t.reload_errors,
                               "loaded_step": (t.reloader.loaded_step
                                               if t.reloader is not None
                                               else t.engine.checkpoint_step)}
                        for name, t in tenants.items()},
            "kernel_cache": self.kernel_cache.stats(),
            "batcher": bstats,
        }

    def usage(self) -> Dict[str, Any]:
        """Per-tenant cost accounting (``telemetry/usage.py``): reads the
        active meter's registry when metering is enabled, else this
        registry's own metrics sink (whose missing ``svgd_usage_*``
        series yield an empty map — enable metering to populate it)."""
        from dist_svgd_tpu.telemetry import usage as _usage

        meter = _usage.get_meter()
        reg = meter.registry if meter is not None else self.metrics
        return {"metering": meter is not None, **_usage.usage_summary(reg)}

    def health(self) -> Dict[str, Any]:
        """The ``/healthz`` aggregate: overall status + per-tenant rows."""
        with self._lock:
            tenants = dict(self._tenants)
        return {
            "status": "ok" if tenants else "empty",
            "tenants": {name: t.summary() for name, t in tenants.items()},
            "kernel_cache": self.kernel_cache.stats(),
        }

    def close(self, drain: bool = True) -> None:
        """Stop the scanner, drain (or cancel) the shared batcher, and
        refuse further tenant adds.  Engines stay usable directly."""
        with self._lock:
            self._closed = True
            rollout_tenant = self._rollout_tenant
        if rollout_tenant is not None:
            self.end_rollout(rollout_tenant)
        self.stop_scanner()
        self.batcher.close(drain=drain)

    def __enter__(self) -> "ModelRegistry":
        return self

    def __exit__(self, *exc) -> None:
        self.close(drain=True)
